#!/usr/bin/env python3
"""Pinned chaos-seed replay: every seed that ever found an invariant
violation becomes a permanent regression test.

Mirrors ``tools/check_metrics.py``: run directly (``python
tools/check_chaos_seeds.py``; exit 1 on any violation) or through its guard
test (``tests/test_chaos_seeds.py``). The chaos injector is fully
deterministic per seed (one ``random.Random(seed)`` drives every fault
decision), so a seed that exposed a bug replays the exact fault sequence —
append it to ``PINNED_SEEDS`` with a comment naming the bug and it guards
the fix forever.

Workflow when a soak (tests/test_chaos.py) or this tool reports a
violation:

1. reproduce: ``python tools/check_chaos_seeds.py --seed <N>``
2. fix the scheduler/runtime bug it exposed
3. append ``(N, SOAK, "<what it caught>")`` to PINNED_SEEDS — the seed now
   replays on every CI run.
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import sys

# runnable as a plain script: the repo root (not tools/) holds the package
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# (seed, plan_name, schedules, why-it-is-pinned)
# plan names index into PLANS below, so a pinned seed replays under the
# exact fault mix that found its bug even if the default soak mix evolves.
PINNED_SEEDS = [
    # Initial coverage set (no violation ever found on these — they pin the
    # baseline fault mix: drops+delays+reorders, ambiguous binds, node
    # flaps, crash-restarts — so the harness itself is regression-guarded):
    (0, "soak-v1", 8, "baseline: delays + transient errors + restarts"),
    (5, "soak-v1", 8, "baseline: ambiguous bind failure mid-gang"),
    (7, "soak-v1", 8, "baseline: heavy reorder + drops"),
    (11, "soak-v1", 8, "baseline: multi-chain relax under flaps"),
    (13, "soak-v1", 8, "baseline: bench seed, preemption-heavy mix"),
    # Defrag/migration coverage (ops profile defrag-v1: constructed
    # fragmentation episodes, defrag_tick planning + eviction,
    # resume_migrations re-binds, kill -9 in the after-checkpoint/
    # before-re-bind window; invariants include check_defrag):
    (0, "defrag-v1", 14, "defrag: full plan->evict->rebind->waiter-lands"),
    (13, "defrag-v1", 14, "defrag: kill -9 mid-migration (abort path)"),
    (18, "defrag-v1", 14, "defrag: kill -9 under injected evict faults"),
    (28, "defrag-v1", 14, "defrag: two plans in one soak + rebind"),
    # Doomed-bad accounting under multi-bad-node layouts (the ex-"known
    # pre-existing corner", fixed in ISSUE 10): a reclaim-then-reallocate
    # sequence left a VC's free cell unbacked at a level whose only bad
    # free candidate was later split away by a LOWER-level doomed bind, so
    # total_left < all_vc_free materialized (seed 23: invariant trip;
    # seed 2: VCSafetyBroken at schedule time). Fixed by the top-down
    # doom-bind sweep + rebind re-checks in the bad-parent accounting
    # branches + the bindable-candidate filter (PARITY.md deviations).
    (23, "defrag-v1", 14, "doomed-bad: higher-level excess stranded by a "
                          "lower-level doomed bind (invariant trip)"),
    (2, "defrag-v1", 14, "doomed-bad: VCSafetyBroken raise at schedule "
                         "time from the same accounting gap"),
]


def _plans():
    from hivedscheduler_tpu.chaos import FaultPlan

    soak = FaultPlan(
        drop_event_p=0.08, delay_event_p=0.15, reorder_p=0.35,
        error_p=0.2, max_consecutive_errors=2, bind_fail_after_p=0.5,
    )
    # plan name -> (fault plan, harness ops profile)
    return {
        "soak-v1": (soak, "v1"),
        "defrag-v1": (soak, "defrag-v1"),
    }


def replay(seed: int, plan_name: str = "soak-v1", schedules: int = 8) -> dict:
    from hivedscheduler_tpu.chaos import ChaosHarness

    # every replay doubles as a race/deadlock detector: the lock-order
    # sanitizer (common/lockcheck.py) raises on inversions instead of
    # wedging; HIVED_LOCKCHECK=0 opts out for bisecting. Restored after
    # the run so in-process callers (the determinism guard test) don't
    # leak the env var into their process.
    prev = os.environ.get("HIVED_LOCKCHECK")
    os.environ.setdefault("HIVED_LOCKCHECK", "1")
    try:
        fault_plan, ops_profile = _plans()[plan_name]
        harness = ChaosHarness(seed=seed, plan=fault_plan,
                               restart_every=3, ops_profile=ops_profile)
        return harness.run(schedules)
    finally:
        if prev is None:
            os.environ.pop("HIVED_LOCKCHECK", None)
        else:
            os.environ["HIVED_LOCKCHECK"] = prev


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--seed", type=int, default=None,
                        help="replay ONE seed (debugging) instead of the "
                             "pinned set")
    parser.add_argument("--schedules", type=int, default=8)
    parser.add_argument("--plan", default="soak-v1",
                        choices=["soak-v1", "defrag-v1"])
    args = parser.parse_args(argv)
    logging.disable(logging.CRITICAL)

    if args.seed is not None:
        targets = [(args.seed, args.plan, args.schedules, "ad hoc")]
    else:
        targets = PINNED_SEEDS
    ok = True
    for seed, plan_name, schedules, why in targets:
        report = replay(seed, plan_name, schedules)
        if report["violations"]:
            ok = False
            print(f"SEED {seed} ({why}): {len(report['violations'])} "
                  f"invariant violation(s):")
            for v in report["violations"]:
                print(f"  {v}")
        else:
            print(f"seed {seed} [{plan_name} x{schedules}] OK — "
                  f"{report['gangs_completed']} gangs, "
                  f"{report['restarts']} restarts, "
                  f"injector {json.dumps(report['injector'])}")
    if ok:
        print(f"check_chaos_seeds: OK ({len(targets)} seed(s) clean)")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
