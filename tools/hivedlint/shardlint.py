"""shard_map/collective-contract + env-flag rules (SHD001-004, ENV001/002).

The model/parallel layer's correctness contract is conventions the vma
checker and trace-time errors only police on the meshes CI happens to run
— this module machine-checks them on every tree, mirroring PR 7's
concurrency rules (same pattern: each rule parameterized so the seeded
fixtures in tests/test_shardlint.py drive it against tiny synthetic
trees; ``check(root)`` wires the real package). The rules encode the
CLAUDE.md "shard_map vma rules" blind spots and the contract written down
in ``doc/design/shard-contract.md``:

- **SHD001 vma-loop-carry** — inside a manual (shard_map) function, a
  fresh array (``jnp.zeros/ones/full/empty[_like]``) flowing into a
  ``lax.scan``/``fori_loop``/``while_loop`` carry must pass through
  ``shard_utils.varying(...)`` first (the twice-bitten vma blind spot:
  unvaried fresh carries trip the checker only on multi-axis meshes).
- **SHD002 manual-context-purity** — call-graph fixpoint from every
  shard_map body (and every function passed as a pipeline stage body):
  no reachable call opens ``shard_map``/``_get_shard_map`` — only the
  ``_local`` bodies may be called inside a manual context. A call
  lexically guarded by an ``if`` on a ``manual_*``/``device_local``
  condition is the sanctioned dual-mode dispatch pattern and prunes the
  path (the guard proves the callee runs in GSPMD mode).
- **SHD003 collective-axis-declared** — a string-literal axis name at a
  collective call site (``psum``/``ppermute``/``all_gather``/
  ``axis_index``/``pvary``/...) inside a shard_map body must be declared
  by a ``PartitionSpec`` literal of the installing function — a typo'd
  axis otherwise only fails at trace time on a mesh that has the real
  one. Threaded parameters (``axis_name=...``) are always fine.
- **SHD004 donated-buffer-read** — an argument at a ``donate_argnums``
  position of a jitted entry point must not be read again after the call
  in the same statement sequence: the buffer is dead (JAX may or may not
  have reused it — the read works on CPU and corrupts on TPU).
- **ENV001 env-flag-registered** — every ``HIVED_*`` token in package
  code or docstrings is a row (or family prefix) of
  ``common/envflags.py`` REGISTRY.
- **ENV002 env-flag-read** — every registered flag is actually read
  somewhere in the tree (package, tests, tools, repo-root scripts).
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from tools.hivedlint import Finding

# subpackages the shard rules police (SHD004 adds the train-step factory's
# home implicitly — parallel/ is in the list)
SHARD_SCOPE = ("parallel", "models", "ops")

# collective -> positional index of its axis-name argument
COLLECTIVES: Dict[str, int] = {
    "psum": 1, "pmean": 1, "pmax": 1, "pmin": 1, "ppermute": 1,
    "all_gather": 1, "all_to_all": 1, "psum_scatter": 1,
    "pvary": 1, "pcast": 1, "axis_index": 0, "axis_size": 0,
}
_FRESH = {"zeros", "ones", "full", "empty",
          "zeros_like", "ones_like", "full_like", "empty_like"}
_FRESH_RECV = {"jnp", "np", "numpy"}
_VARYING = {"varying", "_varying", "pvary", "pcast"}
_OPENERS = {"shard_map", "_get_shard_map"}
# functions whose Nth positional argument runs in a manual context
MANUAL_BODY_PARAMS: Dict[str, int] = {"pipeline_apply": 0}


# ---------------------------------------------------------------------------
# shared AST plumbing
# ---------------------------------------------------------------------------

def _walk_py(scan_root: str) -> Iterable[Tuple[str, ast.AST]]:
    base = os.path.dirname(scan_root.rstrip(os.sep))
    for dirpath, _, files in os.walk(scan_root):
        for fn in sorted(files):
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            rel = os.path.relpath(path, base).replace(os.sep, "/")
            with open(path) as f:
                yield rel, ast.parse(f.read(), filename=path)


def _own_nodes(fn: ast.AST) -> Iterable[ast.AST]:
    """Nodes of ``fn``'s body excluding nested function/lambda bodies —
    what actually executes in this frame."""
    stack = list(getattr(fn, "body", []))
    while stack:
        n = stack.pop()
        yield n
        for c in ast.iter_child_nodes(n):
            if isinstance(c, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda)):
                continue
            stack.append(c)


def _functions(tree: ast.AST) -> List[ast.FunctionDef]:
    return [n for n in ast.walk(tree) if isinstance(n, ast.FunctionDef)]


def _call_name(node: ast.Call) -> Optional[str]:
    if isinstance(node.func, ast.Name):
        return node.func.id
    if isinstance(node.func, ast.Attribute):
        return node.func.attr
    return None


def _is_opener_call(node: ast.Call) -> bool:
    """``shard_map(...)`` / ``_get_shard_map(...)`` /
    ``_get_shard_map()(body, ...)``."""
    name = _call_name(node)
    if name in _OPENERS:
        return True
    return isinstance(node.func, ast.Call) and _call_name(node.func) in _OPENERS


def _install_body_arg(node: ast.Call) -> Optional[ast.AST]:
    """For a shard_map install site, the body expression (arg 0 of
    ``shard_map(...)`` or of ``_get_shard_map()(...)``)."""
    if _call_name(node) == "shard_map" and node.args:
        return node.args[0]
    if (isinstance(node.func, ast.Call)
            and _call_name(node.func) in _OPENERS and node.args):
        return node.args[0]
    return None


def _body_names_of(expr: ast.AST) -> List[str]:
    """Function names referenced by a body expression: a bare Name, or the
    first argument of a ``functools.partial(...)``."""
    if isinstance(expr, ast.Name):
        return [expr.id]
    if (isinstance(expr, ast.Call) and _call_name(expr) == "partial"
            and expr.args and isinstance(expr.args[0], ast.Name)):
        return [expr.args[0].id]
    return []


# ---------------------------------------------------------------------------
# SHD001: fresh arrays in manual loop carries must be vma-seeded
# ---------------------------------------------------------------------------

def _taint(expr: ast.AST, env: Dict[str, bool]) -> bool:
    """True when ``expr`` is (built from nothing but) fresh unvaried
    arrays/constants. Any dependence on real data or a varying() wrapper
    clears it."""
    if isinstance(expr, ast.Call):
        name = _call_name(expr)
        if name in _VARYING:
            return False
        if (name in _FRESH and isinstance(expr.func, ast.Attribute)
                and isinstance(expr.func.value, ast.Name)
                and expr.func.value.id in _FRESH_RECV):
            return True
        return False
    if isinstance(expr, ast.Name):
        return env.get(expr.id, False)
    if isinstance(expr, (ast.Tuple, ast.List)):
        return any(_taint(e, env) for e in expr.elts)
    if isinstance(expr, ast.BinOp):
        return _taint(expr.left, env) and _taint(expr.right, env)
    if isinstance(expr, ast.UnaryOp):
        return _taint(expr.operand, env)
    if isinstance(expr, ast.IfExp):
        return _taint(expr.body, env) and _taint(expr.orelse, env)
    if isinstance(expr, ast.Starred):
        return _taint(expr.value, env)
    if isinstance(expr, ast.Constant):
        return True  # vma-neutral: zeros(...) * 2 stays fresh
    return False


_LOOP_INIT = {"scan": 1, "fori_loop": 3, "while_loop": 2}
_LOOP_INIT_KW = {"scan": "init", "fori_loop": "init_val",
                 "while_loop": "init_val"}


def check_vma_carries(scan_root: str) -> List[Finding]:
    out: List[Finding] = []
    for rel, tree in _walk_py(scan_root):
        # shard_map bodies installed in this module count as manual even
        # when the collectives live in their callees
        installed: Set[str] = set()
        for node in ast.walk(tree):
            if isinstance(node, ast.Call):
                body = _install_body_arg(node)
                if body is not None:
                    installed.update(_body_names_of(body))
        for fn in _functions(tree):
            own = list(_own_nodes(fn))
            manual = fn.name in installed or any(
                isinstance(n, ast.Call) and _call_name(n) in COLLECTIVES
                for n in own
            )
            if not manual:
                continue
            env: Dict[str, bool] = {}
            assigns = [n for n in own if isinstance(n, ast.Assign)]
            assigns.sort(key=lambda n: n.lineno)
            for a in assigns:
                for tgt in a.targets:
                    if isinstance(tgt, ast.Name):
                        env[tgt.id] = _taint(a.value, env)
                    elif (isinstance(tgt, ast.Tuple)
                          and isinstance(a.value, ast.Tuple)
                          and len(tgt.elts) == len(a.value.elts)):
                        for t, v in zip(tgt.elts, a.value.elts):
                            if isinstance(t, ast.Name):
                                env[t.id] = _taint(v, env)
            for node in own:
                if not (isinstance(node, ast.Call)
                        and _call_name(node) in _LOOP_INIT):
                    continue
                name = _call_name(node)
                idx = _LOOP_INIT[name]
                init = (node.args[idx] if len(node.args) > idx else None)
                if init is None:
                    for kw in node.keywords:
                        if kw.arg == _LOOP_INIT_KW[name]:
                            init = kw.value
                if init is None:
                    continue
                elts = (init.elts if isinstance(init, (ast.Tuple, ast.List))
                        else [init])
                for e in elts:
                    if _taint(e, env):
                        out.append(Finding(
                            "SHD001", rel, e.lineno,
                            f"fresh array flows into a lax.{name} carry "
                            f"inside a manual (shard_map) context without "
                            f"shard_utils.varying(...) — unvaried carries "
                            f"break the vma checker on multi-axis meshes "
                            f"(doc/design/shard-contract.md)",
                        ))
    return out


# ---------------------------------------------------------------------------
# SHD002: no shard_map reachable from inside a manual context
# ---------------------------------------------------------------------------

class _FrameScan(ast.NodeVisitor):
    """One function frame: opener call sites and callee references, each
    tagged with whether they sit under a manual-axis guard."""

    def __init__(self):
        self.guard = 0
        self.openers: List[Tuple[int, bool]] = []      # (line, guarded)
        self.refs: List[Tuple[str, bool]] = []         # (name, guarded)

    def visit_FunctionDef(self, node):  # nested frames scan separately
        pass

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node):
        pass

    def visit_If(self, node: ast.If) -> None:
        g = any(
            isinstance(n, ast.Name)
            and (n.id == "device_local" or n.id.startswith("manual_"))
            for n in ast.walk(node.test)
        )
        if g:
            self.guard += 1
        self.generic_visit(node)
        if g:
            self.guard -= 1

    def visit_Call(self, node: ast.Call) -> None:
        guarded = self.guard > 0
        if _is_opener_call(node):
            # `_get_shard_map()(body)` matches as outer AND inner call:
            # count the site once
            if (node.lineno, guarded) not in self.openers:
                self.openers.append((node.lineno, guarded))
        else:
            name = _call_name(node)
            if name:
                self.refs.append((name, guarded))
            # function references passed as arguments (lax.cond branches,
            # functools.partial bodies) keep the manual taint
            for arg in list(node.args) + [k.value for k in node.keywords]:
                if isinstance(arg, ast.Name):
                    self.refs.append((arg.id, guarded))
        self.generic_visit(node)


def check_manual_context(scan_roots) -> List[Finding]:
    if isinstance(scan_roots, str):
        scan_roots = [scan_roots]
    # index every named function and per-module import aliases
    table: Dict[Tuple[str, str], ast.FunctionDef] = {}
    mod_funcs: Dict[str, Dict[str, List[str]]] = {}
    imports: Dict[str, Dict[str, Tuple[str, str]]] = {}
    trees: Dict[str, ast.AST] = {}
    for rel, tree in (pair for sr in scan_roots for pair in _walk_py(sr)):
        trees[rel] = tree
        funcs: Dict[str, List[str]] = {}
        for fn in _functions(tree):
            table[(rel, fn.name)] = fn
            funcs.setdefault(fn.name, []).append(fn.name)
        mod_funcs[rel] = funcs
        imp: Dict[str, Tuple[str, str]] = {}
        for node in ast.walk(tree):
            if isinstance(node, ast.ImportFrom) and node.module:
                mod_rel = node.module.replace(".", "/") + ".py"
                for alias in node.names:
                    imp[alias.asname or alias.name] = (mod_rel, alias.name)
        imports[rel] = imp

    def resolve(rel: str, name: str) -> Optional[Tuple[str, str]]:
        if name in mod_funcs.get(rel, {}):
            return (rel, name)
        tgt = imports.get(rel, {}).get(name)
        if tgt:
            mod_rel, fname = tgt
            for cand in table:
                if cand[1] == fname and mod_rel.endswith(cand[0]):
                    return cand
        return None

    scans: Dict[Tuple[str, str], _FrameScan] = {}

    def scan_of(key: Tuple[str, str]) -> _FrameScan:
        if key not in scans:
            s = _FrameScan()
            for stmt in table[key].body:
                s.visit(stmt)
            scans[key] = s
        return scans[key]

    # roots: shard_map bodies + pipeline stage bodies
    roots: Set[Tuple[str, str]] = set()
    for rel, tree in trees.items():
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            body = _install_body_arg(node)
            if body is None and _call_name(node) in MANUAL_BODY_PARAMS:
                idx = MANUAL_BODY_PARAMS[_call_name(node)]
                body = node.args[idx] if len(node.args) > idx else None
            if body is None:
                continue
            for name in _body_names_of(body):
                key = resolve(rel, name)
                if key:
                    roots.add(key)

    out: List[Finding] = []
    seen: Set[Tuple[str, str]] = set()
    frontier = sorted(roots)
    while frontier:
        key = frontier.pop()
        if key in seen:
            continue
        seen.add(key)
        rel = key[0]
        s = scan_of(key)
        for line, guarded in s.openers:
            if not guarded:
                out.append(Finding(
                    "SHD002", rel, line,
                    f"shard_map opened on a path reachable from the manual "
                    f"(shard_map/pipeline-stage) body "
                    f"{'.'.join(key[::-1][:1])}() — GSPMD shard_map cannot "
                    f"open inside a manual context; call the _local body "
                    f"directly, or guard the call on the manual_* axes "
                    f"being None (doc/design/shard-contract.md)",
                ))
        for name, guarded in s.refs:
            if guarded:
                continue  # dual-mode dispatch: this branch is GSPMD-only
            callee = resolve(rel, name)
            if callee and callee not in seen:
                frontier.append(callee)
    return out


# ---------------------------------------------------------------------------
# SHD003: literal collective axes must be declared by the install's specs
# ---------------------------------------------------------------------------

def _spec_literals(fn: ast.AST) -> Set[str]:
    """String constants inside P(...)/PartitionSpec(...) calls anywhere in
    ``fn`` (the axes this install site demonstrably knows about)."""
    out: Set[str] = set()
    for node in ast.walk(fn):
        if (isinstance(node, ast.Call)
                and _call_name(node) in ("P", "PartitionSpec")):
            for sub in ast.walk(node):
                if isinstance(sub, ast.Constant) and isinstance(sub.value, str):
                    out.add(sub.value)
    return out


def check_collective_axes(scan_root: str) -> List[Finding]:
    out: List[Finding] = []
    for rel, tree in _walk_py(scan_root):
        # which top-level functions install a shard_map, and what axes
        # their specs declare; which body functions they install
        installer_axes: Dict[str, Set[str]] = {}
        body_axes: Dict[str, Set[str]] = {}
        top_funcs = [n for n in tree.body if isinstance(n, ast.FunctionDef)]
        for fn in top_funcs:
            installs = [n for n in ast.walk(fn)
                        if isinstance(n, ast.Call)
                        and _install_body_arg(n) is not None]
            if not installs:
                continue
            axes = _spec_literals(fn)
            installer_axes[fn.name] = axes
            for call in installs:
                for name in _body_names_of(_install_body_arg(call)):
                    body_axes.setdefault(name, set()).update(axes)
        for fn in top_funcs:
            if fn.name in installer_axes:
                declared: Optional[Set[str]] = installer_axes[fn.name]
            elif fn.name in body_axes:
                declared = body_axes[fn.name]
            else:
                continue  # not demonstrably a manual context: skip
            for node in ast.walk(fn):
                if not (isinstance(node, ast.Call)
                        and _call_name(node) in COLLECTIVES):
                    continue
                idx = COLLECTIVES[_call_name(node)]
                axis = node.args[idx] if len(node.args) > idx else None
                if axis is None:
                    for kw in node.keywords:
                        if kw.arg == "axis_name":
                            axis = kw.value
                if axis is None:
                    continue
                literals = []
                if isinstance(axis, ast.Constant) and isinstance(axis.value, str):
                    literals = [(axis.value, axis.lineno)]
                elif isinstance(axis, (ast.Tuple, ast.List)):
                    literals = [
                        (e.value, e.lineno) for e in axis.elts
                        if isinstance(e, ast.Constant)
                        and isinstance(e.value, str)
                    ]
                for lit, line in literals:
                    if lit not in declared:
                        out.append(Finding(
                            "SHD003", rel, line,
                            f"collective axis {lit!r} at a "
                            f"{_call_name(node)}() site is not declared by "
                            f"any PartitionSpec literal of the installing "
                            f"shard_map — a typo'd axis only fails at trace "
                            f"time on a mesh that has the real one; thread "
                            f"the axis as a parameter or fix the spec",
                        ))
    return out


# ---------------------------------------------------------------------------
# SHD004: donated buffers must not be read after the donating call
# ---------------------------------------------------------------------------

def _donated_indices(call: ast.Call) -> Optional[Set[int]]:
    if _call_name(call) != "jit":
        return None
    for kw in call.keywords:
        if kw.arg == "donate_argnums":
            v = kw.value
            if isinstance(v, ast.Constant) and isinstance(v.value, int):
                return {v.value}
            if isinstance(v, (ast.Tuple, ast.List)):
                return {e.value for e in v.elts
                        if isinstance(e, ast.Constant)
                        and isinstance(e.value, int)}
    return None


def _ref_key(expr: ast.AST) -> Optional[Tuple[str, str]]:
    """A trackable buffer reference: a bare Name or ``self.attr``."""
    if isinstance(expr, ast.Name):
        return ("", expr.id)
    if (isinstance(expr, ast.Attribute) and isinstance(expr.value, ast.Name)
            and expr.value.id == "self"):
        return ("self", expr.attr)
    return None


def _reads_writes(stmt: ast.AST) -> Tuple[Set[Tuple[str, str]],
                                          Set[Tuple[str, str]]]:
    reads: Set[Tuple[str, str]] = set()
    writes: Set[Tuple[str, str]] = set()
    for node in ast.walk(stmt):
        key = _ref_key(node)
        if key is None:
            continue
        ctx = getattr(node, "ctx", None)
        if isinstance(ctx, (ast.Store, ast.Del)):
            writes.add(key)
        elif isinstance(ctx, ast.Load):
            # self.attr Load: only count the attribute access itself, not
            # the bare `self` read inside it
            reads.add(key)
    return reads, writes


def check_donation(scan_root: str) -> List[Finding]:
    out: List[Finding] = []
    for rel, tree in _walk_py(scan_root):
        # jitted-callable name -> donated positional indices
        registry: Dict[Tuple[str, str], Set[int]] = {}
        for node in ast.walk(tree):
            if not isinstance(node, ast.Assign):
                continue
            if not isinstance(node.value, ast.Call):
                continue
            donated = _donated_indices(node.value)
            if not donated:
                continue
            for tgt in node.targets:
                key = _ref_key(tgt)
                if key:
                    registry[key] = donated
        if not registry:
            continue

        def call_in(stmt: ast.AST) -> Optional[ast.Call]:
            for n in ast.walk(stmt):
                if isinstance(n, ast.Call):
                    fkey = _ref_key(n.func)
                    if fkey in registry:
                        return n
            return None

        for node in ast.walk(tree):
            for field in ("body", "orelse", "finalbody"):
                seq = getattr(node, field, None)
                if not isinstance(seq, list):
                    continue
                for i, stmt in enumerate(seq):
                    call = call_in(stmt)
                    if call is None:
                        continue
                    fkey = _ref_key(call.func)
                    _, own_writes = _reads_writes(stmt)
                    for idx in sorted(registry[fkey]):
                        if idx >= len(call.args):
                            continue
                        bkey = _ref_key(call.args[idx])
                        if bkey is None:
                            continue
                        if bkey in own_writes:
                            continue  # x = f(x): rebound by the call stmt
                        for later in seq[i + 1:]:
                            reads, writes = _reads_writes(later)
                            if bkey in reads:
                                buf = (bkey[1] if not bkey[0]
                                       else f"self.{bkey[1]}")
                                fname = (fkey[1] if not fkey[0]
                                         else f"self.{fkey[1]}")
                                out.append(Finding(
                                    "SHD004", rel, later.lineno,
                                    f"{buf} is read after being donated to "
                                    f"{fname}() (donate_argnums index "
                                    f"{idx}) — the buffer is dead after the "
                                    f"call; rebind it from the call's "
                                    f"result first",
                                ))
                                break
                            if bkey in writes:
                                break
    return out


# ---------------------------------------------------------------------------
# ENV001 / ENV002: the HIVED_* flag registry is exact
# ---------------------------------------------------------------------------

_FLAG_TOKEN = re.compile(r"HIVED_[A-Z0-9_]+")
_REGISTRY_FILE = "hivedscheduler_tpu/common/envflags.py"


def _env_read_names(tree: ast.AST) -> Tuple[Set[str], Dict[str, str],
                                            Set[str]]:
    """(direct literal env-read names, module consts NAME->flag, symbol
    loads) for one module."""
    direct: Set[str] = set()
    consts: Dict[str, str] = {}
    loads: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Constant) \
                and isinstance(node.value.value, str) \
                and _FLAG_TOKEN.fullmatch(node.value.value):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    consts[tgt.id] = node.value.value
        if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
            loads.add(node.id)
        if isinstance(node, ast.Attribute):
            loads.add(node.attr)

        arg = None
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute):
            recv = node.func.value
            is_environ = (isinstance(recv, ast.Attribute)
                          and recv.attr == "environ") or (
                isinstance(recv, ast.Name) and recv.id == "environ")
            if node.func.attr == "get" and is_environ and node.args:
                arg = node.args[0]
            elif node.func.attr == "getenv" and node.args:
                arg = node.args[0]
            elif (node.func.attr == "get" and node.args
                  and isinstance(node.args[0], ast.Constant)
                  and isinstance(node.args[0].value, str)
                  and _FLAG_TOKEN.fullmatch(node.args[0].value)):
                # the registry's own accessor (envflags.get("HIVED_X", ...))
                # — a KeyError-checked read, the preferred pattern for new
                # flags
                arg = node.args[0]
        elif isinstance(node, ast.Subscript):
            v = node.value
            if (isinstance(v, ast.Attribute) and v.attr == "environ") or (
                    isinstance(v, ast.Name) and v.id == "environ"):
                arg = node.slice
        if arg is not None and isinstance(arg, ast.Constant) \
                and isinstance(arg.value, str):
            direct.add(arg.value)
    return direct, consts, loads


def check_env_flags(
    root: str,
    names: Optional[Set[str]] = None,
    package_rel: str = "hivedscheduler_tpu",
    read_rels: Sequence[str] = ("hivedscheduler_tpu", "tests", "tools"),
) -> List[Finding]:
    if names is None:
        import sys

        sys.path.insert(0, root)
        try:
            from hivedscheduler_tpu.common import envflags
        finally:
            sys.path.pop(0)
        names = set(envflags.REGISTRY)

    def ok(token: str) -> bool:
        return token in names or any(n.startswith(token) for n in names)

    out: List[Finding] = []

    # ENV001: every HIVED_* token in the package is registered
    for rel, tree in _walk_py(os.path.join(root, package_rel)):
        if rel == _REGISTRY_FILE:
            continue
        for node in ast.walk(tree):
            if isinstance(node, ast.Constant) and isinstance(node.value, str):
                for token in sorted(set(_FLAG_TOKEN.findall(node.value))):
                    if not ok(token):
                        out.append(Finding(
                            "ENV001", rel, node.lineno,
                            f"{token} is not a registered flag — add a row "
                            f"to common/envflags.py REGISTRY (the "
                            f"doc/design/flags.md catalogue renders from "
                            f"it)",
                        ))

    # ENV002: every registered flag is read somewhere in the tree
    direct: Set[str] = set()
    consts: Dict[str, str] = {}
    load_counts: Set[str] = set()
    scan_files: List[Tuple[str, ast.AST]] = []
    for rel_dir in read_rels:
        base = os.path.join(root, rel_dir)
        if os.path.isdir(base):
            scan_files.extend(_walk_py(base))
    for fn in sorted(os.listdir(root)):
        if fn.endswith(".py"):
            with open(os.path.join(root, fn)) as f:
                scan_files.append((fn, ast.parse(f.read(), filename=fn)))
    for rel, tree in scan_files:
        if rel == _REGISTRY_FILE:
            continue
        d, c, l = _env_read_names(tree)
        direct |= d
        consts.update(c)
        load_counts |= l
    reads = set(direct)
    reads |= {flag for const, flag in consts.items() if const in load_counts}
    for name in sorted(names - reads):
        out.append(Finding(
            "ENV002", _REGISTRY_FILE, 1,
            f"flag {name} is registered but never read anywhere in the "
            f"tree — drop the registry row (and its doc/design/flags.md "
            f"entry regenerates without it)",
        ))
    return out


# ---------------------------------------------------------------------------
# entry
# ---------------------------------------------------------------------------

def check(root: str) -> List[Finding]:
    pkg = os.path.join(root, "hivedscheduler_tpu")
    scans = [os.path.join(pkg, sub) for sub in SHARD_SCOPE]
    out: List[Finding] = []
    for scan in scans:
        out += check_vma_carries(scan)
        out += check_collective_axes(scan)
        out += check_donation(scan)
    out += check_manual_context(scans)  # one unit: cross-module call graph
    out += check_env_flags(root)
    return out
