"""Recurring-blind-spot rules (CLI001/002, GRD001, SER001, MET001).

These encode the CLAUDE.md "recurring blind spots" that verify passes have
repeatedly caught by hand: features unreachable from the CLIs, error
messages reworded out from under their ``pytest.raises(match=...)`` guards,
hand-rolled serializers drifting from the canonical ``to_dict``/dataclass
fields, and metric-catalogue drift (folded in from tools/check_metrics.py).
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, Iterable, List, Optional, Set, Tuple

from tools.hivedlint import Finding

# ---------------------------------------------------------------------------
# CLI001: config-field -> CLI-flag reachability
#
# Every TransformerConfig field must either be passed (from args) at the
# CLI's construction site or be allowlisted here WITH a reason. An
# allowlisted field that IS passed is flagged too — the registry must not
# rot. The twice-caught bug this encodes: a new model capability (pipeline,
# moe_top_k) landing without a train flag, unreachable from
# `python -m hivedscheduler_tpu.train`.
# ---------------------------------------------------------------------------

_SERVING_ONLY_REASONS = {
    "dtype": "compute dtype is jnp policy, not a scalar flag",
    "attn_impl": "decode path has its own ragged attention; train-side impl "
                 "selection does not apply",
    "moe_aux_weight": "training-only auxiliary loss",
    "moe_zloss_weight": "training-only router z-loss",
    "pipeline_microbatches": "GPipe is a training construct",
    "remat": "backward-pass policy; no backward at inference",
    "attn_block_q": "flash tiling applies to the training attention kernels",
    "attn_block_k": "flash tiling applies to the training attention kernels",
    "overlap": "collective-matmul overlap gates on the training path",
    "lora_rank": "adapters merge into base weights at checkpoint load "
                 "(restore_serving_params), not a live config field",
    "lora_alpha": "merged at checkpoint load",
    "lora_mlp": "merged at checkpoint load",
}

CLI_CONFIG_SITES: List[Tuple[str, Dict[str, str]]] = [
    ("hivedscheduler_tpu/train.py", {
        "dtype": "compute dtype is jnp policy, not a scalar flag",
    }),
    ("hivedscheduler_tpu/serve.py", dict(_SERVING_ONLY_REASONS)),
    ("hivedscheduler_tpu/generate.py", dict(_SERVING_ONLY_REASONS)),
    ("hivedscheduler_tpu/eval.py", {
        **{k: v for k, v in _SERVING_ONLY_REASONS.items()
           if k not in ("attn_impl",)},
        "rope_theta": "eval consumes train checkpoints; geometry knobs ride "
                      "the restore path (smoke tool, not a product surface)",
        "expert_capacity_factor": "same: eval mirrors the checkpoint config",
    }),
]


def config_fields(transformer_path: str,
                  class_name: str = "TransformerConfig") -> List[str]:
    with open(transformer_path) as f:
        tree = ast.parse(f.read(), filename=transformer_path)
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef) and node.name == class_name:
            return [
                s.target.id for s in node.body
                if isinstance(s, ast.AnnAssign) and isinstance(s.target, ast.Name)
            ]
    raise AssertionError(f"{class_name} not found in {transformer_path}")


def check_cli_reachability(
    root: str,
    fields: List[str],
    sites: Optional[List[Tuple[str, Dict[str, str]]]] = None,
    class_name: str = "TransformerConfig",
) -> List[Finding]:
    out: List[Finding] = []
    for rel, allow in (sites if sites is not None else CLI_CONFIG_SITES):
        path = os.path.join(root, rel)
        with open(path) as f:
            tree = ast.parse(f.read(), filename=path)
        passed: Set[str] = set()
        site_line = 1
        for node in ast.walk(tree):
            if isinstance(node, ast.Call):
                func = node.func
                name = func.attr if isinstance(func, ast.Attribute) else (
                    func.id if isinstance(func, ast.Name) else None)
                if name == class_name:
                    site_line = node.lineno
                    passed.update(kw.arg for kw in node.keywords if kw.arg)
        for field in fields:
            if field not in passed and field not in allow:
                out.append(Finding(
                    "CLI001", rel, site_line,
                    f"config field {field!r} is unreachable from this CLI: "
                    f"pass it at the {class_name}(...) site (add a flag) or "
                    f"allowlist it with a reason in tools/hivedlint/"
                    f"blindspots.py",
                ))
            elif field in passed and field in allow:
                out.append(Finding(
                    "CLI001", rel, site_line,
                    f"config field {field!r} is allowlisted as unreachable "
                    f"but IS passed — drop the stale allowlist entry",
                ))
    return out


# ---------------------------------------------------------------------------
# CLI002: dead flags — every add_argument dest is read in its module
# ---------------------------------------------------------------------------

def check_dead_flags(root: str, cli_files: Iterable[str]) -> List[Finding]:
    out: List[Finding] = []
    for rel in cli_files:
        path = os.path.join(root, rel)
        with open(path) as f:
            tree = ast.parse(f.read(), filename=path)
        attr_reads: Set[str] = {
            n.attr for n in ast.walk(tree) if isinstance(n, ast.Attribute)
        }
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "add_argument"):
                continue
            dest = None
            for kw in node.keywords:
                if kw.arg == "dest" and isinstance(kw.value, ast.Constant):
                    dest = kw.value.value
            if dest is None:
                longopts = [
                    a.value for a in node.args
                    if isinstance(a, ast.Constant) and isinstance(a.value, str)
                    and a.value.startswith("--")
                ]
                if not longopts:
                    continue  # positional / short-only: skip
                dest = longopts[0][2:].replace("-", "_")
            if dest not in attr_reads:
                out.append(Finding(
                    "CLI002", rel, node.lineno,
                    f"flag dest {dest!r} is parsed but never read in this "
                    f"module — dead flag (or the handler forgot to use it)",
                ))
    return out


# ---------------------------------------------------------------------------
# GRD001: pytest.raises(match=...) guards vs raise-message literals
#
# For each match= string literal we extract its LITERAL fragments (what is
# left after removing regex operators, escape-aware); every fragment of
# >= min_len (4) chars must appear in some string literal of the package
# tree or of the guard's own test file. Guards whose pattern yields NO
# checkable fragment (pure regex / only short literals) used to pass
# vacuously — they now must re.search-match at least one package (or
# local) string literal. Rewording a ValueError breaks the lookup and
# fails here — before the guard silently stops matching.
# ---------------------------------------------------------------------------

_REGEX_META = set(".^$*+?()[]{}|")


def regex_literal_fragments(pattern: str, min_len: int = 8) -> List[str]:
    frags: List[str] = []
    cur: List[str] = []
    i = 0
    while i < len(pattern):
        ch = pattern[i]
        if ch == "\\" and i + 1 < len(pattern):
            nxt = pattern[i + 1]
            if nxt.isalnum():  # \d, \s, \b ... a regex class, not a literal
                if cur:
                    frags.append("".join(cur))
                    cur = []
            else:
                cur.append(nxt)
            i += 2
            continue
        if ch in _REGEX_META:
            if cur:
                frags.append("".join(cur))
                cur = []
        else:
            cur.append(ch)
        i += 1
    if cur:
        frags.append("".join(cur))
    return [f for f in frags if len(f) >= min_len]


def _string_constants(tree: ast.AST) -> Iterable[str]:
    for node in ast.walk(tree):
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            yield node.value


def _iter_py(base: str) -> Iterable[str]:
    for dirpath, _, files in os.walk(base):
        for fn in sorted(files):
            if fn.endswith(".py"):
                yield os.path.join(dirpath, fn)


def check_guard_drift(
    package_root: str,
    tests_root: str,
    min_len: int = 4,
) -> List[Finding]:
    corpus: List[str] = []
    for path in _iter_py(package_root):
        with open(path) as f:
            corpus.extend(_string_constants(ast.parse(f.read(), filename=path)))
    blob = "\x00".join(corpus)

    out: List[Finding] = []
    for path in _iter_py(tests_root):
        with open(path) as f:
            tree = ast.parse(f.read(), filename=path)
        rel = os.path.relpath(path, os.path.dirname(tests_root)).replace(os.sep, "/")
        guards: List[Tuple[ast.Call, ast.Constant]] = []
        for node in ast.walk(tree):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "raises"):
                for kw in node.keywords:
                    if kw.arg == "match" and (
                        isinstance(kw.value, ast.Constant)
                        and isinstance(kw.value.value, str)
                    ):
                        guards.append((node, kw.value))
        # the guards' own match literals must not vouch for themselves
        pattern_nodes = {id(c) for _, c in guards}
        local_blob = "\x00".join(
            n.value for n in ast.walk(tree)
            if isinstance(n, ast.Constant) and isinstance(n.value, str)
            and id(n) not in pattern_nodes
        )
        local_strings = local_blob.split("\x00")
        for node, const in guards:
            frags = regex_literal_fragments(const.value, min_len)
            for frag in frags:
                if frag not in blob and frag not in local_blob:
                    out.append(Finding(
                        "GRD001", rel, node.lineno,
                        f"match fragment {frag!r} appears in no package "
                        f"(or local) string literal — the guarded "
                        f"message was likely reworded; update the guard "
                        f"or the message",
                    ))
            if frags:
                continue
            # pure-regex guard (no fragment long enough to pin): it must at
            # least MATCH something — otherwise it vouches for nothing
            try:
                pat = re.compile(const.value)
            except re.error:
                out.append(Finding(
                    "GRD001", rel, node.lineno,
                    f"match pattern {const.value!r} does not compile — the "
                    f"guard can never match",
                ))
                continue
            if not any(pat.search(s) for s in corpus) and not any(
                    pat.search(s) for s in local_strings):
                out.append(Finding(
                    "GRD001", rel, node.lineno,
                    f"pure-regex match pattern {const.value!r} matches no "
                    f"package (or local) string literal — previously this "
                    f"guard passed vacuously; update the pattern or the "
                    f"message",
                ))
    return out


# ---------------------------------------------------------------------------
# SER001: hand-rolled serializer drift
# ---------------------------------------------------------------------------

# files allowed to contain a hand-rolled JSON object template ('{"k":...')
SERIALIZER_SITES = frozenset({
    "hivedscheduler_tpu/runtime/utils.py",  # bind-info head fast path
})

_JSON_TEMPLATE_RE = re.compile(r'^\{"\w+":')


def check_serializer_drift(
    root: str,
    canonical_head_keys: Optional[List[str]] = None,
    serializer_sites: frozenset = SERIALIZER_SITES,
) -> List[Finding]:
    out: List[Finding] = []
    pkg = os.path.join(root, "hivedscheduler_tpu")

    # (a) no unregistered hand-rolled JSON templates anywhere in the package
    templates: Dict[str, List[Tuple[int, str]]] = {}
    for path in _iter_py(pkg):
        rel = os.path.relpath(path, root).replace(os.sep, "/")
        with open(path) as f:
            tree = ast.parse(f.read(), filename=path)
        for node in ast.walk(tree):
            if (isinstance(node, ast.Constant) and isinstance(node.value, str)
                    and _JSON_TEMPLATE_RE.match(node.value)):
                templates.setdefault(rel, []).append((node.lineno, node.value))
    for rel, sites in sorted(templates.items()):
        if rel not in serializer_sites:
            for line, _ in sites:
                out.append(Finding(
                    "SER001", rel, line,
                    "hand-rolled JSON object template outside the "
                    "registered serializer sites — use common.to_json over "
                    "to_dict(), or register the site WITH a key-drift check "
                    "and a pinning guard test",
                ))

    # (b) the bind-info head template stays key-exact with PodBindInfo.to_dict
    if canonical_head_keys is None:
        import sys

        sys.path.insert(0, root)
        try:
            from hivedscheduler_tpu.api.types import PodBindInfo
        finally:
            sys.path.pop(0)
        canonical_head_keys = list(
            PodBindInfo(node="n").to_dict(include_group=False))
    utils_rel = "hivedscheduler_tpu/runtime/utils.py"
    head_templates = templates.get(utils_rel, [])
    if not head_templates:
        out.append(Finding(
            "SER001", utils_rel, 1,
            "bind-info head template not found — if the fast path was "
            "removed, drop the site from SERIALIZER_SITES",
        ))
    for line, lit in head_templates:
        keys = re.findall(r'"(\w+)":', lit)
        if keys != canonical_head_keys:
            out.append(Finding(
                "SER001", utils_rel, line,
                f"hand-rolled head keys {keys} != PodBindInfo.to_dict("
                f"include_group=False) keys {canonical_head_keys} — the "
                f"fast path drifted from the canonical serializer",
            ))

    # (c) LoaderState keeps the canonical dataclasses round-trip
    data_path = os.path.join(pkg, "parallel", "data.py")
    if os.path.exists(data_path):
        with open(data_path) as f:
            tree = ast.parse(f.read(), filename=data_path)
        cls = next((n for n in ast.walk(tree)
                    if isinstance(n, ast.ClassDef) and n.name == "LoaderState"),
                   None)
        if cls is not None:
            def _method_calls(name: str) -> Set[str]:
                fn = next((m for m in cls.body
                           if isinstance(m, ast.FunctionDef) and m.name == name),
                          None)
                if fn is None:
                    return set()
                return {
                    n.func.attr for n in ast.walk(fn)
                    if isinstance(n, ast.Call)
                    and isinstance(n.func, ast.Attribute)
                }
            if "asdict" not in _method_calls("to_dict"):
                out.append(Finding(
                    "SER001", "hivedscheduler_tpu/parallel/data.py", cls.lineno,
                    "LoaderState.to_dict must stay dataclasses.asdict — a "
                    "hand-rolled field list here is exactly the drift the "
                    "checkpoint-resume contract forbids",
                ))
            if "fields" not in _method_calls("from_dict"):
                out.append(Finding(
                    "SER001", "hivedscheduler_tpu/parallel/data.py", cls.lineno,
                    "LoaderState.from_dict must validate against "
                    "dataclasses.fields — unknown-key rejection is the "
                    "resume-compat guard",
                ))
    return out


# ---------------------------------------------------------------------------
# MET001: metrics catalogue (tools/check_metrics.py folded in)
# ---------------------------------------------------------------------------

def check_metrics_catalogue(root: str,
                            package_root: Optional[str] = None) -> List[Finding]:
    import sys

    sys.path.insert(0, os.path.join(root, "tools"))
    try:
        import check_metrics
    finally:
        sys.path.pop(0)
    emitted, described, dynamic = check_metrics.collect(
        package_root or os.path.join(root, "hivedscheduler_tpu"))
    out: List[Finding] = []
    for name in sorted(set(emitted) - described):
        out.append(Finding(
            "MET001", emitted[name][0].split(":")[0],
            int(emitted[name][0].rsplit(":", 1)[1]),
            f"metric {name!r} emitted without a describe() entry",
        ))
    for name in sorted(described - set(emitted)):
        out.append(Finding(
            "MET001", "hivedscheduler_tpu", 1,
            f"metric {name!r} described but never emitted",
        ))
    for site in dynamic:
        file, line = site.split(":")[0], site.split(":")[1]
        out.append(Finding(
            "MET001", file, int(line),
            "metric emit with a non-literal name — use a string literal",
        ))
    return out


# ---------------------------------------------------------------------------
# OBS001: journal event-type / wait-bucket / request-leg schema registry
# (the check_metrics pattern applied to the gang-lifecycle flight recorder
# and — ISSUE 13 — the request flight recorder)
#
# Every `journal.emit("<type>", ...)` / `journal.note_phase(_, _, "<type>")`
# / `journal.note_wait(_, "<bucket>", ..., etype="<type>")` literal in the
# package must be a registered obs/journal.py SCHEMA (resp. WAIT_BUCKETS)
# row, every SCHEMA row must be emitted somewhere, and emit sites must use
# literals (a dynamic type name would dodge both directions). note_wait
# itself counts as an emitter of its default `queued` type; non-literal
# *buckets* are legal (the classify_wait() path) — the runtime validates
# those.
#
# The request flight recorder extends the same contract: every
# `journal.note_leg(_, "<leg>")` literal must be a REQUEST_LEGS row, legs
# must be literals (unlike wait buckets there is no classifier path), and
# every REQUEST_LEGS row must be emitted somewhere. The flight methods
# imply their event types (note_request_submit -> request_submit,
# note_leg -> request_leg, note_request_done -> request_done), exactly
# like note_wait implies `queued`.
# ---------------------------------------------------------------------------

_JOURNAL_RECEIVERS = {"journal", "obs_journal"}
_JOURNAL_METHODS = {"emit", "note_wait", "note_phase", "note_leg",
                    "note_request_submit", "note_request_done"}
# flight methods emit their event type internally; seeing a call site
# marks the implied SCHEMA row as emitted
_IMPLIED_EVENTS = {"note_leg": "request_leg",
                   "note_request_submit": "request_submit",
                   "note_request_done": "request_done"}


def check_journal_schema(
    root: str,
    package_root: Optional[str] = None,
    schema: Optional[Dict[str, str]] = None,
    buckets: Optional[Dict[str, str]] = None,
    legs: Optional[Dict[str, str]] = None,
) -> List[Finding]:
    if schema is None or buckets is None or legs is None:
        import sys

        sys.path.insert(0, root)
        try:
            from hivedscheduler_tpu.obs.journal import (
                REQUEST_LEGS,
                SCHEMA,
                WAIT_BUCKETS,
            )
        finally:
            sys.path.pop(0)
        schema = SCHEMA if schema is None else schema
        buckets = WAIT_BUCKETS if buckets is None else buckets
        # fixture scans (package_root given, legs not passed) skip the
        # legs-never-emitted direction — the pre-ISSUE-13 fixtures are
        # not leg emitters
        check_leg_coverage = legs is not None or package_root is None
        legs = REQUEST_LEGS if legs is None else legs
    else:
        check_leg_coverage = True
    pkg = package_root or os.path.join(root, "hivedscheduler_tpu")
    base = package_root and os.path.dirname(package_root) or root

    def _lit(expr) -> Optional[str]:
        if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
            return expr.value
        return None

    def _kw(node: ast.Call, name: str):
        return next((kw.value for kw in node.keywords if kw.arg == name),
                    None)

    emitted: Set[str] = set()
    emitted_legs: Set[str] = set()
    out: List[Finding] = []
    for path in _iter_py(pkg):
        rel = os.path.relpath(path, base).replace(os.sep, "/")
        with open(path) as f:
            tree = ast.parse(f.read(), filename=path)
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)):
                continue
            attr = node.func.attr
            recv = node.func.value
            recv_ok = (
                (isinstance(recv, ast.Name)
                 and recv.id in _JOURNAL_RECEIVERS)
                or (isinstance(recv, ast.Attribute)
                    and recv.attr == "JOURNAL")
            )
            if not recv_ok or attr not in _JOURNAL_METHODS:
                continue
            if attr in _IMPLIED_EVENTS:
                implied = _IMPLIED_EVENTS[attr]
                if implied not in schema:
                    out.append(Finding(
                        "OBS001", rel, node.lineno,
                        f"journal {attr}() implies event type {implied!r} "
                        f"which is not registered in obs/journal.py SCHEMA",
                    ))
                else:
                    emitted.add(implied)
                if attr == "note_leg":
                    leg_expr = (node.args[1] if len(node.args) > 1
                                else _kw(node, "leg"))
                    if leg_expr is None:
                        out.append(Finding(
                            "OBS001", rel, node.lineno,
                            "journal note_leg() call without a leg — pass "
                            "a string literal so the REQUEST_LEGS registry "
                            "stays machine-checkable",
                        ))
                        continue
                    leg_name = _lit(leg_expr)
                    if leg_name is None:
                        out.append(Finding(
                            "OBS001", rel, node.lineno,
                            "journal note_leg() with a non-literal leg — "
                            "use a string literal (there is no classifier "
                            "path for request legs)",
                        ))
                    elif leg_name not in legs:
                        out.append(Finding(
                            "OBS001", rel, node.lineno,
                            f"request leg {leg_name!r} is not registered "
                            f"in obs/journal.py REQUEST_LEGS",
                        ))
                    else:
                        emitted_legs.add(leg_name)
                continue
            etype_expr = None
            if attr == "emit":
                etype_expr = node.args[0] if node.args else _kw(node, "etype")
            elif attr == "note_phase":
                etype_expr = (node.args[2] if len(node.args) > 2
                              else _kw(node, "etype"))
            else:  # note_wait
                emitted.add("queued")  # the default etype
                etype_expr = _kw(node, "etype")
                bucket_expr = (node.args[1] if len(node.args) > 1
                               else _kw(node, "bucket"))
                b = _lit(bucket_expr) if bucket_expr is not None else None
                if bucket_expr is not None and b is not None \
                        and b not in buckets:
                    out.append(Finding(
                        "OBS001", rel, node.lineno,
                        f"wait bucket {b!r} is not registered in "
                        f"obs/journal.py WAIT_BUCKETS",
                    ))
                if etype_expr is None:
                    continue
            if etype_expr is None:
                out.append(Finding(
                    "OBS001", rel, node.lineno,
                    f"journal {attr}() call without an event type — pass a "
                    f"string literal so the schema registry stays "
                    f"machine-checkable",
                ))
                continue
            name = _lit(etype_expr)
            if name is None:
                out.append(Finding(
                    "OBS001", rel, node.lineno,
                    "journal emit with a non-literal event type — use a "
                    "string literal",
                ))
            elif name not in schema:
                out.append(Finding(
                    "OBS001", rel, node.lineno,
                    f"journal event type {name!r} emitted but not "
                    f"registered in obs/journal.py SCHEMA",
                ))
            else:
                emitted.add(name)
    for name in sorted(set(schema) - emitted):
        out.append(Finding(
            "OBS001", "hivedscheduler_tpu/obs/journal.py", 1,
            f"journal event type {name!r} registered in SCHEMA but never "
            f"emitted in the package — drop the row or wire the emitter",
        ))
    if check_leg_coverage:
        for name in sorted(set(legs) - emitted_legs):
            out.append(Finding(
                "OBS001", "hivedscheduler_tpu/obs/journal.py", 1,
                f"request leg {name!r} registered in REQUEST_LEGS but "
                f"never emitted in the package — drop the row or wire "
                f"the emitter",
            ))
    return out


# ---------------------------------------------------------------------------
# OBS002: capacity-ledger chip-state registry (the OBS001 pattern applied
# to obs/ledger.py CHIP_STATES — ISSUE 14)
#
# Every *literal* state passed to a ledger receiver's state-taking methods
# (`ledger.transition(node, idxs, "<state>")`, `register_node(...,
# state=...)`, `set_idle_diagnosis("<state>")`, `hint_flavor(_,
# "<state>")`) must be a registered CHIP_STATES row, and every CHIP_STATES
# row must be *produced* somewhere — either a literal at a call site or a
# literal inside obs/ledger.py itself outside the CHIP_STATES dict (the
# busy_state()/IDLE_STATE_FOR_BUCKET mapping paths), docstrings excluded.
# Non-literal states are legal (the mapping paths); the runtime raises on
# unregistered ones (CapacityLedger._check_state).
# ---------------------------------------------------------------------------

_LEDGER_RECEIVERS = {"ledger", "obs_ledger", "lg", "_ledger"}
# method -> positional index of the state arg (kw name is always "state")
_LEDGER_STATE_METHODS = {"transition": 2, "register_node": 3,
                         "set_idle_diagnosis": 0, "hint_flavor": 1}


def check_ledger_states(
    root: str,
    package_root: Optional[str] = None,
    states: Optional[Dict[str, str]] = None,
) -> List[Finding]:
    if states is None:
        import sys

        sys.path.insert(0, root)
        try:
            from hivedscheduler_tpu.obs.ledger import CHIP_STATES
        finally:
            sys.path.pop(0)
        states = CHIP_STATES
    pkg = package_root or os.path.join(root, "hivedscheduler_tpu")
    base = package_root and os.path.dirname(package_root) or root

    def _lit(expr) -> Optional[str]:
        if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
            return expr.value
        return None

    produced: Set[str] = set()
    out: List[Finding] = []
    ledger_rel = None
    for path in _iter_py(pkg):
        rel = os.path.relpath(path, base).replace(os.sep, "/")
        with open(path) as f:
            tree = ast.parse(f.read(), filename=path)
        if rel.endswith("obs/ledger.py"):
            # the registry module itself: every string literal outside the
            # CHIP_STATES dict and outside docstrings counts as a producer
            # (busy_state()'s returns, the IDLE_STATE_FOR_BUCKET mapping)
            ledger_rel = rel
            excluded: Set[int] = set()
            for node in ast.walk(tree):
                targets = []
                if isinstance(node, ast.Assign):
                    targets = node.targets
                elif isinstance(node, ast.AnnAssign):
                    targets = [node.target]
                if any(isinstance(t, ast.Name) and t.id == "CHIP_STATES"
                       for t in targets):
                    excluded |= {id(n) for n in ast.walk(node)}
                if isinstance(node, (ast.Module, ast.ClassDef,
                                     ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    body = node.body
                    if (body and isinstance(body[0], ast.Expr)
                            and isinstance(body[0].value, ast.Constant)):
                        excluded.add(id(body[0].value))
            for node in ast.walk(tree):
                if (isinstance(node, ast.Constant)
                        and isinstance(node.value, str)
                        and id(node) not in excluded
                        and node.value in states):
                    produced.add(node.value)
            continue
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)):
                continue
            attr = node.func.attr
            recv = node.func.value
            recv_ok = (
                (isinstance(recv, ast.Name)
                 and recv.id in _LEDGER_RECEIVERS)
                or (isinstance(recv, ast.Attribute)
                    and recv.attr == "LEDGER")
            )
            if not recv_ok or attr not in _LEDGER_STATE_METHODS:
                continue
            pos = _LEDGER_STATE_METHODS[attr]
            expr = (node.args[pos] if len(node.args) > pos
                    else next((kw.value for kw in node.keywords
                               if kw.arg == "state"), None))
            if expr is None:
                continue  # state defaulted (register_node) — idle_free
            name = _lit(expr)
            if name is None:
                continue  # mapping path: the runtime validates
            if name not in states:
                out.append(Finding(
                    "OBS002", rel, node.lineno,
                    f"chip state {name!r} is not registered in "
                    f"obs/ledger.py CHIP_STATES",
                ))
            else:
                produced.add(name)
    for name in sorted(set(states) - produced):
        out.append(Finding(
            "OBS002", ledger_rel or "hivedscheduler_tpu/obs/ledger.py", 1,
            f"chip state {name!r} registered in CHIP_STATES but never "
            f"produced in the package — drop the row or wire the "
            f"transition",
        ))
    return out


# ---------------------------------------------------------------------------
# OBS003: workload goodput step-phase registry (the OBS002 pattern applied
# to obs/goodput.py STEP_PHASES — ISSUE 16)
#
# Every *literal* phase passed to a goodput receiver's phase-taking methods
# (`goodput.phase("<phase>")`, `span("<phase>")`, `start(phase=...)`) must
# be a registered STEP_PHASES row, and every STEP_PHASES row must be
# *produced* somewhere — either a literal at a call site or a literal
# inside obs/goodput.py itself outside the STEP_PHASES dict (note_step's
# compile/rework/step_compute classification, start()'s "init" default),
# docstrings excluded. Non-literal phases are legal; the runtime raises on
# unregistered ones (GoodputLedger._check_phase).
# ---------------------------------------------------------------------------

_GOODPUT_RECEIVERS = {"goodput", "obs_goodput", "gp", "_goodput"}
# method -> positional index of the phase arg (kw name is always "phase")
_GOODPUT_PHASE_METHODS = {"phase": 0, "span": 0, "start": 0}


def check_goodput_phases(
    root: str,
    package_root: Optional[str] = None,
    phases: Optional[Dict[str, str]] = None,
) -> List[Finding]:
    if phases is None:
        import sys

        sys.path.insert(0, root)
        try:
            from hivedscheduler_tpu.obs.goodput import STEP_PHASES
        finally:
            sys.path.pop(0)
        phases = STEP_PHASES
    pkg = package_root or os.path.join(root, "hivedscheduler_tpu")
    base = package_root and os.path.dirname(package_root) or root

    def _lit(expr) -> Optional[str]:
        if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
            return expr.value
        return None

    produced: Set[str] = set()
    out: List[Finding] = []
    goodput_rel = None
    for path in _iter_py(pkg):
        rel = os.path.relpath(path, base).replace(os.sep, "/")
        with open(path) as f:
            tree = ast.parse(f.read(), filename=path)
        if rel.endswith("obs/goodput.py"):
            # the registry module itself: every string literal outside the
            # STEP_PHASES dict and outside docstrings counts as a producer
            # (note_step's classification branches, start()'s default) —
            # the dict's own keys cannot vouch for themselves
            goodput_rel = rel
            excluded: Set[int] = set()
            for node in ast.walk(tree):
                targets = []
                if isinstance(node, ast.Assign):
                    targets = node.targets
                elif isinstance(node, ast.AnnAssign):
                    targets = [node.target]
                if any(isinstance(t, ast.Name) and t.id == "STEP_PHASES"
                       for t in targets):
                    excluded |= {id(n) for n in ast.walk(node)}
                if isinstance(node, (ast.Module, ast.ClassDef,
                                     ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    body = node.body
                    if (body and isinstance(body[0], ast.Expr)
                            and isinstance(body[0].value, ast.Constant)):
                        excluded.add(id(body[0].value))
            for node in ast.walk(tree):
                if (isinstance(node, ast.Constant)
                        and isinstance(node.value, str)
                        and id(node) not in excluded
                        and node.value in phases):
                    produced.add(node.value)
            continue
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)):
                continue
            attr = node.func.attr
            recv = node.func.value
            recv_ok = (
                (isinstance(recv, ast.Name)
                 and recv.id in _GOODPUT_RECEIVERS)
                or (isinstance(recv, ast.Attribute)
                    and recv.attr == "GOODPUT")
            )
            if not recv_ok or attr not in _GOODPUT_PHASE_METHODS:
                continue
            pos = _GOODPUT_PHASE_METHODS[attr]
            expr = (node.args[pos] if len(node.args) > pos
                    else next((kw.value for kw in node.keywords
                               if kw.arg == "phase"), None))
            if expr is None:
                continue  # phase defaulted (start) — init
            name = _lit(expr)
            if name is None:
                continue  # computed phase: the runtime validates
            if name not in phases:
                out.append(Finding(
                    "OBS003", rel, node.lineno,
                    f"step phase {name!r} is not registered in "
                    f"obs/goodput.py STEP_PHASES",
                ))
            else:
                produced.add(name)
    for name in sorted(set(phases) - produced):
        out.append(Finding(
            "OBS003", goodput_rel or "hivedscheduler_tpu/obs/goodput.py", 1,
            f"step phase {name!r} registered in STEP_PHASES but never "
            f"produced in the package — drop the row or wire the "
            f"transition",
        ))
    return out


# ---------------------------------------------------------------------------
# entry
# ---------------------------------------------------------------------------

CLI_FILES = [
    "hivedscheduler_tpu/train.py",
    "hivedscheduler_tpu/serve.py",
    "hivedscheduler_tpu/generate.py",
    "hivedscheduler_tpu/eval.py",
    "hivedscheduler_tpu/cli.py",
]


def check(root: str) -> List[Finding]:
    fields = config_fields(
        os.path.join(root, "hivedscheduler_tpu", "models", "transformer.py"))
    out: List[Finding] = []
    out += check_cli_reachability(root, fields)
    out += check_dead_flags(root, CLI_FILES)
    out += check_guard_drift(
        os.path.join(root, "hivedscheduler_tpu"),
        os.path.join(root, "tests"))
    out += check_serializer_drift(root)
    out += check_metrics_catalogue(root)
    out += check_journal_schema(root)
    out += check_ledger_states(root)
    out += check_goodput_phases(root)
    return out
