import sys

from tools.hivedlint import main

if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
