"""hivedlint: project-specific static analysis for the tpu-hive tree.

Machine-checks the concurrency contract, the shard_map/collective
contract, the env-flag surface, and the CLAUDE.md "recurring blind spots"
that verify passes repeatedly caught by hand. One entry point::

    python -m tools.hivedlint                    # exit 1 on any finding
    python -m tools.hivedlint --rule SHD001      # run a rule subset
    python -m tools.hivedlint --rule SHD001 --explain   # per-rule doc
    python -m tools.hivedlint --json             # machine-readable output

Rule families (each rule has a seeded-violation fixture and the suite is
pinned clean on the real tree in tier-1):

- Concurrency (``concurrency.py``): LCK001/002 lock registry + thread
  spawn sites, CON001-004 scheduler/algorithm lock-path fixpoints —
  documented in ``doc/design/concurrency.md``.
- Shard contract (``shardlint.py``): SHD001-004 vma loop carries,
  shard_map-inside-manual-context, collective axis declaration, donated
  buffer reads; ENV001/002 the ``common/envflags.py`` registry —
  documented in ``doc/design/shard-contract.md``.
- Blind spots (``blindspots.py``): CLI001/002 config/flag reachability,
  GRD001 pytest.raises(match=) guard drift, SER001 serializer drift,
  MET001 metrics catalogue.

The runtime halves of the contract are the opt-in sanitizers:
``HIVED_LOCKCHECK=1`` (lock order, ``common/lockcheck.py``) and
``HIVED_COMPILE_GUARD=1`` (jit recompiles, ``common/compileguard.py``).
"""

from __future__ import annotations

import dataclasses
import json
import os
import sys
from typing import Dict, List, Optional, Sequence


@dataclasses.dataclass
class Finding:
    rule: str
    file: str
    line: int
    message: str

    def __str__(self) -> str:
        return f"{self.file}:{self.line}: [{self.rule}] {self.message}"

    def to_dict(self) -> Dict:
        return dataclasses.asdict(self)


# rule id -> (one-line doc, implementing module). --explain prints this;
# test_shardlint pins every implemented rule to a registry row.
RULES: Dict[str, tuple] = {
    "LCK001": ("every lock is created through lockcheck.make_lock/"
               "make_rlock with a literal name registered in LOCK_HIERARCHY "
               "from the file LOCK_SITES assigns it", "concurrency"),
    "LCK002": ("threading.Thread(...) only in the allowlisted spawn sites "
               "(lockcheck.THREAD_SITES)", "concurrency"),
    "CON001": ("every SchedulerAlgorithm mutator asserts the serialized "
               "contract and wraps its body in the algorithm lock",
               "concurrency"),
    "CON002": ("every HivedScheduler path from an entry point to an "
               "algorithm mutating call holds scheduler_lock; the defrag "
               "probe/planner entries (defrag.LOCKED_ENTRY_ATTRS) and the "
               "batched delta-apply entries (eventbatch.LOCKED_APPLY_ATTRS "
               "— drain consumes the watch-event backlog destructively) "
               "are traversed as mutating calls", "concurrency"),
    "CON003": ("no file outside runtime/scheduler.py calls a mutating "
               "method on a scheduler_algorithm attribute", "concurrency"),
    "CON004": ("the fake ApiServer never fires informer handlers while "
               "lexically holding its store leaf lock", "concurrency"),
    "DFG001": ("defrag-package algorithm mutations are confined to the "
               "transactional probe (defrag/probe.py); CON002 traverses "
               "the runtime executor's probe/planner entry points as "
               "mutating calls", "concurrency"),
    "SHD001": ("fresh arrays (jnp.zeros/ones/full/empty[_like]) flowing "
               "into a shard_map loop carry must pass through "
               "shard_utils.varying(...) — the vma blind spot",
               "shardlint"),
    "SHD002": ("call-graph fixpoint: no shard_map/_get_shard_map call is "
               "reachable from inside a manual (pipeline/shard_map) "
               "context; only _local bodies may be called there",
               "shardlint"),
    "SHD003": ("every literal collective axis name inside a shard_map "
               "body must be declared by the install's PartitionSpec "
               "literals (typo'd axes otherwise fail only at trace time)",
               "shardlint"),
    "SHD004": ("buffers named at a donate_argnums position must not be "
               "read after the donating call in the same statement "
               "sequence", "shardlint"),
    "ENV001": ("every HIVED_* token in the package is registered in "
               "common/envflags.py (the doc/design/flags.md source)",
               "shardlint"),
    "ENV002": ("every registered HIVED_* flag is actually read somewhere "
               "in the tree (package, tests, tools, root scripts)",
               "shardlint"),
    "CLI001": ("every TransformerConfig field is passed from args at each "
               "CLI construction site or allowlisted with a reason",
               "blindspots"),
    "CLI002": ("every add_argument dest is read somewhere in its CLI "
               "module", "blindspots"),
    "GRD001": ("pytest.raises(match=...) literal fragments (>=4 chars) "
               "still appear in package string literals; pure-regex "
               "guards must match some package literal", "blindspots"),
    "SER001": ("hand-rolled serializers stay key-exact with the canonical "
               "to_dict/dataclass fields; no unregistered JSON templates",
               "blindspots"),
    "MET001": ("every emitted metric is described, no dead describes, no "
               "dynamic metric names", "blindspots"),
    "OBS001": ("every journal event type emitted in the package is a "
               "registered obs/journal.py SCHEMA row and vice versa; "
               "literal wait buckets must be WAIT_BUCKETS rows; every "
               "note_leg() request leg is a REQUEST_LEGS row and vice "
               "versa; no dynamic event types or legs", "blindspots"),
    "OBS002": ("every literal chip state at a capacity-ledger call site "
               "is a registered obs/ledger.py CHIP_STATES row, and every "
               "registered state is produced somewhere (call-site "
               "literal or a ledger-module mapping); the runtime raises "
               "on unregistered states", "blindspots"),
    "OBS003": ("every literal step phase at a goodput-ledger call site "
               "is a registered obs/goodput.py STEP_PHASES row, and "
               "every registered phase is produced somewhere (call-site "
               "literal or a goodput-module classification branch); the "
               "runtime raises on unregistered phases", "blindspots"),
}


def repo_root() -> str:
    here = os.path.dirname(os.path.abspath(__file__))
    return os.path.dirname(os.path.dirname(here))


def run_all(root: str) -> List[Finding]:
    from tools.hivedlint import blindspots, concurrency, shardlint

    findings: List[Finding] = []
    findings += concurrency.check(root)
    findings += shardlint.check(root)
    findings += blindspots.check(root)
    return findings


def _parse_rules(values: Sequence[str]) -> List[str]:
    rules: List[str] = []
    for v in values:
        rules.extend(r.strip().upper() for r in v.split(",") if r.strip())
    unknown = [r for r in rules if r not in RULES]
    if unknown:
        raise SystemExit(
            f"hivedlint: unknown rule(s) {unknown}; known: "
            f"{', '.join(sorted(RULES))}"
        )
    return rules


def main(argv: Optional[Sequence[str]] = None) -> int:
    import argparse

    parser = argparse.ArgumentParser(
        prog="python -m tools.hivedlint",
        description="project-specific static analysis for the tpu-hive "
                    "tree (concurrency + shard/collective contract + "
                    "env flags + recurring blind spots)",
    )
    parser.add_argument(
        "--rule", action="append", default=[], metavar="ID[,ID...]",
        help="restrict output to these rule ids (repeatable, "
             "comma-separable); the full suite still runs")
    parser.add_argument(
        "--explain", action="store_true",
        help="print the selected rules' documentation and exit")
    parser.add_argument(
        "--json", action="store_true", dest="as_json",
        help="emit findings (or --explain docs) as JSON for tooling")
    args = parser.parse_args(argv)

    rules = _parse_rules(args.rule)
    selected = rules or sorted(RULES)

    if args.explain:
        if args.as_json:
            print(json.dumps(
                {r: {"doc": RULES[r][0], "module": RULES[r][1]}
                 for r in selected},
                indent=2))
        else:
            for r in selected:
                doc, module = RULES[r]
                print(f"{r}  (tools/hivedlint/{module}.py)\n    {doc}")
        return 0

    root = repo_root()
    findings = run_all(root)
    if rules:
        findings = [f for f in findings if f.rule in rules]
    if args.as_json:
        print(json.dumps(
            {"findings": [f.to_dict() for f in findings],
             "count": len(findings),
             "rules": selected},
            indent=2))
        return 1 if findings else 0
    for f in findings:
        print(f)
    if findings:
        print(f"hivedlint: {len(findings)} finding(s)")
        return 1
    print("hivedlint: OK")
    return 0
