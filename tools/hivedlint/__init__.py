"""hivedlint: project-specific static analysis for the tpu-hive tree.

Machine-checks the concurrency contract and the CLAUDE.md "recurring blind
spots" that verify passes repeatedly caught by hand. One entry point::

    python -m tools.hivedlint          # exit 1 on any finding

Rule catalogue (documented in doc/design/concurrency.md):

Concurrency (tools/hivedlint/concurrency.py):

- **LCK001 lock-registry** — every lock is created through
  ``common.lockcheck.make_lock/make_rlock`` with a literal name registered
  in ``LOCK_HIERARCHY``, from the file ``LOCK_SITES`` assigns it. Direct
  ``threading.Lock()``/``RLock()``/``Condition()``/``Semaphore()`` calls in
  the package are forbidden (the factory is what makes the runtime
  lock-order sanitizer, ``HIVED_LOCKCHECK=1``, cover the lock).
- **LCK002 thread-spawn** — ``threading.Thread(...)`` only in the
  allowlisted spawn sites (``lockcheck.THREAD_SITES``).
- **CON001 algorithm-mutator-lock** — every mutating entry point of the
  ``SchedulerAlgorithm`` contract implemented by ``HivedAlgorithm`` calls
  ``lockcheck.assert_serialized(self)`` and wraps its whole body in
  ``with self.algorithm_lock``.
- **CON002 scheduler-lock-path** — every path inside ``HivedScheduler``
  from an entry point (public routine, informer callback, thread target)
  to a ``scheduler_algorithm`` mutating call holds ``scheduler_lock``.
- **CON003 algorithm-bypass** — no file outside ``runtime/scheduler.py``
  calls a mutating method on a ``scheduler_algorithm`` attribute (the
  runtime is the single serialization chokepoint).
- **CON004 store-leaf-fire** — the fake ApiServer never invokes informer
  handlers while lexically holding its store (leaf) lock.

Blind spots (tools/hivedlint/blindspots.py):

- **CLI001 config-reachability** — every ``TransformerConfig`` field is
  either passed from ``args`` at each CLI's construction site or
  allowlisted with a reason (the twice-caught unreachable-feature bug).
- **CLI002 dead-flag** — every ``add_argument`` dest is read somewhere in
  its CLI module.
- **GRD001 guard-drift** — every ``pytest.raises(match=...)`` literal's
  long literal fragments still appear in some string literal of the
  package (or the test's own file): rewording a ``ValueError`` without
  updating its guard fails here instead of at 3 a.m.
- **SER001 serializer-drift** — the hand-rolled bind-info JSON head stays
  key-exact with ``PodBindInfo.to_dict``, ``LoaderState`` keeps its
  canonical ``dataclasses.asdict`` round-trip, and no NEW hand-rolled JSON
  object template appears outside the registered sites.
- **MET001 metrics-catalogue** — ``tools/check_metrics.py`` folded in:
  every emitted metric described, no dead describes, no dynamic names.

Each rule has a seeded-violation fixture in ``tests/test_hivedlint.py`` and
the suite is pinned clean on the real tree in tier-1.
"""

from __future__ import annotations

import dataclasses
import os
import sys
from typing import List


@dataclasses.dataclass
class Finding:
    rule: str
    file: str
    line: int
    message: str

    def __str__(self) -> str:
        return f"{self.file}:{self.line}: [{self.rule}] {self.message}"


def repo_root() -> str:
    here = os.path.dirname(os.path.abspath(__file__))
    return os.path.dirname(os.path.dirname(here))


def run_all(root: str) -> List[Finding]:
    from tools.hivedlint import blindspots, concurrency

    findings: List[Finding] = []
    findings += concurrency.check(root)
    findings += blindspots.check(root)
    return findings


def main(argv=None) -> int:
    root = repo_root()
    findings = run_all(root)
    for f in findings:
        print(f)
    if findings:
        print(f"hivedlint: {len(findings)} finding(s)")
        return 1
    print("hivedlint: OK")
    return 0
