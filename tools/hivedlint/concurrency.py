"""Concurrency-contract rules (LCK001/002, CON001-004).

All rules are AST-based and parameterized on paths/registries so the seeded
-violation fixtures in tests/test_hivedlint.py can drive them against tiny
synthetic trees; ``check(root)`` wires them to the real package and the
registry in ``hivedscheduler_tpu/common/lockcheck.py``.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, Iterable, List, Optional, Set, Tuple

from tools.hivedlint import Finding

_LOCK_FACTORIES = {"Lock", "RLock", "Condition", "Semaphore", "BoundedSemaphore"}
_MAKE_FUNCS = {"make_lock", "make_rlock"}


def _walk_py(package_root: str) -> Iterable[Tuple[str, ast.AST]]:
    base = os.path.dirname(package_root)
    for dirpath, _, files in os.walk(package_root):
        for fn in sorted(files):
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            rel = os.path.relpath(path, base).replace(os.sep, "/")
            with open(path) as f:
                yield rel, ast.parse(f.read(), filename=path)


def _is_threading_call(node: ast.Call, names: Set[str]) -> Optional[str]:
    func = node.func
    if (isinstance(func, ast.Attribute) and func.attr in names
            and isinstance(func.value, ast.Name)
            and func.value.id == "threading"):
        return func.attr
    return None


# ---------------------------------------------------------------------------
# LCK001 / LCK002: lock creation registry + thread-spawn allowlist
# ---------------------------------------------------------------------------

def check_lock_registry(
    package_root: str,
    hierarchy: Dict[str, int],
    sites: Dict[str, str],
    thread_sites: frozenset,
    factory_file: str = "hivedscheduler_tpu/common/lockcheck.py",
) -> List[Finding]:
    out: List[Finding] = []
    for rel, tree in _walk_py(package_root):
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            prim = _is_threading_call(node, _LOCK_FACTORIES)
            if prim is not None and rel != factory_file:
                out.append(Finding(
                    "LCK001", rel, node.lineno,
                    f"direct threading.{prim}() — create locks through "
                    f"common.lockcheck.make_lock/make_rlock with a name "
                    f"registered in LOCK_HIERARCHY",
                ))
                continue
            if _is_threading_call(node, {"Thread"}) is not None:
                if rel not in thread_sites:
                    out.append(Finding(
                        "LCK002", rel, node.lineno,
                        f"threading.Thread() outside the allowlisted spawn "
                        f"sites (lockcheck.THREAD_SITES) — register {rel} "
                        f"with a rationale or restructure",
                    ))
                continue
            func = node.func
            if (isinstance(func, ast.Attribute) and func.attr in _MAKE_FUNCS
                    and rel != factory_file):
                if not node.args or not (
                    isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)
                ):
                    out.append(Finding(
                        "LCK001", rel, node.lineno,
                        f"{func.attr}() with a non-literal lock name — the "
                        f"registry and sanitizer need a literal",
                    ))
                    continue
                name = node.args[0].value
                if name not in hierarchy:
                    out.append(Finding(
                        "LCK001", rel, node.lineno,
                        f"lock name {name!r} is not in lockcheck."
                        f"LOCK_HIERARCHY — add it with a level",
                    ))
                elif sites.get(name) != rel:
                    out.append(Finding(
                        "LCK001", rel, node.lineno,
                        f"lock {name!r} created in {rel} but LOCK_SITES "
                        f"registers it to {sites.get(name)!r}",
                    ))
    return out


# ---------------------------------------------------------------------------
# mutator discovery: the SchedulerAlgorithm contract
# ---------------------------------------------------------------------------

def contract_mutators(types_path: str) -> List[str]:
    """Mutating methods of the SchedulerAlgorithm interface = every method
    that is not an inspect getter (``get_*``) and not a dunder. A new method
    added to the contract is covered automatically."""
    with open(types_path) as f:
        tree = ast.parse(f.read(), filename=types_path)
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef) and node.name == "SchedulerAlgorithm":
            return [
                n.name for n in node.body
                if isinstance(n, ast.FunctionDef)
                and not n.name.startswith("get_")
                and not n.name.startswith("__")
            ]
    raise AssertionError(f"SchedulerAlgorithm not found in {types_path}")


# ---------------------------------------------------------------------------
# CON001: algorithm mutators assert the contract and hold their own lock
# ---------------------------------------------------------------------------

def _is_assert_serialized(stmt: ast.stmt) -> bool:
    return (isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Call)
            and isinstance(stmt.value.func, ast.Attribute)
            and stmt.value.func.attr == "assert_serialized")


def _is_docstring(stmt: ast.stmt) -> bool:
    return (isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Constant)
            and isinstance(stmt.value.value, str))


def _with_on(stmt: ast.stmt, attr: str) -> bool:
    return (isinstance(stmt, ast.With) and len(stmt.items) == 1
            and isinstance(stmt.items[0].context_expr, ast.Attribute)
            and stmt.items[0].context_expr.attr == attr)


def check_algorithm_mutators(
    hived_path: str,
    mutators: List[str],
    class_name: str = "HivedAlgorithm",
    rel: str = "hivedscheduler_tpu/algorithm/hived.py",
) -> List[Finding]:
    out: List[Finding] = []
    with open(hived_path) as f:
        tree = ast.parse(f.read(), filename=hived_path)
    cls = next((n for n in ast.walk(tree)
                if isinstance(n, ast.ClassDef) and n.name == class_name), None)
    if cls is None:
        return [Finding("CON001", rel, 1, f"class {class_name} not found")]
    methods = {n.name: n for n in cls.body if isinstance(n, ast.FunctionDef)}
    for name in mutators:
        fn = methods.get(name)
        if fn is None:
            out.append(Finding(
                "CON001", rel, cls.lineno,
                f"contract mutator {name}() not implemented on {class_name}",
            ))
            continue
        body = [s for s in fn.body if not _is_docstring(s)]
        if not body or not _is_assert_serialized(body[0]):
            out.append(Finding(
                "CON001", rel, fn.lineno,
                f"{name}() must start with lockcheck.assert_serialized(self) "
                f"(the single-threaded contract assertion)",
            ))
            continue
        rest = body[1:]
        if not rest:
            continue  # contract-only stub (no state touched)
        if len(rest) != 1 or not _with_on(rest[0], "algorithm_lock"):
            out.append(Finding(
                "CON001", rel, fn.lineno,
                f"{name}() body must be exactly `with self.algorithm_lock:` "
                f"after the contract assertion — statements outside the lock "
                f"mutate shared state unserialized",
            ))
    return out


# ---------------------------------------------------------------------------
# CON002: every path to a scheduler_algorithm mutating call holds the lock
# ---------------------------------------------------------------------------

class _MethodScan(ast.NodeVisitor):
    """Per-method scan: mutator call sites and intra-class call edges, each
    tagged with whether the site is lexically under `with self.<lock>`.

    ``extra_mutator_attrs`` names methods that mutate algorithm state
    through ANY receiver (the defrag probe/planner entry points —
    ``defrag.LOCKED_ENTRY_ATTRS``): a call to one of them counts as a
    mutator site for the lock-path fixpoint."""

    def __init__(self, mutators: Set[str], lock_attr: str,
                 extra_mutator_attrs: Optional[Set[str]] = None):
        self.mutators = mutators
        self.lock_attr = lock_attr
        self.extra_mutator_attrs = extra_mutator_attrs or set()
        self.depth = 0
        self.mutator_sites: List[Tuple[int, bool]] = []  # (line, guarded)
        self.calls: List[Tuple[str, bool]] = []          # (callee, guarded)
        self.thread_targets: List[str] = []

    def visit_With(self, node: ast.With) -> None:
        locked = any(
            isinstance(i.context_expr, ast.Attribute)
            and i.context_expr.attr == self.lock_attr
            for i in node.items
        )
        if locked:
            self.depth += 1
        self.generic_visit(node)
        if locked:
            self.depth -= 1

    def visit_Call(self, node: ast.Call) -> None:
        func = node.func
        if isinstance(func, ast.Attribute):
            recv = func.value
            if (isinstance(recv, ast.Attribute)
                    and recv.attr == "scheduler_algorithm"
                    and func.attr in self.mutators):
                self.mutator_sites.append((node.lineno, self.depth > 0))
            elif func.attr in self.extra_mutator_attrs:
                self.mutator_sites.append((node.lineno, self.depth > 0))
            elif (isinstance(recv, ast.Name) and recv.id == "self"):
                self.calls.append((func.attr, self.depth > 0))
            if _is_threading_call(node, {"Thread"}) is not None:
                for kw in node.keywords:
                    if (kw.arg == "target"
                            and isinstance(kw.value, ast.Attribute)
                            and isinstance(kw.value.value, ast.Name)
                            and kw.value.value.id == "self"):
                        self.thread_targets.append(kw.value.attr)
        self.generic_visit(node)


def check_scheduler_lock_paths(
    scheduler_path: str,
    mutators: List[str],
    class_name: str = "HivedScheduler",
    lock_attr: str = "scheduler_lock",
    rel: str = "hivedscheduler_tpu/runtime/scheduler.py",
    extra_mutator_attrs: Optional[Set[str]] = None,
) -> List[Finding]:
    out: List[Finding] = []
    with open(scheduler_path) as f:
        tree = ast.parse(f.read(), filename=scheduler_path)
    cls = next((n for n in ast.walk(tree)
                if isinstance(n, ast.ClassDef) and n.name == class_name), None)
    if cls is None:
        return [Finding("CON002", rel, 1, f"class {class_name} not found")]
    scans: Dict[str, _MethodScan] = {}
    handler_regs: Set[str] = set()
    for fn in (n for n in cls.body if isinstance(n, ast.FunctionDef)):
        scan = _MethodScan(set(mutators), lock_attr,
                           extra_mutator_attrs=extra_mutator_attrs)
        for stmt in fn.body:
            scan.visit(stmt)
        scans[fn.name] = scan
        # informer registrations: on_*_event(self._a, self._b, self._c)
        for node in ast.walk(fn):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr.startswith("on_")
                    and node.func.attr.endswith("_event")):
                for arg in node.args:
                    if (isinstance(arg, ast.Attribute)
                            and isinstance(arg.value, ast.Name)
                            and arg.value.id == "self"):
                        handler_regs.add(arg.attr)

    # roots: externally-invocable frames that start with no lock held
    roots = {m for m in scans if not m.startswith("_")}
    roots |= handler_regs
    for scan in scans.values():
        roots.update(t for t in scan.thread_targets if t in scans)
    roots &= set(scans)

    # BFS: which methods can be ENTERED with the lock not held?
    unlocked_entry: Set[str] = set(roots)
    frontier = list(roots)
    while frontier:
        m = frontier.pop()
        for callee, guarded in scans[m].calls:
            if not guarded and callee in scans and callee not in unlocked_entry:
                unlocked_entry.add(callee)
                frontier.append(callee)

    for name in sorted(unlocked_entry):
        for line, guarded in scans[name].mutator_sites:
            if not guarded:
                out.append(Finding(
                    "CON002", rel, line,
                    f"{class_name}.{name}() reaches a scheduler_algorithm "
                    f"mutating call without holding {lock_attr} on some "
                    f"path from an entry point",
                ))
    return out


# ---------------------------------------------------------------------------
# CON003: no algorithm-mutator calls bypassing the runtime chokepoint
# ---------------------------------------------------------------------------

def check_algorithm_bypass(
    package_root: str,
    mutators: List[str],
    chokepoint: str = "hivedscheduler_tpu/runtime/scheduler.py",
) -> List[Finding]:
    out: List[Finding] = []
    muts = set(mutators)
    for rel, tree in _walk_py(package_root):
        if rel == chokepoint:
            continue
        for node in ast.walk(tree):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in muts
                    and isinstance(node.func.value, ast.Attribute)
                    and node.func.value.attr == "scheduler_algorithm"):
                out.append(Finding(
                    "CON003", rel, node.lineno,
                    f".scheduler_algorithm.{node.func.attr}() outside the "
                    f"runtime chokepoint ({chokepoint}) bypasses the "
                    f"scheduler lock",
                ))
    return out


# ---------------------------------------------------------------------------
# DFG001: defrag cell-state mutation is confined to the probe module
# ---------------------------------------------------------------------------

def check_defrag_mutator_confinement(
    package_root: str,
    mutators: List[str],
    defrag_rel: str = "hivedscheduler_tpu/defrag",
    probe_rel: str = "hivedscheduler_tpu/defrag/probe.py",
) -> List[Finding]:
    """The defrag subsystem may mutate algorithm state ONLY through the
    transactional what-if probe (defrag/probe.py), whose every mutation is
    rolled back before returning; the runtime executor's real mutations
    live in runtime/scheduler.py under the scheduler lock (CON002
    traverses its entry points via ``defrag.LOCKED_ENTRY_ATTRS``). An
    algorithm-mutator call anywhere else in defrag/ is a lock-contract
    bypass waiting to happen."""
    out: List[Finding] = []
    muts = set(mutators)
    for rel, tree in _walk_py(package_root):
        if not rel.startswith(defrag_rel + "/") or rel == probe_rel:
            continue
        for node in ast.walk(tree):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in muts):
                out.append(Finding(
                    "DFG001", rel, node.lineno,
                    f".{node.func.attr}() (a SchedulerAlgorithm mutator) "
                    f"outside {probe_rel} — defrag mutations must go "
                    f"through the probe's rollback transaction or the "
                    f"runtime executor",
                ))
    return out


# ---------------------------------------------------------------------------
# CON004: fake ApiServer never fires handlers under the store leaf lock
# ---------------------------------------------------------------------------

class _LeafFireScan(ast.NodeVisitor):
    def __init__(self, lock_attr: str, fire_names: Set[str]):
        self.lock_attr = lock_attr
        self.fire_names = fire_names
        self.depth = 0
        self.violations: List[int] = []

    def visit_With(self, node: ast.With) -> None:
        locked = any(
            isinstance(i.context_expr, ast.Attribute)
            and i.context_expr.attr == self.lock_attr
            for i in node.items
        )
        if locked:
            self.depth += 1
        self.generic_visit(node)
        if locked:
            self.depth -= 1

    def visit_Call(self, node: ast.Call) -> None:
        func = node.func
        name = None
        if isinstance(func, ast.Attribute) and isinstance(func.value, ast.Name) \
                and func.value.id == "self":
            name = func.attr
        elif isinstance(func, ast.Name):
            name = func.id
        if name in self.fire_names and self.depth > 0:
            self.violations.append(node.lineno)
        self.generic_visit(node)


def check_store_leaf_fire(
    fake_path: str,
    lock_attr: str = "_lock",
    fire_names: Set[str] = frozenset({"_fire", "fire"}),
    rel: str = "hivedscheduler_tpu/k8s/fake.py",
) -> List[Finding]:
    with open(fake_path) as f:
        tree = ast.parse(f.read(), filename=fake_path)
    out: List[Finding] = []
    for fn in (n for n in ast.walk(tree) if isinstance(n, ast.FunctionDef)):
        if fn.name in fire_names:
            continue  # the chokepoint itself delegates to the handler
        scan = _LeafFireScan(lock_attr, set(fire_names))
        for stmt in fn.body:
            scan.visit(stmt)
        for line in scan.violations:
            out.append(Finding(
                "CON004", rel, line,
                f"handler fired while lexically holding the store leaf lock "
                f"({lock_attr}) in {fn.name}() — deliver through _emit, "
                f"which releases the lock first",
            ))
    return out


# ---------------------------------------------------------------------------
# entry
# ---------------------------------------------------------------------------

def check(root: str) -> List[Finding]:
    import sys

    sys.path.insert(0, root)
    try:
        from hivedscheduler_tpu.common import lockcheck
        from hivedscheduler_tpu import defrag as defrag_pkg
        from hivedscheduler_tpu.runtime import eventbatch
    finally:
        sys.path.pop(0)
    pkg = os.path.join(root, "hivedscheduler_tpu")
    mutators = contract_mutators(
        os.path.join(pkg, "runtime", "types.py"))
    out: List[Finding] = []
    out += check_lock_registry(
        pkg, lockcheck.LOCK_HIERARCHY, lockcheck.LOCK_SITES,
        lockcheck.THREAD_SITES)
    out += check_algorithm_mutators(
        os.path.join(pkg, "algorithm", "hived.py"), mutators)
    out += check_scheduler_lock_paths(
        os.path.join(pkg, "runtime", "scheduler.py"), mutators,
        extra_mutator_attrs=(set(defrag_pkg.LOCKED_ENTRY_ATTRS)
                             | set(eventbatch.LOCKED_APPLY_ATTRS)))
    out += check_algorithm_bypass(pkg, mutators)
    out += check_defrag_mutator_confinement(pkg, mutators)
    out += check_store_leaf_fire(os.path.join(pkg, "k8s", "fake.py"))
    return out
