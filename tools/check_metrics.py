#!/usr/bin/env python3
"""Static metric-name lint: every metric emitted anywhere in the package
must carry a ``describe()`` help entry, and every described name must be
emitted somewhere.

Run directly (``python tools/check_metrics.py``; exit 1 on violations) or
through its guard test (``tests/test_check_metrics.py``). The check is
AST-based: it finds ``<anything>.inc("name", ...)`` / ``.observe`` /
``.set_gauge`` calls whose first argument is a string literal, so renaming
a metric at an emit site without updating the catalogue (or vice versa)
fails CI instead of silently shipping an undocumented or dead series.

Emit sites with a NON-literal first argument are reported too: a computed
metric name can't be checked against the catalogue (and can't be grepped
by operators), so the package style forbids it.
"""

from __future__ import annotations

import ast
import os
import sys
from typing import Dict, List, Set, Tuple

_EMIT_METHODS = {"inc", "observe", "set_gauge"}


def _package_root() -> str:
    here = os.path.dirname(os.path.abspath(__file__))
    return os.path.join(os.path.dirname(here), "hivedscheduler_tpu")


def collect(package_root: str) -> Tuple[Dict[str, List[str]], Set[str], List[str]]:
    """Returns (emitted name -> [file:line sites], described names,
    non-literal emit sites)."""
    emitted: Dict[str, List[str]] = {}
    described: Set[str] = set()
    dynamic: List[str] = []
    for dirpath, _, files in os.walk(package_root):
        for fn in files:
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            rel = os.path.relpath(path, os.path.dirname(package_root))
            with open(path) as f:
                tree = ast.parse(f.read(), filename=path)
            for node in ast.walk(tree):
                if not isinstance(node, ast.Call):
                    continue
                func = node.func
                if not isinstance(func, ast.Attribute):
                    continue
                if func.attr == "describe" and node.args:
                    arg = node.args[0]
                    if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
                        described.add(arg.value)
                    continue
                if func.attr not in _EMIT_METHODS or not node.args:
                    continue
                arg = node.args[0]
                site = f"{rel}:{node.lineno}"
                if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
                    # only our namespace: .observe()/.inc() on unrelated
                    # objects (e.g. test doubles) shouldn't trip the lint
                    if arg.value.startswith("tpu_hive_"):
                        emitted.setdefault(arg.value, []).append(site)
                elif func.attr in ("inc", "set_gauge") or _looks_like_registry(func):
                    dynamic.append(f"{site}: {func.attr}() with non-literal name")
    return emitted, described, dynamic


def _looks_like_registry(func: ast.Attribute) -> bool:
    """``REGISTRY.observe`` / ``metrics.observe`` — ignore observe() on
    other receivers (it is a common method name)."""
    base = func.value
    return isinstance(base, ast.Name) and base.id.lower() in (
        "registry", "metrics", "_metrics",
    )


def main() -> int:
    emitted, described, dynamic = collect(_package_root())
    ok = True
    undescribed = sorted(set(emitted) - described)
    unused = sorted(described - set(emitted))
    for name in undescribed:
        ok = False
        sites = ", ".join(emitted[name])
        print(f"UNDESCRIBED metric {name!r} emitted at {sites} has no "
              f"REGISTRY.describe() help entry")
    for name in unused:
        ok = False
        print(f"UNUSED metric {name!r} is described but never emitted")
    for site in dynamic:
        ok = False
        print(f"DYNAMIC metric name at {site} — use a string literal")
    if ok:
        print(f"check_metrics: OK ({len(emitted)} emitted names, "
              f"{len(described)} described)")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
