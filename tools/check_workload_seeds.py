#!/usr/bin/env python3
"""Pinned workload-chaos seed replay: every seed whose kill/hang/restart
episode plan ever caught a workload-supervision bug becomes a permanent
regression test.

Mirrors ``tools/check_chaos_seeds.py``, but for the *workload* fault ladder
(``hivedscheduler_tpu/chaos/workload.py``): each seed deterministically
draws a plan of SIGKILL / SIGTERM / injected-hang episodes against a
CPU-only training subprocess sharing one checkpoint directory, then asserts
the per-fault exit contracts and that the merged loss trajectory is
bit-exact against an uninterrupted reference run.

Run directly (``python tools/check_workload_seeds.py``; exit 1 on any
violation) or through the guard test (``tests/test_workload_seeds.py``,
``slow``-marked: each seed spawns several jax subprocesses). Workflow when
a soak or this tool reports a violation:

1. reproduce: ``python tools/check_workload_seeds.py --seed <N>``
2. fix the supervisor/checkpoint/loader bug it exposed
3. append ``(N, EPISODES, "<what it caught>")`` to PINNED_SEEDS — the seed
   now replays on every CI run.

Subprocesses always use the CLAUDE.md CPU-only env recipe
(``chaos.workload.cpu_only_env``): nothing spawned here may ever hold the
single-grant TPU tunnel, because this tool kills its children for a living.
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import sys
import tempfile

# runnable as a plain script: the repo root (not tools/) holds the package
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# (seed, episodes, why-it-is-pinned)
PINNED_SEEDS = [
    # Initial coverage set (no violation ever found — they pin the baseline
    # fault ladder so the harness itself is regression-guarded; together the
    # two plans cover all three episode kinds):
    (0, 2, "baseline: SIGTERM checkpoint-and-exit + hard kill, "
           "bit-exact resume"),
    (15, 2, "baseline: hard kill + injected hang -> watchdog exit"),
]

# (seed, why-it-is-pinned) — the elastic ladder episode
# (chaos.workload.ElasticWorkloadHarness): kill -9 on the full slice ->
# shrink resume on half the devices (cross-topology restore) -> grow
# promote back, merged trajectory allclose vs an uninterrupted full-slice
# reference. Same pin-the-seed policy as PINNED_SEEDS.
ELASTIC_PINNED_SEEDS = [
    (3, "elastic baseline: kill@3 -> shrink resume -> SIGTERM grow "
        "offer@6 -> full-slice completion"),
]


def replay(seed: int, episodes: int = 2, workdir: str | None = None) -> dict:
    from hivedscheduler_tpu.chaos.workload import (
        WorkloadChaosHarness,
        WorkloadFaultPlan,
    )

    def _run(d: str) -> dict:
        harness = WorkloadChaosHarness(
            seed=seed, workdir=d, plan=WorkloadFaultPlan(episodes=episodes))
        return harness.run()

    if workdir is not None:
        return _run(workdir)
    with tempfile.TemporaryDirectory(prefix="hived-workload-chaos-") as d:
        return _run(d)


def replay_elastic(seed: int, workdir: str | None = None) -> dict:
    from hivedscheduler_tpu.chaos.workload import ElasticWorkloadHarness

    def _run(d: str) -> dict:
        # bridge_ledger: the pinned elastic replay also reconciles the
        # workload's goodput accounting against the scheduler-side
        # busy_guaranteed interval (doc/design/observability.md)
        return ElasticWorkloadHarness(seed=seed, workdir=d,
                                      bridge_ledger=True).run()

    if workdir is not None:
        return _run(workdir)
    with tempfile.TemporaryDirectory(prefix="hived-elastic-chaos-") as d:
        return _run(d)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--seed", type=int, default=None,
                        help="replay ONE seed (debugging) instead of the "
                             "pinned set")
    parser.add_argument("--episodes", type=int, default=2)
    parser.add_argument("--elastic", action="store_true",
                        help="with --seed: replay the ELASTIC ladder "
                             "episode (kill -> shrink resume -> grow "
                             "promote) instead of the fault-ladder plan")
    args = parser.parse_args(argv)
    logging.disable(logging.CRITICAL)

    if args.seed is not None:
        targets = [] if args.elastic else [(args.seed, args.episodes,
                                            "ad hoc")]
        elastic_targets = [(args.seed, "ad hoc")] if args.elastic else []
    else:
        targets = PINNED_SEEDS
        elastic_targets = ELASTIC_PINNED_SEEDS
    ok = True
    for seed, episodes, why in targets:
        report = replay(seed, episodes)
        if report["violations"]:
            ok = False
            print(f"SEED {seed} ({why}): {len(report['violations'])} "
                  f"violation(s):")
            for v in report["violations"]:
                print(f"  {v}")
        else:
            gp = report["goodput"]
            print(f"seed {seed} [{episodes} episode(s)] OK — "
                  f"episodes {json.dumps(report['episodes'])}, "
                  f"{report['incarnations']} incarnations, "
                  f"{report['steps']} steps bit-exact, goodput "
                  f"{gp['goodput_fraction']:.2f} "
                  f"({gp['rework_steps']} rework step(s))")
    for seed, why in elastic_targets:
        report = replay_elastic(seed)
        if report["violations"]:
            ok = False
            print(f"ELASTIC SEED {seed} ({why}): "
                  f"{len(report['violations'])} violation(s):")
            for v in report["violations"]:
                print(f"  {v}")
        else:
            bridge = report["goodput"].get("bridge") or {}
            print(f"elastic seed {seed} OK — kill@{report['kill_step']}, "
                  f"grow offer@{report['preempt_step']}, "
                  f"{report['incarnations']} incarnations, "
                  f"{report['steps']} steps allclose, goodput "
                  f"{report['goodput']['goodput_fraction']:.2f}, bridge "
                  f"uncovered {bridge.get('uncovered_s', 0.0):.1f}s")
    total = len(targets) + len(elastic_targets)
    if ok:
        print(f"check_workload_seeds: OK ({total} seed(s) clean)")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
