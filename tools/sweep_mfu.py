"""One-process MFU/decode tuning sweep on the live TPU.

Runs a list of flagship-config variants (remat policy, flash tile sizes,
batch/grad-accum, decode) sequentially inside a SINGLE process — one tunnel
acquisition, one backend — printing one JSON line per config. Used to pick
the defaults shipped in bench_model.py; kept in tools/ so the tuning is
reproducible on future chip generations.

Usage (axon TPU env):  python tools/sweep_mfu.py [--iters 3]
"""

from __future__ import annotations

import argparse
import gc
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import bench_model as bm  # noqa: E402


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--iters", type=int, default=3)
    ap.add_argument("--only", default="", help="comma list of tags to run")
    args = ap.parse_args()

    jax, devices = bm.acquire_backend(
        float(os.environ.get("HIVED_TPU_ACQUIRE_TIMEOUT_S", "600"))
    )
    from hivedscheduler_tpu.models import transformer as tm
    from hivedscheduler_tpu.parallel import topology

    dev = devices[0]
    peak_flops, peak_bw = bm.chip_peaks(dev)
    print(json.dumps({"device": getattr(dev, "device_kind", str(dev)),
                      "backend": jax.default_backend(),
                      "peak_tflops": peak_flops and peak_flops / 1e12}),
          flush=True)
    axes = topology.MeshAxes()
    mesh = topology.make_mesh(axes, jax.devices()[:1])

    base = dict(vocab_size=32768, d_model=2048, n_heads=16, n_kv_heads=8,
                n_layers=6, d_ff=8192, max_seq_len=2048, attn_impl="flash")
    seq = 2048

    def run_train(tag, batch=8, grad_accum=1, **kw):
        cfg = tm.TransformerConfig(**{**base, **kw})
        try:
            t0 = time.time()
            step_s, loss = bm.bench_train(cfg, batch, seq, args.iters, mesh,
                                          grad_accum=grad_accum)
            flops = bm.train_flops_per_step(cfg, batch, seq)
            rec = {
                "tag": tag,
                "step_ms": round(step_s * 1e3, 1),
                "mfu_pct": round(100.0 * flops / step_s / peak_flops, 2)
                if peak_flops else None,
                "tok_per_s": round(batch * seq / step_s),
                "compile_s": round(time.time() - t0 - args.iters * step_s, 1),
                "loss_ok": float(loss) == float(loss),
            }
        except Exception as e:
            rec = {"tag": tag, "error": f"{type(e).__name__}: {str(e)[:300]}"}
        print(json.dumps(rec), flush=True)
        gc.collect()

    def run_decode(tag, dec_batch=16, prompt=128, new=64, decode_steps=1):
        cfg = tm.TransformerConfig(**base)
        try:
            params = bm.serving_params(cfg)
            dec_s = bm.bench_decode(cfg, params, dec_batch, prompt, new,
                                    max(1, args.iters // 2),
                                    decode_steps=decode_steps)
            param_bytes = 2.0 * bm.param_count(cfg)
            rec = {
                "tag": tag,
                "decode_tok_per_s": round(dec_batch * new / dec_s, 1),
                "hbm_frac": round((new * param_bytes / dec_s) / peak_bw, 3)
                if peak_bw else None,
            }
        except Exception as e:
            rec = {"tag": tag, "error": f"{type(e).__name__}: {str(e)[:300]}"}
        print(json.dumps(rec), flush=True)
        gc.collect()

    experiments = [
        # most-load-bearing first: if the tunnel dies mid-sweep we still
        # have the shipped default's number
        ("remat_dots", lambda: run_train("remat_dots", remat="dots")),
        ("decode_bf16_first", lambda: run_decode("decode_bf16_first")),
        ("remat_none", lambda: run_train("remat_none", remat="none")),
        ("remat_full", lambda: run_train("remat_full", remat="full")),
        ("none_accum2", lambda: run_train("none_accum2", remat="none",
                                          grad_accum=2)),
        ("dots_b256k256", lambda: run_train("dots_b256k256", remat="dots",
                                            attn_block_q=256,
                                            attn_block_k=256)),
        ("dots_b256k512", lambda: run_train("dots_b256k512", remat="dots",
                                            attn_block_q=256,
                                            attn_block_k=512)),
        ("dots_b512k512", lambda: run_train("dots_b512k512", remat="dots",
                                            attn_block_q=512,
                                            attn_block_k=512)),
        ("dots_b16", lambda: run_train("dots_b16", remat="dots", batch=16)),
        ("decode_b32", lambda: run_decode("decode_b32", dec_batch=32)),
        # decode-loop unroll (scan unroll=K; exact): does software-
        # pipelining consecutive token steps move the HBM roofline frac?
        ("decode_unroll4", lambda: run_decode("decode_unroll4",
                                              decode_steps=4)),
        ("decode_unroll8", lambda: run_decode("decode_unroll8",
                                              decode_steps=8)),
    ]
    only = {t for t in args.only.split(",") if t}
    for tag, fn in experiments:
        if only and tag not in only:
            continue
        fn()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
