#!/bin/bash
# Runtime launcher — the contract analogue of the reference's
# bin/hivedscheduler/start.sh (exec the scheduler from the install dir,
# passing CLI args through). The config file comes from either an explicit
# --config argument or the CONFIG env var (api/constants.py ENV_CONFIG_FILE),
# which the deployment manifests set; the reference wires the same path via
# its ConfigMap mount.

set -o errexit
set -o nounset
set -o pipefail

BASH_DIR=$(cd "$(dirname "${BASH_SOURCE[0]}")" && pwd)

cd "${BASH_DIR}/.."

exec python -m hivedscheduler_tpu.cli "$@"
