"""Profile one measured 256-chip gang decision from bench.py's scenario.

Usage: python profile_bench.py [--deletes] [--sort tottime] [--rows 40]
Not part of the shipped package; a dev tool for finding scheduling fat.
"""

import cProfile
import pstats
import sys

import bench


def main():
    rows = 40
    sort = "cumtime"
    if "--sort" in sys.argv:
        sort = sys.argv[sys.argv.index("--sort") + 1]
    if "--rows" in sys.argv:
        rows = int(sys.argv[sys.argv.index("--rows") + 1])
    deletes = "--deletes" in sys.argv

    cluster = bench.Cluster()
    # warm-up: one full gang, freed again
    cluster.schedule_gang("vc-a", 10, "warm", 64, 4, allow_preempt=True)
    cluster.free_gang("warm")

    pr = cProfile.Profile()
    if deletes:
        for i in range(8):
            cluster.schedule_gang("vc-a", 10, f"g{i}", 64, 4, allow_preempt=True)
            pr.enable()
            cluster.free_gang(f"g{i}")
            pr.disable()
    else:
        pr.enable()
        for i in range(8):
            cluster.schedule_gang("vc-a", 10, f"g{i}", 64, 4, allow_preempt=True)
            pr.disable()
            cluster.free_gang(f"g{i}")
            pr.enable()
        pr.disable()
    stats = pstats.Stats(pr)
    stats.sort_stats(sort).print_stats(rows)


if __name__ == "__main__":
    main()
