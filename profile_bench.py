"""Profile one measured gang decision from bench.py's scenarios.

Usage: python profile_bench.py [--scale4096] [--deletes] [--sort tottime]
                               [--rows 40]

Default: the 256-chip gang on the v5p-1024 cluster (the headline metric).
``--scale4096``: the 1024-chip gang (256 pods x 4) on the 16x16x16 cluster —
the ``scale4096_p50_ms`` scale point, so regressions there are profilable
too. ``--deletes`` profiles the release path instead of schedule+add.
Not part of the shipped package; a dev tool for finding scheduling fat.
"""

import cProfile
import pstats
import sys

import bench


def _profile_1024(pr, deletes):
    cluster = bench.Cluster()
    # warm-up: one full gang, freed again
    cluster.schedule_gang("vc-a", 10, "warm", 64, 4, allow_preempt=True)
    cluster.free_gang("warm")
    if deletes:
        for i in range(8):
            cluster.schedule_gang("vc-a", 10, f"g{i}", 64, 4, allow_preempt=True)
            pr.enable()
            cluster.free_gang(f"g{i}")
            pr.disable()
    else:
        pr.enable()
        for i in range(8):
            cluster.schedule_gang("vc-a", 10, f"g{i}", 64, 4, allow_preempt=True)
            pr.disable()
            cluster.free_gang(f"g{i}")
            pr.enable()
        pr.disable()


def _profile_4096(pr, deletes):
    """The scale4096 point: reuse run_scale_4096's exact cluster by
    profiling around it — the function owns setup + trials, so the profile
    includes both; setup shows up under HivedAlgorithm.__init__ and is easy
    to discount (it runs once)."""
    if deletes:
        print("--deletes is only wired for the 1024 scenario", file=sys.stderr)
    pr.enable()
    bench.run_scale_4096()
    pr.disable()


def main():
    rows = 40
    sort = "cumtime"
    if "--sort" in sys.argv:
        sort = sys.argv[sys.argv.index("--sort") + 1]
    if "--rows" in sys.argv:
        rows = int(sys.argv[sys.argv.index("--rows") + 1])
    deletes = "--deletes" in sys.argv

    pr = cProfile.Profile()
    if "--scale4096" in sys.argv:
        _profile_4096(pr, deletes)
    else:
        _profile_1024(pr, deletes)
    stats = pstats.Stats(pr)
    stats.sort_stats(sort).print_stats(rows)


if __name__ == "__main__":
    main()
