"""Profile one measured gang decision from bench.py's scenarios.

Usage: python profile_bench.py [--scale4096 | --scale16384] [--deletes]
                               [--sort tottime] [--rows 40]

Default: the 256-chip gang on the v5p-1024 cluster (the headline metric).
``--scale4096``: the 1024-chip gang (256 pods x 4) on the 16x16x16 cluster
(the ``scale4096_p50_ms`` point). ``--scale16384``: the 4096-chip gang
(1024 pods x 4) on the 16x32x32 / 4096-host cluster (the
``scale16384_p50_ms`` point), for finding the remaining fat at the
production-fleet scale. ``--deletes`` profiles the release path instead of
schedule+add — wired for every scenario. Cluster setup runs OUTSIDE the
profiler in the scale scenarios (it runs once; the decision loop is the
regression surface). Not part of the shipped package; a dev tool.
"""

import cProfile
import pstats
import sys

import bench


def _profile_1024(pr, deletes):
    cluster = bench.Cluster()
    # warm-up: one full gang, freed again
    cluster.schedule_gang("vc-a", 10, "warm", 64, 4, allow_preempt=True)
    cluster.free_gang("warm")
    if deletes:
        for i in range(8):
            cluster.schedule_gang("vc-a", 10, f"g{i}", 64, 4, allow_preempt=True)
            pr.enable()
            cluster.free_gang(f"g{i}")
            pr.disable()
    else:
        pr.enable()
        for i in range(8):
            cluster.schedule_gang("vc-a", 10, f"g{i}", 64, 4, allow_preempt=True)
            pr.disable()
            cluster.free_gang(f"g{i}")
            pr.enable()
        pr.disable()


def _profile_scale(pr, n_chips, deletes):
    """The scale4096/scale16384 points: setup outside the profiler, then
    the exact schedule+allocate (or release) loop `_run_scale` times."""
    from hivedscheduler_tpu.runtime.types import FILTERING_PHASE
    from hivedscheduler_tpu.runtime.utils import new_binding_pod

    gang_pods = {4096: 256, 16384: 1024}[n_chips]
    trials = {4096: 4, 16384: 2}[n_chips]
    algo, nodes = bench.build_scale_algo(n_chips)
    for trial in range(trials):
        pods = []
        if not deletes:
            pr.enable()
        for i in range(gang_pods):
            p = bench.make_pod(f"g{trial}-{i}", "vc-a", 10, f"g{trial}",
                               gang_pods, 4)
            r = algo.schedule(p, nodes, FILTERING_PHASE)
            assert r.pod_bind_info is not None, r.pod_wait_info
            bp = new_binding_pod(p, r.pod_bind_info)
            algo.add_allocated_pod(bp)
            pods.append(bp)
        if not deletes:
            pr.disable()
        if deletes:
            pr.enable()
        for bp in pods:
            algo.delete_allocated_pod(bp)
        if deletes:
            pr.disable()


def main():
    rows = 40
    sort = "cumtime"
    if "--sort" in sys.argv:
        sort = sys.argv[sys.argv.index("--sort") + 1]
    if "--rows" in sys.argv:
        rows = int(sys.argv[sys.argv.index("--rows") + 1])
    deletes = "--deletes" in sys.argv

    pr = cProfile.Profile()
    if "--scale16384" in sys.argv:
        _profile_scale(pr, 16384, deletes)
    elif "--scale4096" in sys.argv:
        _profile_scale(pr, 4096, deletes)
    else:
        _profile_1024(pr, deletes)
    stats = pstats.Stats(pr)
    stats.sort_stats(sort).print_stats(rows)


if __name__ == "__main__":
    main()
