"""Workload goodput ledger: step-phase badput attribution with a
wallclock conservation invariant.

PR 14's capacity ledger answers "where did every chip-second go?" from
the *cluster's* side, but a chip counted ``busy_guaranteed`` may really
be recompiling, restoring a checkpoint, or re-doing steps lost to a
kill. This module is the *workload* half: a per-process
:class:`GoodputLedger` where at any instant the process is in **exactly
one** phase from the :data:`STEP_PHASES` registry, transitions close
intervals into per-phase second accumulators, and the **conservation
invariant** — the workload analogue of the journal's legs-sum-to-TTFT
and the capacity ledger's buckets-sum-to-chips×wallclock — holds by
construction::

    sum over STEP_PHASES seconds  ==  process wallclock since start()

``chaos.invariants.check_goodput`` asserts it in-process,
:func:`check_spool` asserts it per incarnation after every workload
chaos soak (including the kill -9 / elastic shrink-grow pinned seeds),
and the bench's goodput stage asserts it in the driver artifact — so
"goodput fraction" is a machine-checked total, not a dashboard estimate.

Phase taxonomy (the registry is the single source of truth; hivedlint
OBS003 cross-checks every literal call site against it, both
directions, and the runtime raises on unregistered phases):

- ``step_compute`` — the one *goodput* phase: forward/backward/optimizer
  work on a step that advances the run past its previous high-water
  mark. Everything else is badput, attributed by cause:
- ``rework`` — re-training steps between a resume point and the
  previously-reached max step. Classified exactly: the resume point is
  the committed ``LoaderState`` position (the checkpoint the incarnation
  restored), the high-water mark is replayed from the shared spool's
  per-step records (or carried in-process across a divergence rollback),
  so a step is rework iff ``step <= max_step_ever_completed``.
- ``init`` / ``compile`` — process bring-up (imports, mesh/model
  construction) and first-step XLA compilation (train.py's compile
  detection — the same first-step boundary the watchdog's second
  heartbeat keys off).
- ``data_wait`` — the step loop blocked on the prefetch consumer
  (``data.CheckpointableBatches`` / ``next(batches)``).
- ``checkpoint_save`` / ``checkpoint_restore`` — ``checkpoint.save`` /
  ``restore`` (including the supervisor's SIGTERM checkpoint-and-exit
  path and rollback/elastic cross-topology restores).
- ``eval`` — held-out evaluation windows.
- ``drain`` — a ServingEngine finishing admitted work while refusing
  new (elastic preemption handshake).
- ``idle`` — enabled but no work (post-training wrap-up, a serving
  loop with no admitted requests).

Feeding: ``train.py``'s step loop (data_wait/compile/step_compute/
rework + rollback), ``parallel/checkpoint.py`` save/restore (so eval/
generate/serve inherit restore attribution free), ``eval.py`` windows,
``serve.py``'s engine loop and the ``ServingEngine`` drain handshake.
The capacity-ledger BRIDGE: each incarnation's spool records its
wallclock span; the chaos/bench episode's scheduler-side
``busy_guaranteed`` interval for the same gang must cover the union of
workload-observed spans (the gap is interpreter startup + teardown and
must stay bounded) — ``reconcile_busy`` computes it.

Served as ``tpu_hive_goodput_seconds_total{phase=}`` counters, a
``--goodput-file`` JSONL spool on train/eval/generate/serve (one record
per transition, flushed per line so kill -9 incarnations keep their
closed intervals), and a ``workload goodput`` Perfetto lane merged into
every ``trace.to_chrome_trace()`` export.

Contracts (the PR 1/11/13/14 obs rules):

- **Zero overhead when disabled** (the default): every module-level
  wrapper gates on one attribute load (``GOODPUT.enabled``) and
  returns before touching the lock.
- **Bounded**: the Perfetto lane is capped; accumulators are keyed by
  the finite phase space.
- **Thread-safe leaf**: ``goodput_lock`` sits with the observability
  leaves in the lock hierarchy — closing an interval observes the
  phase-seconds counter while holding it, and nothing else is ever
  acquired under it.

Enable programmatically (``goodput.enable(spool_path=...)``), via the
CLIs' ``--goodput-file``, or ``HIVED_GOODPUT=1`` in the environment.
"""

from __future__ import annotations

import atexit
import json
import os
import time
from typing import Any, Dict, IO, List, Optional, Tuple

from hivedscheduler_tpu.common import envflags, lockcheck
from hivedscheduler_tpu.obs import journal as _journal

# ---------------------------------------------------------------------------
# step-phase taxonomy. At any instant the workload process is in exactly
# ONE of these; transitions close intervals, and the per-phase seconds
# sum to the process wallclock (the conservation invariant). hivedlint
# OBS003 cross-checks literal call sites against this table, both
# directions; the runtime raises on unregistered phases.
# ---------------------------------------------------------------------------
STEP_PHASES: Dict[str, str] = {
    "init": "process bring-up: imports, mesh/model construction, "
            "supervisor wiring — everything before the first phase "
            "transition",
    "compile": "first-step XLA compilation (train.py's compile "
               "detection; the watchdog keys off the second heartbeat "
               "for the same reason)",
    "step_compute": "forward/backward/optimizer work advancing the run "
                    "past its previous high-water mark — the ONE "
                    "goodput phase; everything else is badput",
    "data_wait": "the step loop blocked on the prefetch consumer "
                 "(next(batches) on data.CheckpointableBatches)",
    "checkpoint_save": "checkpoint.save (periodic commits and the "
                       "supervisor's SIGTERM checkpoint-and-exit path)",
    "checkpoint_restore": "checkpoint.restore/restore_params (resume, "
                          "divergence rollback, elastic cross-topology "
                          "restore, serving weight loads)",
    "rework": "re-training steps between a resume point (the committed "
              "LoaderState position) and the previously-reached max "
              "step — work paid for twice",
    "eval": "held-out evaluation windows (eval.py)",
    "drain": "a ServingEngine finishing admitted work while refusing "
             "new (elastic preemption handshake)",
    "idle": "enabled but no work in flight (post-loop wrap-up, an "
            "empty serving loop)",
}

# the one phase that counts toward goodput_fraction's numerator
GOODPUT_PHASES = ("step_compute",)

_MAX_LANE_SPANS = 2048
# Perfetto tid for the phase lane; journal gang lanes start at 1000,
# capacity-ledger node lanes at 20000.
_LANE_TID = 30000


class GoodputLedger:
    """Per-process phase state machine + phase-second accumulators.

    Instantiable for tests; the module singleton :data:`GOODPUT` is what
    the live stack shares. ``metrics`` gates counter emission so a test
    instance never pollutes the process registry.
    """

    def __init__(self, metrics: bool = True):
        self._lock = lockcheck.make_lock("goodput_lock", late=True)
        self.enabled = False
        self.metrics = metrics
        self._t0: Optional[float] = None
        self._phase: Optional[str] = None
        self._since: float = 0.0
        self._acc: Dict[str, float] = {}
        self._lane: List[Tuple[str, float, float]] = []
        self._steps = 0
        self._rework_steps = 0
        self._max_step = 0  # high-water mark: largest step ever completed
        self._spool: Optional[IO[str]] = None
        self._spool_path = ""
        self._closed = False

    # -- internals --------------------------------------------------------
    @staticmethod
    def _now(at: Optional[float]) -> float:
        return time.perf_counter() if at is None else at

    @staticmethod
    def _check_phase(phase: str) -> None:
        if phase not in STEP_PHASES:
            raise ValueError(
                f"{phase!r} is not a registered step phase — add it to "
                f"obs/goodput.py STEP_PHASES (OBS003)")

    def _emit(self, rec: Dict[str, Any]) -> None:
        """Append one JSONL record (caller holds the lock). Flushed per
        line so a kill -9 incarnation keeps every closed interval; a
        dead spool must never fail a transition."""
        spool = self._spool
        if spool is None:
            return
        try:
            spool.write(json.dumps(rec) + "\n")
            spool.flush()
        except Exception:
            self._spool = None

    def _close_interval(self, at: float) -> None:
        """Close the open phase interval into the accumulator (caller
        holds the lock)."""
        phase = self._phase
        if phase is None:
            return
        dur = at - self._since
        if dur < 0:
            dur = 0.0
        self._acc[phase] = self._acc.get(phase, 0.0) + dur
        if len(self._lane) < _MAX_LANE_SPANS:
            self._lane.append((phase, self._since, at))
        self._emit({"kind": "phase", "pid": os.getpid(), "phase": phase,
                    "start": self._since, "end": at})
        if self.metrics and dur > 0:
            from hivedscheduler_tpu.runtime.metrics import REGISTRY
            REGISTRY.inc("tpu_hive_goodput_seconds_total", amount=dur,
                         phase=phase)
        self._since = at

    # -- mutators (the instrumentation surface) ---------------------------
    def start(self, phase: str = "init", at: Optional[float] = None) -> None:
        """Anchor the process wallclock and open the first phase.
        Idempotent — the first call wins (conservation is measured from
        it)."""
        if not self.enabled or _journal.suppressed():
            return
        self._check_phase(phase)
        t = self._now(at)
        with self._lock:
            if self._t0 is not None:
                return
            self._t0 = t
            self._phase = phase
            self._since = t
            self._emit({"kind": "start", "pid": os.getpid(), "t0": t,
                        "phase": phase})

    def phase(self, phase: str, at: Optional[float] = None) -> None:
        """Transition into ``phase`` (closing the open interval). Same
        phase is a no-op — the interval just continues."""
        if not self.enabled or _journal.suppressed():
            return
        self._check_phase(phase)
        t = self._now(at)
        with self._lock:
            if self._t0 is None:
                self._t0 = t
                self._phase = phase
                self._since = t
                self._emit({"kind": "start", "pid": os.getpid(), "t0": t,
                            "phase": phase})
                return
            if self._closed or self._phase == phase:
                return
            self._close_interval(t)
            self._phase = phase

    def span(self, phase: str, at: Optional[float] = None) -> "_PhaseSpan":
        """``with goodput.span("checkpoint_save"): ...`` — enter the
        phase, restore the surrounding phase on exit. A shared no-op
        when disabled."""
        if not self.enabled or _journal.suppressed():
            return _NOOP_SPAN
        self._check_phase(phase)
        with self._lock:
            prev = self._phase
        self.phase(phase, at=at)
        return _PhaseSpan(self, prev)

    def seed_max_step(self, step: int) -> None:
        """Carry the high-water mark across incarnations (replayed from
        the shared spool's per-step records at enable time, or seeded by
        a harness). Steps at or below it classify as rework."""
        if not self.enabled:
            return
        with self._lock:
            if step > self._max_step:
                self._max_step = step

    def note_step(self, step: int, is_compile: bool = False,
                  at: Optional[float] = None) -> None:
        """The step loop is starting compute for ``step`` (1-based, the
        step number it will commit). Classifies the phase: ``rework`` iff
        ``step <= max_step_ever_completed`` — with precedence over
        ``compile``, because a resumed incarnation's recompile only exists
        to re-reach the old high-water mark, so ALL wallclock until then
        is fault-caused badput — then ``compile`` for the incarnation's
        first step (XLA trace+compile dominates), else ``step_compute``."""
        if not self.enabled or _journal.suppressed():
            return
        with self._lock:
            rework = step <= self._max_step
        if rework:
            self.phase("rework", at=at)
        elif is_compile:
            self.phase("compile", at=at)
        else:
            self.phase("step_compute", at=at)

    def note_step_done(self, step: int, at: Optional[float] = None) -> None:
        """The step's loss is materialized (the host sync). Advances the
        high-water mark and spools a per-step record so the NEXT
        incarnation can classify rework exactly."""
        if not self.enabled or _journal.suppressed():
            return
        with self._lock:
            self._steps += 1
            rework = step <= self._max_step
            if rework:
                self._rework_steps += 1
            else:
                self._max_step = step
            self._emit({"kind": "step", "pid": os.getpid(), "step": step,
                        "rework": rework})

    def close(self, at: Optional[float] = None) -> None:
        """Close the open interval and spool the incarnation summary
        (registered atexit by :func:`enable`; idempotent; not reached by
        kill -9 — torn incarnations keep only their flushed records)."""
        if not self.enabled:
            return
        t = self._now(at)
        with self._lock:
            if self._closed or self._t0 is None:
                return
            self._close_interval(t)
            self._phase = None
            self._closed = True
            self._emit({
                "kind": "summary", "pid": os.getpid(),
                "wallclock_s": t - self._t0,
                "phases": {p: round(s, 9) for p, s in self._acc.items()},
                "steps": self._steps, "rework_steps": self._rework_steps,
                "max_step": self._max_step,
            })
            if self._spool is not None:
                try:
                    self._spool.close()
                except Exception:
                    pass
                self._spool = None

    def open_spool(self, path: str) -> None:
        with self._lock:
            self._spool = open(path, "a", encoding="utf-8")
            self._spool_path = path

    def clear(self) -> None:
        with self._lock:
            self._t0 = None
            self._phase = None
            self._acc = {}
            self._lane = []
            self._steps = 0
            self._rework_steps = 0
            self._max_step = 0
            self._closed = False
            if self._spool is not None:
                try:
                    self._spool.close()
                except Exception:
                    pass
            self._spool = None
            self._spool_path = ""

    # -- read API (copy-on-read) ------------------------------------------
    def totals(self, at: Optional[float] = None) -> Dict[str, float]:
        """Closed + open phase-seconds as of ``at`` — the conservation
        check's left-hand side."""
        t = self._now(at)
        with self._lock:
            out = dict(self._acc)
            if self._phase is not None:
                dur = max(0.0, t - self._since)
                out[self._phase] = out.get(self._phase, 0.0) + dur
            return out

    def wallclock(self, at: Optional[float] = None) -> float:
        """Seconds since :meth:`start` — the conservation check's
        right-hand side (frozen at close)."""
        t = self._now(at)
        with self._lock:
            if self._t0 is None:
                return 0.0
            if self._closed:
                return self._since - self._t0
            return max(0.0, t - self._t0)

    def conservation_gap(self, at: Optional[float] = None) -> float:
        t = self._now(at)
        return sum(self.totals(t).values()) - self.wallclock(t)

    def goodput_fraction(self, at: Optional[float] = None
                         ) -> Optional[float]:
        """goodput seconds / wallclock (None before start)."""
        t = self._now(at)
        wall = self.wallclock(t)
        if wall <= 0:
            return None
        totals = self.totals(t)
        return sum(totals.get(p, 0.0) for p in GOODPUT_PHASES) / wall

    def current_phase(self) -> Optional[str]:
        with self._lock:
            return self._phase

    def snapshot(self, at: Optional[float] = None) -> Dict[str, Any]:
        t = self._now(at)
        totals = self.totals(t)
        wall = self.wallclock(t)
        frac = self.goodput_fraction(t)
        with self._lock:
            steps, rework = self._steps, self._rework_steps
            max_step = self._max_step
        return {
            "enabled": self.enabled,
            "phases": {p: round(totals.get(p, 0.0), 6)
                       for p in STEP_PHASES},
            "wallclockS": round(wall, 6),
            "conservationGapS": round(sum(totals.values()) - wall, 6),
            "goodputFraction": (round(frac, 6)
                                if frac is not None else None),
            "steps": steps, "reworkSteps": rework, "maxStep": max_step,
        }

    def chrome_events(self, t0: float) -> List[Dict[str, Any]]:
        """One named ``workload goodput`` Perfetto lane: an X span per
        closed phase interval (the open phase is drawn to the export
        instant). ``t0`` is the tracer's perf_counter anchor."""
        now = time.perf_counter()
        with self._lock:
            spans = list(self._lane)
            if self._phase is not None:
                spans.append((self._phase, self._since, now))
        if not spans:
            return []
        out: List[Dict[str, Any]] = [
            {"name": "thread_name", "ph": "M", "pid": 1, "tid": _LANE_TID,
             "ts": 0, "args": {"name": "workload goodput"}}]
        for phase, start, end in spans:
            out.append({"name": f"phase:{phase}", "ph": "X",
                        "cat": "goodput", "ts": (start - t0) * 1e6,
                        "dur": max(0.0, (end - start) * 1e6),
                        "pid": 1, "tid": _LANE_TID, "args": {}})
        return out


class _PhaseSpan:
    """Restore the surrounding phase on exit (``goodput.span(...)``)."""

    def __init__(self, ledger: GoodputLedger, prev: Optional[str]):
        self._ledger = ledger
        self._prev = prev

    def __enter__(self) -> "_PhaseSpan":
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        if self._prev is not None:
            self._ledger.phase(self._prev)
        return False


class _NoopSpan:
    def __enter__(self) -> "_NoopSpan":
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        return False


_NOOP_SPAN = _NoopSpan()

GOODPUT = GoodputLedger()


def enabled() -> bool:
    return GOODPUT.enabled


def enable(spool_path: Optional[str] = None) -> None:
    """Turn the ledger on, optionally opening (appending to) a JSONL
    spool. When the spool already holds records from a previous
    incarnation (the harnesses share one ``--goodput-file`` across a
    fault episode), the step high-water mark is replayed from them so
    rework classification is exact across kills."""
    GOODPUT.enabled = True
    if spool_path:
        prev_max = spool_max_step(spool_path)
        GOODPUT.open_spool(spool_path)
        if prev_max:
            GOODPUT.seed_max_step(prev_max)
    GOODPUT.start()
    atexit.register(GOODPUT.close)


def disable() -> None:
    GOODPUT.enabled = False


# module-level wrappers: the instrumentation sites' one-liner surface
# (each gates on the singleton's enabled bit before doing anything; the
# first param is named ``phase`` everywhere so OBS003 extracts keyword
# call sites uniformly)
def phase(phase: str, at: Optional[float] = None) -> None:
    GOODPUT.phase(phase, at=at)


def span(phase: str, at: Optional[float] = None):
    return GOODPUT.span(phase, at=at)


def note_step(step: int, is_compile: bool = False,
              at: Optional[float] = None) -> None:
    GOODPUT.note_step(step, is_compile=is_compile, at=at)


def note_step_done(step: int, at: Optional[float] = None) -> None:
    GOODPUT.note_step_done(step, at=at)


# -- spool readers (harness / bench aggregation side) -----------------------
def read_spool(path: str) -> List[Dict[str, Any]]:
    """Parse a goodput spool, tolerating a torn trailing line (the
    writer may have been kill -9'd mid-write)."""
    records: List[Dict[str, Any]] = []
    try:
        with open(path, "r", encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    records.append(json.loads(line))
                except ValueError:
                    continue  # torn line
    except OSError:
        return []
    return records


def spool_max_step(path: str) -> int:
    """Largest completed step recorded in a spool (0 when absent) — the
    cross-incarnation rework high-water mark."""
    best = 0
    for rec in read_spool(path):
        if rec.get("kind") == "step":
            best = max(best, int(rec.get("step", 0)))
        elif rec.get("kind") == "summary":
            best = max(best, int(rec.get("max_step", 0)))
    return best


def aggregate_spool(records: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Merge a multi-incarnation spool into per-phase totals plus
    per-incarnation bookkeeping. Incarnations are keyed by (start
    record, pid); one with a ``start`` but no ``summary`` is *torn*
    (kill -9 / watchdog os._exit) — its flushed phase records still
    count toward the breakdown, but it has no conservation claim."""
    phases: Dict[str, float] = {}
    observed_by_pid: Dict[int, float] = {}
    summaries: List[Dict[str, Any]] = []
    starts = 0
    steps = rework_steps = 0
    for rec in records:
        kind = rec.get("kind")
        if kind == "start":
            starts += 1
        elif kind == "phase":
            dur = max(0.0, float(rec.get("end", 0.0))
                      - float(rec.get("start", 0.0)))
            ph = str(rec.get("phase", ""))
            phases[ph] = phases.get(ph, 0.0) + dur
            pid = int(rec.get("pid", 0))
            observed_by_pid[pid] = observed_by_pid.get(pid, 0.0) + dur
        elif kind == "step":
            # counted from step records, not summaries, so torn (kill -9)
            # incarnations' completed steps are still attributed
            steps += 1
            if rec.get("rework"):
                rework_steps += 1
        elif kind == "summary":
            summaries.append(rec)
    wall = sum(float(s.get("wallclock_s", 0.0)) for s in summaries)
    goodput_s = sum(phases.get(p, 0.0) for p in GOODPUT_PHASES)
    return {
        "phases": phases,
        "incarnations": starts,
        "summaries": summaries,
        "torn": starts - len(summaries),
        "steps": steps,
        "rework_steps": rework_steps,
        "summarized_wallclock_s": wall,
        "observed_s": sum(observed_by_pid.values()),
        "goodput_fraction": (goodput_s / wall) if wall > 0 else None,
    }


def check_rework_classification(records: List[Dict[str, Any]]
                                ) -> List[str]:
    """Replay the merged spool's ``step`` records in file order against a
    fresh high-water mark: each record's recorded ``rework`` flag must
    match the replay (covers torn incarnations too — a mismatch means
    the cross-incarnation seed replay or the in-process classification
    drifted). Returns violation strings."""
    violations: List[str] = []
    hwm = 0
    for rec in records:
        if rec.get("kind") != "step":
            continue
        step = int(rec.get("step", 0))
        expected = step <= hwm
        got = bool(rec.get("rework", False))
        if got != expected:
            violations.append(
                f"goodput rework misclassified: step {step} (pid "
                f"{rec.get('pid')}) recorded rework={got} but the merged "
                f"high-water mark ({hwm}) implies {expected} — the spool "
                f"seed replay or note_step classification drifted")
        if step > hwm:
            hwm = step
    return violations


def check_spool(path: str, rel_tol: float = 1e-6) -> List[str]:
    """Conservation + registry violations for every summarized
    incarnation in a spool (the chaos harnesses call this after each
    soak). Returns human-readable violation strings, empty when clean."""
    violations: List[str] = []
    records = read_spool(path)
    for rec in records:
        if rec.get("kind") == "phase":
            ph = str(rec.get("phase", ""))
            if ph not in STEP_PHASES:
                violations.append(
                    f"goodput spool {path}: unregistered phase {ph!r} "
                    f"(OBS003)")
    for rec in records:
        if rec.get("kind") != "summary":
            continue
        wall = float(rec.get("wallclock_s", 0.0))
        got = sum(float(v) for v in rec.get("phases", {}).values())
        tol = rel_tol * max(1.0, wall)
        if abs(got - wall) > tol:
            violations.append(
                f"goodput conservation violated (pid {rec.get('pid')}): "
                f"sum(phases)={got:.6f}s != wallclock={wall:.6f}s "
                f"(|gap|={abs(got - wall):.6f}s > tol={tol:.6f}s)")
        for ph in rec.get("phases", {}):
            if ph not in STEP_PHASES:
                violations.append(
                    f"goodput spool {path}: unregistered phase {ph!r} "
                    f"in summary (OBS003)")
    return violations


def reconcile_busy(busy_s: float, observed_s: float,
                   slack_s: float) -> Optional[str]:
    """The workload↔capacity-ledger bridge check: the scheduler-side
    ``busy_guaranteed`` interval for a gang must COVER the workload's
    self-observed phase seconds (a workload can never observe more time
    than the cluster charged for it — that is a clock or accounting
    bug), and the uncovered remainder (interpreter startup/teardown
    plus intervals lost to kill -9) must stay under ``slack_s``.
    Returns a violation string or None."""
    gap = busy_s - observed_s
    if gap < -1e-3:
        return (f"goodput bridge: workload observed {observed_s:.3f}s > "
                f"scheduler busy_guaranteed {busy_s:.3f}s "
                f"(gap {gap:.3f}s) — workload time must be covered by "
                f"the capacity ledger")
    if gap > slack_s:
        return (f"goodput bridge: busy_guaranteed {busy_s:.3f}s exceeds "
                f"workload observed {observed_s:.3f}s by {gap:.3f}s "
                f"(> slack {slack_s:.1f}s) — unattributed busy time")
    return None


if envflags.get("HIVED_GOODPUT") == "1":  # ad-hoc opt-in, like HIVED_LEDGER
    enable()
