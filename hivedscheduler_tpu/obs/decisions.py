"""Scheduler decision traces: "why did this gang land on these cells?".

Every ``HivedAlgorithm.schedule`` call, when recording is enabled, produces
one ``Decision``: the request's identity (pod, group, VC, priority, phase),
every placement **attempt** the ``_schedule_*`` ladder made (which chain or
pinned cell was probed, on which path — within-VC guaranteed, opportunistic,
or multi-chain relaxation — and why it failed if it did), the final outcome
(bind / preempt / wait / error) and its explanation, preemption victims,
and the wall time spent deciding. The last N decisions live in a bounded
ring served at ``GET /v1/inspect/traces`` and printed by the demo CLI's
``--explain`` flag.

Threading contract: a ``Decision`` is mutated only inside
``HivedAlgorithm.schedule`` under the algorithm lock (the layer is
single-threaded by design — CLAUDE.md architecture rules); the ring itself
is locked because the webserver reads it from handler threads.

Like ``obs.trace``, recording is OFF by default and every instrumentation
site is gated on one cheap check (``RECORDER.enabled`` or the decision
object being non-None), so ``bench.py``'s schedule hot path is unchanged.
"""

from __future__ import annotations

import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

from hivedscheduler_tpu.common import lockcheck
from hivedscheduler_tpu.obs import trace

_DEFAULT_CAPACITY = 256


@dataclass
class Attempt:
    """One placement probe: a (chain | pinned cell) x scheduling-path pair."""

    where: str  # "chain v5p-1024" | "pinned cell pc1" | "relax[a,b]"
    path: str  # "guaranteed" | "opportunistic" | "multi-chain-relax" | ...
    outcome: str  # "placed" | "failed"
    reason: str = ""  # failure explanation, verbatim from the ladder

    def to_dict(self) -> Dict[str, Any]:
        return {"where": self.where, "path": self.path,
                "outcome": self.outcome, "reason": self.reason}


@dataclass
class Decision:
    """One ``schedule()`` call, beginning to outcome."""

    pod: str
    phase: str
    group: str = ""
    vc: str = ""
    priority: Optional[int] = None
    suggested_nodes: int = 0
    attempts: List[Attempt] = field(default_factory=list)
    outcome: str = ""  # "bind" | "preempt" | "wait" | "error"
    node: str = ""  # bind target (outcome == "bind")
    victims: List[str] = field(default_factory=list)  # outcome == "preempt"
    reason: str = ""  # wait reason / error message
    started_at: float = field(default_factory=time.time)  # wall epoch
    elapsed_ms: float = 0.0
    _t0: float = field(default_factory=time.perf_counter, repr=False)

    def attempt(self, where: str, path: str, outcome: str,
                reason: str = "") -> None:
        self.attempts.append(Attempt(where, path, outcome, reason))

    def finish(self, outcome: str, node: str = "", victims=(),
               reason: str = "") -> None:
        self.outcome = outcome
        self.node = node
        self.victims = list(victims)
        self.reason = reason
        self.elapsed_ms = (time.perf_counter() - self._t0) * 1e3

    def to_dict(self) -> Dict[str, Any]:
        return {
            "pod": self.pod,
            "group": self.group,
            "vc": self.vc,
            "priority": self.priority,
            "phase": self.phase,
            "suggestedNodes": self.suggested_nodes,
            "attempts": [a.to_dict() for a in self.attempts],
            "outcome": self.outcome,
            "node": self.node,
            "victims": self.victims,
            "reason": self.reason,
            "startedAt": self.started_at,
            "elapsedMs": round(self.elapsed_ms, 3),
        }

    def explain(self) -> str:
        """One human line: the --explain rendering."""
        probes = "; ".join(
            f"{a.where}/{a.path}: {a.outcome}"
            + (f" ({a.reason})" if a.reason else "")
            for a in self.attempts
        ) or "no placement probes"
        tail = {
            "bind": f"-> bind {self.node}",
            "preempt": f"-> preempt {len(self.victims)} victim(s)",
            "wait": f"-> wait: {self.reason}",
            "error": f"-> error: {self.reason}",
        }.get(self.outcome, f"-> {self.outcome}")
        return (f"[{self.pod}] {self.phase} prio={self.priority} "
                f"vc={self.vc}: {probes} {tail} "
                f"({self.elapsed_ms:.1f} ms)")


class DecisionRecorder:
    """Bounded ring of the last N decisions + optional commit callback."""

    def __init__(self, capacity: int = _DEFAULT_CAPACITY):
        self._lock = lockcheck.make_lock("decisions_lock", late=True)
        self._ring: deque = deque(maxlen=capacity)
        self.enabled = False
        self.on_commit: Optional[Callable[[Decision], None]] = None

    def enable(self, capacity: Optional[int] = None) -> None:
        with self._lock:
            if capacity is not None:
                self._ring = deque(self._ring, maxlen=capacity)
            self.enabled = True

    def disable(self) -> None:
        self.enabled = False

    def begin(self, pod: str, phase: str) -> Optional[Decision]:
        """Start a decision (None when disabled — instrumentation sites gate
        on the returned object, keeping the disabled path one check)."""
        if not self.enabled:
            return None
        return Decision(pod=pod, phase=phase)

    def commit(self, decision: Decision) -> None:
        with self._lock:
            self._ring.append(decision)
        # mirror into the shared timeline so the Perfetto export shows
        # schedule decisions alongside extender/serving spans
        if trace.enabled():
            trace.TRACER.complete(
                f"schedule {decision.pod}",
                decision._t0,
                decision._t0 + decision.elapsed_ms / 1e3,
                cat="scheduler",
                args={"outcome": decision.outcome,
                      "attempts": len(decision.attempts),
                      "vc": decision.vc},
            )
        cb = self.on_commit
        if cb is not None:
            try:
                cb(decision)
            except Exception:  # a broken callback must never fail schedule()
                pass

    def last(self, n: Optional[int] = None) -> List[Dict[str, Any]]:
        """Most-recent-first dicts of the last ``n`` (default: all held)."""
        with self._lock:
            items = list(self._ring)
        items.reverse()
        if n is not None:
            items = items[: max(0, n)]
        return [d.to_dict() for d in items]

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()


RECORDER = DecisionRecorder()
