"""Dependency-free span/event tracer with Chrome-trace (Perfetto) export.

The reference is klog-only (SURVEY.md §5): nothing answers "where did this
request's time go?". This tracer is the shared timeline substrate for the
whole stack — scheduler decisions (``obs.decisions``), extender routines
(``runtime/scheduler.py``), serving request lifecycles
(``models/serving.py``), and train step timelines (``train.py``) all emit
into one bounded in-memory ring buffer that exports as Chrome trace event
JSON (the format Perfetto / ``chrome://tracing`` / TensorBoard's trace
viewer load directly).

Design constraints, in order:

- **Zero overhead when disabled** (the default). Every emit path starts
  with one module-level bool check; ``span()`` returns a shared no-op
  context manager without allocating. ``python bench.py`` must not move.
- **Thread-safe**: the serving engine emits from worker threads and the
  webserver reads concurrently; the ring is locked. (The algorithm layer
  is single-threaded under the scheduler lock by contract — its events
  need the lock only because OTHER components share the ring.)
- **Bounded**: a ``deque(maxlen=capacity)`` ring — long-lived servers
  keep the most recent events, never grow.

Enable programmatically (``trace.enable()``) or via ``HIVED_TRACE=1`` in
the environment. Export with ``trace.to_chrome_trace()`` /
``trace.write_chrome_trace(path)``, or over HTTP at
``GET /v1/inspect/traces/chrome`` on the scheduler webserver.

Event schema (Chrome trace event format, the subset we emit):

- ``ph="X"`` complete events: ``name, cat, ts, dur, pid, tid, args``
- ``ph="i"`` instant events:  ``name, cat, ts, s="t", pid, tid, args``
- ``ph="M"`` metadata: process/thread names (emitted on ``enable()``)

``ts``/``dur`` are microseconds on the process-wide ``perf_counter``
clock, re-based to the tracer's start; callers that timestamp with
``time.perf_counter()`` themselves (the serving engine's request
bookkeeping) can hand those values to ``complete()`` verbatim.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

from hivedscheduler_tpu.common import lockcheck

_DEFAULT_CAPACITY = 65536


class Tracer:
    """Bounded ring of Chrome-trace events. Instantiable for tests; the
    module-level singleton ``TRACER`` is what the stack shares."""

    def __init__(self, capacity: int = _DEFAULT_CAPACITY):
        self._lock = lockcheck.make_lock("trace_lock", late=True)
        self._events: deque = deque(maxlen=capacity)
        # perf_counter anchor: all ts are relative to tracer creation so
        # callers' own perf_counter timestamps convert with one subtraction
        self._t0 = time.perf_counter()
        self.dropped = 0  # events displaced by the ring bound

    # -- emit ------------------------------------------------------------
    def _ts_us(self, at: Optional[float] = None) -> float:
        return ((time.perf_counter() if at is None else at) - self._t0) * 1e6

    def _emit(self, ev: Dict[str, Any]) -> None:
        with self._lock:
            if len(self._events) == self._events.maxlen:
                self.dropped += 1
            self._events.append(ev)

    def complete(
        self,
        name: str,
        start: float,
        end: float,
        cat: str = "",
        tid: Optional[int] = None,
        args: Optional[Dict[str, Any]] = None,
    ) -> None:
        """Record a finished span from explicit ``perf_counter`` seconds —
        the path for callers that already keep their own timestamps."""
        self._emit({
            "name": name,
            "ph": "X",
            "cat": cat or "default",
            "ts": self._ts_us(start),
            "dur": max(0.0, (end - start) * 1e6),
            "pid": 1,
            "tid": threading.get_ident() & 0x7FFFFFFF if tid is None else tid,
            "args": args or {},
        })

    def instant(
        self,
        name: str,
        cat: str = "",
        tid: Optional[int] = None,
        at: Optional[float] = None,
        args: Optional[Dict[str, Any]] = None,
    ) -> None:
        self._emit({
            "name": name,
            "ph": "i",
            "s": "t",  # thread-scoped instant
            "cat": cat or "default",
            "ts": self._ts_us(at),
            "pid": 1,
            "tid": threading.get_ident() & 0x7FFFFFFF if tid is None else tid,
            "args": args or {},
        })

    def metadata(self, name: str, value: str, tid: int = 0) -> None:
        """``M`` event naming a pid/tid lane in the viewer."""
        key = "process_name" if name == "process" else "thread_name"
        self._emit({
            "name": key,
            "ph": "M",
            "pid": 1,
            "tid": tid,
            "ts": 0,
            "args": {"name": value},
        })

    def span(self, name: str, cat: str = "", **args: Any) -> "_Span":
        return _Span(self, name, cat, args)

    # -- read ------------------------------------------------------------
    def snapshot(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._events)

    def __len__(self) -> int:
        with self._lock:
            return len(self._events)

    def clear(self) -> None:
        with self._lock:
            self._events.clear()
            self.dropped = 0

    def to_chrome_trace(self) -> Dict[str, Any]:
        """The dict form of the Chrome trace JSON object (Perfetto loads
        ``json.dumps`` of this verbatim)."""
        return {
            "traceEvents": self.snapshot(),
            "displayTimeUnit": "ms",
            "otherData": {"producer": "tpu-hive obs.trace",
                          "dropped_events": self.dropped},
        }

    def write_chrome_trace(self, path: str) -> None:
        with open(path, "w") as f:
            json.dump(self.to_chrome_trace(), f)


class _Span:
    """Context manager recording one complete ("X") event on exit.
    ``add(**kw)`` attaches args mid-flight (e.g. the outcome)."""

    __slots__ = ("_tracer", "_name", "_cat", "_args", "_start")

    def __init__(self, tracer: Tracer, name: str, cat: str, args: Dict[str, Any]):
        self._tracer = tracer
        self._name = name
        self._cat = cat
        self._args = args
        self._start = 0.0

    def add(self, **kw: Any) -> None:
        self._args.update(kw)

    def __enter__(self) -> "_Span":
        self._start = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        if exc_type is not None and "error" not in self._args:
            self._args["error"] = exc_type.__name__
        self._tracer.complete(self._name, self._start, time.perf_counter(),
                              cat=self._cat, args=self._args)
        return False


class _NoopSpan:
    """Shared do-nothing span: the disabled fast path allocates nothing."""

    __slots__ = ()

    def add(self, **kw: Any) -> None:
        pass

    def __enter__(self) -> "_NoopSpan":
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        return False


_NOOP = _NoopSpan()

# Module state: ONE bool gates every emit path. Disabled by default so the
# instrumented hot paths (schedule ladder, serving steps) pay a single
# attribute load; HIVED_TRACE=1 opts in at import for ad-hoc runs.
_enabled = False
TRACER = Tracer()


def enabled() -> bool:
    return _enabled


def enable(capacity: Optional[int] = None) -> None:
    """Turn tracing on (optionally resizing the ring; resets its content)."""
    global _enabled, TRACER
    if capacity is not None:
        TRACER = Tracer(capacity)
    _enabled = True
    TRACER.metadata("process", "tpu-hive")


def disable() -> None:
    global _enabled
    _enabled = False


def span(name: str, cat: str = "", **args: Any):
    """``with trace.span("filter_routine", cat="extender") as sp: ...`` —
    a shared no-op object when tracing is off (no allocation)."""
    if not _enabled:
        return _NOOP
    return TRACER.span(name, cat, **args)


def instant(name: str, cat: str = "", tid: Optional[int] = None,
            **args: Any) -> None:
    if not _enabled:
        return
    TRACER.instant(name, cat, tid=tid, args=args)


def complete(name: str, start: float, end: float, cat: str = "",
             tid: Optional[int] = None, **args: Any) -> None:
    """Record a finished span from caller-held perf_counter timestamps."""
    if not _enabled:
        return
    TRACER.complete(name, start, end, cat=cat, tid=tid, args=args)


def to_chrome_trace() -> Dict[str, Any]:
    """The shared timeline as Chrome-trace JSON. When the gang-lifecycle
    journal is enabled, its per-gang tracks (one named lane per gang:
    lifecycle instants + wait-interval spans) are merged in — every
    exporter (webserver, --trace-file, --metrics-dump) gets them free.
    The capacity ledger's per-node ``state:`` lanes and the workload
    goodput ledger's ``workload goodput`` phase lane merge the same
    way."""
    out = TRACER.to_chrome_trace()
    from hivedscheduler_tpu.obs import goodput as _goodput
    from hivedscheduler_tpu.obs import journal as _journal
    from hivedscheduler_tpu.obs import ledger as _ledger

    if _journal.JOURNAL.enabled:
        out["traceEvents"] = (
            list(out["traceEvents"])
            + _journal.JOURNAL.chrome_events(TRACER._t0)
        )
    if _ledger.LEDGER.enabled:
        out["traceEvents"] = (
            list(out["traceEvents"])
            + _ledger.LEDGER.chrome_events(TRACER._t0)
        )
    if _goodput.GOODPUT.enabled:
        out["traceEvents"] = (
            list(out["traceEvents"])
            + _goodput.GOODPUT.chrome_events(TRACER._t0)
        )
    return out


def write_chrome_trace(path: str) -> None:
    with open(path, "w") as f:
        json.dump(to_chrome_trace(), f)


if os.environ.get("HIVED_TRACE") == "1":  # ad-hoc opt-in without code changes
    enable()
