"""Unified observability layer: tracing, decision traces, and the glue to
the Prometheus registry (``runtime/metrics.py``).

- ``obs.trace`` — dependency-free span/event tracer (bounded ring,
  Chrome-trace/Perfetto JSON export, module-level no-op fast path).
- ``obs.decisions`` — structured scheduler decision traces ("why did this
  gang land on these cells?"), served at ``GET /v1/inspect/traces``.
- ``obs.journal`` — gang-lifecycle flight recorder + request flights
  (TTFT leg attribution), served at ``GET /v1/inspect/gangs`` and
  ``GET /v1/inspect/requests``.
- ``obs.slo`` — declared serving objectives: windowed quantiles,
  error-budget burn rate, violation attribution by dominant leg, served
  at ``GET /v1/inspect/slo``.
- ``obs.ledger`` — capacity ledger: live chip-second attribution over
  the ``CHIP_STATES`` taxonomy with the conservation invariant
  (buckets sum to chips x wallclock), served at
  ``GET /v1/inspect/capacity``.
- ``obs.eta`` — read-only wait-ETA estimator (capacity-without-a-move
  forecasts for waiting gangs), served at
  ``GET /v1/inspect/gangs/<id>/eta``.

See ``doc/design/observability.md`` for the full catalogue of metric
names, trace event schemas, leg taxonomy, and the Perfetto workflow.
"""

from hivedscheduler_tpu.obs import decisions, trace  # noqa: F401

__all__ = ["trace", "decisions"]
