"""Gang-lifecycle flight recorder: a causal event journal + wait attribution.

The decision traces (``obs.decisions``) explain one ``schedule()`` call;
the subsystems landed since — defrag migrations, elastic shrink/grow,
backfill promotions, serving admission — form multi-step causal chains no
single trace captures. This module records every gang's lifecycle as a
bounded, crash-safe, causally-linked event journal::

    submit -> queued(wait_reason) -> defrag_planned -> migration_evict ->
    bind -> elastic_grow_planned -> ... -> released

Each :class:`Event` carries the gang id, a **cause** (the parent event id —
auto-chained to the gang's previous event unless an explicit cross-gang
cause is given, e.g. a mover's eviction caused by the waiter's plan), and,
for waits, a **wait-attribution bucket** from :data:`WAIT_BUCKETS`. Wait
intervals are closed on bucket transitions and on bind/grow/release, each
closure observed into the ``tpu_hive_gang_wait_seconds{reason=}``
histogram — so "why is this gang waiting, since when, and what is in
flight to unblock it" is a queryable fact, not a bench.py post-hoc guess
(BENCH_r05's 89.2% "packing" wait turned out to be ~100% VC-quota
stranding only after manual measurement).

The same recorder also carries **request flights** (ISSUE 13): every
serving request — fleet-routed (``fleet/<fid>``) or single-engine
(``serve/<rid>``) — is a cause-chained sequence of exclusive,
non-overlapping **legs** (:data:`REQUEST_LEGS`:
route/router_queue/retry/admission_wait/prefill/handoff_ship/
handoff_import/first_decode) opened by ``note_request_submit``, advanced
by ``note_leg`` and closed by ``note_request_done``. The legs ending at
or before the first-token mark sum to the measured ``ttft_s`` (the
stored ``ttft_gap`` is asserted ~0 by ``chaos.invariants.check_requests``
and the bench fleet stage), each closed leg is observed into
``tpu_hive_request_leg_seconds{leg=}``, and ``obs/slo.py`` attributes
SLO violations to the dominant leg.

Served three ways:

- ``GET /v1/inspect/gangs`` (per-gang summaries) and
  ``GET /v1/inspect/gangs/<id>/timeline`` (the causal event list) —
  copy-on-read snapshots, like the other inspect endpoints — plus the
  request-flight twins ``GET /v1/inspect/requests`` and
  ``GET /v1/inspect/requests/<id>/timeline``;
- per-gang Perfetto tracks merged into the Chrome-trace export
  (:func:`Journal.chrome_events`, folded in by ``obs.trace``);
- an optional ``--journal-file`` JSONL spool (one event per line,
  flushed per append) for post-mortem replay after a crash.

Contracts (mirroring ``obs.trace`` / ``obs.decisions``, the PR 1 rules):

- **Zero overhead when disabled** (the default): every instrumentation
  site gates on one attribute load (``JOURNAL.enabled``); ``emit`` and the
  ``note_*`` helpers return immediately without taking the lock.
- **Bounded**: the event ring is a ``deque(maxlen=...)``; per-gang records
  and their closed wait intervals are capped, oldest-closed evicted first.
- **Thread-safe leaf**: scheduler/algorithm sites append under the
  scheduler lock; serving appends from worker threads; the webserver
  reads concurrently. ``journal_lock`` is a leaf in the lock hierarchy —
  nothing but the metrics leaf is ever acquired under it.
- **Schema-checked**: every event type must be a :data:`SCHEMA` row and
  every wait bucket a :data:`WAIT_BUCKETS` row (hivedlint OBS001 checks
  the call sites statically; the runtime raises on dynamic misuse).

Enable programmatically (``journal.enable()``), via the CLIs'
``--journal-file``, or ``HIVED_JOURNAL=1`` in the environment.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from hivedscheduler_tpu.common import lockcheck

_DEFAULT_CAPACITY = 16384
_MAX_GANGS = 4096
_MAX_INTERVALS_PER_GANG = 64
_MAX_LEGS_PER_REQUEST = 64

# ---------------------------------------------------------------------------
# wait-attribution taxonomy. Buckets are monotonic accounting categories:
# at any instant a waiting gang is in exactly ONE bucket, transitions close
# the previous interval, and the per-bucket chip-time sums to the gang's
# total wait — the invariant bench.py's trace replay asserts.
# ---------------------------------------------------------------------------
WAIT_BUCKETS: Dict[str, str] = {
    "vc_quota": "the gang's VC has no free guaranteed cells left (quota "
                "stranding; backfill/promotion is the unblocking arm)",
    "fragmentation": "enough capacity exists but no contiguous placement "
                     "(defrag migration is the unblocking arm)",
    "capacity": "fewer free chips than the gang needs anywhere: pure "
                "queueing, no scheduler can help",
    "bad_hardware": "placement forced onto bad/doomed nodes; waiting on "
                    "node recovery",
    "reservation_hold": "blocked by cells held for a defrag waiter or a "
                        "mid-migration re-placement",
    "priority": "waiting on preemption of lower-priority victims to "
                "complete",
    "elastic_degraded": "running on a degraded elastic slice, waiting for "
                        "grow-promotion back to full shape",
    "unknown": "wait reason not classified (classifier fallback — a "
               "growing share here is a bug)",
}

# ---------------------------------------------------------------------------
# request-leg taxonomy: the serving tier's analogue of WAIT_BUCKETS. A
# request flight is a contiguous sequence of exclusive, non-overlapping
# legs — each ``note_leg(req, leg, at=t)`` attributes the interval from
# the flight's previous mark to ``t`` to exactly one leg, so the legs up
# to the first-token mark SUM to the measured TTFT (asserted by
# ``note_request_done``'s gap accounting, ``chaos.invariants
# .check_requests``, and the bench fleet stage — not plotted and hoped).
# hivedlint OBS001 cross-checks every ``note_leg`` literal against this
# table, both directions.
# ---------------------------------------------------------------------------
REQUEST_LEGS: Dict[str, str] = {
    "route": "router dispatch: fleet submit (or retry re-dispatch) to the "
             "chosen replica engine's own submit timestamp",
    "router_queue": "a completed prefill leg waiting for the router step "
                    "that advances its KV handoff",
    "retry": "a shed/preempted/lost leg's whole wasted attempt, up to the "
             "moment the router abandons it (re-attribution: no time is "
             "lost between shed and retry)",
    "admission_wait": "engine queue wait: engine submit to slot admission "
                      "(the strict-priority / block-availability gate)",
    "prefill": "slot admission to the leg's first emitted token on a "
               "prefill-role or unified replica (prompt prefill)",
    "handoff_ship": "host-side export of the prefill replica's prefix-"
                    "cache payload (HIVED_FLEET_KV_SHIP=1)",
    "handoff_import": "importing the shipped payload into the decode "
                      "replica's block pool as refcounted prefix blocks",
    "first_decode": "decode-leg admission to its first token after a KV "
                    "handoff (imported-prefix restore + tail prefill + "
                    "the first decode window)",
}

# ---------------------------------------------------------------------------
# event schema registry — the single source of truth for journal event
# types. hivedlint OBS001 cross-checks every `journal.emit(...)` /
# `journal.note_*(...)` literal in the package against this table and
# flags registered types nothing emits.
# ---------------------------------------------------------------------------
SCHEMA: Dict[str, str] = {
    # scheduler core lifecycle (algorithm/hived.py)
    "queued": "gang is waiting; bucket = wait attribution (re-emitted only "
              "on bucket transition)",
    "bind": "gang's placement committed (first member bind of an "
            "incarnation opens its running episode)",
    "preempt_planned": "preemption decided for this gang; victims listed "
                       "(opens/continues a `priority` wait)",
    "released": "gang's allocation fully released (complete, evicted, or "
                "preempted — the cause chain says which)",
    # defrag executor (runtime/scheduler.py, under the scheduler lock)
    "defrag_planned": "migration plan accepted for this waiting gang; "
                      "moves + reserved slice in args",
    "migration_evict": "a mover gang's pods are being evicted (cause = the "
                       "waiter's defrag_planned / grow plan event)",
    "migration_rebound": "a mover re-placed on its reserved target "
                         "(work-preserving: resumed from checkpoint)",
    "migration_done": "every move rebound; the waiter's slice is free",
    "migration_failed": "a move could not re-place; holds released, the "
                        "evicted job resubmits from its checkpoint",
    "migration_aborted": "the job died mid-migration or an operator "
                         "cancelled; holds released",
    "reservation_expired": "a TTL sweep released a hold whose partner "
                           "never came back",
    "backfill_admitted": "a gang rode reserved/idle cells (outcome: "
                         "admitted = preemptible rider, fits-window = "
                         "duration-bounded guaranteed rider)",
    # elastic arm (runtime/scheduler.py)
    "elastic_offer": "a blocked elastic waiter is offered its largest "
                     "feasible shrink rung",
    "elastic_grow_planned": "a degraded gang's full shape fits again; "
                            "grow-migration planned",
    "elastic_grow_done": "grow-promotion landed: the gang runs at full "
                         "shape (closes its elastic_degraded wait)",
    # serving admission/preemption (models/serving.py)
    "serve_submit": "request entered the serving queue",
    "serve_admit": "request admitted to a decode slot (queue wait closed)",
    "serve_shed": "request shed on the queue-wait deadline before it ran",
    "serve_preempt": "stream truncated to relieve KV block-pool exhaustion",
    "serve_finish": "request finished (finish_reason in args)",
    # serving fleet tier (fleet/router.py + fleet/autoscaler.py)
    "fleet_route": "a fleet request leg was routed to a replica (leg = "
                   "prefill/decode; policy in args)",
    "fleet_handoff": "disaggregated prefill->decode KV handoff (mode = "
                     "ship/miss/reprefill; cause = the prefill leg's last "
                     "serve event)",
    "fleet_retry": "a shed/preempted/lost leg was re-routed to another "
                   "replica (the stream restarts from scratch, "
                   "token-exactly for greedy)",
    "fleet_scale": "autoscaler decision (direction, phase = "
                   "pending/added/draining/removed, replica, reason)",
    # request flight recorder (fleet/router.py + models/serving.py):
    # request-scoped, cause-chained TTFT decomposition — note_request_*
    # and note_leg emit these (OBS001 treats each method as the emitter
    # of its implied type)
    "request_submit": "a request flight opened (the TTFT clock's zero "
                      "mark; fleet/<fid> or serve/<rid>)",
    "request_leg": "one closed flight leg (bucket = the REQUEST_LEGS "
                   "name; legs tile the flight, TTFT legs sum to ttft_s)",
    "request_done": "the flight's single terminal: finish reason, "
                    "measured TTFT and the leg-sum gap in args",
    # wait-ETA estimator (obs/eta.py): forecast annotation on a waiting
    # gang's timeline, scored against the realized wait by later PRs
    "eta_forecast": "capacity-without-a-move forecast for a waiting gang "
                    "(etaS/basis/needChips in args; obs/eta.py)",
    # workload supervisor (train.py / parallel/supervisor.py)
    "train_resume": "a training incarnation resumed from a committed "
                    "checkpoint (preemption/crash restart)",
    "train_rollback": "divergence-guard rollback to the last good "
                      "checkpoint",
}

# event types that close a gang's open wait interval when emitted through
# note_phase (bind ends the queue wait; grow ends the degraded wait;
# released ends whatever was open)
_PHASE_CLOSED = "closed"


@dataclass
class Event:
    """One journal event. ``t`` is the monotonic timestamp used for
    durations (``perf_counter`` seconds, or the caller's virtual clock in
    sim contexts); ``ts`` is the wall epoch (0.0 when virtual)."""

    id: int
    gang: str
    type: str
    cause: Optional[int] = None
    bucket: str = ""
    detail: str = ""
    t: float = 0.0
    ts: float = 0.0
    args: Dict[str, Any] = field(default_factory=dict)

    def to_dict(self) -> Dict[str, Any]:
        return {
            "id": self.id,
            "gang": self.gang,
            "type": self.type,
            "cause": self.cause,
            "bucket": self.bucket,
            "detail": self.detail,
            "t": round(self.t, 6),
            "ts": self.ts,
            "args": self.args,
        }


class Journal:
    """Bounded ring of lifecycle events + per-gang wait accounting.

    Instantiable for tests and for the bench's virtual-clock replay; the
    module singleton :data:`JOURNAL` is what the stack shares. ``metrics``
    gates the ``tpu_hive_gang_wait_seconds`` observation so a sim-time
    instance never pollutes the process registry with virtual durations.
    """

    def __init__(self, capacity: int = _DEFAULT_CAPACITY,
                 max_gangs: int = _MAX_GANGS, metrics: bool = True,
                 intervals_per_gang: int = _MAX_INTERVALS_PER_GANG):
        self._lock = lockcheck.make_lock("journal_lock", late=True)
        self._ring: deque = deque(maxlen=capacity)
        # gang -> record; insertion-ordered so eviction drops the oldest
        # closed gang first
        self._gangs: Dict[str, Dict[str, Any]] = {}
        self._max_gangs = max_gangs
        self._intervals_per_gang = intervals_per_gang
        self._seq = 0
        self._next_tid = 1000  # stable Perfetto lane per gang
        self.enabled = False
        self.metrics = metrics
        self.evicted = 0  # events displaced by the ring bound
        self._spool = None
        self._spool_path = ""

    # -- internal (caller holds self._lock) -----------------------------
    def _record(self, gang: str, at: float) -> Dict[str, Any]:
        rec = self._gangs.get(gang)
        if rec is None:
            if len(self._gangs) >= self._max_gangs:
                # evict the oldest CLOSED gang; live gangs are never dropped
                for name, r in list(self._gangs.items()):
                    if r["phase"] == _PHASE_CLOSED:
                        del self._gangs[name]
                        break
            self._next_tid += 1
            rec = {
                "tid": self._next_tid,
                "phase": "new",
                "wait": None,  # (bucket, start_t) while a wait is open
                "waits": {},  # bucket -> closed seconds
                "intervals": [],  # (bucket, start, end), capped
                "last": None,  # last event id (the auto-chain cause)
                "last_type": "",
                "first_t": at,
                "last_t": at,
                "events": 0,
                "flight": None,  # request-flight record (see _flight)
            }
            self._gangs[gang] = rec
        return rec

    def _append(self, etype: str, gang: str, cause: Optional[int],
                bucket: str, detail: str, at: Optional[float],
                args: Dict[str, Any]) -> int:
        if etype not in SCHEMA:
            raise ValueError(
                f"{etype!r} is not a registered journal event type — add it "
                f"to obs/journal.py SCHEMA (OBS001)")
        virtual = at is not None
        t = time.perf_counter() if at is None else at
        with self._lock:
            rec = self._record(gang, t)
            self._seq += 1
            if cause is None:
                cause = rec["last"]
            ev = Event(id=self._seq, gang=gang, type=etype, cause=cause,
                       bucket=bucket, detail=detail, t=t,
                       ts=0.0 if virtual else time.time(), args=args)
            if len(self._ring) == self._ring.maxlen:
                self.evicted += 1
            self._ring.append(ev)
            rec["last"] = ev.id
            rec["last_type"] = etype
            rec["last_t"] = t
            rec["events"] += 1
            spool = self._spool
            if spool is not None:
                try:
                    spool.write(json.dumps(ev.to_dict()) + "\n")
                    spool.flush()  # crash-safe: every line survives kill -9
                except OSError:
                    self._spool = None  # a dead spool must not fail emit
            return ev.id

    def _close_wait(self, rec: Dict[str, Any], at: float) -> None:
        open_wait = rec["wait"]
        if open_wait is None:
            return
        bucket, start = open_wait
        rec["wait"] = None
        dur = max(0.0, at - start)
        rec["waits"][bucket] = rec["waits"].get(bucket, 0.0) + dur
        if len(rec["intervals"]) < self._intervals_per_gang:
            rec["intervals"].append((bucket, start, at))
        if self.metrics:
            from hivedscheduler_tpu.runtime.metrics import REGISTRY
            REGISTRY.observe("tpu_hive_gang_wait_seconds", dur,
                             reason=bucket)

    # -- emit API --------------------------------------------------------
    def emit(self, etype: str, gang: str, cause: Optional[int] = None,
             bucket: str = "", detail: str = "", at: Optional[float] = None,
             **args: Any) -> Optional[int]:
        """Append one event (no phase bookkeeping). Returns the event id,
        or None when disabled — the single-check contract — or while this
        thread is inside a suppressed (probe) transaction."""
        if not self.enabled or suppressed():
            return None
        return self._append(etype, gang, cause, bucket, detail, at, args)

    def note_wait(self, gang: str, bucket: str, detail: str = "",
                  cause: Optional[int] = None, at: Optional[float] = None,
                  etype: str = "queued", **args: Any) -> Optional[int]:
        """Open (or re-attribute) a gang's wait. Same bucket: no event, the
        interval continues. Bucket change: the previous interval closes
        (accumulated + observed) and a new one opens at ``at``."""
        if not self.enabled or suppressed():
            return None
        if bucket not in WAIT_BUCKETS:
            raise ValueError(
                f"{bucket!r} is not a registered wait-attribution bucket — "
                f"add it to obs/journal.py WAIT_BUCKETS (OBS001)")
        t = time.perf_counter() if at is None else at
        with self._lock:
            rec = self._record(gang, t)
            open_wait = rec["wait"]
            if open_wait is not None and open_wait[0] == bucket:
                return rec["last"]
            self._close_wait(rec, t)
            rec["wait"] = (bucket, t)
            if rec["phase"] != "running":
                rec["phase"] = "waiting"
        return self._append(etype, gang, cause, bucket, detail, at, args)

    def note_phase(self, gang: str, phase: str, etype: str,
                   cause: Optional[int] = None, at: Optional[float] = None,
                   **args: Any) -> Optional[int]:
        """Transition a gang's lifecycle phase (``running`` / ``closed``),
        closing any open wait interval. Idempotent: a repeat transition to
        the current phase emits nothing (so every member pod of a gang can
        report the bind and only the first opens the episode)."""
        if not self.enabled or suppressed():
            return None
        t = time.perf_counter() if at is None else at
        with self._lock:
            rec = self._gangs.get(gang)
            if rec is None:
                if phase == _PHASE_CLOSED:
                    # release of a gang the journal never saw open (e.g.
                    # enabled mid-flight): nothing to close, keep the
                    # open->close invariant vacuously true
                    return None
                rec = self._record(gang, t)
            if rec["phase"] == phase and rec["wait"] is None:
                # idempotent repeat (e.g. every member pod reporting the
                # gang bind) — but a same-phase transition that closes an
                # open wait (elastic_grow_done while running-degraded)
                # still emits
                return rec["last"]
            self._close_wait(rec, t)
            rec["phase"] = phase
        return self._append(etype, gang, cause, "", "", at, args)

    # -- request flight recorder (TTFT leg attribution) ------------------
    def _flight(self, rec: Dict[str, Any], at: float,
                opened: bool) -> Dict[str, Any]:
        fl = rec["flight"]
        if fl is None:
            fl = rec["flight"] = {
                "t0": at,       # flight zero mark (= submit time when opened)
                "mark": at,     # end of the last attributed leg
                "legs": [],     # (leg, start, end), contiguous, capped
                "dropped_legs": 0,
                "terminals": 0,
                "terminal": None,       # finish reason once terminal
                "first_token_t": None,
                "done_t": None,
                "ttft_gap": None,       # ttft-leg sum minus measured ttft
                # False when the recorder was enabled mid-flight (first
                # contact was a leg, not the submit): the TTFT gap is then
                # unknowable and note_request_done skips the accounting
                "opened": opened,
            }
        return fl

    def note_request_submit(self, req: str, at: Optional[float] = None,
                            cause: Optional[int] = None,
                            **args: Any) -> Optional[int]:
        """Open (or re-open — a fresh incarnation resets the record) a
        request flight at ``at``: the zero mark every later leg and the
        measured TTFT are anchored to."""
        if not self.enabled or suppressed():
            return None
        t = time.perf_counter() if at is None else at
        with self._lock:
            rec = self._record(req, t)
            rec["flight"] = None  # re-submission = a fresh incarnation
            self._flight(rec, t, opened=True)
        return self._append("request_submit", req, cause, "", "", at, args)

    def note_leg(self, req: str, leg: str, at: Optional[float] = None,
                 cause: Optional[int] = None, **args: Any) -> Optional[int]:
        """Attribute the interval from the flight's previous mark to
        ``at`` to ``leg`` (one of :data:`REQUEST_LEGS`) and advance the
        mark — legs are exclusive and non-overlapping by construction, so
        instrument *coverage* is what the sum-to-TTFT assertion checks."""
        if not self.enabled or suppressed():
            return None
        if leg not in REQUEST_LEGS:
            raise ValueError(
                f"{leg!r} is not a registered request leg — add it to "
                f"obs/journal.py REQUEST_LEGS (OBS001)")
        t = time.perf_counter() if at is None else at
        with self._lock:
            rec = self._record(req, t)
            fl = self._flight(rec, t, opened=False)
            start = fl["mark"]
            if t < start:  # defensive: a late-arriving mark never
                t = start  # produces an overlapping/negative leg
            if len(fl["legs"]) < _MAX_LEGS_PER_REQUEST:
                fl["legs"].append((leg, start, t))
            else:
                fl["dropped_legs"] += 1
            fl["mark"] = t
            if self.metrics:
                from hivedscheduler_tpu.runtime.metrics import REGISTRY
                REGISTRY.observe("tpu_hive_request_leg_seconds",
                                 max(0.0, t - start), leg=leg)
        return self._append("request_leg", req, cause, leg, "", at,
                            dict(args, durS=round(t - start, 6)))

    def note_request_done(self, req: str, reason: str,
                          first_token_at: Optional[float] = None,
                          at: Optional[float] = None,
                          cause: Optional[int] = None,
                          **args: Any) -> Optional[int]:
        """The flight's single terminal. ``first_token_at`` (the same
        clock value the caller's ``ttft_s`` derives from) closes the TTFT
        accounting: the legs ending at or before it must sum to
        ``first_token_at - t0`` — the stored ``ttft_gap`` is the deficit,
        and a non-zero gap means an uninstrumented segment on the request
        path (check_requests and the bench assert it is ~0)."""
        if not self.enabled or suppressed():
            return None
        t = time.perf_counter() if at is None else at
        with self._lock:
            rec = self._record(req, t)
            fl = self._flight(rec, t, opened=False)
            fl["terminals"] += 1
            fl["terminal"] = reason
            fl["done_t"] = t
            gap = None
            if first_token_at is not None:
                fl["first_token_t"] = first_token_at
                if fl["opened"]:
                    ttft = first_token_at - fl["t0"]
                    sum_legs = sum(
                        e - s for _l, s, e in fl["legs"]
                        if e <= first_token_at + 1e-9)
                    gap = fl["ttft_gap"] = sum_legs - ttft
            rec["phase"] = _PHASE_CLOSED  # eviction-eligible, no extra event
        extra = dict(args, finishReason=reason)
        if gap is not None:
            extra["ttftGapS"] = round(gap, 9)
        return self._append("request_done", req, cause, "", "", at, extra)

    @staticmethod
    def _dominant_leg_of(fl: Dict[str, Any]) -> str:
        """The leg holding the most TTFT time (all legs when the flight
        never emitted a token) — the SLO violation-attribution key."""
        ft = fl["first_token_t"]
        totals: Dict[str, float] = {}
        for leg, s, e in fl["legs"]:
            if ft is None or e <= ft + 1e-9:
                totals[leg] = totals.get(leg, 0.0) + (e - s)
        if not totals:
            return ""
        return max(totals.items(), key=lambda kv: (kv[1], kv[0]))[0]

    def request_dominant_leg(self, req: str) -> str:
        with self._lock:
            rec = self._gangs.get(req)
            if rec is None or rec["flight"] is None:
                return ""
            return self._dominant_leg_of(rec["flight"])

    def flights(self) -> Dict[str, Dict[str, Any]]:
        """Copy-on-read raw flight records — the invariant checks' and
        the bench's attribution source."""
        with self._lock:
            out = {}
            for gang, rec in self._gangs.items():
                fl = rec["flight"]
                if fl is None:
                    continue
                out[gang] = dict(fl, legs=list(fl["legs"]))
            return out

    def requests(self) -> List[Dict[str, Any]]:
        """Per-request flight summaries, most recently active first (the
        ``/v1/inspect/requests`` payload)."""
        with self._lock:
            out = []
            for gang, rec in self._gangs.items():
                fl = rec["flight"]
                if fl is None:
                    continue
                legs: Dict[str, float] = {}
                for leg, s, e in fl["legs"]:
                    legs[leg] = legs.get(leg, 0.0) + (e - s)
                ft = fl["first_token_t"]
                out.append({
                    "request": gang,
                    "terminal": fl["terminal"],
                    "legs": {k: round(v, 6)
                             for k, v in sorted(legs.items())},
                    "dominantLeg": self._dominant_leg_of(fl),
                    "ttftS": (None if ft is None or not fl["opened"]
                              else round(ft - fl["t0"], 6)),
                    "ttftGapS": (None if fl["ttft_gap"] is None
                                 else round(fl["ttft_gap"], 9)),
                    "wallS": (None if fl["done_t"] is None
                              else round(fl["done_t"] - fl["t0"], 6)),
                    "lastT": rec["last_t"],
                })
        out.sort(key=lambda r: r.pop("lastT"), reverse=True)
        return out

    def request_timeline(self, req: str) -> Dict[str, Any]:
        """One request's retained events in causal order plus its leg
        decomposition (the ``/v1/inspect/requests/<id>/timeline``
        payload)."""
        with self._lock:
            events = [e.to_dict() for e in self._ring if e.gang == req]
            rec = self._gangs.get(req)
            fl = rec["flight"] if rec is not None else None
            legs = summary = None
            if fl is not None:
                legs = [{"leg": leg, "start": round(s, 6),
                         "end": round(e, 6), "durS": round(e - s, 6)}
                        for leg, s, e in fl["legs"]]
                ft = fl["first_token_t"]
                summary = {
                    "terminal": fl["terminal"],
                    "dominantLeg": self._dominant_leg_of(fl),
                    "ttftS": (None if ft is None or not fl["opened"]
                              else round(ft - fl["t0"], 6)),
                    "ttftGapS": (None if fl["ttft_gap"] is None
                                 else round(fl["ttft_gap"], 9)),
                    "droppedLegs": fl["dropped_legs"],
                }
        return {"request": req, "events": events, "legs": legs,
                "summary": summary, "ringEvicted": self.evicted}

    def last_id(self, gang: str) -> Optional[int]:
        """The gang's most recent event id (for explicit cross-gang
        causes), or None."""
        with self._lock:
            rec = self._gangs.get(gang)
            return None if rec is None else rec["last"]

    def close_all(self, at: float) -> None:
        """Close every open wait interval at ``at`` (sim end-of-replay)."""
        with self._lock:
            for rec in self._gangs.values():
                self._close_wait(rec, at)

    # -- read API (copy-on-read snapshots) -------------------------------
    def snapshot(self) -> List[Event]:
        with self._lock:
            return list(self._ring)

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    def gangs(self) -> List[Dict[str, Any]]:
        """Per-gang summaries, most recently active first."""
        with self._lock:
            out = []
            for gang, rec in self._gangs.items():
                open_wait = rec["wait"]
                out.append({
                    "gang": gang,
                    "phase": rec["phase"],
                    "events": rec["events"],
                    "lastType": rec["last_type"],
                    "firstT": round(rec["first_t"], 6),
                    "lastT": round(rec["last_t"], 6),
                    "waits": {b: round(s, 6)
                              for b, s in sorted(rec["waits"].items())},
                    "openWait": None if open_wait is None else {
                        "bucket": open_wait[0],
                        "since": round(open_wait[1], 6),
                    },
                })
        out.sort(key=lambda r: r["lastT"], reverse=True)
        return out

    def timeline(self, gang: str) -> Dict[str, Any]:
        """The gang's retained events in causal (id) order, plus its wait
        summary. Events older than the ring bound are gone — ``evicted``
        says whether the ring ever wrapped."""
        with self._lock:
            events = [e.to_dict() for e in self._ring if e.gang == gang]
            rec = self._gangs.get(gang)
            summary = None
            if rec is not None:
                open_wait = rec["wait"]
                summary = {
                    "phase": rec["phase"],
                    "waits": {b: round(s, 6)
                              for b, s in sorted(rec["waits"].items())},
                    "openWait": None if open_wait is None else {
                        "bucket": open_wait[0],
                        "since": round(open_wait[1], 6),
                    },
                }
        return {"gang": gang, "events": events, "summary": summary,
                "ringEvicted": self.evicted}

    def wait_intervals(self) -> List[Tuple[str, str, float, float]]:
        """Every CLOSED wait interval: (gang, bucket, start, end) — the
        bench replay's attribution source."""
        with self._lock:
            return [
                (gang, bucket, start, end)
                for gang, rec in self._gangs.items()
                for bucket, start, end in rec["intervals"]
            ]

    def wait_totals(self) -> Dict[str, float]:
        """Closed wait seconds per bucket, summed over all gangs."""
        totals: Dict[str, float] = {}
        for _gang, bucket, start, end in self.wait_intervals():
            totals[bucket] = totals.get(bucket, 0.0) + (end - start)
        return totals

    def chrome_events(self, t0: float) -> List[Dict[str, Any]]:
        """Per-gang Perfetto tracks: one named thread lane per gang, an
        instant per journal event and an X span per closed wait interval.
        ``t0`` is the tracer's perf_counter anchor so the lanes align with
        the span tracer's timeline."""
        with self._lock:
            lanes = {gang: rec["tid"] for gang, rec in self._gangs.items()}
            requests = {gang for gang, rec in self._gangs.items()
                        if rec["flight"] is not None}
            intervals = [
                (rec["tid"], bucket, start, end)
                for rec in self._gangs.values()
                for bucket, start, end in rec["intervals"]
            ]
            legs = [
                (rec["tid"], leg, start, end)
                for rec in self._gangs.values()
                if rec["flight"] is not None
                for leg, start, end in rec["flight"]["legs"]
            ]
            events = list(self._ring)
        out: List[Dict[str, Any]] = []
        for gang, tid in lanes.items():
            kind = "request" if gang in requests else "gang"
            out.append({"name": "thread_name", "ph": "M", "pid": 1,
                        "tid": tid, "ts": 0,
                        "args": {"name": f"{kind} {gang}"}})
        for ev in events:
            tid = lanes.get(ev.gang)
            if tid is None:
                continue  # gang record evicted; no lane to draw on
            args = dict(ev.args)
            args.update(id=ev.id, cause=ev.cause)
            if ev.bucket:
                args["bucket"] = ev.bucket
            out.append({"name": ev.type, "ph": "i", "s": "t",
                        "cat": "journal", "ts": (ev.t - t0) * 1e6,
                        "pid": 1, "tid": tid, "args": args})
        for tid, bucket, start, end in intervals:
            out.append({"name": f"wait:{bucket}", "ph": "X",
                        "cat": "journal", "ts": (start - t0) * 1e6,
                        "dur": max(0.0, (end - start) * 1e6),
                        "pid": 1, "tid": tid, "args": {"bucket": bucket}})
        for tid, leg, start, end in legs:
            out.append({"name": f"leg:{leg}", "ph": "X",
                        "cat": "journal", "ts": (start - t0) * 1e6,
                        "dur": max(0.0, (end - start) * 1e6),
                        "pid": 1, "tid": tid, "args": {"leg": leg}})
        return out

    # -- lifecycle -------------------------------------------------------
    def open_spool(self, path: str) -> None:
        self._spool = open(path, "a", encoding="utf-8")
        self._spool_path = path

    def close_spool(self) -> None:
        if self._spool is not None:
            try:
                self._spool.close()
            except OSError:
                pass
            self._spool = None

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()
            self._gangs.clear()
            self._seq = 0
            self.evicted = 0


JOURNAL = Journal()

# -- thread-local suppression ------------------------------------------------
# The defrag what-if probes (defrag/probe.py) run real schedule/delete
# transactions on the live cell trees and roll them back bit-exactly; their
# churn never really happened, so it must not enter the journal. Suppression
# is PER-THREAD: the probe always runs under the scheduler lock on one
# thread, while serving engines keep journaling from theirs.

_tls = threading.local()


class _Suppress:
    __slots__ = ()

    def __enter__(self) -> "_Suppress":
        _tls.depth = getattr(_tls, "depth", 0) + 1
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        _tls.depth -= 1
        return False


_SUPPRESS = _Suppress()


def suppress() -> _Suppress:
    """``with journal.suppress(): ...`` — mute this thread's emissions
    (what-if probe transactions)."""
    return _SUPPRESS


def suppressed() -> bool:
    return getattr(_tls, "depth", 0) > 0


def enabled() -> bool:
    return JOURNAL.enabled


def enable(capacity: Optional[int] = None,
           spool_path: Optional[str] = None) -> None:
    """Turn the journal on (optionally resizing — which resets — the ring,
    and/or opening a JSONL spool)."""
    global JOURNAL
    if capacity is not None:
        JOURNAL.close_spool()
        JOURNAL = Journal(capacity)
    if spool_path:
        JOURNAL.open_spool(spool_path)
    JOURNAL.enabled = True


def disable() -> None:
    JOURNAL.enabled = False
    JOURNAL.close_spool()


def emit(etype: str, gang: str, cause: Optional[int] = None,
         bucket: str = "", detail: str = "", at: Optional[float] = None,
         **args: Any) -> Optional[int]:
    return JOURNAL.emit(etype, gang, cause=cause, bucket=bucket,
                        detail=detail, at=at, **args)


def note_wait(gang: str, bucket: str, detail: str = "",
              cause: Optional[int] = None, at: Optional[float] = None,
              etype: str = "queued", **args: Any) -> Optional[int]:
    return JOURNAL.note_wait(gang, bucket, detail=detail, cause=cause,
                             at=at, etype=etype, **args)


def note_phase(gang: str, phase: str, etype: str,
               cause: Optional[int] = None, at: Optional[float] = None,
               **args: Any) -> Optional[int]:
    return JOURNAL.note_phase(gang, phase, etype, cause=cause, at=at,
                              **args)


def note_request_submit(req: str, at: Optional[float] = None,
                        cause: Optional[int] = None,
                        **args: Any) -> Optional[int]:
    return JOURNAL.note_request_submit(req, at=at, cause=cause, **args)


def note_leg(req: str, leg: str, at: Optional[float] = None,
             cause: Optional[int] = None, **args: Any) -> Optional[int]:
    return JOURNAL.note_leg(req, leg, at=at, cause=cause, **args)


def note_request_done(req: str, reason: str,
                      first_token_at: Optional[float] = None,
                      at: Optional[float] = None,
                      cause: Optional[int] = None,
                      **args: Any) -> Optional[int]:
    return JOURNAL.note_request_done(req, reason,
                                     first_token_at=first_token_at,
                                     at=at, cause=cause, **args)


# ---------------------------------------------------------------------------
# wait-reason classifier: the algorithm ladder's human reason strings ->
# attribution buckets. Substring-keyed on the stable fragments of the
# ladder's messages (the same fragments GRD001 pins for the error guards);
# anything unmatched lands in `unknown` so drift is visible, never silent.
# ---------------------------------------------------------------------------

def classify_wait(reason: str) -> str:
    r = (reason or "").lower()
    if "reservation" in r:
        return "reservation_hold"
    if "bad node" in r or "doomed" in r or "bad or non-suggested" in r:
        return "bad_hardware"
    if "insufficient free cell in the vc" in r or "insufficient quota" in r:
        return "vc_quota"
    if "non-suggested" in r:
        return "reservation_hold"
    if "insufficient capacity" in r:
        return "fragmentation"
    if "preempt" in r:
        return "priority"
    return "unknown"


if os.environ.get("HIVED_JOURNAL") == "1":  # ad-hoc opt-in, like HIVED_TRACE
    enable()
