"""Declared serving SLOs: windowed quantiles, error-budget burn rate,
and violation attribution by dominant request leg.

PR 12 made "goodput at a p99 TTFT ceiling" the serving headline, but the
ceiling lived only in the bench: the autoscaler hand-sorted a ring of
recent TTFTs (``fleet/router.py`` pre-ISSUE-13) while nothing in the
running system knew what the *objective* was, how fast its error budget
was burning, or which leg of the request path caused a violation. This
module is that layer:

- :class:`SLObjective` — one declared objective: a quantile ceiling over
  a series (``ttft`` or ``tpot``), optionally scoped to one priority
  class. Declared in config (``FleetConfig`` ``slo_*`` keys) or the
  serve CLI (``--slo-ttft-p99`` / ``--slo-window-s``).
- :class:`SLOTracker` — a bounded, windowed observation ring shared by
  three consumers so they all report the SAME number:

  1. ``FleetAutoscaler`` reads ``quantile(0.95, "ttft")`` as its TTFT
     up-pressure signal (replacing the ad-hoc sort — the scaling signal
     and the reported SLO are one computation);
  2. ``GET /v1/inspect/slo`` serves :meth:`SLOTracker.snapshot`
     (copy-on-read);
  3. the exposition surface: ``tpu_hive_slo_ttft_p99_seconds`` /
     ``tpu_hive_slo_burn_rate`` gauges and the
     ``tpu_hive_slo_violations_total{objective=,leg=}`` counter.

**Burn-rate math** (the SRE error-budget convention): an objective
"quantile q of the series stays under the ceiling" grants a violation
budget of ``1 - q`` (p99 → 1% of requests may exceed the ceiling). Over
the window, ``burn = violating_fraction / (1 - q)``: burn 1.0 consumes
the budget exactly as fast as allowed, burn 2.0 exhausts a month's
budget in half a month — the standard multi-window alerting input.

**Violation attribution**: each observation carries the request's
dominant leg (``obs.journal.request_dominant_leg`` — the
:data:`~hivedscheduler_tpu.obs.journal.REQUEST_LEGS` name holding the
most TTFT time), so "the p99 ceiling is violated" comes with "and the
time went to ``admission_wait``" instead of a guess. Empty when the
flight recorder is off (attribution degrades, tracking does not).

Quantile convention: ``sorted(values)[int(q * (len - 1))]`` — exactly
the index the autoscaler's hand-rolled p95 used, so replacing the sort
is decision-identical (pinned by tests/test_request_flights.py).

Threading: ``observe`` is called under the fleet router lock and reads
come from the webserver/autoscaler — ``slo_lock`` is a leaf between
``fleet_router_lock`` and ``metrics_lock`` in the lock hierarchy (the
only acquisition under it is the metrics leaf).
"""

from __future__ import annotations

import dataclasses
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

from hivedscheduler_tpu.common import envflags, lockcheck

_DEFAULT_CAP = 256  # observations retained per series (the old ring size)


def default_window_s() -> float:
    """``HIVED_SLO_WINDOW_S``: the default sliding window for quantiles
    and burn rates (0 disables time-windowing — pure ring semantics)."""
    return float(envflags.get("HIVED_SLO_WINDOW_S", "60"))


@dataclasses.dataclass(frozen=True)
class SLObjective:
    """One declared objective: ``quantile`` of ``series`` must stay at or
    under ``ceiling_s`` (seconds). ``priority`` scopes the objective to
    one priority class (None = all traffic)."""

    series: str = "ttft"        # "ttft" | "tpot"
    quantile: float = 0.99
    ceiling_s: float = 0.0      # must be > 0 for a real objective
    priority: Optional[int] = None

    def __post_init__(self):
        if self.series not in ("ttft", "tpot"):
            raise ValueError(f"unknown SLO series {self.series!r} "
                             f"(choose ttft or tpot)")
        if not 0.0 < self.quantile < 1.0:
            raise ValueError(f"SLO quantile must be in (0, 1), got "
                             f"{self.quantile}")
        if self.ceiling_s <= 0:
            raise ValueError(f"SLO ceiling must be > 0 s, got "
                             f"{self.ceiling_s}")

    @property
    def name(self) -> str:
        prio = "" if self.priority is None else f"/p{self.priority}"
        return f"{self.series}_p{round(self.quantile * 100):d}{prio}"

    def to_dict(self) -> Dict[str, Any]:
        return {"name": self.name, "series": self.series,
                "quantile": self.quantile, "ceilingS": self.ceiling_s,
                "priority": self.priority}


class SLOTracker:
    """Bounded, windowed TTFT/TPOT observations + objective accounting.

    ``window_s`` None reads :func:`default_window_s`; 0 disables time
    windowing (last-``cap`` ring semantics — what the autoscaler pin test
    and the pre-ISSUE-13 deque used). ``metrics=False`` keeps a
    virtual-clock instance (bench replays, fake-clock tests) out of the
    process metrics registry, mirroring ``Journal(metrics=...)``.
    """

    def __init__(self, objectives: Tuple[SLObjective, ...] = (),
                 window_s: Optional[float] = None, cap: int = _DEFAULT_CAP,
                 clock=time.perf_counter, metrics: bool = True):
        self._lock = lockcheck.make_lock("slo_lock")
        self.objectives = tuple(objectives)
        self.window_s = default_window_s() if window_s is None else window_s
        self._clock = clock
        self.metrics = metrics
        # series -> deque of (t, value, priority, dominant_leg)
        self._obs: Dict[str, deque] = {
            "ttft": deque(maxlen=cap), "tpot": deque(maxlen=cap)}
        # objective name -> {leg: violation count} (lifetime)
        self.violations: Dict[str, Dict[str, int]] = {
            o.name: {} for o in self.objectives}

    # -- write -----------------------------------------------------------
    def observe(self, series: str, value: float, priority: int = 0,
                leg: str = "", at: Optional[float] = None) -> None:
        """Record one finished request's ``series`` seconds. ``leg`` is
        the request's dominant TTFT leg ("" when the flight recorder is
        off). Updates the objective violation books and — for a real
        (``metrics=True``) tracker — the slo gauges/counters."""
        t = self._clock() if at is None else at
        with self._lock:
            self._obs[series].append((t, value, priority, leg))
            violated: List[str] = []
            for o in self.objectives:
                if o.series != series or value <= o.ceiling_s:
                    continue
                if o.priority is not None and priority != o.priority:
                    continue
                by_leg = self.violations[o.name]
                key = leg or "unattributed"
                by_leg[key] = by_leg.get(key, 0) + 1
                violated.append(o.name)
            if self.metrics:
                from hivedscheduler_tpu.runtime.metrics import REGISTRY
                for name in violated:
                    REGISTRY.inc("tpu_hive_slo_violations_total",
                                 objective=name, leg=leg or "unattributed")
                REGISTRY.set_gauge("tpu_hive_slo_ttft_p99_seconds",
                                   self._quantile_locked(0.99, "ttft", t))
                burns = [self._burn_locked(o, t) for o in self.objectives]
                REGISTRY.set_gauge(
                    "tpu_hive_slo_burn_rate",
                    max((b for b in burns if b is not None), default=0.0))

    # -- read ------------------------------------------------------------
    def _window_locked(self, series: str, now: float,
                       priority: Optional[int] = None):
        cutoff = now - self.window_s if self.window_s > 0 else None
        return [
            (t, v, p, leg) for t, v, p, leg in self._obs[series]
            if (cutoff is None or t >= cutoff)
            and (priority is None or p == priority)
        ]

    def _quantile_locked(self, q: float, series: str, now: float,
                         priority: Optional[int] = None) -> float:
        vals = sorted(v for _t, v, _p, _leg
                      in self._window_locked(series, now, priority))
        if not vals:
            return 0.0
        return vals[int(q * (len(vals) - 1))]

    def quantile(self, q: float, series: str = "ttft",
                 now: Optional[float] = None,
                 priority: Optional[int] = None) -> float:
        """Windowed quantile (0.0 with no observations) — the
        autoscaler's up-pressure signal and the inspect payload share
        this exact computation."""
        t = self._clock() if now is None else now
        with self._lock:
            return self._quantile_locked(q, series, t, priority)

    def _burn_locked(self, o: SLObjective, now: float) -> Optional[float]:
        obs = self._window_locked(o.series, now, o.priority)
        if not obs:
            return None
        viol = sum(1 for _t, v, _p, _leg in obs if v > o.ceiling_s)
        return (viol / len(obs)) / max(1e-9, 1.0 - o.quantile)

    def burn_rate(self, objective: SLObjective,
                  now: Optional[float] = None) -> Optional[float]:
        """Error-budget burn over the window: violating fraction divided
        by the budget fraction ``1 - q`` (None with no observations)."""
        t = self._clock() if now is None else now
        with self._lock:
            return self._burn_locked(objective, t)

    def snapshot(self, now: Optional[float] = None) -> Dict[str, Any]:
        """The ``/v1/inspect/slo`` payload (copy-on-read)."""
        t = self._clock() if now is None else now
        with self._lock:
            series = {}
            for name in ("ttft", "tpot"):
                obs = self._window_locked(name, t)
                series[name] = {
                    "count": len(obs),
                    "p50": round(self._quantile_locked(0.50, name, t), 6),
                    "p95": round(self._quantile_locked(0.95, name, t), 6),
                    "p99": round(self._quantile_locked(0.99, name, t), 6),
                }
            objectives = []
            for o in self.objectives:
                obs = self._window_locked(o.series, t, o.priority)
                viol = sum(1 for _t, v, _p, _leg in obs
                           if v > o.ceiling_s)
                burn = self._burn_locked(o, t)
                objectives.append(dict(
                    o.to_dict(),
                    value=round(self._quantile_locked(
                        o.quantile, o.series, t, o.priority), 6),
                    windowCount=len(obs),
                    windowViolations=viol,
                    compliance=(None if not obs
                                else round(1.0 - viol / len(obs), 6)),
                    burnRate=None if burn is None else round(burn, 4),
                    attribution=dict(sorted(
                        self.violations[o.name].items())),
                ))
        return {"windowS": self.window_s, "series": series,
                "objectives": objectives}


def objectives_from_knobs(ttft_p99_s: float = 0.0, tpot_p95_s: float = 0.0,
                          per_priority_ttft_p99: Optional[
                              Dict[int, float]] = None,
                          ) -> Tuple[SLObjective, ...]:
    """Build the objective tuple from the flat config/CLI knobs (0 = the
    objective is not declared)."""
    out: List[SLObjective] = []
    if ttft_p99_s > 0:
        out.append(SLObjective("ttft", 0.99, ttft_p99_s))
    if tpot_p95_s > 0:
        out.append(SLObjective("tpot", 0.95, tpot_p95_s))
    for prio, ceiling in sorted((per_priority_ttft_p99 or {}).items()):
        if ceiling > 0:
            out.append(SLObjective("ttft", 0.99, ceiling, priority=prio))
    return tuple(out)
