"""Migration-aware wait-ETA estimator (ROADMAP item 1, read-only half).

"When would this waiting gang get capacity **without a move**?" — the
planner's migrate-vs-wait scoring, elastic grow timing, and SLO-aware
victim selection (ROADMAP item 4b) all want the same forecast. This
module lands it as an *observability surface first*: a pure estimator
over the capacity ledger's running-gang ages and completed-gang
durations plus the defrag reservations' TTL deadlines, served at
``GET /v1/inspect/gangs/<id>/eta`` and recorded as a journal annotation
(``eta_forecast``) so later PRs can score planner/elastic decisions
against realized waits. No consumer changes behavior on it yet.

The forecast is deliberately simple and *always finite*:

1. **idle-now** — enough diagnosed-idle chips already exist: ETA 0 (the
   gang is blocked by quota/fragmentation/reservations, not capacity —
   exactly the case a migration or backfill exists to fix; the forecast
   says "without a move you'd start now if the chips were reachable").
2. **release-projection** — walk projected gang completions in time
   order, accumulating freed chips (plus reservation-held chips at their
   TTL deadlines) until the need is covered. A running gang's expected
   remaining time is ``median(completed durations) - age`` (the ledger
   supplies both), floored at half the expectation for overdue gangs —
   an overdue gang is expected to finish within another half-median, a
   documented heuristic, not a guarantee.
3. **horizon-fallback** — the projection never covers the need (the gang
   is bigger than what completions can free): the last projected release
   plus one full expected duration. Finite by construction; the basis
   field says the number is a horizon, not a projection.

Forecast error is reported honestly wherever a realized wait exists
(the bench replay records forecast-vs-realized per admitted gang in the
driver artifact); there is no accuracy bar yet.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Sequence, Tuple

from hivedscheduler_tpu.common import envflags
from hivedscheduler_tpu.obs import journal

# expected run time used before any gang has completed (seconds live,
# trace time units in the bench's virtual-clock replay)
DEFAULT_RUN_S = float(envflags.get("HIVED_ETA_DEFAULT_RUN_S", "300")
                      or 300)


@dataclasses.dataclass
class WaitEta:
    """One forecast: how long until ``need_chips`` free up without a
    migration, and what the number is based on."""

    gang: str
    need_chips: int
    eta_s: float
    basis: str              # idle-now | release-projection | horizon-fallback
    idle_chips: int
    running_gangs: int
    expected_run_s: float   # the per-gang duration expectation used
    projected_releases: int  # completions the projection consumed

    def to_dict(self) -> Dict[str, Any]:
        return {
            "gang": self.gang,
            "needChips": self.need_chips,
            "etaS": round(self.eta_s, 6),
            "basis": self.basis,
            "idleChips": self.idle_chips,
            "runningGangs": self.running_gangs,
            "expectedRunS": round(self.expected_run_s, 6),
            "projectedReleases": self.projected_releases,
        }


def _expected_run(completed_durations: Sequence[float],
                  default_run_s: float) -> float:
    xs = sorted(d for d in completed_durations if d > 0)
    if not xs:
        return default_run_s
    return xs[len(xs) // 2]


def estimate(
    gang: str,
    need_chips: int,
    idle_chips: int,
    running: Sequence[Tuple[str, int, float, str]],
    reserved: Sequence[Tuple[float, int]] = (),
    completed_durations: Sequence[float] = (),
    default_run_s: Optional[float] = None,
) -> WaitEta:
    """Pure forecast. ``running`` is the ledger's ``running_gangs()``
    shape — (gang, chips, age_s, vc); ``reserved`` is (release_eta_s,
    chips) per reservation hold (TTL deadline relative to now). Returns
    a finite ETA for every input."""
    default = DEFAULT_RUN_S if default_run_s is None else default_run_s
    expect = _expected_run(completed_durations, default)
    if idle_chips >= need_chips:
        return WaitEta(gang, need_chips, 0.0, "idle-now", idle_chips,
                       len(running), expect, 0)
    releases: List[Tuple[float, int]] = []
    for name, chips, age_s, _vc in running:
        if name == gang:
            continue  # a degraded incarnation of the waiter frees nothing
        remaining = expect - age_s
        if remaining <= 0.0:
            remaining = expect * 0.5  # overdue: another half-expectation
        releases.append((remaining, chips))
    releases.extend((max(0.0, eta), chips) for eta, chips in reserved)
    releases.sort()
    acc = idle_chips
    used = 0
    for t, chips in releases:
        acc += chips
        used += 1
        if acc >= need_chips:
            return WaitEta(gang, need_chips, t, "release-projection",
                           idle_chips, len(running), expect, used)
    horizon = (releases[-1][0] if releases else 0.0) + expect
    return WaitEta(gang, need_chips, horizon, "horizon-fallback",
                   idle_chips, len(running), expect, used)


def record(forecast: WaitEta, jr=None,
           at: Optional[float] = None) -> None:
    """Journal the forecast as an annotation on the waiting gang's
    timeline, so later PRs can score it against the realized wait."""
    args = dict(etaS=round(forecast.eta_s, 6), basis=forecast.basis,
                needChips=forecast.need_chips,
                idleChips=forecast.idle_chips)
    if jr is None:
        journal.emit("eta_forecast", forecast.gang, at=at, **args)
    else:  # a caller-held (e.g. virtual-clock) journal instance
        jr.emit("eta_forecast", forecast.gang, at=at, **args)
