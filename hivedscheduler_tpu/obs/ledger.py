"""Cluster capacity ledger: live chip-second attribution with a
conservation invariant.

PR 11 attributed gang *waits* and PR 13 attributed request *TTFT*; this
module closes the triangle and attributes the cluster's *capacity*: at any
instant every registered leaf cell (chip) is in **exactly one** state from
the :data:`CHIP_STATES` registry, transitions close intervals into
per-``(state, vc, chain)`` chip-second accumulators, and the
**conservation invariant** — the ledger's analogue of the journal's
sum-to-ttft assertion — holds by construction::

    sum over (state, vc, chain) buckets  ==  sum over chips (now - registered_at)

``chaos.invariants.check_ledger`` asserts it in every soak and the bench
asserts it in the driver artifact, so "where did every chip-second go?"
is a queryable fact with a machine-checked total, not a dashboard curve
that silently leaks time.

State taxonomy (the registry is the single source of truth; hivedlint
OBS002 cross-checks every literal call site against it and the runtime
raises on unregistered states):

- ``busy_*`` — a gang's pods own the chip (guaranteed / opportunistic /
  backfill-admitted rider);
- ``migration_downtime`` — the chip is fenced for a mid-migration
  re-placement (defrag/elastic grow), or (in the bench's virtual-clock
  replay) carries the checkpoint->restore downtime charged to a moved
  gang — occupancy that is provably not useful work;
- ``idle_free`` / ``idle_quota_stranded`` / ``idle_fragmented`` — free
  chips, split by the *diagnosis* of why they are idle while gangs wait
  (no waiter / a waiter's VC quota is exhausted elsewhere / capacity
  exists but no contiguous placement). The split is a best-effort
  diagnosis (driven by the oldest waiter's journal wait bucket) and does
  not affect conservation;
- ``idle_reserved`` — held by a defrag *waiter* reservation;
- ``bad_hardware`` — the chip's node is bad; the pre-bad state is
  shadowed and restored on recovery.

Feeding: the algorithm's ``add_allocated_pod`` / ``delete_allocated_pod``
/ ``_set_bad_node`` / ``_set_healthy_node`` chokepoints (every placement
path — filter routine, recovery, gang-atomic rebinds — funnels through
them, under the scheduler lock in the runtime), plus the
reservation-mutation sites in ``runtime/scheduler.py``. The defrag
what-if probes' rolled-back churn is muted exactly like the journal's:
the ledger checks the same thread-local ``journal.suppress()`` flag.

Served as ``tpu_hive_chip_seconds_total{state=,vc=}`` counters and the
``tpu_hive_chip_state_chips{state=}`` occupancy gauges, as
``GET /v1/inspect/capacity`` (+ ``/v1/inspect/capacity/<vc>`` drilldown,
copy-on-read), and as per-node ``state:`` Perfetto lanes merged into
every ``trace.to_chrome_trace()`` export. The read side also feeds the
wait-ETA estimator (``obs/eta.py``): running-gang ages and completed-gang
durations come from here.

Contracts (the PR 1/11 obs rules):

- **Zero overhead when disabled** (the default): every instrumentation
  site gates on one attribute load (``LEDGER.enabled``); the mutators
  return before touching the lock.
- **Bounded**: per-node Perfetto lanes and the completed-duration ring
  are capped; accumulators are keyed by the finite (state, vc, chain)
  space.
- **Thread-safe leaf**: ``ledger_lock`` sits just below the metrics leaf
  in the lock hierarchy — closing an interval observes the chip-second
  counter while holding it, and nothing else is ever acquired under it.

Enable programmatically (``ledger.enable()``), via the scheduler CLI
(on unless ``HIVED_LEDGER=0``), or ``HIVED_LEDGER=1`` in the environment.
"""

from __future__ import annotations

import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

from hivedscheduler_tpu.common import envflags, lockcheck
from hivedscheduler_tpu.obs import journal as _journal

# ---------------------------------------------------------------------------
# chip-state taxonomy. At any instant every registered chip is in exactly
# ONE of these; transitions close intervals, and the per-(state, vc, chain)
# chip-seconds sum to chips x wallclock (the conservation invariant).
# hivedlint OBS002 cross-checks literal call sites against this table,
# both directions; the runtime raises on unregistered states.
# ---------------------------------------------------------------------------
CHIP_STATES: Dict[str, str] = {
    "busy_guaranteed": "owned by a guaranteed-priority gang's pod (useful "
                       "work within the VC's quota)",
    "busy_opportunistic": "owned by a natively opportunistic gang's pod "
                          "(preemptible beyond-quota work)",
    "busy_backfill": "owned by a backfill-admitted rider (a quota-stranded "
                     "guaranteed gang running opportunistically, or a "
                     "duration-bounded guaranteed rider in a reserved hole)",
    "migration_downtime": "fenced for a mid-migration re-placement, or (in "
                          "the bench replay) the checkpoint->restore "
                          "downtime charged to a moved gang — occupied but "
                          "provably not useful work",
    "idle_free": "free with no waiter diagnosis: genuinely spare capacity",
    "idle_quota_stranded": "free while a guaranteed gang waits because its "
                           "OWN VC quota is exhausted (backfill/promotion "
                           "is the unblocking arm)",
    "idle_fragmented": "free while a gang waits because no contiguous "
                       "placement exists (defrag migration is the "
                       "unblocking arm)",
    "idle_reserved": "held by a defrag waiter reservation: fenced for the "
                     "consolidated slice until the waiter binds or TTL",
    "bad_hardware": "the chip's node is bad; the pre-bad state is shadowed "
                    "and restored on node recovery",
}

# the states a free chip may be diagnosed into (reclassified as waiters
# come and go); idle_reserved is a *hold*, not a diagnosis, and is managed
# by the reservation sync
IDLE_DIAG_STATES = ("idle_free", "idle_quota_stranded", "idle_fragmented")

# journal wait bucket -> idle diagnosis. `capacity` waiters leave idle
# chips as idle_free: the chips really are spare, there are just too few.
IDLE_STATE_FOR_BUCKET: Dict[str, str] = {
    "vc_quota": "idle_quota_stranded",
    "fragmentation": "idle_fragmented",
}

# defrag reservation kind -> the state its held idle chips burn as (the
# runtime's sync_reserved feeds through this; doc/design/defrag.md)
HOLD_STATE_FOR_KIND: Dict[str, str] = {
    "waiter": "idle_reserved",
    "migration": "migration_downtime",
}

_BUSY_STATES = ("busy_guaranteed", "busy_opportunistic", "busy_backfill")

# chip record field indices (a list per chip, mutated in place)
_STATE, _VC, _GANG, _SINCE, _SHADOW = 0, 1, 2, 3, 4

_MAX_DURATIONS = 256
_MAX_LANE_SPANS = 512
_LANE_TID_BASE = 20000  # Perfetto tids; journal gang lanes start at 1000


class CapacityLedger:
    """Per-chip state machine + chip-second accumulators.

    Instantiable for tests and for the bench's virtual-clock replay; the
    module singleton :data:`LEDGER` is what the live stack shares.
    ``metrics`` gates the counter/gauge emission so a sim-time instance
    never pollutes the process registry with virtual durations.
    """

    def __init__(self, metrics: bool = True):
        self._lock = lockcheck.make_lock("ledger_lock", late=True)
        self.enabled = False
        self.metrics = metrics
        # node -> {"chain": str, "bad": bool, "chips": [chip...],
        #          "lane": [(label, start, end)...], "open": (label, since)}
        self._nodes: Dict[str, Dict[str, Any]] = {}
        self._acc: Dict[Tuple[str, str, str], float] = {}
        # live busy gangs: gang -> {"chips": n, "since": t, "vc": vc}
        self._gangs: Dict[str, Dict[str, Any]] = {}
        # per-gang closed chip-seconds by state (bounded via gang count:
        # closed gangs are evicted oldest-first past the cap)
        self._gang_acc: Dict[str, Dict[str, float]] = {}
        self._gang_acc_cap = 4096
        self._durations: deque = deque(maxlen=_MAX_DURATIONS)
        self._flavors: Dict[str, str] = {}  # gang -> busy flavor hint
        self._reserved: Dict[str, str] = {}  # node -> hold state
        self._idle_default = "idle_free"
        self._registered: List[Tuple[int, float]] = []  # (chips, at)
        self._occ: Dict[str, int] = {}  # live state -> chip count
        self._next_tid = _LANE_TID_BASE

    # -- internals (caller holds self._lock) -----------------------------
    @staticmethod
    def _now(at: Optional[float]) -> float:
        return time.perf_counter() if at is None else at

    @staticmethod
    def _check_state(state: str) -> None:
        if state not in CHIP_STATES:
            raise ValueError(
                f"{state!r} is not a registered chip state — add it to "
                f"obs/ledger.py CHIP_STATES (OBS002)")

    def _observe(self, state: str, vc: str, dur: float) -> None:
        if self.metrics and dur > 0:
            from hivedscheduler_tpu.runtime.metrics import REGISTRY
            REGISTRY.inc("tpu_hive_chip_seconds_total", amount=dur,
                         state=state, vc=vc)

    def _close_chip(self, chain: str, chip: list, at: float) -> None:
        dur = at - chip[_SINCE]
        if dur <= 0:
            chip[_SINCE] = at
            return
        key = (chip[_STATE], chip[_VC], chain)
        self._acc[key] = self._acc.get(key, 0.0) + dur
        gang = chip[_GANG]
        if gang:
            acc = self._gang_acc.get(gang)
            if acc is None:
                if len(self._gang_acc) >= self._gang_acc_cap:
                    # evict the oldest entry not backing a live gang
                    for name in list(self._gang_acc):
                        if name not in self._gangs:
                            del self._gang_acc[name]
                            break
                acc = self._gang_acc[gang] = {}
            acc[chip[_STATE]] = acc.get(chip[_STATE], 0.0) + dur
        self._observe(chip[_STATE], chip[_VC], dur)
        chip[_SINCE] = at

    def _gang_join(self, gang: str, vc: str, at: float) -> None:
        rec = self._gangs.get(gang)
        if rec is None:
            self._gangs[gang] = {"chips": 1, "since": at, "vc": vc}
        else:
            rec["chips"] += 1

    def _gang_leave(self, gang: str, at: float) -> None:
        rec = self._gangs.get(gang)
        if rec is None:
            return
        rec["chips"] -= 1
        if rec["chips"] <= 0:
            self._durations.append(max(0.0, at - rec["since"]))
            del self._gangs[gang]
            self._flavors.pop(gang, None)

    def _set_chip(self, nrec: Dict[str, Any], chip: list, state: str,
                  vc: str, gang: str, at: float) -> None:
        """Core per-chip transition. On a bad chip the *shadow* state is
        updated instead (the live state stays bad_hardware until node
        recovery), but vc/gang attribution changes take effect
        immediately so releases while bad stay exact."""
        if chip[_SHADOW] is not None:
            if (chip[_SHADOW], chip[_VC], chip[_GANG]) == (state, vc, gang):
                return
            self._close_chip(nrec["chain"], chip, at)
            if chip[_GANG] != gang:
                if chip[_GANG]:
                    self._gang_leave(chip[_GANG], at)
                if gang:
                    self._gang_join(gang, vc, at)
            chip[_SHADOW] = state
            chip[_VC] = vc
            chip[_GANG] = gang
            return
        if (chip[_STATE], chip[_VC], chip[_GANG]) == (state, vc, gang):
            return
        self._close_chip(nrec["chain"], chip, at)
        if chip[_GANG] != gang:
            if chip[_GANG]:
                self._gang_leave(chip[_GANG], at)
            if gang:
                self._gang_join(gang, vc, at)
        self._occ[chip[_STATE]] = self._occ.get(chip[_STATE], 0) - 1
        self._occ[state] = self._occ.get(state, 0) + 1
        chip[_STATE] = state
        chip[_VC] = vc
        chip[_GANG] = gang

    def _relane(self, nrec: Dict[str, Any], at: float) -> None:
        """Maintain the node's Perfetto lane: one span per period of a
        constant dominant state."""
        counts: Dict[str, int] = {}
        for chip in nrec["chips"]:
            st = chip[_STATE]
            counts[st] = counts.get(st, 0) + 1
        dominant = max(counts.items(), key=lambda kv: (kv[1], kv[0]))[0] \
            if counts else "idle_free"
        label = f"state:{dominant}"
        open_span = nrec["open"]
        if open_span is not None and open_span[0] == label:
            return
        if open_span is not None:
            if len(nrec["lane"]) < _MAX_LANE_SPANS:
                nrec["lane"].append((open_span[0], open_span[1], at))
        nrec["open"] = (label, at)

    def _idle_state(self, node: str) -> str:
        return self._reserved.get(node, self._idle_default)

    def _update_gauges(self) -> None:
        if not self.metrics:
            return
        from hivedscheduler_tpu.runtime.metrics import REGISTRY
        with self._lock:
            occ = dict(self._occ)
        for state in CHIP_STATES:
            REGISTRY.set_gauge("tpu_hive_chip_state_chips",
                               occ.get(state, 0), state=state)

    # -- mutators (the instrumentation surface) --------------------------
    def register_node(self, node: str, count: int, chain: str = "",
                      at: Optional[float] = None,
                      state: str = "idle_free") -> None:
        """Idempotent: re-registering a known node keeps its chips and
        their accumulated history (crash-restart continuity)."""
        if not self.enabled or _journal.suppressed():
            return
        self._check_state(state)
        t = self._now(at)
        with self._lock:
            if node in self._nodes:
                return
            self._nodes[node] = {
                "chain": chain, "bad": False, "tid": self._next_tid,
                "chips": [[state, "", "", t, None] for _ in range(count)],
                "lane": [], "open": (f"state:{state}", t),
            }
            self._next_tid += 1
            self._registered.append((count, t))
            self._occ[state] = self._occ.get(state, 0) + count
        self._update_gauges()

    def _node_for(self, node: str, max_idx: int, at: float) -> Dict[str, Any]:
        nrec = self._nodes.get(node)
        if nrec is None:
            # lazy fallback for a ledger enabled mid-run: register what we
            # can see (explicit register_cluster is the full-count path)
            self._nodes[node] = nrec = {
                "chain": "", "bad": False, "tid": self._next_tid,
                "chips": [], "lane": [], "open": None,
            }
            self._next_tid += 1
        grow = max_idx + 1 - len(nrec["chips"])
        if grow > 0:
            idle = self._idle_state(node)
            nrec["chips"].extend(
                [idle, "", "", at, None] for _ in range(grow))
            self._registered.append((grow, at))
            self._occ[idle] = self._occ.get(idle, 0) + grow
        return nrec

    def transition(self, node: str, idxs, state: str, vc: str = "",
                   gang: str = "", at: Optional[float] = None) -> None:
        """Move the chips at ``idxs`` on ``node`` into ``state`` (closing
        their open intervals). Same (state, vc, gang) is a no-op — the
        interval just continues (recovery replays are idempotent)."""
        if not self.enabled or _journal.suppressed():
            return
        self._check_state(state)
        idxs = list(idxs)
        if not idxs:
            return
        t = self._now(at)
        with self._lock:
            nrec = self._node_for(node, max(idxs), t)
            for i in idxs:
                self._set_chip(nrec, nrec["chips"][i], state, vc, gang, t)
            self._relane(nrec, t)
        self._update_gauges()

    def release(self, node: str, idxs, at: Optional[float] = None) -> None:
        """Chips return to idle: the reservation hold state when the node
        is held, else the current idle diagnosis."""
        if not self.enabled or _journal.suppressed():
            return
        self.transition(node, idxs, self._idle_state(node), at=at)

    def set_node_bad(self, node: str, bad: bool,
                     at: Optional[float] = None) -> None:
        """All chips of a bad node burn as ``bad_hardware``; their pre-bad
        states shadow and restore on recovery (transitions while bad
        update the shadow, so a release-while-bad restores idle)."""
        if not self.enabled or _journal.suppressed():
            return
        t = self._now(at)
        with self._lock:
            nrec = self._nodes.get(node)
            if nrec is None or nrec["bad"] == bad:
                return
            nrec["bad"] = bad
            for chip in nrec["chips"]:
                self._close_chip(nrec["chain"], chip, t)
                self._occ[chip[_STATE]] = self._occ.get(chip[_STATE], 0) - 1
                if bad:
                    chip[_SHADOW] = chip[_STATE]
                    chip[_STATE] = "bad_hardware"
                else:
                    chip[_STATE] = chip[_SHADOW] or "idle_free"
                    chip[_SHADOW] = None
                self._occ[chip[_STATE]] = self._occ.get(chip[_STATE], 0) + 1
            self._relane(nrec, t)
        self._update_gauges()

    def sync_reserved(self, holds: Dict[str, str],
                      at: Optional[float] = None) -> None:
        """Reconcile the reservation holds (node -> hold state, from the
        runtime's reservation table): newly held nodes' diagnosed-idle
        chips move into the hold state, released nodes' held chips return
        to the idle diagnosis. Busy chips are never touched — a hold on a
        node still running the mover only captures chips as they free."""
        if not self.enabled or _journal.suppressed():
            return
        for state in set(holds.values()):
            self._check_state(state)
        t = self._now(at)
        with self._lock:
            changed = set(self._reserved) | set(holds)
            for node in changed:
                new = holds.get(node)
                if self._reserved.get(node) == new:
                    continue
                nrec = self._nodes.get(node)
                if nrec is not None:
                    from_states = ((self._reserved.get(node),)
                                   if node in self._reserved
                                   else IDLE_DIAG_STATES)
                    to = new if new is not None else self._idle_default
                    for chip in nrec["chips"]:
                        live = (chip[_SHADOW] if chip[_SHADOW] is not None
                                else chip[_STATE])
                        if live in from_states:
                            self._set_chip(nrec, chip, to, "", "", t)
                    self._relane(nrec, t)
                if new is None:
                    self._reserved.pop(node, None)
                else:
                    self._reserved[node] = new
        self._update_gauges()

    def set_idle_diagnosis(self, state: str,
                           at: Optional[float] = None) -> None:
        """Reclassify diagnosed-idle chips (idle_free / idle_quota_stranded
        / idle_fragmented) under a new diagnosis — driven by the oldest
        waiter's journal wait bucket. Reserved holds are untouched."""
        if not self.enabled or _journal.suppressed():
            return
        if state not in IDLE_DIAG_STATES:
            self._check_state(state)  # raise the OBS002 message
            raise ValueError(
                f"{state!r} is a registered chip state but not an idle "
                f"diagnosis ({'/'.join(IDLE_DIAG_STATES)})")
        t = self._now(at)
        with self._lock:
            if self._idle_default == state:
                return
            self._idle_default = state
            for node, nrec in self._nodes.items():
                if node in self._reserved:
                    continue
                touched = False
                for chip in nrec["chips"]:
                    live = (chip[_SHADOW] if chip[_SHADOW] is not None
                            else chip[_STATE])
                    if live in IDLE_DIAG_STATES and live != state:
                        self._set_chip(nrec, chip, state, "", "", t)
                        touched = True
                if touched:
                    self._relane(nrec, t)
        self._update_gauges()

    def hint_flavor(self, gang: str, state: str) -> None:
        """The runtime knows a gang is a backfill rider before its pods
        bind; the algorithm chokepoint reads the hint at bind time."""
        if not self.enabled:
            return
        self._check_state(state)
        self._flavors[gang] = state

    def busy_state(self, gang: str, priority: int) -> str:
        hinted = self._flavors.get(gang)
        if hinted is not None:
            return hinted
        return "busy_guaranteed" if priority >= 0 else "busy_opportunistic"

    def reattribute(self, chip_seconds: float,
                    src: Tuple[str, str, str],
                    dst: Tuple[str, str, str]) -> None:
        """Move closed chip-seconds between buckets (conservation-
        preserving). Sim-only hook: the bench's virtual-clock replay
        charges a moved gang's checkpoint->restore downtime out of its
        busy bucket the way the legacy counters subtract overhead; the
        live ledger never needs it (live downtime is real elapsed time in
        ``migration_downtime``). The source bucket may go transiently
        negative mid-replay (the downtime is paid by *future* occupancy);
        conservation of the TOTAL is unaffected."""
        if not self.enabled:
            return
        self._check_state(src[0])
        self._check_state(dst[0])
        with self._lock:
            self._acc[src] = self._acc.get(src, 0.0) - chip_seconds
            self._acc[dst] = self._acc.get(dst, 0.0) + chip_seconds
            self._observe(dst[0], dst[1], chip_seconds)

    def settle(self, at: Optional[float] = None) -> None:
        """Close every open interval at ``at`` (sim end-of-replay / dump
        points); states are kept, intervals restart at ``at``."""
        t = self._now(at)
        with self._lock:
            for nrec in self._nodes.values():
                for chip in nrec["chips"]:
                    self._close_chip(nrec["chain"], chip, t)

    def clear(self) -> None:
        with self._lock:
            self._nodes.clear()
            self._acc.clear()
            self._gangs.clear()
            self._gang_acc.clear()
            self._durations.clear()
            self._flavors.clear()
            self._reserved.clear()
            self._idle_default = "idle_free"
            self._registered = []
            self._occ.clear()
            self._next_tid = _LANE_TID_BASE

    # -- read API (copy-on-read) -----------------------------------------
    def totals(self, at: Optional[float] = None) -> Dict[Tuple[str, str, str],
                                                         float]:
        """Closed + open chip-seconds per (state, vc, chain) bucket as of
        ``at`` — the conservation check's left-hand side."""
        t = self._now(at)
        with self._lock:
            out = dict(self._acc)
            for nrec in self._nodes.values():
                for chip in nrec["chips"]:
                    dur = t - chip[_SINCE]
                    if dur > 0:
                        key = (chip[_STATE], chip[_VC], nrec["chain"])
                        out[key] = out.get(key, 0.0) + dur
            return out

    def expected_chip_seconds(self, at: Optional[float] = None) -> float:
        """chips x wallclock, honoring per-chip registration times — the
        conservation check's right-hand side."""
        t = self._now(at)
        with self._lock:
            return sum(n * max(0.0, t - t0) for n, t0 in self._registered)

    def conservation_gap(self, at: Optional[float] = None) -> float:
        t = self._now(at)
        return sum(self.totals(t).values()) - self.expected_chip_seconds(t)

    def chips(self) -> int:
        with self._lock:
            return sum(len(nrec["chips"]) for nrec in self._nodes.values())

    def occupancy(self) -> Dict[str, int]:
        with self._lock:
            return {s: n for s, n in self._occ.items() if n}

    def running_gangs(self, at: Optional[float] = None
                      ) -> List[Tuple[str, int, float, str]]:
        """(gang, chips, age_s, vc) per live busy gang — the wait-ETA
        estimator's release-projection input."""
        t = self._now(at)
        with self._lock:
            return [(g, rec["chips"], max(0.0, t - rec["since"]), rec["vc"])
                    for g, rec in self._gangs.items()]

    def completed_durations(self) -> List[float]:
        with self._lock:
            return list(self._durations)

    def gang_seconds(self, gang: str) -> Dict[str, float]:
        """Closed chip-seconds by state for one gang (the bench's wasted-
        work derivation)."""
        with self._lock:
            return dict(self._gang_acc.get(gang, {}))

    def snapshot(self, at: Optional[float] = None) -> Dict[str, Any]:
        """The ``GET /v1/inspect/capacity`` payload (copy-on-read)."""
        t = self._now(at)
        totals = self.totals(t)
        by_state: Dict[str, float] = {}
        by_vc: Dict[str, Dict[str, float]] = {}
        for (state, vc, _chain), secs in totals.items():
            by_state[state] = by_state.get(state, 0.0) + secs
            if vc:
                by_vc.setdefault(vc, {})
                by_vc[vc][state] = by_vc[vc].get(state, 0.0) + secs
        occ = self.occupancy()
        expected = self.expected_chip_seconds(t)
        durations = self.completed_durations()
        return {
            "enabled": self.enabled,
            "chips": self.chips(),
            "states": {
                s: {"chipSeconds": round(by_state.get(s, 0.0), 6),
                    "chips": occ.get(s, 0)}
                for s in CHIP_STATES
            },
            "byVc": {vc: {s: round(v, 6) for s, v in sorted(states.items())}
                     for vc, states in sorted(by_vc.items())},
            "idleDiagnosis": self._idle_default,
            "runningGangs": len(self._gangs),
            "completedGangDurationP50S": (
                round(sorted(durations)[len(durations) // 2], 6)
                if durations else None),
            "expectedChipSeconds": round(expected, 6),
            "conservationGapChipSeconds": round(
                sum(totals.values()) - expected, 6),
        }

    def vc_snapshot(self, vc: str, at: Optional[float] = None
                    ) -> Dict[str, Any]:
        """The ``GET /v1/inspect/capacity/<vc>`` drilldown: this VC's
        capacity burn by state plus its live gangs."""
        t = self._now(at)
        totals = self.totals(t)
        states: Dict[str, float] = {}
        for (state, v, _chain), secs in totals.items():
            if v == vc:
                states[state] = states.get(state, 0.0) + secs
        with self._lock:
            gangs = [
                {"gang": g, "chips": rec["chips"],
                 "ageS": round(max(0.0, t - rec["since"]), 6)}
                for g, rec in sorted(self._gangs.items())
                if rec["vc"] == vc
            ]
            chips_now = sum(
                1 for nrec in self._nodes.values()
                for chip in nrec["chips"] if chip[_VC] == vc
            )
        return {
            "vc": vc, "enabled": self.enabled,
            "states": {s: round(v, 6) for s, v in sorted(states.items())},
            "chipsNow": chips_now,
            "gangs": gangs,
        }

    def chrome_events(self, t0: float) -> List[Dict[str, Any]]:
        """Per-node Perfetto lanes: one named thread lane per node, an X
        span per closed dominant-state period (open periods are drawn to
        the export instant). ``t0`` is the tracer's perf_counter anchor."""
        now = time.perf_counter()
        with self._lock:
            lanes = [
                (node, nrec["tid"],
                 list(nrec["lane"]),
                 nrec["open"])
                for node, nrec in self._nodes.items()
            ]
        out: List[Dict[str, Any]] = []
        for node, tid, spans, open_span in lanes:
            out.append({"name": "thread_name", "ph": "M", "pid": 1,
                        "tid": tid, "ts": 0,
                        "args": {"name": f"node {node}"}})
            if open_span is not None:
                spans = spans + [(open_span[0], open_span[1], now)]
            for label, start, end in spans:
                out.append({"name": label, "ph": "X", "cat": "ledger",
                            "ts": (start - t0) * 1e6,
                            "dur": max(0.0, (end - start) * 1e6),
                            "pid": 1, "tid": tid, "args": {}})
        return out


LEDGER = CapacityLedger()


def enabled() -> bool:
    return LEDGER.enabled


def enable() -> None:
    LEDGER.enabled = True


def disable() -> None:
    LEDGER.enabled = False


def register_cluster(algo, at: Optional[float] = None) -> None:
    """Register every leaf cell of an algorithm's cell trees (node ->
    chip count + chain), syncing current node badness. Idempotent — a
    crash-restarted scheduler re-registers into the same timeline."""
    if not LEDGER.enabled:
        return
    for node, leaves in getattr(algo, "_leaves_by_node", {}).items():
        LEDGER.register_node(node, len(leaves),
                             chain=str(leaves[0].chain), at=at)
        if node in getattr(algo, "bad_nodes", ()):
            LEDGER.set_node_bad(node, True, at=at)


if envflags.get("HIVED_LEDGER") == "1":  # ad-hoc opt-in, like HIVED_JOURNAL
    enable()
