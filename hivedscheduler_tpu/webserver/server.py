"""HTTP server exposing the scheduler-extender and inspect APIs.

TPU-native analogue of the reference's ``pkg/webserver/webserver.go``: routes
``/v1/extender/{filter,bind,preempt}`` (POST) and ``/v1/inspect/...`` (GET)
with per-request panic->HTTP-error recovery (``webserver.go:142-155``).
Implemented on the stdlib ThreadingHTTPServer — requests are serialized by the
scheduler lock anyway (the algorithm is single-threaded by design).
"""

from __future__ import annotations

import json
import logging
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Optional, Tuple

from hivedscheduler_tpu.api import constants as C
from hivedscheduler_tpu.api.types import WebServerError
from hivedscheduler_tpu.runtime import extender as ei
from hivedscheduler_tpu.runtime.scheduler import HivedScheduler

log = logging.getLogger(__name__)


class WebServer:
    """Reference: webserver.go:62-137."""

    def __init__(self, scheduler: HivedScheduler, address: str = ""):
        self.scheduler = scheduler
        address = address or scheduler.config.web_server_address
        host, _, port = address.rpartition(":")
        self.host = host or "0.0.0.0"
        self.port = int(port)
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        # readiness vs liveness split: draining flips /readyz (stop SENDING
        # me work) while /healthz (restart me if dead) stays green — the
        # k8s-conventional graceful-termination sequence
        self.draining = False
        self.retry_after_s = 30

    def begin_drain(self, retry_after_s: int = 30) -> None:
        """Flip /readyz to 503 + ``Retry-After`` while the process keeps
        serving in-flight requests; callers then stop() after their drain
        grace. Liveness (/healthz) is NOT affected — a draining scheduler
        is healthy, it just must not receive new work."""
        self.retry_after_s = retry_after_s
        self.draining = True
        log.info("WebServer draining: /readyz now 503 (Retry-After %ss)",
                 retry_after_s)

    def async_run(self) -> Tuple[str, int]:
        """Start serving in a background thread; returns (host, port) with the
        actually-bound port (reference: AsyncRun, webserver.go:93-137)."""
        handler = _make_handler(self.scheduler, self)
        self._httpd = ThreadingHTTPServer((self.host, self.port), handler)
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="webserver", daemon=True
        )
        self._thread.start()
        host, port = self._httpd.server_address[:2]
        log.info("WebServer serving on %s:%s", host, port)
        return str(host), int(port)

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()


def _make_handler(scheduler: HivedScheduler, webserver: Optional[WebServer] = None):
    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, fmt: str, *args: Any) -> None:  # route to logging
            log.debug("%s - %s", self.address_string(), fmt % args)

        def _reply(self, code: int, obj: Any) -> None:
            from hivedscheduler_tpu.runtime.metrics import REGISTRY

            body = json.dumps(obj).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
            # count only after a successful write: a broken-pipe mid-response
            # must not double-count the request via the 500 fallback
            REGISTRY.inc("tpu_hive_http_requests_total",
                         method=self.command, code=str(code))

        def _reply_error(self, e: Exception) -> None:
            """Panic -> HTTP error (reference: webserver.go:142-155):
            WebServerError keeps its code; anything else is a 500."""
            if isinstance(e, WebServerError):
                self._reply(e.code, e.to_dict())
            else:
                log.exception("Internal error serving %s", self.path)
                self._reply(500, {"code": 500, "message": str(e)})

        def _read_json(self) -> Any:
            length = int(self.headers.get("Content-Length") or 0)
            raw = self.rfile.read(length) if length else b""
            if not raw:
                raise WebServerError(400, "Request body is empty")
            try:
                return json.loads(raw)
            except json.JSONDecodeError as je:
                raise WebServerError(400, f"Request body is not valid JSON: {je}")

        # ---------------- POST: extender ----------------
        def do_POST(self) -> None:
            try:
                path = self.path.rstrip("/")
                if path == C.FILTER_PATH:
                    args = ei.ExtenderArgs.from_dict(self._read_json())
                    self._reply(200, scheduler.filter_routine(args).to_dict())
                elif path == C.BIND_PATH:
                    args = ei.ExtenderBindingArgs.from_dict(self._read_json())
                    self._reply(200, scheduler.bind_routine(args).to_dict())
                elif path == C.PREEMPT_PATH:
                    args = ei.ExtenderPreemptionArgs.from_dict(self._read_json())
                    self._reply(200, scheduler.preempt_routine(args).to_dict())
                else:
                    self._reply(404, {"code": 404, "message": f"Unknown path {self.path}"})
            except ValueError as ve:
                self._reply_error(WebServerError(400, str(ve)))
            except Exception as e:
                self._reply_error(e)

        # ---------------- GET: inspect ----------------
        def do_GET(self) -> None:
            try:
                full, _, query = self.path.partition("?")
                path = full.rstrip("/")
                if path == "/healthz":
                    # bounded liveness: a wedged scheduler lock or dead watch
                    # threads must fail the probe, not just a dead HTTP server.
                    # Liveness is drain-BLIND: a draining process is alive
                    # (restarting it would lose the in-flight work the drain
                    # exists to finish) — only /readyz flips.
                    ok = scheduler.healthy()
                    body = b"ok" if ok else b"unhealthy: scheduler lock wedged or watch threads dead"
                    self.send_response(200 if ok else 503)
                    self.send_header("Content-Type", "text/plain")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                elif path == "/readyz":
                    # readiness: "send me work?" — 503 while draining (with
                    # Retry-After so well-behaved clients back off onto
                    # another replica) or while unhealthy. Flips BEFORE
                    # /healthz ever would: drain starts at SIGTERM, liveness
                    # only fails on a genuine wedge.
                    draining = webserver is not None and webserver.draining
                    ok = not draining and scheduler.healthy()
                    if draining:
                        body = b"draining"
                    elif ok:
                        body = b"ready"
                    else:
                        body = b"unhealthy: scheduler lock wedged or watch threads dead"
                    self.send_response(200 if ok else 503)
                    self.send_header("Content-Type", "text/plain")
                    if draining:
                        self.send_header(
                            "Retry-After", str(webserver.retry_after_s))
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                elif path == "/metrics":
                    from hivedscheduler_tpu.runtime.metrics import REGISTRY

                    REGISTRY.inc("tpu_hive_http_requests_total",
                                 method=self.command, code="200")
                    body = REGISTRY.render().encode()
                    self.send_response(200)
                    self.send_header("Content-Type", "text/plain; version=0.0.4")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                elif path == C.VERSION_PREFIX or path == "":
                    self._reply(200, {"paths": [
                        C.FILTER_PATH, C.BIND_PATH, C.PREEMPT_PATH,
                        C.AFFINITY_GROUPS_PATH, C.CLUSTER_STATUS_PATH,
                        C.PHYSICAL_CLUSTER_PATH, C.VIRTUAL_CLUSTERS_PATH,
                        C.TRACES_PATH, C.TRACES_CHROME_PATH,
                        C.ADMISSION_HINTS_PATH, C.DEFRAG_PATH,
                        C.GANGS_PATH, C.FLEET_PATH,
                        C.REQUESTS_PATH, C.SLO_PATH,
                        C.CAPACITY_PATH,
                    ]})
                elif path == C.FLEET_PATH:
                    # serving-fleet router snapshot (copy-on-read under
                    # the router's leaf lock; empty when no fleet runs in
                    # this process)
                    from hivedscheduler_tpu.fleet import router as fleet_router

                    r = fleet_router.published()
                    payload = {"enabled": r is not None}
                    if r is not None:
                        payload.update(r.snapshot())
                    self._reply(200, payload)
                elif path == C.SLO_PATH:
                    # declared SLOs: windowed quantiles, burn rates and
                    # violation attribution from the published fleet's
                    # tracker (copy-on-read; empty when no fleet runs in
                    # this process)
                    from hivedscheduler_tpu.fleet import router as fleet_router

                    r = fleet_router.published()
                    payload = {"enabled": r is not None, "objectives": []}
                    if r is not None:
                        payload.update(r.slo.snapshot())
                    self._reply(200, payload)
                elif path == C.REQUESTS_PATH:
                    # request flight recorder: per-request TTFT leg
                    # summaries (copy-on-read; empty when the journal is
                    # off)
                    from hivedscheduler_tpu.obs import journal as obs_journal

                    self._reply(200, {
                        "enabled": obs_journal.JOURNAL.enabled,
                        "items": obs_journal.JOURNAL.requests(),
                    })
                elif (full.startswith(C.REQUESTS_PATH + "/")
                        and path.endswith("/timeline")):
                    # /v1/inspect/requests/<id>/timeline — <id> may
                    # contain slashes (fleet/<fid>, serve/<rid>)
                    from hivedscheduler_tpu.obs import journal as obs_journal

                    rid = path[len(C.REQUESTS_PATH) + 1:-len("/timeline")]
                    if not rid:
                        raise WebServerError(400, "request id is empty")
                    self._reply(
                        200, obs_journal.JOURNAL.request_timeline(rid))
                elif path == C.CAPACITY_PATH:
                    # capacity ledger: per-state chip-seconds + occupancy
                    # with the conservation fields (copy-on-read; valid
                    # JSON with zeros when the ledger is off)
                    from hivedscheduler_tpu.obs import ledger as obs_ledger

                    self._reply(200, obs_ledger.LEDGER.snapshot())
                elif full.startswith(C.CAPACITY_PATH + "/"):
                    # /v1/inspect/capacity/<vc> — one VC's capacity burn
                    from hivedscheduler_tpu.obs import ledger as obs_ledger

                    vc = full[len(C.CAPACITY_PATH) + 1:].rstrip("/")
                    if not vc:
                        raise WebServerError(400, "vc name is empty")
                    self._reply(200, obs_ledger.LEDGER.vc_snapshot(vc))
                elif (full.startswith(C.GANGS_PATH + "/")
                        and path.endswith("/eta")):
                    # /v1/inspect/gangs/<id>/eta — wait-ETA forecast for
                    # a waiting gang (slash-tolerant ids, like /timeline)
                    gang = path[len(C.GANGS_PATH) + 1:-len("/eta")]
                    if not gang:
                        raise WebServerError(400, "gang id is empty")
                    self._reply(200, scheduler.get_gang_eta(gang))
                elif path == C.ADMISSION_HINTS_PATH:
                    # serving headroom + defrag holds, for gang admission
                    self._reply(200, scheduler.get_admission_hints())
                elif path == C.DEFRAG_PATH:
                    self._reply(200, scheduler.get_defrag_status())
                elif path == C.GANGS_PATH:
                    # gang-lifecycle flight recorder: per-gang summaries
                    # (copy-on-read snapshot; empty when the journal is off)
                    from hivedscheduler_tpu.obs import journal as obs_journal

                    self._reply(200, {
                        "enabled": obs_journal.JOURNAL.enabled,
                        "items": obs_journal.JOURNAL.gangs(),
                    })
                elif (full.startswith(C.GANGS_PATH + "/")
                        and path.endswith("/timeline")):
                    # /v1/inspect/gangs/<id>/timeline — <id> may contain
                    # slashes (namespace-qualified group names)
                    from hivedscheduler_tpu.obs import journal as obs_journal

                    gang = path[len(C.GANGS_PATH) + 1:-len("/timeline")]
                    if not gang:
                        raise WebServerError(400, "gang id is empty")
                    self._reply(200, obs_journal.JOURNAL.timeline(gang))
                elif path == C.TRACES_CHROME_PATH:
                    from hivedscheduler_tpu.obs import trace

                    self._reply(200, trace.to_chrome_trace())
                elif path == C.TRACES_PATH:
                    from urllib.parse import parse_qs

                    from hivedscheduler_tpu.obs.decisions import RECORDER

                    try:
                        n = int(parse_qs(query).get("n", ["32"])[0])
                    except ValueError:
                        raise WebServerError(400, "n must be an integer")
                    self._reply(200, {
                        "enabled": RECORDER.enabled,
                        "items": RECORDER.last(n),
                    })
                elif path == C.AFFINITY_GROUPS_PATH.rstrip("/"):
                    groups = scheduler.get_all_affinity_groups()
                    self._reply(200, {"items": [g.to_dict() for g in groups]})
                elif full.startswith(C.AFFINITY_GROUPS_PATH):
                    name = full[len(C.AFFINITY_GROUPS_PATH):].rstrip("/")
                    self._reply(200, scheduler.get_affinity_group(name).to_dict())
                elif path == C.CLUSTER_STATUS_PATH:
                    # copy-on-read: serialize under the scheduler lock
                    # instead of deep-copying the whole status forest
                    if hasattr(scheduler, "get_cluster_status_dict"):
                        self._reply(200, scheduler.get_cluster_status_dict())
                    else:
                        self._reply(200, scheduler.get_cluster_status().to_dict())
                elif path == C.PHYSICAL_CLUSTER_PATH:
                    if hasattr(scheduler, "get_physical_cluster_status_dict"):
                        self._reply(200, scheduler.get_physical_cluster_status_dict())
                    else:
                        self._reply(
                            200,
                            [s.to_dict() for s in scheduler.get_physical_cluster_status()],
                        )
                elif path == C.VIRTUAL_CLUSTERS_PATH.rstrip("/"):
                    if hasattr(scheduler, "get_all_virtual_clusters_status_dict"):
                        self._reply(200, scheduler.get_all_virtual_clusters_status_dict())
                    else:
                        vcs = scheduler.get_all_virtual_clusters_status()
                        self._reply(
                            200,
                            {vc: [s.to_dict() for s in lst] for vc, lst in vcs.items()},
                        )
                elif full.startswith(C.VIRTUAL_CLUSTERS_PATH):
                    vcn = full[len(C.VIRTUAL_CLUSTERS_PATH):].rstrip("/")
                    if hasattr(scheduler, "get_virtual_cluster_status_dict"):
                        self._reply(200, scheduler.get_virtual_cluster_status_dict(vcn))
                    else:
                        self._reply(
                            200,
                            [s.to_dict() for s in scheduler.get_virtual_cluster_status(vcn)],
                        )
                else:
                    self._reply(404, {"code": 404, "message": f"Unknown path {self.path}"})
            except Exception as e:
                self._reply_error(e)

    return Handler
