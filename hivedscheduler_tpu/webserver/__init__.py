from hivedscheduler_tpu.webserver.server import WebServer  # noqa: F401
