"""External contracts: config specs, annotations, wire types, inspect DTOs.

TPU-native analogue of the reference's ``pkg/api`` (types at
``pkg/api/types.go:42-273``, constants at ``pkg/api/constants.go:42-94``,
config at ``pkg/api/config.go:39-230``).
"""
