"""Annotation / resource-name contract, priority ranges, HTTP paths.

TPU-native analogue of the reference's ``pkg/api/constants.go``. The three-
annotation contract (spec / isolation / bind-info, ``constants.go:42-55``) is
kept because it doubles as the crash-recovery store; the isolation handoff
targets the Cloud TPU device plugin (``TPU_VISIBLE_CHIPS``) instead of
``NVIDIA_VISIBLE_DEVICES`` (reference: ``doc/user-manual.md:164-175``).
"""

GROUP_NAME = "hivedscheduler.microsoft.com"
COMPONENT_NAME = "tpu-hive"

# --- Pod contract -----------------------------------------------------------
# A pod opts in by declaring this (fake) resource limit on some container
# (reference: constants.go:42, internal/utils.go:116-139).
RESOURCE_NAME_POD_SCHEDULING_ENABLE = f"{GROUP_NAME}/pod-scheduling-enable"

# User-written scheduling request (reference: constants.go:46).
ANNOTATION_POD_SCHEDULING_SPEC = f"{GROUP_NAME}/pod-scheduling-spec"

# Scheduler-written chip-isolation decision, consumed by the TPU device plugin
# as TPU_VISIBLE_CHIPS (reference GPU analogue: constants.go:50).
ANNOTATION_POD_CHIP_ISOLATION = f"{GROUP_NAME}/pod-leaf-cell-isolation"

# Scheduler-written durable placement record; replayed at startup for crash
# recovery (reference: constants.go:55, scheduler.go:306-337).
ANNOTATION_POD_BIND_INFO = f"{GROUP_NAME}/pod-bind-info"

# Environment variable the Cloud TPU device plugin / tpu runtime reads.
ENV_TPU_VISIBLE_CHIPS = "TPU_VISIBLE_CHIPS"

# --- Scheduling-spec extension keys (no reference analogue) -----------------
# Inside the pod-scheduling-spec annotation (api/types.py PodSchedulingSpec):
# the job's expected run time, consumed by duration-aware guaranteed backfill
# (defrag/backfill.py: a guaranteed gang may ride a reserved hole only when
# now + duration*slack <= the hold's expiry), and the elastic shape ladder
# (doc/design/elastic.md: a gang declaring elasticMinChips accepts any
# halving-ladder shape down to that floor; elasticFullMembers is written by
# the scheduler onto a DEGRADED incarnation's pods so the full shape
# survives crashes and grow-promotion can find its way back).
SPEC_KEY_DURATION_SECONDS = "durationSeconds"
SPEC_KEY_ELASTIC_MIN_CHIPS = "elasticMinChips"
SPEC_KEY_ELASTIC_FULL_MEMBERS = "elasticFullMembers"

# --- Priorities (reference: constants.go:57-62) -----------------------------
MAX_GUARANTEED_PRIORITY = 1000
MIN_GUARANTEED_PRIORITY = 0
OPPORTUNISTIC_PRIORITY = -1

# --- Web server routes (reference: constants.go:72-94) ----------------------
VERSION_PREFIX = "/v1"
EXTENDER_PATH = VERSION_PREFIX + "/extender"
FILTER_PATH = EXTENDER_PATH + "/filter"
BIND_PATH = EXTENDER_PATH + "/bind"
PREEMPT_PATH = EXTENDER_PATH + "/preempt"

INSPECT_PATH = VERSION_PREFIX + "/inspect"
AFFINITY_GROUPS_PATH = INSPECT_PATH + "/affinitygroups/"
CLUSTER_STATUS_PATH = INSPECT_PATH + "/clusterstatus"
PHYSICAL_CLUSTER_PATH = CLUSTER_STATUS_PATH + "/physicalcluster"
VIRTUAL_CLUSTERS_PATH = CLUSTER_STATUS_PATH + "/virtualclusters/"
# tpu-hive additions (no reference analogue — klog-only, SURVEY.md §5):
# the last-N scheduler decision traces and the Chrome-trace/Perfetto export
# of the shared obs timeline (doc/design/observability.md)
TRACES_PATH = INSPECT_PATH + "/traces"
TRACES_CHROME_PATH = TRACES_PATH + "/chrome"
# scheduler-visible admission hints (serving block-pool headroom) and the
# defrag subsystem's reservations/migrations
ADMISSION_HINTS_PATH = INSPECT_PATH + "/admission-hints"
DEFRAG_PATH = INSPECT_PATH + "/defrag"
# gang-lifecycle flight recorder (obs/journal.py): per-gang summaries and
# the causal event timeline (GET /v1/inspect/gangs/<id>/timeline)
GANGS_PATH = INSPECT_PATH + "/gangs"
# serving fleet tier (fleet/router.py): the published router's
# copy-on-read snapshot (replicas, handoffs, retries, autoscale state)
FLEET_PATH = INSPECT_PATH + "/fleet"
# request flight recorder + SLO layer (obs/journal.py REQUEST_LEGS +
# obs/slo.py): per-request TTFT leg summaries
# (GET /v1/inspect/requests/<id>/timeline for one flight's causal events
# + leg decomposition) and the declared objectives' windowed quantiles /
# burn rates / violation attribution
REQUESTS_PATH = INSPECT_PATH + "/requests"
SLO_PATH = INSPECT_PATH + "/slo"
# capacity ledger (obs/ledger.py): live chip-second attribution with the
# conservation invariant — per-state chip-seconds + occupancy, with a
# per-VC drilldown at GET /v1/inspect/capacity/<vc>; the wait-ETA
# estimator rides the gangs surface (GET /v1/inspect/gangs/<id>/eta)
CAPACITY_PATH = INSPECT_PATH + "/capacity"

# --- Config (reference: constants.go:65) ------------------------------------
ENV_CONFIG_FILE = "CONFIG"
DEFAULT_CONFIG_FILE_PATH = "./tpu-hive.yaml"
DEFAULT_WEB_SERVER_ADDRESS = ":30096"
