"""Scheduler config: load, default, validate, watch.

TPU-native analogue of the reference's ``pkg/api/config.go``:

- ``Config`` (``config.go:39-85``) with the same knobs
  (``forcePodBindThreshold``, ``waitingPodSchedulingBlockMilliSec``, ...);
- recursive physical-cell address inference (``inferPhysicalCellSpec``,
  ``config.go:134-167``): child default address = parent*childNumber+i,
  reset to 0 at node level so leaf cells carry in-node indices;
- ``watch_config`` — exits the process when the config file's effective
  content changes, relying on restart + annotation recovery for
  work-preserving reconfiguration (``config.go:202-217``).
"""

from __future__ import annotations

import logging
import os
import threading
from dataclasses import dataclass, field
from typing import Any, Dict, Optional

from hivedscheduler_tpu.api import constants
from hivedscheduler_tpu.api.types import (
    CellType,
    CellTypeSpec,
    PhysicalCellSpec,
    PhysicalClusterSpec,
    VirtualClusterName,
    VirtualClusterSpec,
)
from hivedscheduler_tpu.common import utils as common

log = logging.getLogger(__name__)


@dataclass
class Config:
    """Reference: config.go:39-85."""

    kube_api_server_address: str = ""
    kube_config_file_path: str = ""
    web_server_address: str = constants.DEFAULT_WEB_SERVER_ADDRESS
    force_pod_bind_threshold: int = 3
    waiting_pod_scheduling_block_milli_sec: int = 0
    physical_cluster: PhysicalClusterSpec = field(default_factory=PhysicalClusterSpec)
    virtual_clusters: Dict[VirtualClusterName, VirtualClusterSpec] = field(default_factory=dict)

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "Config":
        return Config(
            kube_api_server_address=d.get("kubeApiServerAddress")
            or os.environ.get("KUBE_APISERVER_ADDRESS", ""),
            kube_config_file_path=d.get("kubeConfigFilePath")
            or _default_kube_config_file_path(),
            web_server_address=d.get("webServerAddress") or constants.DEFAULT_WEB_SERVER_ADDRESS,
            force_pod_bind_threshold=int(
                d.get("forcePodBindThreshold", 3) if d.get("forcePodBindThreshold") is not None else 3
            ),
            waiting_pod_scheduling_block_milli_sec=int(
                d.get("waitingPodSchedulingBlockMilliSec") or 0
            ),
            physical_cluster=PhysicalClusterSpec.from_dict(d.get("physicalCluster") or {}),
            virtual_clusters={
                vc: VirtualClusterSpec.from_dict(spec or {})
                for vc, spec in (d.get("virtualClusters") or {}).items()
            },
        )

    def to_dict(self) -> Dict[str, Any]:
        return {
            "kubeApiServerAddress": self.kube_api_server_address,
            "kubeConfigFilePath": self.kube_config_file_path,
            "webServerAddress": self.web_server_address,
            "forcePodBindThreshold": self.force_pod_bind_threshold,
            "waitingPodSchedulingBlockMilliSec": self.waiting_pod_scheduling_block_milli_sec,
            "physicalCluster": self.physical_cluster.to_dict(),
            "virtualClusters": {vc: s.to_dict() for vc, s in self.virtual_clusters.items()},
        }


def _default_kube_config_file_path() -> str:
    path = os.environ.get("KUBECONFIG", os.path.expanduser("~/.kube/config"))
    return path if os.path.exists(path) else ""


def new_config(raw: Config) -> Config:
    """Defaulting + address inference (reference: NewConfig, config.go:87-120)."""
    defaulting_physical_cells(raw.physical_cluster)
    return raw


def defaulting_physical_cells(pc: PhysicalClusterSpec) -> None:
    """Reference: defaultingPhysicalCells, config.go:122-132. Mesh chains skip
    tree inference here — their cell trees are generated geometrically by the
    constructor (algorithm/mesh.py)."""
    for idx, spec in enumerate(pc.physical_cells):
        if spec.cell_type not in pc.cell_types:
            raise ValueError(f"physicalCells contains unknown cellType: {spec.cell_type}")
        if pc.cell_types[spec.cell_type].mesh is not None:
            if not spec.cell_address:
                spec.cell_address = str(idx)
            continue
        _infer_physical_cell_spec(spec, pc.cell_types, spec.cell_type, idx, "")


def _infer_physical_cell_spec(
    spec: PhysicalCellSpec,
    cts: Dict[CellType, CellTypeSpec],
    cell_type: CellType,
    default_address: int,
    address_prefix: str,
) -> None:
    """Reference: inferPhysicalCellSpec, config.go:134-167."""
    if not spec.cell_type:
        spec.cell_type = cell_type
    if not spec.cell_address:
        spec.cell_address = address_prefix + str(default_address)
    else:
        spec.cell_address = address_prefix + spec.cell_address

    ct = cts.get(cell_type)
    if ct is None:
        return  # leaf cell type
    if ct.is_node_level:
        # Reset so leaf cells carry flat in-node indices used for isolation.
        default_address = 0
    if ct.child_cell_number > 0 and not spec.cell_children:
        spec.cell_children = [PhysicalCellSpec(cell_type="") for _ in range(ct.child_cell_number)]
    for i, child in enumerate(spec.cell_children):
        _infer_physical_cell_spec(
            child,
            cts,
            ct.child_cell_type or "",
            default_address * ct.child_cell_number + i,
            spec.cell_address + "/",
        )


def init_raw_config(config_path: Optional[str] = None) -> Config:
    """Reference: InitRawConfig, config.go:188-200."""
    path = config_path or os.environ.get(
        constants.ENV_CONFIG_FILE, constants.DEFAULT_CONFIG_FILE_PATH
    )
    with open(path, "r", encoding="utf-8") as f:
        raw = common.from_yaml(f.read()) or {}
    return Config.from_dict(raw)


def load_config(config_path: Optional[str] = None) -> Config:
    return new_config(init_raw_config(config_path))


def watch_config(
    config_path: str,
    current: Config,
    poll_interval_sec: float = 2.0,
    on_change=None,
) -> threading.Thread:
    """Poll the config file; when the *effective* config changes, exit(0) so
    the orchestrator restarts us and annotation replay recovers all allocated
    pods — work-preserving reconfiguration (reference: WatchConfig,
    config.go:202-217; feature doc example/feature/README.md:151-208).

    ``on_change`` overrides the exit for tests."""
    snapshot = current.to_dict()

    def _loop() -> None:
        while True:
            threading.Event().wait(poll_interval_sec)
            try:
                changed = load_config(config_path).to_dict() != snapshot
            except Exception as e:  # unreadable mid-write; retry next tick
                log.warning("Config watch read failed (retrying): %s", e)
                continue
            if changed:
                log.error("Config file content changed, exiting ...")
                if on_change is not None:
                    on_change()
                    return
                os._exit(0)

    t = threading.Thread(target=_loop, name="config-watch", daemon=True)
    t.start()
    return t
