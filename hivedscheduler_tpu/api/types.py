"""Config specs, scheduling/bind wire types, inspect DTOs.

TPU-native analogue of the reference's ``pkg/api/types.go``:

- cluster config specs (``types.go:42-76``) extended with an ICI-mesh chain
  spec (``mesh:``) so a cell type can be declared as a contiguous sub-mesh
  hierarchy instead of a generic child-count tree;
- ``PodSchedulingSpec`` / ``AffinityGroupSpec`` (``types.go:78-98``) with
  ``chipType``/``chipNumber`` TPU aliases (and backward-compatible
  ``gpuType``/``gpuNumber``/``leafCellType`` keys, mirroring
  ``internal/utils.go:189-197``);
- ``PodBindInfo`` — the durable placement record (``types.go:100-118``);
- inspect DTOs with physical<->virtual cross-links (``types.go:140-273``).

Everything (de)serializes to the reference's camelCase YAML/JSON keys so
existing HiveD configs and clients carry over.
"""

from __future__ import annotations

import copy
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

CellType = str
CellAddress = str
PinnedCellId = str
VirtualClusterName = str


class WebServerError(Exception):
    """HTTP-mapped error (reference: types.go:122-137)."""

    def __init__(self, code: int, message: str):
        super().__init__(f"Code: {code}, Message: {message}")
        self.code = code
        self.message = message

    def to_dict(self) -> Dict[str, Any]:
        return {"code": self.code, "message": self.message}


def as_bad_request(message: str) -> WebServerError:
    return WebServerError(400, message)


# ---------------------------------------------------------------------------
# Physical cluster spec
# ---------------------------------------------------------------------------


@dataclass
class MeshLevelSpec:
    """One named level of an ICI-mesh chain: a contiguous sub-mesh shape.

    Each level's shape must tile the next level's shape exactly, so buddy
    split/merge is mesh tiling and contiguity is guaranteed by construction
    (TPU-first replacement for the reference's child-count levels).
    """

    name: CellType
    shape: Tuple[int, ...]

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "MeshLevelSpec":
        return MeshLevelSpec(name=d["name"], shape=tuple(int(x) for x in d["shape"]))

    def to_dict(self) -> Dict[str, Any]:
        return {"name": self.name, "shape": list(self.shape)}


@dataclass
class MeshSpec:
    """ICI-mesh declaration of a cell chain.

    ``topology`` is the full mesh of the top cell (e.g. ``[8, 8, 16]`` for a
    v5p-1024 pod), ``chipType`` names the leaf cells, ``hostShape`` is the
    sub-mesh directly attached to one host/node (e.g. ``[2, 2, 1]`` for v5p's
    4-chip hosts), and ``levels`` are the named allocatable shapes in
    ascending order. Chip level and host level are implicit (auto-inserted if
    not listed).

    ``hostNameFormat`` maps each host sub-mesh to its Kubernetes node name:
    a format string over ``{cell}`` (the physical cell's cellAddress) and
    ``{coords}`` (the host origin, dash-joined, e.g. ``2-0-0``). The default
    ``{cell}/{coords}`` is stable and readable for simulation/inspection but
    contains ``/`` — NOT a legal K8s node name — so real-control-plane
    deployments must set a DNS-1123-compatible format matching their actual
    hostnames (e.g. ``tpu-{coords}.gke.internal``); the config parser
    validates legality whenever a custom format is given."""

    topology: Tuple[int, ...]
    chip_type: CellType
    host_shape: Tuple[int, ...]
    levels: List[MeshLevelSpec] = field(default_factory=list)
    host_name_format: Optional[str] = None

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "MeshSpec":
        return MeshSpec(
            topology=tuple(int(x) for x in d["topology"]),
            chip_type=d["chipType"],
            host_shape=tuple(int(x) for x in d["hostShape"]),
            levels=[MeshLevelSpec.from_dict(x) for x in d.get("levels", [])],
            host_name_format=d.get("hostNameFormat"),
        )

    def to_dict(self) -> Dict[str, Any]:
        out = {
            "topology": list(self.topology),
            "chipType": self.chip_type,
            "hostShape": list(self.host_shape),
            "levels": [x.to_dict() for x in self.levels],
        }
        if self.host_name_format is not None:
            out["hostNameFormat"] = self.host_name_format
        return out


@dataclass
class CellTypeSpec:
    """Reference: types.go:46-50, plus the TPU ``mesh`` extension.

    Exactly one of (child_cell_type+child_cell_number) or ``mesh`` may be set;
    neither set means a leaf cell type."""

    child_cell_type: Optional[CellType] = None
    child_cell_number: int = 0
    is_node_level: bool = False
    mesh: Optional[MeshSpec] = None

    @staticmethod
    def from_dict(d: Optional[Dict[str, Any]]) -> "CellTypeSpec":
        d = d or {}
        return CellTypeSpec(
            child_cell_type=d.get("childCellType"),
            child_cell_number=int(d.get("childCellNumber", 0)),
            is_node_level=bool(d.get("isNodeLevel", False)),
            mesh=MeshSpec.from_dict(d["mesh"]) if d.get("mesh") else None,
        )

    def to_dict(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {}
        if self.mesh is not None:
            out["mesh"] = self.mesh.to_dict()
        else:
            if self.child_cell_type is not None:
                out["childCellType"] = self.child_cell_type
                out["childCellNumber"] = self.child_cell_number
            if self.is_node_level:
                out["isNodeLevel"] = True
        return out


@dataclass
class PhysicalCellSpec:
    """Reference: types.go:53-59."""

    cell_type: CellType
    cell_address: CellAddress = ""
    pinned_cell_id: PinnedCellId = ""
    cell_children: List["PhysicalCellSpec"] = field(default_factory=list)

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "PhysicalCellSpec":
        return PhysicalCellSpec(
            cell_type=d.get("cellType", ""),
            cell_address=str(d.get("cellAddress", "")),
            pinned_cell_id=d.get("pinnedCellId", ""),
            cell_children=[PhysicalCellSpec.from_dict(c) for c in d.get("cellChildren", [])],
        )

    def to_dict(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {"cellType": self.cell_type, "cellAddress": self.cell_address}
        if self.pinned_cell_id:
            out["pinnedCellId"] = self.pinned_cell_id
        if self.cell_children:
            out["cellChildren"] = [c.to_dict() for c in self.cell_children]
        return out


@dataclass
class PhysicalClusterSpec:
    """Reference: types.go:41-44, plus ``skuTypes`` as a superset of the
    reference schema: the reference's YAML decoder silently drops the key
    (external tooling reads it from the raw config instead), while this build
    carries it through so configs round-trip losslessly. The scheduler never
    consumes it."""

    cell_types: Dict[CellType, CellTypeSpec] = field(default_factory=dict)
    physical_cells: List[PhysicalCellSpec] = field(default_factory=list)
    sku_types: Dict[str, Any] = field(default_factory=dict)

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "PhysicalClusterSpec":
        return PhysicalClusterSpec(
            cell_types={k: CellTypeSpec.from_dict(v) for k, v in (d.get("cellTypes") or {}).items()},
            physical_cells=[PhysicalCellSpec.from_dict(c) for c in d.get("physicalCells", [])],
            sku_types=dict(d.get("skuTypes") or {}),
        )

    def to_dict(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {
            "cellTypes": {k: v.to_dict() for k, v in self.cell_types.items()},
            "physicalCells": [c.to_dict() for c in self.physical_cells],
        }
        if self.sku_types:
            out["skuTypes"] = copy.deepcopy(self.sku_types)  # fresh structure
        return out


# ---------------------------------------------------------------------------
# Virtual cluster spec
# ---------------------------------------------------------------------------


@dataclass
class VirtualCellSpec:
    """Reference: types.go:69-72. ``cell_type`` uses the ``chain.type`` path
    syntax for non-top cell types (reference: config.go:370-374)."""

    cell_number: int
    cell_type: CellType

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "VirtualCellSpec":
        return VirtualCellSpec(cell_number=int(d["cellNumber"]), cell_type=d["cellType"])

    def to_dict(self) -> Dict[str, Any]:
        return {"cellNumber": self.cell_number, "cellType": self.cell_type}


@dataclass
class PinnedCellSpec:
    """Reference: types.go:74-76."""

    pinned_cell_id: PinnedCellId

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "PinnedCellSpec":
        return PinnedCellSpec(pinned_cell_id=d["pinnedCellId"])

    def to_dict(self) -> Dict[str, Any]:
        return {"pinnedCellId": self.pinned_cell_id}


@dataclass
class VirtualClusterSpec:
    """Reference: types.go:64-67, plus the per-VC ``schedulingPolicy`` hook
    (the reference leaves this as a TODO, hived_algorithm.go:133):
    ``pack`` (default — busiest nodes first, tightest affinity) or ``spread``
    (emptiest nodes first, for failure-domain spreading)."""

    virtual_cells: List[VirtualCellSpec] = field(default_factory=list)
    pinned_cells: List[PinnedCellSpec] = field(default_factory=list)
    scheduling_policy: str = "pack"

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "VirtualClusterSpec":
        return VirtualClusterSpec(
            virtual_cells=[VirtualCellSpec.from_dict(c) for c in d.get("virtualCells", [])],
            pinned_cells=[PinnedCellSpec.from_dict(c) for c in d.get("pinnedCells", [])],
            scheduling_policy=d.get("schedulingPolicy", "pack"),
        )

    def to_dict(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {"virtualCells": [c.to_dict() for c in self.virtual_cells]}
        if self.pinned_cells:
            out["pinnedCells"] = [c.to_dict() for c in self.pinned_cells]
        if self.scheduling_policy != "pack":
            out["schedulingPolicy"] = self.scheduling_policy
        return out


# ---------------------------------------------------------------------------
# Pod scheduling spec + bind info
# ---------------------------------------------------------------------------


@dataclass
class AffinityGroupMemberSpec:
    """Reference: types.go:95-98."""

    pod_number: int
    leaf_cell_number: int

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "AffinityGroupMemberSpec":
        n = d.get("chipNumber", d.get("leafCellNumber", d.get("gpuNumber", 0)))
        return AffinityGroupMemberSpec(
            pod_number=int(d["podNumber"]), leaf_cell_number=int(n or 0)
        )

    def to_dict(self) -> Dict[str, Any]:
        return {"podNumber": self.pod_number, "leafCellNumber": self.leaf_cell_number}


@dataclass
class AffinityGroupSpec:
    """Reference: types.go:90-93."""

    name: str
    members: List[AffinityGroupMemberSpec] = field(default_factory=list)

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "AffinityGroupSpec":
        return AffinityGroupSpec(
            name=d.get("name", ""),
            members=[AffinityGroupMemberSpec.from_dict(m) for m in d.get("members", [])],
        )

    def to_dict(self) -> Dict[str, Any]:
        return {"name": self.name, "members": [m.to_dict() for m in self.members]}


@dataclass
class PodSchedulingSpec:
    """User request carried in the pod-scheduling-spec annotation.

    Reference: types.go:78-88. ``chipType``/``chipNumber`` are the TPU-native
    keys; ``leafCellType``/``leafCellNumber`` and the legacy
    ``gpuType``/``gpuNumber`` are accepted on input (internal/utils.go:189-197)
    so HiveD specs work unchanged."""

    virtual_cluster: VirtualClusterName = ""
    priority: int = 0
    pinned_cell_id: PinnedCellId = ""
    leaf_cell_type: str = ""
    leaf_cell_number: int = 0
    gang_release_enable: bool = False
    lazy_preemption_enable: bool = False
    ignore_k8s_suggested_nodes: bool = True
    # opt-out for gangs that need single-chain interconnect locality:
    # with False the group waits (reference behavior) instead of being
    # split across same-leaf-type chains when no single chain fits
    multi_chain_relax_enable: bool = True
    # how a relaxed gang is partitioned across chains: "fewest" (default)
    # takes the largest prefix each chain accepts — fewest cross-chain
    # (DCN) boundaries; "balanced" equalizes sub-gang chip counts over the
    # minimal chain set — the per-sub-gang ICI phase of a hierarchical
    # collective is balanced instead of straggled by one oversized
    # sub-gang
    multi_chain_relax_policy: str = "fewest"
    # expected run time in seconds (0 = unknown): duration-aware guaranteed
    # backfill admits a gang into a reserved hole only when it finishes
    # before the hold expires (defrag/backfill.py)
    duration_seconds: float = 0.0
    # elastic shape ladder floor in TOTAL gang chips (0 = not elastic): the
    # gang accepts any halving-ladder shape down to this floor when its
    # full shape is blocked (doc/design/elastic.md)
    elastic_min_chips: int = 0
    # scheduler-written onto a DEGRADED incarnation's pods: the original
    # (full-shape) member list, so the full shape survives crashes and the
    # grow-promotion path can restore it
    elastic_full_members: Optional[List[AffinityGroupMemberSpec]] = None
    affinity_group: Optional[AffinityGroupSpec] = None

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "PodSchedulingSpec":
        leaf_type = d.get("chipType", d.get("leafCellType", d.get("gpuType", "")))
        leaf_num = d.get("chipNumber", d.get("leafCellNumber", d.get("gpuNumber", 0)))
        return PodSchedulingSpec(
            virtual_cluster=d.get("virtualCluster", ""),
            priority=int(d.get("priority", 0)),
            pinned_cell_id=d.get("pinnedCellId", ""),
            leaf_cell_type=leaf_type or "",
            leaf_cell_number=int(leaf_num or 0),
            gang_release_enable=bool(d.get("gangReleaseEnable", False)),
            lazy_preemption_enable=bool(d.get("lazyPreemptionEnable", False)),
            ignore_k8s_suggested_nodes=bool(d.get("ignoreK8sSuggestedNodes", True)),
            multi_chain_relax_enable=bool(d.get("multiChainRelaxEnable", True)),
            multi_chain_relax_policy=d.get("multiChainRelaxPolicy", "fewest"),
            duration_seconds=float(d.get("durationSeconds", 0) or 0),
            elastic_min_chips=int(d.get("elasticMinChips", 0) or 0),
            elastic_full_members=(
                [AffinityGroupMemberSpec.from_dict(m)
                 for m in d["elasticFullMembers"]]
                if d.get("elasticFullMembers") else None
            ),
            affinity_group=(
                AffinityGroupSpec.from_dict(d["affinityGroup"]) if d.get("affinityGroup") else None
            ),
        )

    def to_dict(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {
            "virtualCluster": self.virtual_cluster,
            "priority": self.priority,
            "leafCellType": self.leaf_cell_type,
            "leafCellNumber": self.leaf_cell_number,
            "gangReleaseEnable": self.gang_release_enable,
            "lazyPreemptionEnable": self.lazy_preemption_enable,
            "ignoreK8sSuggestedNodes": self.ignore_k8s_suggested_nodes,
            "multiChainRelaxEnable": self.multi_chain_relax_enable,
        }
        if self.multi_chain_relax_policy != "fewest":
            out["multiChainRelaxPolicy"] = self.multi_chain_relax_policy
        if self.duration_seconds:
            out["durationSeconds"] = self.duration_seconds
        if self.elastic_min_chips:
            out["elasticMinChips"] = self.elastic_min_chips
        if self.elastic_full_members is not None:
            out["elasticFullMembers"] = [
                m.to_dict() for m in self.elastic_full_members
            ]
        if self.pinned_cell_id:
            out["pinnedCellId"] = self.pinned_cell_id
        if self.affinity_group is not None:
            out["affinityGroup"] = self.affinity_group.to_dict()
        return out


@dataclass
class PodPlacementInfo:
    """Reference: types.go:110-118."""

    physical_node: str
    physical_leaf_cell_indices: List[int] = field(default_factory=list)
    preassigned_cell_types: List[CellType] = field(default_factory=list)

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "PodPlacementInfo":
        return PodPlacementInfo(
            physical_node=d.get("physicalNode", ""),
            physical_leaf_cell_indices=[int(i) for i in d.get("physicalLeafCellIndices", [])],
            preassigned_cell_types=list(d.get("preassignedCellTypes") or []),
        )

    def to_dict(self) -> Dict[str, Any]:
        return {
            "physicalNode": self.physical_node,
            "physicalLeafCellIndices": self.physical_leaf_cell_indices,
            "preassignedCellTypes": self.preassigned_cell_types,
        }


@dataclass
class AffinityGroupMemberBindInfo:
    """Reference: types.go:106-108."""

    pod_placements: List[PodPlacementInfo] = field(default_factory=list)

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "AffinityGroupMemberBindInfo":
        return AffinityGroupMemberBindInfo(
            pod_placements=[PodPlacementInfo.from_dict(p) for p in d.get("podPlacements", [])]
        )

    def to_dict(self) -> Dict[str, Any]:
        return {"podPlacements": [p.to_dict() for p in self.pod_placements]}


@dataclass
class PodBindInfo:
    """Durable placement record written into the pod-bind-info annotation at
    bind time and replayed at startup (reference: types.go:100-104,
    scheduler.go:306-337)."""

    node: str
    leaf_cell_isolation: List[int] = field(default_factory=list)
    cell_chain: str = ""
    affinity_group_bind_info: List[AffinityGroupMemberBindInfo] = field(default_factory=list)

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "PodBindInfo":
        return PodBindInfo(
            node=d.get("node", ""),
            leaf_cell_isolation=[int(i) for i in d.get("leafCellIsolation", [])],
            cell_chain=d.get("cellChain", ""),
            affinity_group_bind_info=[
                AffinityGroupMemberBindInfo.from_dict(m)
                for m in d.get("affinityGroupBindInfo", [])
            ],
        )

    def to_dict(self, include_group: bool = True) -> Dict[str, Any]:
        out: Dict[str, Any] = {
            "node": self.node,
            "leafCellIsolation": self.leaf_cell_isolation,
            "cellChain": self.cell_chain,
        }
        if include_group:
            out["affinityGroupBindInfo"] = [
                m.to_dict() for m in self.affinity_group_bind_info
            ]
        return out


# ---------------------------------------------------------------------------
# Inspect DTOs (reference: types.go:140-273)
# ---------------------------------------------------------------------------

CELL_HEALTHY = "Healthy"
CELL_BAD = "Bad"


@dataclass
class LazyPreemptionStatus:
    preemptor: str
    preemption_time: str

    def to_dict(self) -> Dict[str, Any]:
        return {"preemptor": self.preemptor, "preemptionTime": self.preemption_time}


@dataclass
class AffinityGroupStatus:
    vc: VirtualClusterName = ""
    priority: int = 0
    state: str = ""
    physical_placement: Dict[str, List[int]] = field(default_factory=dict)
    virtual_placement: Dict[CellAddress, List[CellAddress]] = field(default_factory=dict)
    allocated_pods: List[str] = field(default_factory=list)
    preempting_pods: List[str] = field(default_factory=list)
    lazy_preemption_status: Optional[LazyPreemptionStatus] = None

    def to_dict(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {"vc": self.vc, "priority": self.priority, "state": self.state}
        if self.physical_placement:
            out["physicalPlacement"] = self.physical_placement
        if self.virtual_placement:
            out["virtualPlacement"] = self.virtual_placement
        if self.allocated_pods:
            out["allocatedPods"] = self.allocated_pods
        if self.preempting_pods:
            out["preemptingPods"] = self.preempting_pods
        if self.lazy_preemption_status is not None:
            out["lazyPreemptionStatus"] = self.lazy_preemption_status.to_dict()
        return out


@dataclass
class AffinityGroup:
    name: str
    status: AffinityGroupStatus

    def to_dict(self) -> Dict[str, Any]:
        return {"metadata": {"name": self.name}, "status": self.status.to_dict()}


@dataclass
class CellStatus:
    """Reference: types.go:184-205. ``mesh_origin``/``mesh_shape`` are TPU
    extensions exposing the cell's sub-mesh geometry."""

    cell_type: CellType = ""
    cell_address: CellAddress = ""
    cell_state: str = ""
    cell_healthiness: str = CELL_HEALTHY
    cell_priority: int = 0
    leaf_cell_type: str = ""
    is_node_level: bool = False
    mesh_origin: Optional[Tuple[int, ...]] = None
    mesh_shape: Optional[Tuple[int, ...]] = None

    def base_dict(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {
            "cellType": self.cell_type,
            "cellAddress": self.cell_address,
            "cellState": self.cell_state,
            "cellHealthiness": self.cell_healthiness,
            "cellPriority": self.cell_priority,
        }
        if self.leaf_cell_type:
            out["leafCellType"] = self.leaf_cell_type
        if self.is_node_level:
            out["isNodeLevel"] = True
        if self.mesh_origin is not None:
            out["meshOrigin"] = list(self.mesh_origin)
        if self.mesh_shape is not None:
            out["meshShape"] = list(self.mesh_shape)
        return out


@dataclass
class PhysicalCellStatus(CellStatus):
    cell_children: List["PhysicalCellStatus"] = field(default_factory=list)
    vc: VirtualClusterName = ""
    virtual_cell: Optional["VirtualCellStatus"] = None

    def to_dict(self) -> Dict[str, Any]:
        out = self.base_dict()
        if self.cell_children:
            out["cellChildren"] = [c.to_dict() for c in self.cell_children]
        if self.vc:
            out["vc"] = self.vc
        if self.virtual_cell is not None:
            out["virtualCell"] = self.virtual_cell.to_dict()
        return out

    def deep_copy(self) -> "PhysicalCellStatus":
        return copy.deepcopy(self)


@dataclass
class VirtualCellStatus(CellStatus):
    cell_children: List["VirtualCellStatus"] = field(default_factory=list)
    physical_cell: Optional[PhysicalCellStatus] = None

    def to_dict(self) -> Dict[str, Any]:
        out = self.base_dict()
        if self.cell_children:
            out["cellChildren"] = [c.to_dict() for c in self.cell_children]
        if self.physical_cell is not None:
            out["physicalCell"] = self.physical_cell.to_dict()
        return out

    def deep_copy(self) -> "VirtualCellStatus":
        return copy.deepcopy(self)


@dataclass
class ClusterStatus:
    physical_cluster: List[PhysicalCellStatus] = field(default_factory=list)
    virtual_clusters: Dict[VirtualClusterName, List[VirtualCellStatus]] = field(
        default_factory=dict
    )

    def to_dict(self) -> Dict[str, Any]:
        return {
            "physicalCluster": [c.to_dict() for c in self.physical_cluster],
            "virtualClusters": {
                vc: [c.to_dict() for c in cells] for vc, cells in self.virtual_clusters.items()
            },
        }
