"""TPU kernels (Pallas) with XLA fallbacks."""

from hivedscheduler_tpu.ops.attention import flash_attention, xla_attention  # noqa: F401
