"""Fused causal attention.

``flash_attention`` is a Pallas TPU kernel (online-softmax over key/value
blocks, never materializing the [T, T] score matrix in HBM); on non-TPU
backends it runs the same kernel through the Pallas interpreter, and
``xla_attention`` is the plain einsum reference used for correctness checks
and as a safe fallback. Blocks are sized to the MXU/VPU tiling constraints
(multiples of 128 lanes).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

NEG_INF = -1e30


def xla_attention(q: jax.Array, k: jax.Array, v: jax.Array, causal: bool = True) -> jax.Array:
    """Reference attention: q/k/v [B, T, H, D] -> [B, T, H, D]."""
    scale = 1.0 / (q.shape[-1] ** 0.5)
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32) * scale
    if causal:
        t_q, t_k = q.shape[1], k.shape[1]
        mask = lax.iota(jnp.int32, t_q)[:, None] >= lax.iota(jnp.int32, t_k)[None, :]
        s = jnp.where(mask[None, None], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(p.dtype)).astype(q.dtype)


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, *, block_k: int, causal: bool, scale: float):
    """One grid step handles one (batch*head, q-block); loops over k blocks
    with online softmax. Refs are [block_q, D] / [T, D] slices."""
    block_q, d = q_ref.shape
    t_k = k_ref.shape[0]
    q_blk_idx = pl.program_id(1)
    q = q_ref[:].astype(jnp.float32) * scale
    q_offset = q_blk_idx * block_q

    m = jnp.full((block_q,), NEG_INF, jnp.float32)
    l = jnp.zeros((block_q,), jnp.float32)
    o = jnp.zeros((block_q, d), jnp.float32)

    num_k_blocks = t_k // block_k
    if causal:
        # only blocks up to (and including) the diagonal contribute
        last_block = lax.div(q_offset + block_q - 1, block_k) + 1
    else:
        last_block = num_k_blocks

    def body(j, carry):
        m, l, o = carry
        k_blk = k_ref[pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        s = jnp.dot(q, k_blk.T, preferred_element_type=jnp.float32)  # [bq, bk]
        if causal:
            q_pos = q_offset + lax.iota(jnp.int32, block_q)
            k_pos = j * block_k + lax.iota(jnp.int32, block_k)
            s = jnp.where(q_pos[:, None] >= k_pos[None, :], s, NEG_INF)
        m_blk = jnp.max(s, axis=-1)
        m_new = jnp.maximum(m, m_blk)
        m_safe = jnp.maximum(m_new, -0.5 * abs(NEG_INF))
        p = jnp.exp(s - m_safe[:, None])
        corr = jnp.exp(jnp.maximum(m, -0.5 * abs(NEG_INF)) - m_safe)
        l = l * corr + jnp.sum(p, axis=-1)
        o = o * corr[:, None] + jnp.dot(p, v_blk, preferred_element_type=jnp.float32)
        return m_new, l, o

    m, l, o = lax.fori_loop(0, last_block, body, (m, l, o))
    l_safe = jnp.where(l == 0.0, 1.0, l)
    o_ref[:] = (o / l_safe[:, None]).astype(o_ref.dtype)


try:  # pallas is TPU/GPU-oriented; import lazily-tolerant for exotic builds
    from jax.experimental import pallas as pl
except Exception:  # pragma: no cover
    pl = None


@functools.partial(jax.jit, static_argnames=("causal", "block_q", "block_k", "interpret"))
def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = True,
    block_q: int = 128,
    block_k: int = 128,
    interpret: bool | None = None,
) -> jax.Array:
    """Fused attention: q/k/v [B, T, H, D] -> [B, T, H, D].

    Falls back to :func:`xla_attention` when Pallas is unavailable or shapes
    don't tile (T must divide by the block sizes, D a multiple of 8)."""
    b, t, h, d = q.shape
    if pl is None or t % block_q or t % block_k or d % 8:
        return xla_attention(q, k, v, causal=causal)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    scale = 1.0 / (d**0.5)

    # fold batch and heads into the grid; blocks are [block_q, D] per program
    qf = q.transpose(0, 2, 1, 3).reshape(b * h, t, d)
    kf = k.transpose(0, 2, 1, 3).reshape(b * h, t, d)
    vf = v.transpose(0, 2, 1, 3).reshape(b * h, t, d)

    kernel = functools.partial(
        _flash_kernel, block_k=block_k, causal=causal, scale=scale
    )
    out = pl.pallas_call(
        kernel,
        grid=(b * h, t // block_q),
        in_specs=[
            pl.BlockSpec((None, block_q, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((None, t, d), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((None, t, d), lambda i, j: (i, 0, 0)),
        ],
        out_specs=pl.BlockSpec((None, block_q, d), lambda i, j: (i, j, 0)),
        out_shape=jax.ShapeDtypeStruct((b * h, t, d), q.dtype),
        interpret=interpret,
    )(qf, kf, vf)
    return out.reshape(b, h, t, d).transpose(0, 2, 1, 3)
