"""Fused causal attention with a trainable Pallas TPU kernel.

``flash_attention`` is a flash-attention Pallas kernel (online-softmax over
key/value blocks, never materializing the [T, T] score matrix in HBM) with a
``jax.custom_vjp``: the forward kernel additionally emits the per-row
log-sum-exp residual and two backward kernels recompute block scores to
produce dq and dk/dv, so the op is usable in training, not just inference.
On non-TPU backends the same kernels run through the Pallas interpreter;
``xla_attention`` is the plain einsum reference used for correctness checks
and as a safe fallback for shapes that don't tile.

Grouped-query attention is supported natively: k/v may carry fewer heads than
q (``h % h_kv == 0``) and the kernels index the shared k/v head for each
query-head grid step directly, so compact GQA k/v never has to be
materialized to the full head count.

TPU/mosaic notes: all iotas are 2-D ``broadcasted_iota`` and the log-sum-exp
residual is stored 128-lanes wide ([B*H, T, 128], every lane equal), matching
the layout constraints the hardware vector unit imposes (the same convention
jax's reference TPU kernel uses). Softmax statistics live as [block, 1]
columns, which mosaic lane-broadcasts.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

NEG_INF = -1e30
_LANES = 128  # minimum lane width for stored residuals


def _sds(shape, dtype, svma=None):
    """ShapeDtypeStruct with the vma stamp only where the JAX version
    supports it: pre-vma JAX (0.4.x) has no ``vma`` kwarg at all, and
    passing it — even as None — raises TypeError, taking the whole
    compiled-kernel path down with it. There is nothing to stamp on those
    versions (shard_map does not track varying axes), so dropping it is
    exact, not a degradation."""
    if svma:
        try:
            return jax.ShapeDtypeStruct(shape, dtype, vma=svma)
        except TypeError:  # pre-vma JAX
            pass
    return jax.ShapeDtypeStruct(shape, dtype)


def xla_attention(q: jax.Array, k: jax.Array, v: jax.Array, causal: bool = True) -> jax.Array:
    """Reference attention: q [B, T, H, D], k/v [B, T, H_kv, D] -> [B, T, H, D].

    Supports grouped-query attention (H_kv dividing H) via grouped einsums,
    without materializing repeated k/v heads.
    """
    b, t_q, h, d = q.shape
    h_kv = k.shape[2]
    if h % h_kv:
        raise ValueError(
            f"GQA needs n_heads divisible by kv heads: {h} % {h_kv} != 0"
        )
    scale = 1.0 / (d**0.5)
    if h_kv == h:
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32) * scale
        if causal:
            t_k = k.shape[1]
            mask = lax.iota(jnp.int32, t_q)[:, None] >= lax.iota(jnp.int32, t_k)[None, :]
            s = jnp.where(mask[None, None], s, NEG_INF)
        p = jax.nn.softmax(s, axis=-1)
        return jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(p.dtype)).astype(q.dtype)
    g = h // h_kv
    qg = q.reshape(b, t_q, h_kv, g, d)
    s = jnp.einsum("bqhgd,bkhd->bhgqk", qg, k, preferred_element_type=jnp.float32) * scale
    if causal:
        t_k = k.shape[1]
        mask = lax.iota(jnp.int32, t_q)[:, None] >= lax.iota(jnp.int32, t_k)[None, :]
        s = jnp.where(mask[None, None, None], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bhgqk,bkhd->bqhgd", p, v.astype(p.dtype))
    return out.reshape(b, t_q, h, d).astype(q.dtype)


try:  # pallas is TPU/GPU-oriented; import lazily-tolerant for exotic builds
    from jax.experimental import pallas as pl
except Exception:  # pragma: no cover
    pl = None


def _causal_mask(s, q_offset, k_offset):
    """Mask [bq, bk] scores with absolute row/col offsets (2-D iotas only)."""
    bq, bk = s.shape
    rows = lax.broadcasted_iota(jnp.int32, (bq, bk), 0) + q_offset
    cols = lax.broadcasted_iota(jnp.int32, (bq, bk), 1) + k_offset
    return jnp.where(rows >= cols, s, NEG_INF)


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, block_k, causal, scale):
    """One grid step handles one (batch*q-head, q-block); loops over k blocks
    with online softmax. q/o refs are [block_q, D]; k/v refs [T, D] (the
    shared GQA head for this q head); lse_ref [block_q, 128]."""
    block_q, d = q_ref.shape
    t_k = k_ref.shape[0]
    q_blk_idx = pl.program_id(1)
    q = q_ref[:].astype(jnp.float32) * scale
    q_offset = q_blk_idx * block_q

    m = jnp.full((block_q, 1), NEG_INF, jnp.float32)
    l = jnp.zeros((block_q, 1), jnp.float32)
    o = jnp.zeros((block_q, d), jnp.float32)

    num_k_blocks = t_k // block_k
    if causal:
        # only blocks up to (and including) the diagonal contribute
        last_block = lax.div(q_offset + block_q - 1, block_k) + 1
    else:
        last_block = num_k_blocks

    def body(j, carry):
        m, l, o = carry
        k_blk = k_ref[pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        s = jnp.dot(q, k_blk.T, preferred_element_type=jnp.float32)  # [bq, bk]
        if causal:
            s = _causal_mask(s, q_offset, j * block_k)
        m_blk = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m, m_blk)
        # clamp so fully-masked partial rows exp() to 0 instead of 1
        m_safe = jnp.maximum(m_new, -0.5 * abs(NEG_INF))
        p = jnp.exp(s - m_safe)
        corr = jnp.exp(jnp.maximum(m, -0.5 * abs(NEG_INF)) - m_safe)
        l = l * corr + jnp.sum(p, axis=-1, keepdims=True)
        o = o * corr + jnp.dot(p, v_blk, preferred_element_type=jnp.float32)
        return m_new, l, o

    m, l, o = lax.fori_loop(0, last_block, body, (m, l, o))
    l_safe = jnp.where(l == 0.0, 1.0, l)
    o_ref[:] = (o / l_safe).astype(o_ref.dtype)
    lse = jnp.maximum(m, -0.5 * abs(NEG_INF)) + jnp.log(l_safe)
    lse_ref[:] = jnp.broadcast_to(lse, (block_q, _LANES))


def _bwd_dq_kernel(
    q_ref, k_ref, v_ref, o_ref, do_ref, lse_ref, dq_ref, *, block_k, causal, scale
):
    """dq for one (batch*q-head, q-block): recompute scores per k block.

    ds = p * (dp - delta), dq = scale * ds @ k  (standard flash backward)."""
    block_q, d = q_ref.shape
    t_k = k_ref.shape[0]
    q_blk_idx = pl.program_id(1)
    q_offset = q_blk_idx * block_q
    q = q_ref[:].astype(jnp.float32) * scale
    do = do_ref[:].astype(jnp.float32)
    o = o_ref[:].astype(jnp.float32)
    lse = lse_ref[:, :1]  # [bq, 1]
    delta = jnp.sum(do * o, axis=-1, keepdims=True)  # [bq, 1]

    if causal:
        last_block = lax.div(q_offset + block_q - 1, block_k) + 1
    else:
        last_block = t_k // block_k

    def body(j, dq):
        k_blk = k_ref[pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        s = jnp.dot(q, k_blk.T, preferred_element_type=jnp.float32)
        if causal:
            s = _causal_mask(s, q_offset, j * block_k)
        p = jnp.exp(s - lse)  # masked entries: exp(NEG_INF - lse) == 0
        dp = jnp.dot(do, v_blk.T, preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        return dq + jnp.dot(ds, k_blk, preferred_element_type=jnp.float32)

    dq = lax.fori_loop(0, last_block, body, jnp.zeros((block_q, d), jnp.float32))
    dq_ref[:] = (dq * scale).astype(dq_ref.dtype)


def _bwd_dkv_kernel(
    k_ref, v_ref, q_ref, o_ref, do_ref, lse_ref, dk_ref, dv_ref,
    *, block_q, causal, scale,
):
    """dk/dv for one (batch*q-head, k-block): loop over contributing q blocks.

    dv = p^T @ do ; dk = scale * ds^T @ q. For GQA the per-q-head partials
    are summed over the head group outside the kernel."""
    block_k, d = k_ref.shape
    t_q = q_ref.shape[0]
    k_blk_idx = pl.program_id(1)
    k_offset = k_blk_idx * block_k
    k = k_ref[:].astype(jnp.float32)
    v = v_ref[:].astype(jnp.float32)

    num_q_blocks = t_q // block_q
    first_block = lax.div(k_offset, block_q) if causal else 0

    def body(i, carry):
        dk, dv = carry
        q_blk = q_ref[pl.ds(i * block_q, block_q), :].astype(jnp.float32) * scale
        do_blk = do_ref[pl.ds(i * block_q, block_q), :].astype(jnp.float32)
        o_blk = o_ref[pl.ds(i * block_q, block_q), :].astype(jnp.float32)
        lse_blk = lse_ref[pl.ds(i * block_q, block_q), :1]  # [bq, 1]
        s = jnp.dot(q_blk, k.T, preferred_element_type=jnp.float32)
        if causal:
            s = _causal_mask(s, i * block_q, k_offset)
        p = jnp.exp(s - lse_blk)  # [bq, bk]
        # dv += p^T @ do  (contract the q axis)
        dv = dv + lax.dot_general(
            p, do_blk, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        delta = jnp.sum(do_blk * o_blk, axis=-1, keepdims=True)
        dp = jnp.dot(do_blk, v.T, preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        dk = dk + lax.dot_general(
            ds, q_blk, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return dk, dv

    zeros = jnp.zeros((block_k, d), jnp.float32)
    dk, dv = lax.fori_loop(first_block, num_q_blocks, body, (zeros, zeros))
    # q_blk already carried the 1/sqrt(d) scale; dk = d(scale*q k^T)/dk * ...
    dk_ref[:] = dk.astype(dk_ref.dtype)
    dv_ref[:] = dv.astype(dv_ref.dtype)


def _fold(x):
    """[B, T, H, D] -> [B*H, T, D]."""
    b, t, h, d = x.shape
    return x.transpose(0, 2, 1, 3).reshape(b * h, t, d)


def _unfold(x, b, h):
    bh, t, d = x.shape
    return x.reshape(b, h, t, d).transpose(0, 2, 1, 3)


def _flash_forward(q, k, v, *, causal, block_q, block_k, interpret, vma=(),
                   out_dtype=None):
    """-> (o [B,T,H,D], lse [B*H, T, 128] f32). Accepts compact GQA k/v.

    ``vma``: mesh axes the data varies over when called inside a manual
    (shard_map) context with check_vma=True — stamped on the pallas
    out_shape avals so the vma checker can type the outputs.

    ``out_dtype``: override the output dtype (default ``q.dtype``) — the
    ring-attention schedules merge per-block partials across ring steps and
    need them in f32 so accumulation precision matches the einsum ring."""
    svma = frozenset(vma) if vma else None
    b, t, h, d = q.shape
    h_kv = k.shape[2]
    group = h // h_kv
    qf, kf, vf = _fold(q), _fold(k), _fold(v)
    kernel = functools.partial(_fwd_kernel, block_k=block_k, causal=causal,
                               scale=1.0 / (d**0.5))
    o, lse = pl.pallas_call(
        kernel,
        grid=(b * h, t // block_q),
        in_specs=[
            pl.BlockSpec((None, block_q, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((None, t, d), lambda i, j, g=group: (i // g, 0, 0)),
            pl.BlockSpec((None, t, d), lambda i, j, g=group: (i // g, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((None, block_q, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((None, block_q, _LANES), lambda i, j: (i, j, 0)),
        ],
        out_shape=[
            _sds((b * h, t, d), out_dtype or q.dtype, svma),
            _sds((b * h, t, _LANES), jnp.float32, svma),
        ],
        interpret=interpret,
    )(qf, kf, vf)
    return _unfold(o, b, h), lse


def _flash_backward(q, k, v, o, lse, g, *, causal, block_q, block_k, interpret,
                    vma=(), grad_dtype=None):
    """``grad_dtype``: override the dq/dk/dv dtype (default ``q.dtype`` /
    ``k.dtype`` / ``v.dtype``) — the ring schedules accumulate per-block
    gradient partials across ring steps and need them in f32."""
    svma = frozenset(vma) if vma else None
    b, t, h, d = q.shape
    h_kv = k.shape[2]
    group = h // h_kv
    qf, kf, vf = _fold(q), _fold(k), _fold(v)
    of, gf = _fold(o), _fold(g)
    scale = 1.0 / (d**0.5)

    dq_kernel = functools.partial(
        _bwd_dq_kernel, block_k=block_k, causal=causal, scale=scale
    )
    dqf = pl.pallas_call(
        dq_kernel,
        grid=(b * h, t // block_q),
        in_specs=[
            pl.BlockSpec((None, block_q, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((None, t, d), lambda i, j, g=group: (i // g, 0, 0)),
            pl.BlockSpec((None, t, d), lambda i, j, g=group: (i // g, 0, 0)),
            pl.BlockSpec((None, block_q, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((None, block_q, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((None, block_q, _LANES), lambda i, j: (i, j, 0)),
        ],
        out_specs=pl.BlockSpec((None, block_q, d), lambda i, j: (i, j, 0)),
        out_shape=_sds((b * h, t, d), grad_dtype or q.dtype, svma),
        interpret=interpret,
    )(qf, kf, vf, of, gf, lse)

    dkv_kernel = functools.partial(
        _bwd_dkv_kernel, block_q=block_q, causal=causal, scale=scale
    )
    # per-q-head partials; the GQA head-group sum happens below in XLA
    dkf, dvf = pl.pallas_call(
        dkv_kernel,
        grid=(b * h, t // block_k),
        in_specs=[
            pl.BlockSpec((None, block_k, d), lambda i, j, g=group: (i // g, j, 0)),
            pl.BlockSpec((None, block_k, d), lambda i, j, g=group: (i // g, j, 0)),
            pl.BlockSpec((None, t, d), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((None, t, d), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((None, t, d), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((None, t, _LANES), lambda i, j: (i, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((None, block_k, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((None, block_k, d), lambda i, j: (i, j, 0)),
        ],
        out_shape=[
            _sds((b * h, t, d), jnp.float32, svma),
            _sds((b * h, t, d), jnp.float32, svma),
        ],
        interpret=interpret,
    )(kf, vf, qf, of, gf, lse)

    dq = _unfold(dqf, b, h)
    if group > 1:
        dkf = dkf.reshape(b, h_kv, group, t, d).sum(axis=2)
        dvf = dvf.reshape(b, h_kv, group, t, d).sum(axis=2)
        dk = dkf.transpose(0, 2, 1, 3)
        dv = dvf.transpose(0, 2, 1, 3)
    else:
        dk = _unfold(dkf, b, h)
        dv = _unfold(dvf, b, h)
    return (
        dq,
        dk.astype(grad_dtype or k.dtype),
        dv.astype(grad_dtype or v.dtype),
    )


_FLASH_CORES = {}


def _flash_core(causal: bool, block_q: int, block_k: int, interpret: bool,
                vma: tuple = ()):
    """custom_vjp-wrapped kernel pair, cached per static configuration
    (pattern shared with parallel/ring_attention._make_vjp_core)."""
    key = (causal, block_q, block_k, interpret, vma)
    core = _FLASH_CORES.get(key)
    if core is not None:
        return core

    kw = dict(causal=causal, block_q=block_q, block_k=block_k,
              interpret=interpret, vma=vma)

    @jax.custom_vjp
    def core(q, k, v):
        o, _ = _flash_forward(q, k, v, **kw)
        return o

    def fwd(q, k, v):
        o, lse = _flash_forward(q, k, v, **kw)
        return o, (q, k, v, o, lse)

    def bwd(res, g):
        q, k, v, o, lse = res
        return _flash_backward(q, k, v, o, lse, g, **kw)

    core.defvjp(fwd, bwd)
    _FLASH_CORES[key] = core
    return core


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = True,
    block_q: int = 128,
    block_k: int = 128,
    interpret: bool | None = None,
    vma: tuple = (),
) -> jax.Array:
    """Fused trainable attention: q [B, T, H, D], k/v [B, T, H_kv, D].

    Differentiable (custom_vjp with flash backward kernels) and GQA-aware
    (H_kv may divide H; compact k/v is consumed directly). Falls back to
    :func:`xla_attention` when Pallas is unavailable or shapes don't tile
    (T must divide by the block sizes, D a multiple of 8, H by H_kv).

    ``vma``: pass the manual-context varying axes when calling inside a
    shard_map (e.g. a pipeline stage body) so the vma checker can type the
    kernel outputs.
    """
    b, t, h, d = q.shape
    h_kv = k.shape[2]
    # cross-length q/k (e.g. KV-cache decode) must fall back too: the
    # BlockSpecs size k/v with q's sequence length
    if (pl is None or t % block_q or t % block_k or d % 8
            or (h_kv and h % h_kv) or k.shape[1] != t):
        return xla_attention(q, k, v, causal=causal)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if interpret and vma:
        # the Pallas HLO interpreter re-typechecks the kernel jaxpr under
        # the enclosing shard_map's vma rules, which the kernel's fresh
        # accumulators cannot satisfy; interpret mode only exists for
        # CPU testing, so use the einsum reference there. On real TPU the
        # compiled kernel is opaque and the vma-stamped out_shapes type it.
        return xla_attention(q, k, v, causal=causal)
    return _flash_core(causal, block_q, block_k, interpret, tuple(vma))(q, k, v)


# -- paged KV reads ----------------------------------------------------------
# The serving engine's paged cache (models/serving.py) stores KV as a block
# pool [n_blocks, block, ...] shared by every stream; a per-row block table
# maps logical token positions to pool blocks. The attention read is then a
# gather through the table — these helpers are the ONE home for that
# indirection so the decode, prefill and speculative-verify programs cannot
# disagree about the position <-> (block, offset) mapping.


def gather_block_kv(pool: jax.Array, table: jax.Array) -> jax.Array:
    """Per-row KV view of a paged block pool.

    ``pool``: [n_blocks, block, ...tail] (k/v: tail = [H_kv, D]; int8
    scales: tail = [H_kv]). ``table``: int32 [B, nbs] of block ids — entry
    ``j`` backs logical positions [j*block, (j+1)*block). Returns
    [B, nbs*block, ...tail] where axis 1 IS the logical token position, so
    the caller's causal position mask (key_pos <= query position) applies
    unchanged; unassigned table entries point at the reserved trash block
    (id 0) whose garbage only ever sits at masked positions.
    """
    g = pool[table]  # [B, nbs, block, ...tail]
    return g.reshape(g.shape[0], g.shape[1] * g.shape[2], *g.shape[3:])


def block_coords(positions: jax.Array, table: jax.Array, block: int):
    """(block id, in-block offset) scatter coordinates for writing new KV
    at ``positions`` [B, S] through ``table`` [B, nbs] (prefill callers pass
    a [1, nbs] row slice). Positions are clamped to the table's addressable
    range [0, nbs*block): idle/parked rows sit AT the clamp and write into
    whatever their last table entry points at — the trash block for
    unassigned entries, or a position at/past the row's live length for an
    owned block — which no query ever attends before the row itself
    rewrites it (the same drop-the-garbage invariant the dense ragged
    cache's out-of-bounds scatters rely on)."""
    nbs = table.shape[-1]
    pos = jnp.minimum(positions, nbs * block - 1)
    blk = jnp.take_along_axis(table, pos // block, axis=1)
    return blk, pos % block
