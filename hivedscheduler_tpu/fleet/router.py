"""Cross-replica request router with prefill/decode disaggregation.

``FleetRouter`` is the layer between user traffic and N per-replica
``ServingEngine``\\ s. Each replica is an engine plus its scheduler gang
identity and a role (``serve`` for a unified replica, ``prefill`` /
``decode`` in disaggregated mode). The router:

- **routes** each request by a pluggable policy:
  ``least_blocks`` (default) picks the replica with the fewest
  outstanding KV blocks (live pool blocks + queued prompt cover — the
  same footprint currency the paged engine's admission gate uses);
  ``prefix_affinity`` first consults a content-hash prefix index — when a
  replica's block-aligned prefix cache already holds the prompt's leading
  blocks, the request routes there (the prefill FLOPs it skips are worth
  more than load spread), falling back to least-blocks;
- **retries** shed/preempted/lost requests on another replica (the
  authoritative stream restarts from scratch; greedy streams are a pure
  function of (params, prompt), so the retried stream is token-exact vs
  an unshed run — guard: tests/test_fleet_router.py);
- **disaggregates** prefill from decode (``disaggregate=True``): each
  request runs a prefill leg on a prefill-role replica (a budget-1
  submit — the full prompt prefill plus one token, which also populates
  that replica's prefix cache), then a decode leg on a decode-role
  replica. The KV handoff between the legs is selected by
  ``HIVED_FLEET_KV_SHIP``: ``1`` (default) ships the prefix-cache
  payload host-side (block table + block contents; the decode replica
  imports it into its own pool and the decode leg's submit hits the
  imported prefix — token-exact by the prefix-cache exactness
  guarantee), ``0`` re-prefills on the decode side through its own
  prefix cache (the re-prefill-on-miss path; prefill/decode roles are
  then routed role-blind so no replica idles). Both modes are
  token-exact vs single-replica serving.

Concurrency: the router is driven by ONE thread (``submit``/``step``,
like the engines it owns); ``fleet_router_lock`` — a leaf above only the
observability leaves in the lock hierarchy — guards the bookkeeping so
the webserver's ``/v1/inspect/fleet`` snapshot can read concurrently.
Never call scheduler entry points (which take ``scheduler_lock``) while
holding it; the autoscaler's scale backends run outside it.

Chaos invariants (``chaos.invariants.check_fleet``): no request is lost
between shed and retry, no stream is double-routed, scale-down always
drains before teardown, and a handoff never leaves orphaned blocks.

Observability (ISSUE 13): with the journal enabled every fleet request
is a recorded **flight** (``fleet/<fid>``) — the router marks the
``route``/``router_queue``/``retry``/``handoff_ship``/``handoff_import``
legs, the engines mark ``admission_wait`` and ``prefill``/
``first_decode``, and the legs up to the first token sum to the measured
``ttft_s`` (``chaos.invariants.check_requests``). Every finished request
is observed into ``self.slo`` (:class:`obs.slo.SLOTracker`) with its
dominant-leg attribution; the autoscaler's TTFT up-pressure reads the
same tracker.
"""

from __future__ import annotations

import dataclasses
import time
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Tuple

from hivedscheduler_tpu.common import envflags, lockcheck
from hivedscheduler_tpu.models.serving import (
    EngineDraining,
    Request,
    ServingEngine,
    SpeculativeServingEngine,
)
from hivedscheduler_tpu.obs import journal as obs_journal
from hivedscheduler_tpu.obs import slo as obs_slo
from hivedscheduler_tpu.runtime.metrics import REGISTRY as metrics

_POLICIES = ("least_blocks", "prefix_affinity")
_RETRYABLE = ("shed", "preempted")


def kv_ship_enabled() -> bool:
    """``HIVED_FLEET_KV_SHIP``: ``0`` selects re-prefill-on-miss instead
    of host-side KV shipping for the disaggregated handoff."""
    return envflags.get("HIVED_FLEET_KV_SHIP", "1") != "0"


class Replica:
    """One serving replica: an engine + its scheduler gang identity.

    ``state`` lifecycle: ``active`` -> (``draining`` -> ``drained``) |
    ``dead``. Only ``active`` replicas receive new routes; ``draining``
    replicas finish their in-flight work (work-preserving scale-down);
    ``dead`` is the chaos/abrupt-loss state — streams that were on a dead
    replica are retried elsewhere by the router."""

    def __init__(self, name: str, engine: ServingEngine, role: str = "serve",
                 gang: str = ""):
        self.name = name
        self.engine = engine
        self.role = role
        self.gang = gang or name
        self.state = "active"
        self.routed = 0  # legs dispatched here, lifetime

    def outstanding_blocks(self) -> int:
        """Outstanding work in KV blocks — the least-loaded routing key.
        Paged: live pool blocks + queued prompts' block cover (+1 decode
        headroom each, mirroring the admission gate). Dense: resident +
        queued tokens at a 16-token pseudo-block granularity, so mixed
        fleets still order sensibly."""
        eng = self.engine
        if getattr(eng, "paged", False):
            bs = eng.page_size
            queued = sum(-(-len(r.prompt) // bs) + 1 for r in eng.queue)
            return eng.blocks_in_use + queued
        tokens = sum(len(r.prompt) + len(r.tokens_out)
                     for r in eng.slots if r is not None)
        tokens += sum(len(r.prompt) for r in eng.queue)
        return -(-tokens // 16)

    def has_work(self) -> bool:
        eng = self.engine
        return bool(eng.queue) or any(s is not None for s in eng.slots)

    def to_dict(self) -> Dict[str, Any]:
        return {
            "name": self.name, "role": self.role, "gang": self.gang,
            "state": self.state, "routed": self.routed,
            "outstandingBlocks": self.outstanding_blocks(),
            "queueDepth": len(self.engine.queue),
            "activeSlots": sum(
                s is not None for s in self.engine.slots),
        }


@dataclasses.dataclass
class FleetRequest:
    """User-visible handle for one fleet request. ``attempts`` lists every
    (replica, engine-Request) decode leg dispatched — the LAST entry is
    the authoritative stream (earlier ones were shed/preempted/lost and
    retried); ``handoff`` holds the in-flight disaggregated prefill leg.
    Invariant (check_fleet): a live request has exactly one of a live
    handoff or a live last attempt — never neither (lost) nor both
    (double-routed)."""

    fid: int
    prompt: List[int]
    max_new_tokens: int
    priority: int = 0
    submitted_at: float = 0.0
    done: bool = False
    finish_reason: Optional[str] = None
    tokens_out: List[int] = dataclasses.field(default_factory=list)
    retries: int = 0
    replica: Optional[str] = None  # authoritative decode replica
    attempts: List[Tuple[str, Request]] = dataclasses.field(
        default_factory=list)
    handoff: Optional[Dict[str, Any]] = None
    first_token_at: Optional[float] = None
    done_at: Optional[float] = None

    @property
    def ttft_s(self) -> Optional[float]:
        """Fleet-level TTFT: earliest first token over every leg (in ship
        mode the prefill leg's first token IS the request's first token —
        greedy legs agree on it, so serving it early is legitimate)."""
        if self.first_token_at is None:
            return None
        return self.first_token_at - self.submitted_at

    @property
    def tpot_s(self) -> Optional[float]:
        """Mean seconds per output token after the first (None until done
        or when only one token was emitted) — the fleet twin of
        ``Request.tpot_s``, observed into the SLO tracker."""
        if self.done_at is None or self.first_token_at is None:
            return None
        n = len(self.tokens_out) - 1
        if n <= 0:
            return None
        return (self.done_at - self.first_token_at) / n


class FleetRouter:
    """See the module docstring. Engines must be config-identical
    (same TransformerConfig/page_size/kv_dtype) for the KV handoff and
    for the token-exactness of retries; the constructor of each replica
    is the caller's business (``add_replica`` takes a built engine)."""

    def __init__(self, policy: str = "least_blocks",
                 disaggregate: bool = False,
                 kv_ship: Optional[bool] = None,
                 max_retries: int = 2,
                 affinity_index_cap: int = 4096,
                 slo: Optional[obs_slo.SLOTracker] = None,
                 clock=time.perf_counter):
        if policy not in _POLICIES:
            raise ValueError(f"unknown routing policy {policy!r} "
                             f"(choose from {_POLICIES})")
        self._lock = lockcheck.make_lock("fleet_router_lock")
        self._clock = clock
        self.policy = policy
        self.disaggregate = disaggregate
        self.kv_ship = kv_ship_enabled() if kv_ship is None else kv_ship
        self.max_retries = max_retries
        self.replicas: Dict[str, Replica] = {}
        self.removed: List[Replica] = []
        self.requests: List[FleetRequest] = []
        self._next_fid = 0
        # content-hash prefix index: hash(prompt[:boundary]) -> replica
        # name whose prefix cache holds that chunk (bounded, LRU-evicted;
        # a stale/colliding entry only costs a suboptimal route, never
        # correctness)
        self._prefix_index: "OrderedDict[int, str]" = OrderedDict()
        self._index_cap = affinity_index_cap
        self.handoffs = {"ship": 0, "miss": 0, "reprefill": 0}
        self.retried = 0
        self.affinity_hits = 0
        # windowed TTFT/TPOT observations + declared-objective accounting
        # (obs/slo.py): the autoscaler's up-pressure signal, the
        # /v1/inspect/slo payload, and the tpu_hive_slo_* exposition are
        # all THIS tracker — one computation, one number
        self.slo = slo if slo is not None else obs_slo.SLOTracker(
            clock=clock)

    # -- replica lifecycle -------------------------------------------------
    def add_replica(self, name: str, engine: ServingEngine,
                    role: str = "serve", gang: str = "") -> Replica:
        if role not in ("serve", "prefill", "decode"):
            raise ValueError(f"unknown replica role {role!r}")
        if self.disaggregate and self.kv_ship:
            if isinstance(engine, SpeculativeServingEngine):
                raise ValueError(
                    "KV shipping across replicas does not support the "
                    "speculative engine; run the fleet with "
                    "HIVED_FLEET_KV_SHIP=0 (re-prefill-on-miss)"
                )
            if engine.prefix_cache_size <= 0:
                raise ValueError(
                    "disaggregated KV shipping needs prefix_cache_size > 0 "
                    "on every replica (the handoff payload travels through "
                    "the prefix cache)"
                )
        with self._lock:
            if name in self.replicas:
                raise ValueError(f"replica {name!r} already exists")
            rep = Replica(name, engine, role=role, gang=gang)
            self.replicas[name] = rep
            self._update_gauge_locked()
        return rep

    def begin_drain(self, name: str) -> None:
        """Work-preserving scale-down, step 1: stop routing to the
        replica and flip its engine's admission off; in-flight work keeps
        running until ``step()`` observes it drained."""
        with self._lock:
            rep = self.replicas.get(name)
            if rep is not None and rep.state == "active":
                rep.state = "draining"
                rep.engine.begin_drain()

    def kill(self, name: str) -> None:
        """Chaos/abrupt replica loss: the engine's in-flight streams are
        gone; the next ``step()`` retries their fleet requests on other
        replicas (the no-request-lost invariant)."""
        with self._lock:
            rep = self.replicas.get(name)
            if rep is not None:
                rep.state = "dead"
                self._update_gauge_locked()

    def remove_replica(self, name: str) -> None:
        """Teardown, step 2: only a drained or dead replica may be
        removed — drain-before-teardown on every scale-down is a
        check_fleet invariant, not a convention."""
        with self._lock:
            rep = self.replicas.get(name)
            if rep is None:
                return
            if rep.state not in ("drained", "dead"):
                raise ValueError(
                    f"scale-down must drain before teardown: replica "
                    f"{name!r} is {rep.state!r} (begin_drain + step until "
                    f"drained first)"
                )
            del self.replicas[name]
            self.removed.append(rep)
            for h, rname in list(self._prefix_index.items()):
                if rname == name:
                    del self._prefix_index[h]
            self._update_gauge_locked()

    def _update_gauge_locked(self) -> None:
        metrics.set_gauge(
            "tpu_hive_fleet_replicas",
            sum(1 for r in self.replicas.values()
                if r.state in ("active", "draining")))

    # -- routing -----------------------------------------------------------
    def _candidates_locked(self, leg: str, exclude=()) -> List[Replica]:
        role_blind = not self.disaggregate or not self.kv_ship
        return [
            r for r in self.replicas.values()
            if r.state == "active" and r.name not in exclude
            and (role_blind or r.role in (leg, "serve"))
        ]

    def _boundaries(self, prompt: List[int], engine) -> List[int]:
        """Chunk boundaries of the prefix index — the same rule the
        engine's ``_store_prefix`` keys on (block-aligned for paged,
        power-of-two for dense), so an index hit really names a cached
        chunk."""
        pl = len(prompt)
        out = [pl]
        if getattr(engine, "paged", False):
            b = engine.page_size
            while b < pl:
                out.append(b)
                b += engine.page_size
        else:
            b = 2
            while b < pl:
                out.append(b)
                b <<= 1
        return sorted(set(out), reverse=True)

    def _register_affinity_locked(self, prompt: List[int],
                                  rep: Replica) -> None:
        if rep.engine.prefix_cache_size <= 0:
            return
        for b in self._boundaries(prompt, rep.engine):
            key = hash(tuple(prompt[:b]))
            self._prefix_index.pop(key, None)
            self._prefix_index[key] = rep.name
        while len(self._prefix_index) > self._index_cap:
            self._prefix_index.popitem(last=False)

    def _pick_locked(self, prompt: List[int], leg: str,
                     exclude=()) -> Optional[Replica]:
        cands = self._candidates_locked(leg, exclude)
        if not cands:
            return None
        if self.policy == "prefix_affinity":
            by_name = {r.name: r for r in cands}
            for b in self._boundaries(prompt, cands[0].engine):
                name = self._prefix_index.get(hash(tuple(prompt[:b])))
                if name in by_name:
                    self.affinity_hits += 1
                    metrics.inc("tpu_hive_fleet_prefix_affinity_hits_total")
                    return by_name[name]
        return min(cands, key=lambda r: (r.outstanding_blocks(), r.routed,
                                         r.name))

    # -- request lifecycle -------------------------------------------------
    def submit(self, prompt: List[int], max_new_tokens: int,
               priority: int = 0) -> FleetRequest:
        with self._lock:
            freq = FleetRequest(self._next_fid, list(prompt),
                                max_new_tokens, priority=priority,
                                submitted_at=self._clock())
            self._next_fid += 1
            self.requests.append(freq)
            if obs_journal.JOURNAL.enabled:
                obs_journal.note_request_submit(
                    f"fleet/{freq.fid}", at=freq.submitted_at,
                    priority=priority, promptTokens=len(freq.prompt))
            self._dispatch_locked(freq)
        return freq

    def _dispatch_locked(self, freq: FleetRequest, exclude=()) -> None:
        if self.disaggregate and self.kv_ship:
            pre = self._pick_locked(freq.prompt, "prefill", exclude)
            if pre is not None:
                try:
                    req = pre.engine.submit(list(freq.prompt), 1,
                                            priority=freq.priority)
                except EngineDraining:
                    pre.state = "draining"  # drained out-of-band: honor it
                    self._dispatch_locked(freq, tuple(exclude) + (pre.name,))
                    return
                pre.routed += 1
                freq.handoff = {"replica": pre.name, "req": req}
                if obs_journal.JOURNAL.enabled:
                    req.flight = f"fleet/{freq.fid}"
                    obs_journal.note_leg(f"fleet/{freq.fid}", "route",
                                         at=req.submitted_at,
                                         replica=pre.name)
                    obs_journal.emit("fleet_route", f"fleet/{freq.fid}",
                                     leg="prefill", replica=pre.name,
                                     policy=self.policy)
                return
            # no prefill replica left: degrade to a re-prefill decode leg
        if self.disaggregate and not self.kv_ship:
            # the re-prefill-on-miss handoff mode: the decode leg carries
            # the whole request and re-prefills through its own cache
            self.handoffs["reprefill"] += 1
            metrics.inc("tpu_hive_fleet_handoffs_total", mode="reprefill")
            if obs_journal.JOURNAL.enabled:
                obs_journal.emit("fleet_handoff", f"fleet/{freq.fid}",
                                 mode="reprefill")
        self._dispatch_decode_locked(freq, exclude)

    def _dispatch_decode_locked(self, freq: FleetRequest, exclude=(),
                                cause: Optional[int] = None,
                                prefer: Optional[Replica] = None,
                                imported: bool = False) -> None:
        dec = prefer
        if dec is None or dec.state != "active" or dec.name in exclude:
            dec = self._pick_locked(freq.prompt, "decode", exclude)
        if dec is None:
            freq.done = True
            freq.finish_reason = "no_replica"
            freq.done_at = self._clock()
            metrics.inc("tpu_hive_fleet_requests_total",
                        outcome="no_replica")
            self._finish_flight_locked(freq)
            return
        try:
            req = dec.engine.submit(list(freq.prompt), freq.max_new_tokens,
                                    priority=freq.priority)
        except EngineDraining:
            dec.state = "draining"
            self._dispatch_decode_locked(freq,
                                         tuple(exclude) + (dec.name,),
                                         cause=cause, imported=imported)
            return
        dec.routed += 1
        freq.attempts.append((dec.name, req))
        freq.replica = dec.name
        self._register_affinity_locked(freq.prompt, dec)
        if obs_journal.JOURNAL.enabled:
            # the leg's engine marks (admission_wait + prefill/
            # first_decode) attribute into this fleet flight; ``imported``
            # legs resume from a shipped prefix, so their first token is
            # the `first_decode` leg, not a full `prefill`
            req.flight = f"fleet/{freq.fid}"
            req.flight_decode = imported
            obs_journal.note_leg(f"fleet/{freq.fid}", "route",
                                 at=req.submitted_at, cause=cause,
                                 replica=dec.name)
            obs_journal.emit("fleet_route", f"fleet/{freq.fid}",
                             cause=cause, leg="decode", replica=dec.name,
                             policy=self.policy)

    # -- the engine tick ---------------------------------------------------
    def step(self) -> bool:
        """Step every live replica's engine once, then advance handoffs,
        harvest finished legs (retrying shed/preempted/lost streams), and
        advance drains. Returns whether any fleet work remains."""
        with self._lock:
            reps = list(self.replicas.values())
        for rep in reps:
            if rep.state != "dead" and rep.has_work():
                rep.engine.step()
        with self._lock:
            self._advance_handoffs_locked()
            self._harvest_locked()
            self._advance_drains_locked()
            return any(not f.done for f in self.requests)

    def run_until_drained(self, max_steps: int = 100_000) -> None:
        for _ in range(max_steps):
            if not self.step():
                return
        raise RuntimeError(f"fleet did not drain in {max_steps} steps")

    def _leg_cause_locked(self, req: Request) -> Optional[int]:
        if not obs_journal.JOURNAL.enabled:
            return None
        return obs_journal.JOURNAL.last_id(f"serve/{req.rid}")

    def _advance_handoffs_locked(self) -> None:
        for freq in self.requests:
            if freq.done or freq.handoff is None:
                continue
            h = freq.handoff
            rep = self.replicas.get(h["replica"])
            req = h["req"]
            if rep is None or rep.state == "dead":
                # prefill replica lost mid-handoff: restart the dispatch
                freq.handoff = None
                freq.retries += 1
                self.retried += 1
                metrics.inc("tpu_hive_fleet_retries_total", leg="prefill")
                if obs_journal.JOURNAL.enabled:
                    # re-attribution: the lost leg's whole interval lands
                    # in `retry` — nothing between shed and retry is lost
                    obs_journal.note_leg(f"fleet/{freq.fid}", "retry",
                                         at=self._clock(),
                                         fromReplica=h["replica"],
                                         reason="replica_lost")
                    obs_journal.emit("fleet_retry", f"fleet/{freq.fid}",
                                     leg="prefill",
                                     fromReplica=h["replica"],
                                     reason="replica_lost")
                self._dispatch_locked(freq, (h["replica"],))
                continue
            if not req.done:
                continue
            cause = self._leg_cause_locked(req)
            freq.handoff = None
            journaled = obs_journal.JOURNAL.enabled
            if req.finish_reason in _RETRYABLE:
                # the prefill leg itself was shed/preempted: re-prefill on
                # the decode side (counted as a miss — no KV crossed)
                if journaled:
                    obs_journal.note_leg(f"fleet/{freq.fid}", "retry",
                                         at=self._clock(), cause=cause,
                                         fromReplica=rep.name,
                                         reason=req.finish_reason)
                mode, prefer = "miss", None
            else:
                if freq.first_token_at is None:
                    freq.first_token_at = req.first_token_at
                if journaled:
                    # the gap between the prefill leg finishing and THIS
                    # router step picking the handoff up
                    obs_journal.note_leg(f"fleet/{freq.fid}",
                                         "router_queue",
                                         at=self._clock(), cause=cause)
                prefer = self._pick_locked(freq.prompt, "decode")
                exp = rep.engine.export_prefix(freq.prompt)
                if journaled:
                    obs_journal.note_leg(f"fleet/{freq.fid}",
                                         "handoff_ship", at=self._clock(),
                                         fromReplica=rep.name,
                                         hit=exp is not None)
                if exp is not None and prefer is not None:
                    pkey, plen, data = exp
                    prefer.engine.import_prefix(pkey, plen, data)
                    self._register_affinity_locked(list(pkey), prefer)
                    if journaled:
                        obs_journal.note_leg(f"fleet/{freq.fid}",
                                             "handoff_import",
                                             at=self._clock(),
                                             toReplica=prefer.name,
                                             prefixTokens=plen)
                    mode = "ship"
                else:
                    mode = "miss"
            self.handoffs[mode] += 1
            metrics.inc("tpu_hive_fleet_handoffs_total", mode=mode)
            if journaled:
                obs_journal.emit("fleet_handoff", f"fleet/{freq.fid}",
                                 cause=cause, mode=mode,
                                 fromReplica=rep.name)
            self._dispatch_decode_locked(freq, cause=cause, prefer=prefer,
                                         imported=mode == "ship")

    def _harvest_locked(self) -> None:
        for freq in self.requests:
            if freq.done or freq.handoff is not None or not freq.attempts:
                continue
            rep_name, req = freq.attempts[-1]
            rep = self.replicas.get(rep_name)
            lost = (rep is None or rep.state == "dead") and not (
                req.done and req.finish_reason in ("eos", "length"))
            if lost:
                reason = "preempted"
                if not req.done:
                    # finalize the orphaned leg: its engine died with it
                    # (check_fleet pins that only the LAST attempt may be
                    # live)
                    req.done = True
                    req.done_at = self._clock()
                    req.finish_reason = "preempted"
            elif req.done:
                reason = req.finish_reason
            else:
                if freq.first_token_at is None and req.first_token_at:
                    freq.first_token_at = req.first_token_at
                continue
            if reason in _RETRYABLE and freq.retries < self.max_retries:
                alt = self._pick_locked(freq.prompt, "decode",
                                        exclude=(rep_name,))
                if alt is not None:
                    freq.retries += 1
                    self.retried += 1
                    metrics.inc("tpu_hive_fleet_retries_total", leg="decode")
                    cause = self._leg_cause_locked(req)
                    if obs_journal.JOURNAL.enabled:
                        obs_journal.note_leg(f"fleet/{freq.fid}", "retry",
                                             at=self._clock(), cause=cause,
                                             fromReplica=rep_name,
                                             reason=reason)
                        obs_journal.emit("fleet_retry", f"fleet/{freq.fid}",
                                         cause=cause, leg="decode",
                                         fromReplica=rep_name,
                                         reason=reason)
                    # the truncated stream is discarded whole: a greedy
                    # re-run emits the identical tokens from scratch
                    self._dispatch_decode_locked(freq, (rep_name,),
                                                 cause=cause, prefer=alt)
                    continue
            freq.done = True
            freq.finish_reason = reason
            freq.tokens_out = list(req.tokens_out)
            freq.done_at = self._clock()
            if freq.first_token_at is None:
                firsts = [r.first_token_at for _n, r in freq.attempts
                          if r.first_token_at is not None]
                freq.first_token_at = min(firsts) if firsts else None
            self._finish_flight_locked(freq)
            metrics.inc("tpu_hive_fleet_requests_total", outcome=reason)

    def _finish_flight_locked(self, freq: FleetRequest) -> None:
        """ONE home for a fleet request's terminal: close the journal
        flight (the sum-to-ttft accounting happens there) and observe the
        request into the SLO tracker with its dominant-leg attribution —
        the autoscaler's signal and the /v1/inspect/slo payload both read
        that tracker."""
        dom = ""
        if obs_journal.JOURNAL.enabled:
            key = f"fleet/{freq.fid}"
            obs_journal.note_request_done(
                key, freq.finish_reason,
                first_token_at=freq.first_token_at, at=freq.done_at,
                retries=freq.retries, tokensOut=len(freq.tokens_out))
            dom = obs_journal.JOURNAL.request_dominant_leg(key)
        if freq.ttft_s is not None:
            self.slo.observe("ttft", freq.ttft_s, priority=freq.priority,
                             leg=dom, at=freq.done_at)
        if freq.tpot_s is not None:
            self.slo.observe("tpot", freq.tpot_s, priority=freq.priority,
                             leg=dom, at=freq.done_at)

    def _advance_drains_locked(self) -> None:
        for rep in self.replicas.values():
            if rep.state == "draining" and not rep.has_work():
                rep.state = "drained"

    # -- read API (copy-on-read; the /v1/inspect/fleet payload) ------------
    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            live = [f for f in self.requests if not f.done]
            done = [f for f in self.requests if f.done]
            outcomes: Dict[str, int] = {}
            for f in done:
                outcomes[f.finish_reason] = outcomes.get(
                    f.finish_reason, 0) + 1
            return {
                "policy": self.policy,
                "disaggregate": self.disaggregate,
                "kvShip": self.kv_ship,
                "replicas": [r.to_dict() for r in self.replicas.values()],
                "removedReplicas": [
                    {"name": r.name, "state": r.state, "role": r.role}
                    for r in self.removed],
                "requests": {
                    "live": len(live), "done": len(done),
                    "outcomes": outcomes,
                    "inHandoff": sum(1 for f in live
                                     if f.handoff is not None),
                },
                "handoffs": dict(self.handoffs),
                "retries": self.retried,
                "affinityHits": self.affinity_hits,
                "prefixIndexSize": len(self._prefix_index),
            }


# -- module-level publication for the inspect endpoint ----------------------
_PUBLISHED: Optional[FleetRouter] = None


def publish(router: Optional[FleetRouter]) -> None:
    """Make ``router`` the process's inspectable fleet
    (``GET /v1/inspect/fleet``); ``None`` unpublishes."""
    global _PUBLISHED
    _PUBLISHED = router


def published() -> Optional[FleetRouter]:
    return _PUBLISHED
