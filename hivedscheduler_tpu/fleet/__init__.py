"""Serving fleet tier: the cross-replica layer between user traffic and
per-replica :class:`~hivedscheduler_tpu.models.serving.ServingEngine`\\ s.

The pieces composed here all predate this package — serving exports
block-pool occupancy as admission hints (``/v1/inspect/admission-hints``),
the scheduler shrinks/grow-promotes elastic gangs, and ``ServingEngine``
drains work-preservingly — but they did not talk. This package closes the
serving<->scheduler loop (ROADMAP item 2):

- :mod:`~hivedscheduler_tpu.fleet.router` — :class:`FleetRouter` owns N
  replica handles (engine + gang id + role), routes each request by a
  pluggable policy (least-outstanding-blocks default; prefix-affinity via
  a content-hash prefix index), retries shed/preempted/lost requests on
  another replica, and in disaggregated mode splits each request into a
  prefill leg and a decode leg with a KV handoff
  (``HIVED_FLEET_KV_SHIP=1`` ships block contents host-side;
  ``0`` re-prefills through the decode replica's prefix cache).
- :mod:`~hivedscheduler_tpu.fleet.autoscaler` — :class:`FleetAutoscaler`
  reads the engines' existing gauges (pool occupancy, queue depth, TTFT)
  and decides a target replica count per role with hysteresis + cooldown;
  scale-down is always drain-based, scale-up is effected through a scale
  backend — in-process for the bench, or through a live
  :class:`~hivedscheduler_tpu.runtime.scheduler.HivedScheduler` where
  each replica is a gang member pod competing under VC quotas.

Design doc: doc/design/fleet.md. Chaos invariants:
``chaos.invariants.check_fleet`` (rides ``check_all`` via ``router=``).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional

from hivedscheduler_tpu.fleet.router import (  # noqa: F401
    FleetRequest,
    FleetRouter,
    Replica,
    kv_ship_enabled,
    publish,
    published,
)
from hivedscheduler_tpu.fleet.autoscaler import (  # noqa: F401
    AutoscalePolicy,
    FleetAutoscaler,
    LocalScaleBackend,
    SchedulerScaleBackend,
)


@dataclasses.dataclass
class FleetConfig:
    """The ``fleet:`` section of a config YAML
    (example/config/design/fleet.yaml): router + disaggregation +
    autoscaler knobs, consumable by ``serve --fleet-config``. Unknown keys
    raise — a typo'd knob must not silently fall back to a default."""

    replicas: int = 2
    prefill_replicas: int = 1
    disaggregate: bool = False
    policy: str = "least_blocks"
    autoscale: bool = False
    min_replicas: int = 1
    max_replicas: int = 4
    occ_high: float = 0.75
    occ_low: float = 0.25
    queue_high: float = 4.0
    cooldown_s: float = -1.0
    up_stable_ticks: int = 2
    down_stable_ticks: int = 4
    # declared SLOs (obs/slo.py): 0 = the objective is not declared.
    # slo_window_s < 0 reads HIVED_SLO_WINDOW_S (0 = no time window);
    # slo_ttft_p99_by_priority maps priority class -> ceiling seconds
    slo_ttft_p99_s: float = 0.0
    slo_tpot_p95_s: float = 0.0
    slo_window_s: float = -1.0
    slo_ttft_p99_by_priority: Dict[int, float] = dataclasses.field(
        default_factory=dict)

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "FleetConfig":
        fields = {f.name for f in dataclasses.fields(FleetConfig)}
        unknown = sorted(set(d) - fields)
        if unknown:
            raise ValueError(f"unknown fleet config keys: {unknown} "
                             f"(known: {sorted(fields)})")
        d = dict(d)
        if "slo_ttft_p99_by_priority" in d:
            d["slo_ttft_p99_by_priority"] = {
                int(k): float(v)
                for k, v in (d["slo_ttft_p99_by_priority"] or {}).items()}
        return FleetConfig(**d)

    @staticmethod
    def from_yaml(path: str) -> Optional["FleetConfig"]:
        """The ``fleet:`` section of ``path`` (None when absent). The rest
        of the file is an ordinary scheduler config — one YAML serves both
        the scheduler boot and the serving-fleet CLI."""
        import yaml

        with open(path) as f:
            raw = yaml.safe_load(f) or {}
        section = raw.get("fleet")
        if section is None:
            return None
        return FleetConfig.from_dict(section)

    def autoscale_policy(self) -> AutoscalePolicy:
        return AutoscalePolicy(
            min_replicas=self.min_replicas,
            max_replicas=self.max_replicas,
            occ_high=self.occ_high, occ_low=self.occ_low,
            queue_high=self.queue_high, cooldown_s=self.cooldown_s,
            up_stable_ticks=self.up_stable_ticks,
            down_stable_ticks=self.down_stable_ticks,
        )

    def slo_tracker(self, clock=None, metrics: bool = True):
        """Build the router's :class:`obs.slo.SLOTracker` from the
        declared ``slo_*`` knobs (objectives may be empty — the tracker
        still feeds the autoscaler's quantile signal)."""
        import time as _time

        from hivedscheduler_tpu.obs import slo as obs_slo

        return obs_slo.SLOTracker(
            objectives=obs_slo.objectives_from_knobs(
                ttft_p99_s=self.slo_ttft_p99_s,
                tpot_p95_s=self.slo_tpot_p95_s,
                per_priority_ttft_p99=self.slo_ttft_p99_by_priority),
            window_s=None if self.slo_window_s < 0 else self.slo_window_s,
            clock=clock or _time.perf_counter, metrics=metrics,
        )
