"""Scheduler-driven fleet autoscaler: gauges in, elastic gangs out.

``FleetAutoscaler`` closes the serving->scheduler loop: it reads the
signals the serving tier already exports — block-pool occupancy (the
``tpu_hive_serve_block_pool_occupancy`` gauge's source fields, read
per-engine), queue depth, and the router's SLO tracker's windowed TTFT
quantile (``obs/slo.py`` — the scaling signal and the reported SLO are
one number) — and decides a target replica count per role. Decisions are
deliberately boring control theory:

- **hysteresis**: scale up only after ``up_stable_ticks`` consecutive
  ticks of up-pressure (occupancy above ``occ_high``, queue depth above
  ``queue_high`` per replica, or p95 TTFT above ``ttft_ceiling_s``);
  scale down only after ``down_stable_ticks`` ticks of idle signal
  (occupancy below ``occ_low`` AND empty queues) — a diurnal shoulder
  must not flap the fleet;
- **cooldown**: at most one scale action per role per ``cooldown_s``
  (default from ``HIVED_FLEET_AUTOSCALE_COOLDOWN_S``), so a replica's
  warm-up transient cannot trigger a second action before its effect is
  visible;
- **drain-based scale-down, always**: the victim (least outstanding
  work) gets ``router.begin_drain``; teardown happens only after the
  router observes it drained — work-preserving by construction, enforced
  by ``remove_replica`` and check_fleet.

Scale-UP is effected through a pluggable backend, because capacity is
the scheduler's to grant, not the autoscaler's to assume:

- :class:`LocalScaleBackend` builds replicas in-process (the CPU bench's
  A/B and most tests);
- :class:`SchedulerScaleBackend` drives a live ``HivedScheduler``: each
  replica is a gang member pod (with ``elasticMinChips`` so the
  scheduler's elastic arm can degrade it under pressure, exactly like
  any PR 10 gang) submitted through filter/bind — when the VC has no
  quota the grow stays PENDING and is retried each tick, i.e. scale-up
  competes under VC quotas like any gang instead of conjuring capacity.

Every decision is journaled (``fleet_scale``) and counted
(``tpu_hive_fleet_scale_events_total``); the current target is the
``tpu_hive_fleet_target_replicas`` gauge. Design doc:
doc/design/fleet.md (state machine + hysteresis table).
"""

from __future__ import annotations

import dataclasses
import logging
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from hivedscheduler_tpu.common import envflags
from hivedscheduler_tpu.fleet.router import FleetRouter, Replica
from hivedscheduler_tpu.obs import journal as obs_journal
from hivedscheduler_tpu.runtime.metrics import REGISTRY as metrics

log = logging.getLogger(__name__)


@dataclasses.dataclass
class AutoscalePolicy:
    """Per-role autoscaler knobs. ``cooldown_s < 0`` reads the
    ``HIVED_FLEET_AUTOSCALE_COOLDOWN_S`` flag (default 30)."""

    min_replicas: int = 1
    max_replicas: int = 4
    occ_high: float = 0.75
    occ_low: float = 0.25
    queue_high: float = 4.0      # queued requests per active replica
    ttft_ceiling_s: float = 0.0  # 0 = TTFT exerts no up-pressure
    up_stable_ticks: int = 2
    down_stable_ticks: int = 4
    cooldown_s: float = -1.0

    def resolved_cooldown(self) -> float:
        if self.cooldown_s >= 0:
            return self.cooldown_s
        return float(envflags.get("HIVED_FLEET_AUTOSCALE_COOLDOWN_S", "30"))


class LocalScaleBackend:
    """In-process replica factory: ``factory(role) -> (name, engine)`` or
    ``(name, engine, gang)``. grow() never fails for capacity — the CPU
    bench's static-vs-autoscaled A/B wants the autoscaler's decisions,
    not the scheduler's admission, to be the variable."""

    def __init__(self, factory: Callable[[str], tuple]):
        self._factory = factory

    def grow(self, role: str) -> Optional[tuple]:
        out = self._factory(role)
        if out is not None and len(out) == 2:
            out = (out[0], out[1], "")
        return out

    def shrink(self, role: str, replica: Replica) -> None:
        pass


class SchedulerScaleBackend:
    """Replica capacity through a live scheduler (see module docstring).

    ``factory(role, name) -> engine`` builds the engine once the pod is
    bound. One single-member gang per replica, all in ``vc`` — the VC's
    quota IS the fleet's chip budget, so a grow beyond quota stays
    pending until capacity frees (and the scheduler's backfill/elastic
    arms may be what frees it). NOTE: call only from outside the router
    lock — filter/bind take the scheduler lock, which sits below
    ``fleet_router_lock`` in the hierarchy."""

    def __init__(self, scheduler, kube, nodes: List[str],
                 factory: Callable[[str, str], Any], vc: str,
                 leaf_cell_type: str, chips_per_replica: int = 1,
                 priority: int = 5, elastic_min_chips: int = 0,
                 namespace: str = "default"):
        self.scheduler = scheduler
        self.kube = kube
        self.nodes = list(nodes)
        self.factory = factory
        self.vc = vc
        self.leaf_cell_type = leaf_cell_type
        self.chips = chips_per_replica
        self.priority = priority
        self.elastic_min_chips = elastic_min_chips
        self.namespace = namespace
        self._seq = 0
        self._pending: Dict[str, Any] = {}  # role -> waiting Pod

    def _make_pod(self, role: str):
        from hivedscheduler_tpu.api import constants as C
        from hivedscheduler_tpu.common.utils import to_json
        from hivedscheduler_tpu.k8s.types import Container, Pod

        self._seq += 1
        name = f"fleet-{role}-{self._seq}"
        spec = {
            "virtualCluster": self.vc, "priority": self.priority,
            "leafCellType": self.leaf_cell_type,
            "leafCellNumber": self.chips,
            "affinityGroup": {
                "name": name,
                "members": [{"podNumber": 1,
                             "leafCellNumber": self.chips}],
            },
        }
        if self.elastic_min_chips:
            spec["elasticMinChips"] = self.elastic_min_chips
        return Pod(
            name=name, uid=name, namespace=self.namespace,
            annotations={C.ANNOTATION_POD_SCHEDULING_SPEC: to_json(spec)},
            containers=[Container(resource_limits={
                C.RESOURCE_NAME_POD_SCHEDULING_ENABLE: 1})],
        )

    def grow(self, role: str) -> Optional[tuple]:
        from hivedscheduler_tpu.runtime import extender as ei

        pod = self._pending.get(role)
        if pod is None:
            pod = self._make_pod(role)
            self.kube.create_pod(pod)
        r = self.scheduler.filter_routine(
            ei.ExtenderArgs(pod=pod, node_names=list(self.nodes)))
        if not r.node_names:
            # no quota/capacity for the gang right now: the pod stays
            # submitted and the grow is retried next tick — scale-up
            # competes under the VC quota like any gang
            self._pending[role] = pod
            return None
        self.scheduler.bind_routine(ei.ExtenderBindingArgs(
            pod_name=pod.name, pod_namespace=pod.namespace,
            pod_uid=pod.uid, node=r.node_names[0]))
        self._pending.pop(role, None)
        return pod.name, self.factory(role, pod.name), pod.name

    def shrink(self, role: str, replica: Replica) -> None:
        self.kube.delete_pod(self.namespace, replica.gang)


class FleetAutoscaler:
    """The control loop. Call ``tick()`` periodically (the serve CLI
    ticks once per arrival batch; the bench per engine step). ``clock``
    is injectable so hysteresis/cooldown are deterministically
    testable."""

    def __init__(self, router: FleetRouter, backend,
                 policy: Optional[AutoscalePolicy] = None,
                 roles: Optional[Tuple[str, ...]] = None,
                 clock=time.perf_counter):
        self.router = router
        self.backend = backend
        self.policy = policy or AutoscalePolicy()
        self._cooldown = self.policy.resolved_cooldown()
        if roles is None:
            roles = (("prefill", "decode")
                     if router.disaggregate and router.kv_ship
                     else ("serve",))
        self.roles = roles
        self._clock = clock
        self._up: Dict[str, int] = {r: 0 for r in roles}
        self._down: Dict[str, int] = {r: 0 for r in roles}
        self._last_action: Dict[str, float] = {r: float("-inf")
                                               for r in roles}
        self._pending_down: Dict[str, str] = {}  # role -> draining name
        self.target: Dict[str, int] = {}
        self.actions: List[Dict[str, Any]] = []
        self.replica_seconds = 0.0  # integral of live replicas (bench cost)
        self._last_tick: Optional[float] = None

    # -- signals -----------------------------------------------------------
    def _role_replicas(self, role: str) -> List[Replica]:
        role_blind = len(self.roles) == 1
        return [r for r in self.router.replicas.values()
                if r.state == "active"
                and (role_blind or r.role in (role, "serve"))]

    def signals(self, role: str) -> Dict[str, Any]:
        reps = self._role_replicas(role)
        occs = []
        qdepth = 0
        for rep in reps:
            eng = rep.engine
            if getattr(eng, "paged", False):
                occs.append(eng.blocks_in_use / max(1, eng.num_blocks - 1))
            else:
                occs.append(
                    sum(s is not None for s in eng.slots) / eng.max_batch)
            qdepth += len(eng.queue)
        # the SLO tracker's windowed quantile (obs/slo.py) — the SAME
        # computation /v1/inspect/slo reports, replacing the pre-ISSUE-13
        # hand-sorted recent_ttfts ring (decision-identical: pinned by
        # tests/test_request_flights.py)
        slo = self.router.slo
        return {
            "replicas": len(reps),
            "occupancy": sum(occs) / len(occs) if occs else 0.0,
            "queueDepth": qdepth,
            "ttftP95": slo.quantile(0.95, "ttft"),
            "ttftP99": slo.quantile(0.99, "ttft"),
        }

    # -- the loop ----------------------------------------------------------
    def tick(self) -> List[Dict[str, Any]]:
        now = self._clock()
        if self._last_tick is not None:
            live = sum(1 for r in self.router.replicas.values()
                       if r.state in ("active", "draining"))
            self.replica_seconds += live * max(0.0, now - self._last_tick)
        self._last_tick = now
        done: List[Dict[str, Any]] = []
        for role in self.roles:
            done.extend(self._tick_role(role, now))
        self._complete_drains(now, done)
        metrics.set_gauge("tpu_hive_fleet_target_replicas",
                          sum(self.target.values()) if self.target else
                          sum(1 for r in self.router.replicas.values()
                              if r.state == "active"))
        self.actions.extend(done)
        return done

    def _tick_role(self, role: str, now: float) -> List[Dict[str, Any]]:
        p = self.policy
        sig = self.signals(role)
        n = sig["replicas"]
        self.target.setdefault(role, max(p.min_replicas, n))
        up_pressure = (
            sig["occupancy"] > p.occ_high
            or sig["queueDepth"] > p.queue_high * max(1, n)
            or (p.ttft_ceiling_s > 0 and sig["ttftP95"] > p.ttft_ceiling_s)
        )
        down_pressure = (
            sig["occupancy"] < p.occ_low and sig["queueDepth"] == 0
        )
        self._up[role] = self._up[role] + 1 if up_pressure else 0
        self._down[role] = self._down[role] + 1 if down_pressure else 0
        out: List[Dict[str, Any]] = []
        if now - self._last_action[role] < self._cooldown:
            return out
        if (self._up[role] >= p.up_stable_ticks and n < p.max_replicas
                and role not in self._pending_down):
            reason = ("occupancy" if sig["occupancy"] > p.occ_high else
                      "queue" if sig["queueDepth"] > p.queue_high * max(1, n)
                      else "ttft")
            handle = self.backend.grow(role)
            if handle is None:
                # competing under the VC quota: the grow stays pending at
                # the scheduler and is retried next tick
                out.append({"role": role, "direction": "up",
                            "phase": "pending", "reason": reason})
                if obs_journal.JOURNAL.enabled:
                    obs_journal.emit("fleet_scale", f"fleetrole/{role}",
                                     direction="up", phase="pending",
                                     reason=reason)
                return out
            name, engine, gang = handle
            self.router.add_replica(name, engine, role=role, gang=gang)
            self.target[role] = n + 1
            self._last_action[role] = now
            self._up[role] = 0
            metrics.inc("tpu_hive_fleet_scale_events_total", direction="up")
            if obs_journal.JOURNAL.enabled:
                obs_journal.emit("fleet_scale", f"fleetrole/{role}",
                                 direction="up", phase="added",
                                 replica=name, reason=reason)
            log.info("fleet autoscaler: %s scaled up to %d (%s; occ %.2f, "
                     "queue %d)", role, n + 1, reason, sig["occupancy"],
                     sig["queueDepth"])
            out.append({"role": role, "direction": "up", "phase": "added",
                        "replica": name, "reason": reason})
        elif (self._down[role] >= p.down_stable_ticks
                and n > p.min_replicas and role not in self._pending_down):
            reps = self._role_replicas(role)
            victim = min(reps, key=lambda r: (r.outstanding_blocks(),
                                              r.name))
            self.router.begin_drain(victim.name)
            self._pending_down[role] = victim.name
            self.target[role] = n - 1
            self._last_action[role] = now
            self._down[role] = 0
            metrics.inc("tpu_hive_fleet_scale_events_total",
                        direction="down")
            if obs_journal.JOURNAL.enabled:
                obs_journal.emit("fleet_scale", f"fleetrole/{role}",
                                 direction="down", phase="draining",
                                 replica=victim.name, reason="idle")
            log.info("fleet autoscaler: %s draining %s toward %d replicas",
                     role, victim.name, n - 1)
            out.append({"role": role, "direction": "down",
                        "phase": "draining", "replica": victim.name,
                        "reason": "idle"})
        return out

    def _complete_drains(self, now: float,
                         out: List[Dict[str, Any]]) -> None:
        for role, name in list(self._pending_down.items()):
            rep = self.router.replicas.get(name)
            if rep is None:
                del self._pending_down[role]
                continue
            if rep.state != "drained":
                continue
            self.backend.shrink(role, rep)
            self.router.remove_replica(name)
            del self._pending_down[role]
            if obs_journal.JOURNAL.enabled:
                obs_journal.emit("fleet_scale", f"fleetrole/{role}",
                                 direction="down", phase="removed",
                                 replica=name)
            log.info("fleet autoscaler: %s removed drained replica %s",
                     role, name)
            out.append({"role": role, "direction": "down",
                        "phase": "removed", "replica": name})
