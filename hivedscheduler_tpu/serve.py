"""Continuous-batching serving demo CLI.

``python -m hivedscheduler_tpu.serve --requests 8 --max-batch 4 ...`` —
generates a synthetic stream of requests with random prompts/budgets and
staggered arrivals, serves them through ``models.serving.ServingEngine``
(ragged KV cache, slot recycling, bucketed prefill), and prints one line of
tokens per request plus occupancy/throughput stats. Model flags mirror
``hivedscheduler_tpu.generate``; ``--checkpoint-dir`` restores trained
params the same way.
"""

from __future__ import annotations

import argparse
import logging
import sys
import time

from hivedscheduler_tpu.common import utils as common

log = logging.getLogger(__name__)


def _log_slo(tracker) -> None:
    """One SLO summary block at exit: windowed quantiles plus, per
    declared objective, compliance / burn rate / dominant-leg violation
    attribution (the /v1/inspect/slo payload, logged)."""
    snap = tracker.snapshot()
    s = snap["series"]["ttft"]
    if s["count"]:
        log.info("slo: ttft p50 %.0f ms, p95 %.0f ms, p99 %.0f ms over "
                 "%s requests (window %ss)", 1e3 * s["p50"],
                 1e3 * s["p95"], 1e3 * s["p99"], s["count"],
                 snap["windowS"])
    for o in snap["objectives"]:
        log.info(
            "slo objective %s: ceiling %.0f ms, observed %.0f ms, "
            "compliance %s, burn rate %s, violation attribution %s",
            o["name"], 1e3 * o["ceilingS"], 1e3 * o["value"],
            "n/a" if o["compliance"] is None else f"{o['compliance']:.4f}",
            "n/a" if o["burnRate"] is None else f"{o['burnRate']:.2f}",
            o["attribution"] or "{}",
        )


def _run_fleet(args, router, autoscaler, pending, prio_of) -> int:
    """Drive the synthetic load through the FleetRouter (the --fleet
    path): staggered arrivals, per-step autoscaler ticks, and a fleet
    summary mirroring the single-engine report."""
    from hivedscheduler_tpu import fleet as fleet_pkg

    reqs = []
    steps = 0
    t0 = time.perf_counter()
    try:
        if args.arrival_every == 0:  # all up front
            while pending:
                prompt, budget = pending.pop(0)
                reqs.append(router.submit(prompt, budget,
                                          priority=prio_of(len(reqs))))
        while pending or (reqs and not all(f.done for f in reqs)):
            if pending and steps % args.arrival_every == 0:
                prompt, budget = pending.pop(0)
                reqs.append(router.submit(prompt, budget,
                                          priority=prio_of(len(reqs))))
            if autoscaler is not None:
                autoscaler.tick()
            router.step()
            steps += 1
    finally:
        fleet_pkg.publish(None)
    dt = time.perf_counter() - t0

    total_tokens = sum(len(f.tokens_out) for f in reqs)
    for f in reqs:
        print(f"[{f.fid}] " + " ".join(str(t) for t in f.tokens_out))
    ttfts = sorted(f.ttft_s for f in reqs if f.ttft_s is not None)
    if ttfts:
        log.info("fleet time-to-first-token: p50 %.0f ms, max %.0f ms",
                 1e3 * ttfts[len(ttfts) // 2], 1e3 * ttfts[-1])
    snap = router.snapshot()
    log.info(
        "fleet: %s requests, %s tokens in %.2fs (%.1f tok/s) over %s "
        "replicas (policy %s%s)",
        len(reqs), total_tokens, dt, total_tokens / dt,
        len(snap["replicas"]), router.policy,
        ", disaggregated" if router.disaggregate else "",
    )
    if router.disaggregate:
        log.info("fleet handoffs: %s shipped, %s missed, %s re-prefilled "
                 "(HIVED_FLEET_KV_SHIP=%s)", router.handoffs["ship"],
                 router.handoffs["miss"], router.handoffs["reprefill"],
                 "1" if router.kv_ship else "0")
    if router.retried:
        log.info("fleet retries: %s shed/preempted/lost legs re-routed",
                 router.retried)
    if router.policy == "prefix_affinity":
        log.info("fleet prefix-affinity hits: %s", router.affinity_hits)
    _log_slo(router.slo)
    if autoscaler is not None:
        ups = sum(1 for a in autoscaler.actions
                  if a["direction"] == "up" and a["phase"] == "added")
        downs = sum(1 for a in autoscaler.actions
                    if a["phase"] == "removed")
        log.info("fleet autoscaler: %s scale-ups, %s drain-based "
                 "removals, %s live replicas at exit", ups, downs,
                 sum(1 for r in snap["replicas"]
                     if r["state"] in ("active", "draining")))
    if args.metrics_dump:
        from hivedscheduler_tpu.obs import trace as obs_trace
        from hivedscheduler_tpu.runtime.metrics import REGISTRY

        with open(args.metrics_dump, "w") as f:
            f.write(REGISTRY.render())
        trace_path = args.metrics_dump + ".trace.json"
        obs_trace.write_chrome_trace(trace_path)
        log.info("metrics exposition -> %s; Chrome trace -> %s",
                 args.metrics_dump, trace_path)
    return 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="tpu-hive-serve")
    parser.add_argument("--requests", type=int, default=8)
    parser.add_argument("--max-batch", type=int, default=4,
                        help="engine slots (concurrent sequences)")
    parser.add_argument("--max-len", type=int, default=256,
                        help="KV-cache arena length per slot")
    parser.add_argument("--max-new-tokens", type=int, default=32)
    parser.add_argument("--arrival-every", type=int, default=3,
                        help="admit a new request every N engine steps "
                        "(0 = all up front)")
    parser.add_argument("--age-boost-secs", type=float, default=0.0,
                        help="bounded-wait aging for the priority queue: a "
                             "waiter gains one effective priority level per "
                             "this many seconds queued, so low-priority "
                             "requests cannot be starved indefinitely by a "
                             "sustained high-priority stream (0 = strict "
                             "priority, the default)")
    parser.add_argument("--queue-timeout", type=float, default=0.0,
                        help="shed requests whose queue wait exceeds this "
                             "many seconds (finish_reason=shed, counted in "
                             "tpu_hive_serve_shed_total); 0 = never shed")
    parser.add_argument("--high-priority-every", type=int, default=0,
                        help="submit every Nth request at priority 10 "
                        "(0 = all priority 0); high-priority waiters jump "
                        "the admission queue — per-class TTFT is reported")
    parser.add_argument("--temperature", type=float, default=0.0)
    parser.add_argument("--top-k", type=int, default=0)
    parser.add_argument("--top-p", type=float, default=1.0)
    parser.add_argument("--eos-id", type=int, default=-1, help="-1 = none")
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--vocab-size", type=int, default=32000)
    parser.add_argument("--d-model", type=int, default=512)
    parser.add_argument("--n-layers", type=int, default=8)
    parser.add_argument("--n-heads", type=int, default=8)
    parser.add_argument("--n-kv-heads", type=int, default=0)
    parser.add_argument("--d-ff", type=int, default=1408)
    parser.add_argument("--n-experts", type=int, default=0,
                        help="serve a MoE model (routing-exact: no-drop "
                        "inference capacity)")
    parser.add_argument("--moe-top-k", type=int, default=1)
    parser.add_argument("--expert-capacity-factor", type=float, default=1.25,
                        help="MoE expert capacity factor (must match the "
                        "checkpoint's training value)")
    parser.add_argument("--rope-theta", type=float, default=10000.0,
                        help="RoPE base frequency (must match the "
                        "checkpoint's training value)")
    parser.add_argument("--tp", type=int, default=1,
                        help="tensor-parallel serving over a tp mesh axis")
    parser.add_argument("--dp", type=int, default=1,
                        help="shard engine slots over a dp mesh axis "
                        "(--max-batch must divide it)")
    parser.add_argument("--page-size", type=int, default=0,
                        help="paged KV cache: tokens per block (0 = dense "
                        "per-slot slabs). Blocks come from a shared pool "
                        "with a free-list allocator; admission is gated on "
                        "block availability, the prefix cache shares "
                        "reference-counted blocks with copy-on-write, and "
                        "streams stay token-exact vs the dense path "
                        "(HIVED_PAGED_KV=0 forces dense)")
    parser.add_argument("--num-blocks", type=int, default=0,
                        help="paged KV pool size in blocks (0 = capacity "
                        "parity with the dense slabs: max_batch * "
                        "ceil(max_len/page_size) + 1). Size it SMALLER "
                        "with a larger --max-batch to serve more "
                        "concurrent streams from the same KV HBM")
    parser.add_argument("--spec-decode", action="store_true",
                        help="first-class speculative serving: construct "
                        "the engine with ServingEngine(spec_decode=...) "
                        "(composes with paging, chunked prefill and the "
                        "prefix cache); uses --draft-layers (default 2 "
                        "when unset) and --gamma for the draft model")
    parser.add_argument("--draft-layers", type=int, default=0,
                        help="speculative serving: draft-model layers "
                        "(0 = off; per-row acceptance — no batch-min "
                        "barrier; greedy is bit-exact, sampled does "
                        "per-row residual resampling)")
    parser.add_argument("--draft-d-model", type=int, default=0,
                        help="draft width (default: half the target, "
                        "rounded to an even head_dim)")
    parser.add_argument("--gamma", type=int, default=4,
                        help="draft tokens proposed per verify round")
    parser.add_argument("--prefix-cache", type=int, default=0,
                        help="prompt prefix cache entries (0 = off): reuse "
                        "the KV of cached prompt prefixes instead of "
                        "re-prefilling them — the synthetic load then "
                        "shares a system prompt so hits occur")
    parser.add_argument("--system-prompt-len", type=int, default=24,
                        help="shared prompt prefix length for the synthetic "
                        "load (only with --prefix-cache)")
    parser.add_argument("--decode-steps", type=int, default=1,
                        help="fuse up to K decode iterations into one "
                        "jitted scan per engine step (sampling on device, "
                        "token fed straight back): the per-token Python "
                        "dispatch + host sync amortizes over the window. "
                        "Streams are exact for any K (adaptive fallback "
                        "to 1 when a slot may finish inside the window); "
                        "ignored by the speculative engine (--draft-layers)")
    parser.add_argument("--prefill-chunk", type=int, default=0,
                        help="absorb prompts at most this many tokens per "
                        "engine step (0 = whole prompt at admission): a "
                        "long prompt then cannot stall decoding rows")
    parser.add_argument("--quantize", choices=["none", "int8"], default="none",
                        help="weight-only int8 serving (halves weight HBM "
                        "traffic; the engine's shared helpers dequantize "
                        "into the consuming einsums)")
    parser.add_argument("--kv-quantize", choices=["none", "int8"],
                        default="none",
                        help="int8 KV cache (per-token-per-head scales; "
                        "halves decode KV bytes from HBM — the "
                        "long-context decode bottleneck)")
    parser.add_argument("--lora-rank", type=int, default=0,
                        help="serve a LoRA fine-tune checkpoint: adapters "
                        "are merged into the base weights at load (as in "
                        "generate.py)")
    parser.add_argument("--lora-alpha", type=float, default=16.0)
    parser.add_argument("--lora-mlp", action="store_true",
                        help="the checkpoint carries MLP adapters too")
    parser.add_argument("--drain-deadline", type=float, default=10.0,
                        help="graceful preemption: on SIGTERM/SIGINT stop "
                        "admitting (pending synthetic arrivals are rejected "
                        "through the engine's draining guard — the 503 + "
                        "Retry-After path), finish in-flight decodes for up "
                        "to this many seconds, then exit; expired in-flight "
                        "requests finish with finish_reason=preempted")
    parser.add_argument("--checkpoint-dir", default="")
    parser.add_argument("--metrics-dump", default="",
                        help="after the run, write the Prometheus exposition "
                        "text (per-priority TTFT/TPOT/queue-wait histograms) "
                        "to this path and a Chrome-trace/Perfetto JSON of "
                        "request lifecycles to <path>.trace.json")
    parser.add_argument("--journal-file", default="",
                        help="enable the gang-lifecycle journal "
                        "(obs/journal.py) and append its request "
                        "admission/shed/preemption events — plus the "
                        "per-request flight legs (REQUEST_LEGS) — to this "
                        "JSONL spool (one line per event, flushed per "
                        "append)")
    parser.add_argument("--goodput-file", default="",
                        help="enable the workload goodput ledger "
                        "(obs/goodput.py) and append this run's step-phase "
                        "records — engine steps, the drain handshake — to "
                        "this JSONL spool")
    parser.add_argument("--slo-ttft-p99", type=float, default=0.0,
                        help="declare a p99 TTFT objective (seconds): the "
                        "SLO tracker (obs/slo.py) then reports windowed "
                        "compliance, error-budget burn rate and "
                        "violation attribution by dominant request leg "
                        "(0 = no objective; quantiles are tracked either "
                        "way and feed the fleet autoscaler)")
    parser.add_argument("--slo-window-s", type=float, default=-1.0,
                        help="SLO tracker sliding window in seconds "
                        "(-1 = the HIVED_SLO_WINDOW_S default, 0 = no "
                        "time window — pure last-N ring)")
    parser.add_argument("--fleet", type=int, default=0,
                        help="serve through a FleetRouter over this many "
                        "replicas (0 = single engine). Each replica is a "
                        "fresh engine over the same weights; requests are "
                        "routed by --route-policy, shed/preempted streams "
                        "retry on another replica (doc/design/fleet.md)")
    parser.add_argument("--disaggregate", action="store_true",
                        help="fleet mode: split prefill from decode — the "
                        "first --prefill-replicas replicas take prefill "
                        "legs, the rest decode legs, with the KV handoff "
                        "selected by HIVED_FLEET_KV_SHIP (1 = ship block "
                        "contents host-side, 0 = re-prefill through the "
                        "decode replica's prefix cache). Token-exact vs "
                        "single-replica either way")
    parser.add_argument("--prefill-replicas", type=int, default=1,
                        help="with --disaggregate: replicas dedicated to "
                        "prefill legs (the rest decode)")
    parser.add_argument("--route-policy", default="least_blocks",
                        choices=["least_blocks", "prefix_affinity"],
                        help="fleet routing policy: least outstanding KV "
                        "blocks, or prefix-affinity (route to the replica "
                        "whose prefix cache holds the prompt's leading "
                        "blocks, falling back to least-blocks)")
    parser.add_argument("--autoscale", action="store_true",
                        help="fleet mode: run the FleetAutoscaler over the "
                        "replica set (hysteresis + cooldown; scale-down is "
                        "always drain-based) between --fleet-min and "
                        "--fleet-max replicas; --fleet sizes the starting "
                        "set")
    parser.add_argument("--fleet-min", type=int, default=1,
                        help="autoscaler floor (replicas)")
    parser.add_argument("--fleet-max", type=int, default=0,
                        help="autoscaler ceiling (0 = the --fleet value)")
    parser.add_argument("--fleet-config", default="",
                        help="YAML with a `fleet:` section (see example/"
                        "config/design/fleet.yaml) providing fleet/"
                        "disaggregation/autoscaler knobs; explicit fleet "
                        "flags override it")
    parser.add_argument("-v", "--verbose", action="store_true")
    args = parser.parse_args(argv)
    fleet_cfg = None
    if args.fleet_config:
        from hivedscheduler_tpu.fleet import FleetConfig

        fleet_cfg = FleetConfig.from_yaml(args.fleet_config)
        if fleet_cfg is None:
            parser.error(f"{args.fleet_config} has no `fleet:` section")
        if args.fleet == 0:
            args.fleet = fleet_cfg.replicas
        if not args.disaggregate:
            args.disaggregate = fleet_cfg.disaggregate
        if args.prefill_replicas == 1:
            args.prefill_replicas = fleet_cfg.prefill_replicas
        if args.route_policy == "least_blocks":
            args.route_policy = fleet_cfg.policy
        if not args.autoscale:
            args.autoscale = fleet_cfg.autoscale
        if args.fleet_min == 1:
            args.fleet_min = fleet_cfg.min_replicas
        if args.fleet_max == 0 and fleet_cfg.autoscale:
            args.fleet_max = fleet_cfg.max_replicas
        if args.slo_ttft_p99 == 0.0:
            args.slo_ttft_p99 = fleet_cfg.slo_ttft_p99_s
        if args.slo_window_s < 0:
            args.slo_window_s = fleet_cfg.slo_window_s
    if args.fleet > 0:
        if args.disaggregate and not 0 < args.prefill_replicas < args.fleet:
            parser.error(
                f"--disaggregate needs 0 < --prefill-replicas "
                f"{args.prefill_replicas} < --fleet {args.fleet} (at least "
                f"one prefill and one decode replica)"
            )
        if args.tp > 1 or args.dp > 1:
            parser.error("--fleet does not compose with --tp/--dp (each "
                         "replica is a single-host engine in this CLI)")
    if args.prefix_cache > 0:
        # synthetic prompts are system + up to 16 tokens; fail fast instead
        # of letting a mid-run submit() raise past the engine guard
        worst = args.system_prompt_len + 16 + args.max_new_tokens
        if worst > args.max_len:
            parser.error(
                f"--system-prompt-len {args.system_prompt_len} + prompt tail "
                f"(16) + --max-new-tokens {args.max_new_tokens} = {worst} "
                f"exceeds --max-len {args.max_len}"
            )

    common.init_all(logging.DEBUG if args.verbose else logging.INFO)
    if args.metrics_dump:
        # request-lifecycle spans only reach the ring while tracing is on
        from hivedscheduler_tpu.obs import trace as obs_trace

        obs_trace.enable()
    if args.journal_file:
        from hivedscheduler_tpu.obs import journal as obs_journal

        obs_journal.enable(spool_path=args.journal_file)
    from hivedscheduler_tpu.obs import goodput as obs_goodput

    if args.goodput_file:
        obs_goodput.enable(spool_path=args.goodput_file)
    import jax
    import jax.numpy as jnp

    from hivedscheduler_tpu.models import serving, transformer as tm

    cfg = tm.TransformerConfig(
        vocab_size=args.vocab_size,
        d_model=args.d_model,
        n_heads=args.n_heads,
        n_kv_heads=args.n_kv_heads,
        n_layers=args.n_layers,
        d_ff=args.d_ff,
        max_seq_len=args.max_len,
        n_experts=args.n_experts,
        moe_top_k=args.moe_top_k,
        expert_capacity_factor=args.expert_capacity_factor,
        rope_theta=args.rope_theta,
    )
    from hivedscheduler_tpu.parallel import checkpoint as ckpt

    try:
        params, step = ckpt.restore_serving_params(
            cfg, args.checkpoint_dir, jax.random.PRNGKey(args.seed),
            lora_rank=args.lora_rank, lora_alpha=args.lora_alpha,
            lora_mlp=args.lora_mlp,
        )
    except FileNotFoundError as e:
        log.error("%s", e)
        return 1
    if step is not None:
        log.info("restored params from step %s", step)
    if args.lora_rank > 0:
        log.info("merged rank-%s LoRA adapters into the base weights",
                 args.lora_rank)
    if args.quantize == "int8":
        from hivedscheduler_tpu.models import quant

        params = quant.quantize_params(params, cfg)
        log.info("quantized weights to int8 (per-output-channel scales)")
    else:
        # serving streams weights every step: hold them in the compute dtype
        params = tm.cast_params(params, cfg.dtype)

    mesh = None
    if args.tp > 1 or args.dp > 1:
        from hivedscheduler_tpu.parallel import topology

        axes = topology.MeshAxes(dp=args.dp, tp=args.tp)
        mesh = topology.make_mesh(axes, topology.get_devices(axes.size))
    kw = dict(
        max_batch=args.max_batch, max_len=args.max_len,
        temperature=args.temperature, top_k=args.top_k, top_p=args.top_p,
        eos_id=None if args.eos_id < 0 else args.eos_id, seed=args.seed,
        mesh=mesh, prefix_cache_size=args.prefix_cache,
        prefill_chunk=args.prefill_chunk,
        kv_dtype=None if args.kv_quantize == "none" else args.kv_quantize,
        queue_timeout_s=args.queue_timeout if args.queue_timeout > 0 else None,
        age_boost_secs=args.age_boost_secs if args.age_boost_secs > 0 else None,
        decode_steps=args.decode_steps,
        page_size=args.page_size, num_blocks=args.num_blocks,
    )
    speculative = args.spec_decode or args.draft_layers > 0
    if speculative and args.decode_steps > 1:
        log.warning("--decode-steps is ignored by the speculative "
                    "engine (a verify round already amortizes the "
                    "host round-trip)")
    spec_cfg = None
    if speculative:
        from hivedscheduler_tpu.models.speculative import (
            SpecDecodeConfig,
            derive_draft_config,
        )

        dft_cfg = derive_draft_config(cfg, args.draft_layers or 2,
                                      args.draft_d_model)
        dft_params = tm.cast_params(
            tm.init_params(dft_cfg, jax.random.PRNGKey(args.seed + 3)),
            dft_cfg.dtype,
        )
        # the first-class construction path: one constructor, every
        # composition (paging, chunked prefill, prefix cache)
        spec_cfg = SpecDecodeConfig(draft_params=dft_params,
                                    draft_cfg=dft_cfg, gamma=args.gamma)

    def build_engine():
        return serving.ServingEngine(params, cfg, spec_decode=spec_cfg,
                                     **kw)

    from hivedscheduler_tpu.obs import slo as obs_slo

    slo_tracker = obs_slo.SLOTracker(
        objectives=obs_slo.objectives_from_knobs(
            ttft_p99_s=args.slo_ttft_p99,
            tpot_p95_s=fleet_cfg.slo_tpot_p95_s if fleet_cfg else 0.0,
            per_priority_ttft_p99=(fleet_cfg.slo_ttft_p99_by_priority
                                   if fleet_cfg else None)),
        window_s=None if args.slo_window_s < 0 else args.slo_window_s,
    )
    router = autoscaler = None
    try:
        if args.fleet > 0:
            from hivedscheduler_tpu import fleet as fleet_pkg

            router = fleet_pkg.FleetRouter(policy=args.route_policy,
                                           disaggregate=args.disaggregate,
                                           slo=slo_tracker)
            if (args.disaggregate and router.kv_ship
                    and kw["prefix_cache_size"] == 0):
                # the handoff payload travels through the prefix cache
                kw["prefix_cache_size"] = 8
                log.info("fleet: --disaggregate with KV shipping needs a "
                         "prefix cache; defaulting to 8 entries/replica")
            for i in range(args.fleet):
                role = "serve"
                if args.disaggregate:
                    role = ("prefill" if i < args.prefill_replicas
                            else "decode")
                router.add_replica(f"r{i}-{role}", build_engine(),
                                   role=role)
            fleet_pkg.publish(router)
            if args.autoscale:
                fleet_max = args.fleet_max or args.fleet
                seq = [0]

                def factory(role):
                    seq[0] += 1
                    return f"auto{seq[0]}-{role}", build_engine()

                autoscaler = fleet_pkg.FleetAutoscaler(
                    router, fleet_pkg.LocalScaleBackend(factory),
                    fleet_pkg.AutoscalePolicy(
                        min_replicas=args.fleet_min,
                        max_replicas=fleet_max),
                )
        else:
            eng = build_engine()
            from hivedscheduler_tpu.obs import journal as obs_journal

            if args.journal_file or obs_journal.JOURNAL.enabled:
                # single-engine flights: serve/<rid> legs + terminal in
                # the journal/spool (the fleet path's router installs
                # fleet/<fid> flights instead)
                eng.record_flights = True
    except ValueError as e:
        log.error("%s", e)
        return 1
    key = jax.random.PRNGKey(args.seed + 1)
    system = []
    if args.prefix_cache > 0 and args.system_prompt_len > 0:
        key, ks = jax.random.split(key)
        system = [int(t) for t in jax.random.randint(
            ks, (args.system_prompt_len,), 0, cfg.vocab_size)]
    pending = []
    for i in range(args.requests):
        key, k1, k2, k3 = jax.random.split(key, 4)
        plen = int(jax.random.randint(k1, (), 2, 17))
        budget = int(jax.random.randint(k2, (), 4, args.max_new_tokens + 1))
        prompt = system + [int(t) for t in jax.random.randint(
            k3, (plen,), 0, cfg.vocab_size)]
        pending.append((prompt, budget))

    def prio_of(i: int) -> int:
        hp = args.high_priority_every
        return 10 if hp > 0 and (i + 1) % hp == 0 else 0

    if router is not None:
        return _run_fleet(args, router, autoscaler, pending, prio_of)

    from hivedscheduler_tpu.parallel import supervisor as sup_lib

    # graceful preemption: SIGTERM/SIGINT request a drain instead of dying
    # mid-decode (the workload side of HiveD's work-preserving preemption);
    # HIVED_FAULT_SERVE_PREEMPT_AT triggers the same path deterministically
    # for the chaos/fault-ladder tests
    listener = sup_lib.PreemptionListener().install()
    faults = sup_lib.FaultInjection.from_env()
    reqs = []
    rejected = 0
    drained = True
    t0 = time.perf_counter()
    steps = 0
    try:
        if args.arrival_every == 0:  # all up front
            while pending:
                prompt, budget = pending.pop(0)
                reqs.append(eng.submit(prompt, budget,
                                       priority=prio_of(len(reqs))))
        while pending or (reqs and not all(r.done for r in reqs)):
            if faults.take_serve_preempt(steps):
                listener.trigger()
            if listener.requested:
                break
            if pending and steps % args.arrival_every == 0:
                prompt, budget = pending.pop(0)
                reqs.append(eng.submit(prompt, budget,
                                       priority=prio_of(len(reqs))))
                log.info("admitted request %s (prompt %s, budget %s, prio %s)",
                         reqs[-1].rid, len(prompt), budget, reqs[-1].priority)
            obs_goodput.phase("step_compute")
            eng.step()
            steps += 1
        obs_goodput.phase("idle")
        if listener.requested:
            # drain: admission off first (503 + Retry-After analogue for the
            # not-yet-submitted synthetic arrivals), then finish in-flight
            # decodes bounded by the deadline
            eng.begin_drain()
            for prompt, budget in pending:
                try:
                    eng.submit(prompt, budget)
                except serving.EngineDraining:
                    rejected += 1
            pending.clear()
            drained = eng.drain(args.drain_deadline)
    finally:
        listener.uninstall()
    dt = time.perf_counter() - t0

    total_tokens = sum(len(r.tokens_out) for r in reqs)
    for r in reqs:
        print(f"[{r.rid}] " + " ".join(str(t) for t in r.tokens_out))
    from hivedscheduler_tpu.obs import journal as obs_journal

    for r in reqs:
        if not r.done:
            continue
        dom = (obs_journal.JOURNAL.request_dominant_leg(f"serve/{r.rid}")
               if obs_journal.JOURNAL.enabled else "")
        if r.ttft_s is not None:
            slo_tracker.observe("ttft", r.ttft_s, priority=r.priority,
                                leg=dom, at=r.done_at)
        if r.tpot_s is not None:
            slo_tracker.observe("tpot", r.tpot_s, priority=r.priority,
                                leg=dom, at=r.done_at)
    _log_slo(slo_tracker)
    ttfts = sorted(r.ttft_s for r in reqs if r.ttft_s is not None)
    if ttfts:
        log.info("time-to-first-token: p50 %.0f ms, max %.0f ms",
                 1e3 * ttfts[len(ttfts) // 2], 1e3 * ttfts[-1])
        if args.high_priority_every > 0:
            # derive the classes from the requests themselves so the
            # report stays correct if the priority values change
            for cls in sorted({r.priority for r in reqs}, reverse=True):
                cl = sorted(r.ttft_s for r in reqs
                            if r.priority == cls and r.ttft_s is not None)
                if cl:
                    log.info("  priority-%s TTFT: p50 %.0f ms over %s "
                             "requests", cls, 1e3 * cl[len(cl) // 2],
                             len(cl))
    log.info(
        "%s requests, %s tokens in %.2fs (%.1f tok/s), occupancy %.0f%% "
        "over %s decode steps",
        len(reqs), total_tokens, dt, total_tokens / dt,
        100.0 * eng.occupancy, eng.steps,
    )
    shed = [r for r in reqs if r.finish_reason == "shed"]
    if shed:
        log.info("shed %s request(s) on the %.1fs queue-wait deadline: %s",
                 len(shed), args.queue_timeout,
                 " ".join(str(r.rid) for r in shed))
    if listener.requested:
        preempted = [r for r in reqs if r.finish_reason == "preempted"]
        log.info(
            "preemption drain: rejected %s not-yet-admitted arrival(s) "
            "(503 + Retry-After path), %s in-flight finished, %s preempted "
            "at the %.1fs deadline (%s)",
            rejected, sum(1 for r in reqs if r.done and r.finish_reason
                          in ("eos", "length")),
            len(preempted), args.drain_deadline,
            "fully drained" if drained else "deadline expired",
        )
    if args.decode_steps > 1 and not speculative:
        log.info("fused decode: %s multi-step windows (decode_steps=%s) "
                 "over %s device steps", eng.fused_windows,
                 args.decode_steps, eng.steps)
    if speculative:
        log.info("speculation: %s/%s draft tokens accepted (%.0f%%)",
                 eng.accepted, eng.drafted, 100.0 * eng.acceptance)
    if args.prefix_cache > 0:
        log.info("prefix cache: %s hits, %s prompt tokens reused "
                 "(%s entries held)",
                 eng.prefix_hits, eng.prefix_tokens_reused,
                 len(eng._prefix_cache))
    if eng.paged:
        log.info("paged KV: %s/%s blocks in use at exit, %s prefix block "
                 "hits, %s COW copies, %s pool preemptions",
                 eng.blocks_in_use, eng.num_blocks - 1,
                 eng.prefix_block_hits, eng.blocks_cow, eng.pool_preempted)
    if args.metrics_dump:
        from hivedscheduler_tpu.obs import trace as obs_trace
        from hivedscheduler_tpu.runtime.metrics import REGISTRY

        with open(args.metrics_dump, "w") as f:
            f.write(REGISTRY.render())
        trace_path = args.metrics_dump + ".trace.json"
        obs_trace.write_chrome_trace(trace_path)
        log.info("metrics exposition -> %s; Chrome trace -> %s "
                 "(open in https://ui.perfetto.dev)",
                 args.metrics_dump, trace_path)
    return 0


if __name__ == "__main__":
    sys.exit(main())
