"""tpu-hive: a TPU-native cluster scheduler with the capabilities of HiveD.

Re-designed from microsoft/hivedscheduler (reference surveyed in SURVEY.md) for
TPU pods on Kubernetes/GKE:

- the GPU cell hierarchy (GPU -> PCIe switch -> NVLink node -> rack) becomes an
  ICI-mesh hierarchy (chip -> tray -> cube -> pod slice) with coordinate cells,
- the buddy-cell allocator hands out *contiguous* mesh slices via mesh tiling,
- the scheduler-extender binding delivers chip isolation through the Cloud TPU
  device plugin (``TPU_VISIBLE_CHIPS``) instead of ``NVIDIA_VISIBLE_DEVICES``,
- the workload runtime (``hivedscheduler_tpu.parallel`` / ``.models`` /
  ``.ops``) consumes the scheduler's bind decision and builds a
  ``jax.sharding.Mesh`` over the allocated sub-mesh for SPMD training.

Capability parity targets (reference file:line cited per module):
virtual-cluster topology guarantees, gang scheduling via affinity groups,
guaranteed/opportunistic priorities, intra/inter-VC and lazy preemption,
bad-hardware awareness, and work-preserving reconfiguration.
"""

__version__ = "0.1.0"
