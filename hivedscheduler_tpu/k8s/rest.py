"""REST Kubernetes client: list/watch + bind over the K8s HTTP API.

Stdlib-only implementation of the ``KubeClient`` interface against an
ApiServer address (insecure port or ``kubectl proxy``), mirroring what the
reference gets from client-go (reference: ``pkg/api/config.go:39-60`` for the
address contract, ``internal/utils.go:291-314`` for Bind):

- ``sync()`` lists nodes+pods (delivering adds) and then starts streaming
  watches from the returned resourceVersions;
- watches reconnect on EOF with the last seen resourceVersion; a 410 Gone
  falls back to a fresh list+watch;
- ``bind_pod`` POSTs the Bind subresource with the scheduler's annotations in
  ``binding.metadata.annotations`` — the ApiServer merges them onto the pod,
  which is exactly how the placement record becomes durable.

Failure ladder (doc/design/fault-model.md): transient request failures
(429/5xx/timeout/connection) retry with bounded exponential backoff +
jitter, counted in ``tpu_hive_k8s_retries_total``; watch disconnects
reconnect with their own backoff ladder, a 410 Gone falls back to
list+reconcile, and a watch that cannot reconnect past
``watch_failure_threshold`` consecutive attempts reports itself dead
through ``watches_alive()`` (flipping the scheduler's /healthz) until a
reconnect succeeds.
"""

from __future__ import annotations

import http.client
import json
import logging
import os
import random
import ssl
import threading
import urllib.error
import urllib.parse
import urllib.request
from typing import Dict, List, Optional

from hivedscheduler_tpu.k8s import serde
from hivedscheduler_tpu.k8s.client import KubeClient
from hivedscheduler_tpu.k8s.types import Binding, Node, Pod
from hivedscheduler_tpu.runtime.metrics import REGISTRY as metrics

log = logging.getLogger(__name__)


SERVICE_ACCOUNT_DIR = "/var/run/secrets/kubernetes.io/serviceaccount"

# HTTP statuses worth a retry: throttled or server-side transient. Anything
# else 4xx is a real rejection and must surface immediately.
_RETRYABLE_CODES = frozenset({429, 500, 502, 503, 504})


class RestKubeClient(KubeClient):
    """``bearer_token``/``ca_cert`` enable authenticated in-cluster access
    (both default to the mounted service-account credentials when present);
    plain HTTP against an insecure port / kubectl proxy needs neither."""

    def __init__(
        self,
        base_url: str,
        timeout: float = 30.0,
        bearer_token: Optional[str] = None,
        ca_cert: Optional[str] = None,
        max_retries: int = 4,
        retry_backoff_s: float = 0.1,
        retry_backoff_cap_s: float = 2.0,
        watch_backoff_s: float = 1.0,
        watch_backoff_cap_s: float = 30.0,
        watch_failure_threshold: int = 3,
    ):
        """Retry knobs: each request makes up to ``1 + max_retries``
        attempts on retryable failures (429/5xx/timeout/connection), backing
        off exponentially from ``retry_backoff_s`` with jitter, capped at
        ``retry_backoff_cap_s``. Watches reconnect forever on their own
        ladder (``watch_backoff_s`` .. ``watch_backoff_cap_s``); after
        ``watch_failure_threshold`` consecutive failed reconnects the watch
        reports unhealthy via ``watches_alive()`` until it reconnects."""
        self.base_url = base_url.rstrip("/")
        self.timeout = timeout
        self.max_retries = max(0, max_retries)
        self.retry_backoff_s = retry_backoff_s
        self.retry_backoff_cap_s = retry_backoff_cap_s
        self.watch_backoff_s = watch_backoff_s
        self.watch_backoff_cap_s = watch_backoff_cap_s
        self.watch_failure_threshold = max(1, watch_failure_threshold)
        self._jitter = random.Random()
        # path -> is the watch stream believed healthy (missing = not
        # started yet, which counts as healthy: a pre-sync client is not
        # wedged)
        self._watch_ok: Dict[str, bool] = {}
        if bearer_token is not None and not self.base_url.startswith("https"):
            # the TLS-only rule for the auto-detected SA token applies to
            # explicit tokens too: a bearer token must never ride plaintext
            # off-host. Loopback (kubectl proxy, test fakes) is allowed with
            # a loud warning.
            host = urllib.parse.urlsplit(self.base_url).hostname or ""
            if host not in ("localhost", "127.0.0.1", "::1"):
                raise ValueError(
                    f"refusing to send a bearer token over plaintext to "
                    f"non-loopback {self.base_url}; use https:// or a local proxy"
                )
            log.warning(
                "bearer token will ride plaintext HTTP to loopback %s",
                self.base_url,
            )
        self.bearer_token = bearer_token
        # auto-use the mounted service-account token only over TLS (a bearer
        # token must never ride plaintext), re-read per request because bound
        # SA tokens rotate (~1h lifetime)
        self._sa_token_file: Optional[str] = None
        token_file = os.path.join(SERVICE_ACCOUNT_DIR, "token")
        if (
            bearer_token is None
            and self.base_url.startswith("https")
            and os.path.exists(token_file)
        ):
            self._sa_token_file = token_file
        self._ssl_context: Optional[ssl.SSLContext] = None
        if self.base_url.startswith("https"):
            if ca_cert is not None and not os.path.exists(ca_cert):
                raise FileNotFoundError(f"ca_cert not found: {ca_cert}")
            ca_file = ca_cert or os.path.join(SERVICE_ACCOUNT_DIR, "ca.crt")
            self._ssl_context = ssl.create_default_context(
                cafile=ca_file if os.path.exists(ca_file) else None
            )
        self._node_handlers = []
        self._pod_handlers = []
        self._stop = threading.Event()
        self._watch_threads: List[threading.Thread] = []

    # --- HTTP helpers -----------------------------------------------------
    def _current_token(self) -> Optional[str]:
        if self.bearer_token:
            return self.bearer_token
        if self._sa_token_file:
            try:
                with open(self._sa_token_file) as f:
                    return f.read().strip()
            except OSError:
                return None
        return None

    def _headers(self, has_body: bool) -> dict:
        headers = {}
        if has_body:
            headers["Content-Type"] = "application/json"
        token = self._current_token()
        if token:
            headers["Authorization"] = f"Bearer {token}"
        return headers

    @staticmethod
    def _retry_reason(e: Exception) -> Optional[str]:
        """Bounded-cardinality label for a retryable failure; None means the
        failure is terminal (a real 4xx rejection, malformed response...)."""
        if isinstance(e, urllib.error.HTTPError):
            return str(e.code) if e.code in _RETRYABLE_CODES else None
        if isinstance(e, urllib.error.URLError):
            if isinstance(e.reason, (TimeoutError, ssl.SSLError)):
                return "timeout"
            return "connection"
        if isinstance(e, TimeoutError):
            return "timeout"
        if isinstance(e, (ConnectionError, http.client.HTTPException)):
            # reset/refused mid-exchange, truncated chunked body, bad status
            # line from a bouncing proxy — all transport-transient
            return "connection"
        return None

    def _backoff(self, attempt: int, base: float, cap: float) -> float:
        """Exponential backoff with equal jitter: half deterministic, half
        uniform — spreads a thundering herd of schedulers without ever
        collapsing the delay to ~0."""
        d = min(cap, base * (2 ** attempt))
        return d / 2 + self._jitter.uniform(0, d / 2)

    def _request(self, method: str, path: str, body: Optional[dict] = None):
        """One API request with bounded retry on transient failures. Safe
        for the Bind POST too: a bind is idempotent (same pod, same node,
        same annotations merge), so at-least-once delivery after an
        ambiguous timeout converges."""
        data = json.dumps(body).encode() if body is not None else None
        attempt = 0
        while True:
            req = urllib.request.Request(
                self.base_url + path,
                data=data,
                method=method,
                headers=self._headers(data is not None),
            )
            try:
                with urllib.request.urlopen(
                    req, timeout=self.timeout, context=self._ssl_context
                ) as resp:
                    raw = resp.read()
                    return json.loads(raw) if raw else None
            except Exception as e:
                reason = self._retry_reason(e)
                if reason is None or attempt >= self.max_retries or self._stop.is_set():
                    raise
                metrics.inc("tpu_hive_k8s_retries_total",
                            op=method, reason=reason)
                delay = self._backoff(
                    attempt, self.retry_backoff_s, self.retry_backoff_cap_s
                )
                log.warning(
                    "%s %s failed transiently (%s); retry %d/%d in %.2fs",
                    method, path, e, attempt + 1, self.max_retries, delay,
                )
                self._stop.wait(delay)
                attempt += 1

    # --- informer registration --------------------------------------------
    def on_node_event(self, add, update, delete) -> None:
        self._node_handlers.append((add, update, delete))

    def on_pod_event(self, add, update, delete) -> None:
        self._pod_handlers.append((add, update, delete))

    def sync(self) -> None:
        """List (replay as adds) then watch — the recovery barrier. Like
        client-go informers, a local object cache per resource supplies the
        real old objects on MODIFIED events and synthesizes deletes when a
        410-Gone relist finds objects vanished during a watch gap."""
        node_cache: dict = {}
        pod_cache: dict = {}
        node_rv = self._list_and_diff(
            "/api/v1/nodes", serde.node_from_k8s, self._node_handlers,
            lambda n: n.name, node_cache,
        )
        pod_rv = self._list_and_diff(
            "/api/v1/pods", serde.pod_from_k8s, self._pod_handlers,
            lambda p: p.key, pod_cache,
        )
        self._watch_threads = [
            threading.Thread(
                target=self._watch_loop,
                args=("/api/v1/nodes", serde.node_from_k8s, self._node_handlers,
                      lambda n: n.name, node_cache, node_rv),
                name="watch-nodes", daemon=True,
            ),
            threading.Thread(
                target=self._watch_loop,
                args=("/api/v1/pods", serde.pod_from_k8s, self._pod_handlers,
                      lambda p: p.key, pod_cache, pod_rv),
                name="watch-pods", daemon=True,
            ),
        ]
        for t in self._watch_threads:
            t.start()

    def stop(self) -> None:
        self._stop.set()

    def watches_alive(self) -> bool:
        """Liveness for the scheduler's /healthz: dead watch threads — or
        live threads stuck past ``watch_failure_threshold`` consecutive
        failed reconnects — mean the informer stream stopped delivering.
        Recovers to True as soon as every watch reconnects. A deliberately
        stopped client (or one that has not synced yet) is not 'wedged'."""
        if self._stop.is_set():
            return True
        return all(t.is_alive() for t in self._watch_threads) and all(
            self._watch_ok.values()
        )

    def _list_and_diff(self, path: str, parse, handlers, key_fn, cache: dict) -> str:
        """List and reconcile against the cache: adds for new objects,
        updates for known ones, deletes for vanished ones."""
        body = self._request("GET", path) or {}
        new = {}
        for item in body.get("items") or []:
            obj = parse(item)
            new[key_fn(obj)] = obj
        for k in list(cache):
            if k not in new:
                old = cache.pop(k)
                for _, _, delete in handlers:
                    delete(old)
        for k, obj in new.items():
            old = cache.get(k)
            cache[k] = obj
            if old is None:
                for add, _, _ in handlers:
                    add(obj)
            else:
                for _, update, _ in handlers:
                    update(old, obj)
        return (body.get("metadata") or {}).get("resourceVersion", "")

    def _watch_loop(
        self, path: str, parse, handlers, key_fn, cache: dict, resource_version: str
    ) -> None:
        rv = resource_version
        failures = 0  # consecutive failed connect/stream attempts
        self._watch_ok[path] = True
        while not self._stop.is_set():
            url = f"{self.base_url}{path}?watch=true"
            if rv:
                url += f"&resourceVersion={rv}"
            try:
                req = urllib.request.Request(url, headers=self._headers(False))
                with urllib.request.urlopen(
                    req, timeout=None, context=self._ssl_context
                ) as resp:
                    # connected: the stream is delivering again
                    failures = 0
                    self._watch_ok[path] = True
                    for line in resp:
                        if self._stop.is_set():
                            return
                        if not line.strip():
                            continue
                        event = json.loads(line)
                        etype = event.get("type")
                        raw_obj = event.get("object") or {}
                        rv = (raw_obj.get("metadata") or {}).get("resourceVersion", rv)
                        if etype == "ERROR":
                            code = (raw_obj.get("code") or 0)
                            log.warning("watch %s error event: %s", path, raw_obj)
                            if code == 410:  # Gone: relist + reconcile
                                rv = self._list_and_diff(
                                    path, parse, handlers, key_fn, cache
                                )
                            continue
                        obj = parse(raw_obj)
                        k = key_fn(obj)
                        old = cache.get(k)
                        if etype == "ADDED":
                            cache[k] = obj
                            if old is None:
                                for add, _, _ in handlers:
                                    add(obj)
                            else:  # replayed add after resume
                                for _, update, _ in handlers:
                                    update(old, obj)
                        elif etype == "MODIFIED":
                            cache[k] = obj
                            for _, update, _ in handlers:
                                update(old if old is not None else obj, obj)
                        elif etype == "DELETED":
                            cache.pop(k, None)
                            for _, _, delete in handlers:
                                delete(obj)
            except Exception as e:
                if self._stop.is_set():
                    return
                failures += 1
                if failures >= self.watch_failure_threshold:
                    # stuck, not blipping: flip /healthz until a reconnect
                    self._watch_ok[path] = False
                delay = self._backoff(
                    failures - 1, self.watch_backoff_s, self.watch_backoff_cap_s
                )
                log.warning(
                    "watch %s disconnected (%s); reconnect attempt %d in %.2fs",
                    path, e, failures, delay,
                )
                self._stop.wait(delay)

    # --- reads ------------------------------------------------------------
    def get_node(self, name: str) -> Optional[Node]:
        try:
            return serde.node_from_k8s(self._request("GET", f"/api/v1/nodes/{name}"))
        except urllib.error.HTTPError as e:
            if e.code == 404:
                return None
            raise

    def list_nodes(self) -> List[Node]:
        body = self._request("GET", "/api/v1/nodes") or {}
        return [serde.node_from_k8s(i) for i in body.get("items") or []]

    def get_pod(self, namespace: str, name: str) -> Optional[Pod]:
        try:
            return serde.pod_from_k8s(
                self._request("GET", f"/api/v1/namespaces/{namespace}/pods/{name}")
            )
        except urllib.error.HTTPError as e:
            if e.code == 404:
                return None
            raise

    def list_pods(self) -> List[Pod]:
        body = self._request("GET", "/api/v1/pods") or {}
        return [serde.pod_from_k8s(i) for i in body.get("items") or []]

    # --- writes -----------------------------------------------------------
    def bind_pod(self, binding: Binding) -> None:
        """POST the Bind subresource; annotations ride on binding metadata and
        are merged onto the pod by the ApiServer (the durable placement
        record)."""
        self._request(
            "POST",
            f"/api/v1/namespaces/{binding.pod_namespace}/pods/{binding.pod_name}/binding",
            {
                "apiVersion": "v1",
                "kind": "Binding",
                "metadata": {
                    "name": binding.pod_name,
                    "namespace": binding.pod_namespace,
                    "uid": binding.pod_uid,
                    "annotations": dict(binding.annotations),
                },
                "target": {"apiVersion": "v1", "kind": "Node", "name": binding.node},
            },
        )
