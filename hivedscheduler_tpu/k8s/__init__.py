"""Minimal Kubernetes object model + clients.

The reference vendors k8s.io/client-go; this build uses a self-contained
object model (``k8s/types.py``), a pluggable client interface
(``k8s/client.py``), and an in-memory fake ApiServer with watch support
(``k8s/fake.py``) used for tests and e2e — exceeding the reference's test
strategy, which has no automated integration harness (SURVEY.md §4).
"""
