"""K8s-wire JSON <-> object model conversion.

Only the fields the scheduler-extender protocol touches are mapped, matching
the subset of core/v1 the reference consumes through client-go.
"""

from __future__ import annotations

from typing import Any, Dict

from hivedscheduler_tpu.k8s.types import Container, Node, NodeCondition, Pod


def pod_from_k8s(d: Dict[str, Any]) -> Pod:
    meta = d.get("metadata") or {}
    spec = d.get("spec") or {}
    status = d.get("status") or {}
    containers = []
    for c in (spec.get("containers") or []) + (spec.get("initContainers") or []):
        limits = ((c.get("resources") or {}).get("limits")) or {}
        containers.append(Container(name=c.get("name", ""), resource_limits=dict(limits)))
    return Pod(
        name=meta.get("name", ""),
        namespace=meta.get("namespace", "default"),
        uid=meta.get("uid", ""),
        annotations=dict(meta.get("annotations") or {}),
        containers=containers,
        node_name=spec.get("nodeName", "") or "",
        phase=status.get("phase", "Pending") or "Pending",
        deletion_timestamp=meta.get("deletionTimestamp"),
    )


def pod_to_k8s(p: Pod) -> Dict[str, Any]:
    return {
        "metadata": {
            "name": p.name,
            "namespace": p.namespace,
            "uid": p.uid,
            "annotations": dict(p.annotations),
            **({"deletionTimestamp": p.deletion_timestamp} if p.deletion_timestamp else {}),
        },
        "spec": {
            "nodeName": p.node_name or None,
            "containers": [
                {"name": c.name, "resources": {"limits": dict(c.resource_limits)}}
                for c in p.containers
            ],
        },
        "status": {"phase": p.phase},
    }


def node_from_k8s(d: Dict[str, Any]) -> Node:
    meta = d.get("metadata") or {}
    spec = d.get("spec") or {}
    status = d.get("status") or {}
    # no conditions reported => NOT ready (the reference requires an explicit
    # Ready=True condition, internal/utils.go:160-170)
    conditions = [
        NodeCondition(type=c.get("type", ""), status=c.get("status", ""))
        for c in status.get("conditions") or []
    ]
    return Node(
        name=meta.get("name", ""),
        unschedulable=bool(spec.get("unschedulable", False)),
        conditions=conditions,
    )


def node_to_k8s(n: Node) -> Dict[str, Any]:
    return {
        "metadata": {"name": n.name},
        "spec": {"unschedulable": n.unschedulable},
        "status": {
            "conditions": [{"type": c.type, "status": c.status} for c in n.conditions]
        },
    }
