"""In-memory fake Kubernetes ApiServer with watch support.

Used by tests and e2e harnesses; exceeds the reference's test strategy, which
has no automated integration tests (SURVEY.md §4). Thread-safe; events are
delivered synchronously on the mutating thread (like a zero-latency informer).
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional

from hivedscheduler_tpu.k8s.client import KubeClient
from hivedscheduler_tpu.k8s.types import Binding, Node, Pod


class FakeKubeClient(KubeClient):
    def __init__(self):
        self._lock = threading.RLock()
        self._nodes: Dict[str, Node] = {}
        self._pods: Dict[str, Pod] = {}  # key: namespace/name
        self._node_handlers = []
        self._pod_handlers = []

    # --- informer registration ------------------------------------------
    def on_node_event(self, add, update, delete) -> None:
        self._node_handlers.append((add, update, delete))

    def on_pod_event(self, add, update, delete) -> None:
        self._pod_handlers.append((add, update, delete))

    def sync(self) -> None:
        with self._lock:
            for node in list(self._nodes.values()):
                for add, _, _ in self._node_handlers:
                    add(node.deep_copy())
            for pod in list(self._pods.values()):
                for add, _, _ in self._pod_handlers:
                    add(pod.deep_copy())

    # --- reads ------------------------------------------------------------
    def get_node(self, name: str) -> Optional[Node]:
        with self._lock:
            n = self._nodes.get(name)
            return n.deep_copy() if n else None

    def list_nodes(self) -> List[Node]:
        with self._lock:
            return [n.deep_copy() for n in self._nodes.values()]

    def get_pod(self, namespace: str, name: str) -> Optional[Pod]:
        with self._lock:
            p = self._pods.get(f"{namespace}/{name}")
            return p.deep_copy() if p else None

    def list_pods(self) -> List[Pod]:
        with self._lock:
            return [p.deep_copy() for p in self._pods.values()]

    # --- cluster mutation (the "kubectl" surface) -------------------------
    def create_node(self, node: Node) -> None:
        with self._lock:
            self._nodes[node.name] = node.deep_copy()
            for add, _, _ in self._node_handlers:
                add(node.deep_copy())

    def update_node(self, node: Node) -> None:
        with self._lock:
            old = self._nodes.get(node.name)
            self._nodes[node.name] = node.deep_copy()
            if old is None:
                for add, _, _ in self._node_handlers:
                    add(node.deep_copy())
            else:
                for _, update, _ in self._node_handlers:
                    update(old.deep_copy(), node.deep_copy())

    def delete_node(self, name: str) -> None:
        with self._lock:
            node = self._nodes.pop(name, None)
            if node is not None:
                for _, _, delete in self._node_handlers:
                    delete(node.deep_copy())

    def create_pod(self, pod: Pod) -> None:
        with self._lock:
            self._pods[pod.key] = pod.deep_copy()
            for add, _, _ in self._pod_handlers:
                add(pod.deep_copy())

    def update_pod(self, pod: Pod) -> None:
        with self._lock:
            old = self._pods.get(pod.key)
            self._pods[pod.key] = pod.deep_copy()
            if old is None:
                for add, _, _ in self._pod_handlers:
                    add(pod.deep_copy())
            else:
                for _, update, _ in self._pod_handlers:
                    update(old.deep_copy(), pod.deep_copy())

    def delete_pod(self, namespace: str, name: str) -> None:
        with self._lock:
            pod = self._pods.pop(f"{namespace}/{name}", None)
            if pod is not None:
                for _, _, delete in self._pod_handlers:
                    delete(pod.deep_copy())

    # --- writes -----------------------------------------------------------
    def bind_pod(self, binding: Binding) -> None:
        with self._lock:
            key = f"{binding.pod_namespace}/{binding.pod_name}"
            pod = self._pods.get(key)
            if pod is None:
                raise KeyError(f"pod {key} not found")
            if pod.uid != binding.pod_uid:
                raise ValueError(f"pod {key} UID mismatch")
            old = pod.deep_copy()
            pod.node_name = binding.node
            pod.annotations.update(binding.annotations)
            for _, update, _ in self._pod_handlers:
                update(old, pod.deep_copy())
