"""In-memory fake Kubernetes ApiServer with watch support.

Used by tests and e2e harnesses; exceeds the reference's test strategy, which
has no automated integration tests (SURVEY.md §4). Thread-safe with informer
semantics:

- the object store lock is a LEAF lock, never held while handlers run, so
  handler code may hold the scheduler lock or read back into the store
  without lock-order inversions;
- events for one object are delivered in store-mutation order even when
  multiple threads mutate the same object (e.g. the force-bind executor
  racing a pod delete): each mutation enqueues its events under the store
  lock, and exactly one thread at a time drains a given object's queue.
"""

from __future__ import annotations

from collections import deque
from typing import Dict, List, Optional

from hivedscheduler_tpu.common import lockcheck
from hivedscheduler_tpu.k8s.client import KubeClient
from hivedscheduler_tpu.k8s.types import Binding, Node, Pod


class FakeKubeClient(KubeClient):
    def __init__(self):
        self._lock = lockcheck.make_rlock("store_lock")
        self._nodes: Dict[str, Node] = {}
        self._pods: Dict[str, Pod] = {}  # key: namespace/name
        self._node_handlers = []
        self._pod_handlers = []
        # per-object event queues + the set of keys currently being drained
        self._queues: Dict[str, deque] = {}
        self._draining: set = set()

    # --- ordered delivery --------------------------------------------------
    def _fire(self, fire, copies: tuple) -> None:
        """The single chokepoint through which every handler is invoked.

        Debug-mode leaf-lock assertion: the store lock must NOT be held by
        the calling thread while a handler runs — handlers may take the
        scheduler lock and read back into the store, so firing under the
        store lock inverts the lock order (the architecture rule pinned in
        CLAUDE.md: the store lock is a leaf lock, never call handlers under
        it). Plain ``assert`` so ``python -O`` removes the check."""
        assert not self._lock._is_owned(), (
            "FakeKubeClient handler invoked while the store (leaf) lock is "
            "held by this thread — lock-order inversion; deliver through "
            "_emit, which releases the lock before firing"
        )
        fire(*copies)

    def _emit(self, key: str, handlers: List, slot: int, *objs) -> None:
        """Must be called with self._lock held: enqueue one event per handler
        (events of one key keep store-mutation order), then drain outside the
        lock unless another thread already drains this key."""
        q = self._queues.setdefault(key, deque())
        for handler_tuple in handlers:
            fire = handler_tuple[slot]
            copies = tuple(o.deep_copy() for o in objs)
            q.append((fire, copies))
        if key in self._draining:
            return  # the current drainer will deliver our events
        self._draining.add(key)
        self._lock.release()
        try:
            while True:
                with self._lock:
                    if not q:
                        self._draining.discard(key)
                        return
                    fire, copies = q.popleft()
                try:
                    self._fire(fire, copies)
                except Exception:
                    # release drainership (remaining events stay queued, in
                    # order, for the next mutator of this key) and surface
                    # the handler failure
                    with self._lock:
                        self._draining.discard(key)
                    raise
        finally:
            self._lock.acquire()  # restore caller's lock balance

    # --- informer registration ------------------------------------------
    def on_node_event(self, add, update, delete) -> None:
        self._node_handlers.append((add, update, delete))

    def on_pod_event(self, add, update, delete) -> None:
        self._pod_handlers.append((add, update, delete))

    def sync(self) -> None:
        # re-read each object at emission time (one critical section per key):
        # a concurrent delete between snapshot and emission must not let a
        # stale add resurrect the object
        with self._lock:
            node_names = list(self._nodes)
            pod_keys = list(self._pods)
        for name in node_names:
            with self._lock:
                node = self._nodes.get(name)
                if node is not None:
                    self._emit(f"node/{name}", self._node_handlers, 0, node)
        for key in pod_keys:
            with self._lock:
                pod = self._pods.get(key)
                if pod is not None:
                    self._emit(f"pod/{key}", self._pod_handlers, 0, pod)

    # --- reads ------------------------------------------------------------
    def get_node(self, name: str) -> Optional[Node]:
        with self._lock:
            n = self._nodes.get(name)
            return n.deep_copy() if n else None

    def list_nodes(self) -> List[Node]:
        with self._lock:
            return [n.deep_copy() for n in self._nodes.values()]

    def get_pod(self, namespace: str, name: str) -> Optional[Pod]:
        with self._lock:
            p = self._pods.get(f"{namespace}/{name}")
            return p.deep_copy() if p else None

    def list_pods(self) -> List[Pod]:
        with self._lock:
            return [p.deep_copy() for p in self._pods.values()]

    # --- cluster mutation (the "kubectl" surface) -------------------------
    def create_node(self, node: Node) -> None:
        with self._lock:
            old = self._nodes.get(node.name)
            self._nodes[node.name] = node.deep_copy()
            if old is None:
                self._emit(f"node/{node.name}", self._node_handlers, 0, node)
            else:
                self._emit(f"node/{node.name}", self._node_handlers, 1, old, node)

    def update_node(self, node: Node) -> None:
        self.create_node(node)

    def delete_node(self, name: str) -> None:
        with self._lock:
            node = self._nodes.pop(name, None)
            if node is not None:
                self._emit(f"node/{name}", self._node_handlers, 2, node)

    def create_pod(self, pod: Pod) -> None:
        with self._lock:
            old = self._pods.get(pod.key)
            self._pods[pod.key] = pod.deep_copy()
            if old is None:
                self._emit(f"pod/{pod.key}", self._pod_handlers, 0, pod)
            else:
                self._emit(f"pod/{pod.key}", self._pod_handlers, 1, old, pod)

    def update_pod(self, pod: Pod) -> None:
        self.create_pod(pod)

    def delete_pod(self, namespace: str, name: str) -> None:
        key = f"{namespace}/{name}"
        with self._lock:
            pod = self._pods.pop(key, None)
            if pod is not None:
                self._emit(f"pod/{key}", self._pod_handlers, 2, pod)

    # --- writes -----------------------------------------------------------
    def bind_pod(self, binding: Binding) -> None:
        key = f"{binding.pod_namespace}/{binding.pod_name}"
        with self._lock:
            pod = self._pods.get(key)
            if pod is None:
                raise KeyError(f"pod {key} not found")
            if pod.uid != binding.pod_uid:
                raise ValueError(f"pod {key} UID mismatch")
            old = pod.deep_copy()
            pod.node_name = binding.node
            pod.annotations.update(binding.annotations)
            self._emit(f"pod/{key}", self._pod_handlers, 1, old, pod)
