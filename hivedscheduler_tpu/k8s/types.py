"""Kubernetes-shaped object model (pods, nodes, bindings).

Only the fields the scheduler reads/writes are modeled, matching the shapes
used by the reference through client-go: pod metadata + annotations + container
resource limits + spec.nodeName + status.phase; node schedulability +
conditions.
"""

from __future__ import annotations

import copy
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional


@dataclass
class Container:
    name: str = ""
    resource_limits: Dict[str, Any] = field(default_factory=dict)


@dataclass
class Pod:
    name: str = ""
    namespace: str = "default"
    uid: str = ""
    annotations: Dict[str, str] = field(default_factory=dict)
    containers: List[Container] = field(default_factory=list)
    node_name: str = ""  # spec.nodeName: non-empty iff bound
    phase: str = "Pending"  # status.phase
    deletion_timestamp: Optional[str] = None

    @property
    def key(self) -> str:
        return f"{self.namespace}/{self.name}"

    def deep_copy(self) -> "Pod":
        # hand-rolled: this runs on every informer delivery and binding;
        # generic deepcopy is ~5x slower for this flat shape
        return Pod(
            name=self.name,
            namespace=self.namespace,
            uid=self.uid,
            annotations=dict(self.annotations),
            containers=[
                Container(name=c.name, resource_limits=dict(c.resource_limits))
                for c in self.containers
            ],
            node_name=self.node_name,
            phase=self.phase,
            deletion_timestamp=self.deletion_timestamp,
        )


@dataclass
class NodeCondition:
    type: str = "Ready"
    status: str = "True"


@dataclass
class Node:
    name: str = ""
    unschedulable: bool = False
    conditions: List[NodeCondition] = field(default_factory=lambda: [NodeCondition()])

    def deep_copy(self) -> "Node":
        return copy.deepcopy(self)


@dataclass
class Binding:
    """Bind subresource payload: target node + annotations to merge
    (reference: internal/utils.go:291-314)."""

    pod_name: str
    pod_namespace: str
    pod_uid: str
    node: str
    annotations: Dict[str, str] = field(default_factory=dict)
