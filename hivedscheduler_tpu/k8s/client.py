"""Kubernetes client interface.

The reference talks to K8s through client-go informers + the Bind subresource
(``scheduler.go:132-137``, ``internal/utils.go:291-314``). This build defines a
minimal client interface with informer-style callbacks; ``k8s/fake.py``
implements it in memory (tests/e2e), and a real REST implementation can be
plugged in for cluster deployments.
"""

from __future__ import annotations

from typing import Callable, List, Optional

from hivedscheduler_tpu.k8s.types import Binding, Node, Pod

NodeHandler = Callable[[Node], None]
NodeUpdateHandler = Callable[[Node, Node], None]
PodHandler = Callable[[Pod], None]
PodUpdateHandler = Callable[[Pod, Pod], None]


class KubeClient:
    """Informer + write interface the scheduler runtime consumes."""

    # --- informer registration ------------------------------------------
    def on_node_event(
        self,
        add: NodeHandler,
        update: NodeUpdateHandler,
        delete: NodeHandler,
    ) -> None:
        raise NotImplementedError

    def on_pod_event(
        self,
        add: PodHandler,
        update: PodUpdateHandler,
        delete: PodHandler,
    ) -> None:
        raise NotImplementedError

    def sync(self) -> None:
        """Replay current state through the registered handlers and block
        until done — the crash-recovery barrier (reference: WaitForCacheSync,
        scheduler.go:202-209)."""
        raise NotImplementedError

    def watches_alive(self) -> bool:
        """Whether the post-sync watch/informer streams are still delivering.

        Consumed by the scheduler's /healthz liveness probe; clients without
        background watch threads (e.g. the fake in-memory ApiServer) are
        always alive."""
        return True

    # --- reads ------------------------------------------------------------
    def get_node(self, name: str) -> Optional[Node]:
        raise NotImplementedError

    def list_nodes(self) -> List[Node]:
        raise NotImplementedError

    def get_pod(self, namespace: str, name: str) -> Optional[Pod]:
        raise NotImplementedError

    def list_pods(self) -> List[Pod]:
        raise NotImplementedError

    # --- writes -----------------------------------------------------------
    def bind_pod(self, binding: Binding) -> None:
        """Commit a binding: set spec.nodeName and merge annotations
        (reference: BindPod, internal/utils.go:291-314)."""
        raise NotImplementedError
