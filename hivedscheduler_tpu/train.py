"""Workload entry point: train the flagship transformer on a tpu-hive slice.

Ties the handoff chain together end to end: the scheduler grants a
contiguous sub-mesh (``TPU_VISIBLE_CHIPS`` + bind-info annotation), this
entry point initializes ``jax.distributed`` across the gang's hosts
(``parallel/distributed.py``), lays the dp/fsdp/tp/sp mesh over the slice,
and runs the sharded train step with periodic orbax checkpoints — resuming
automatically when the gang was preempted and rescheduled.

Run inside a scheduled pod (see example/request/request.yaml), or locally:

    python -m hivedscheduler_tpu.train --steps 20 --tp 2 --sp 2 \
        --d-model 256 --n-layers 2
"""

from __future__ import annotations

import argparse
import logging
import os
import time

log = logging.getLogger(__name__)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="tpu-hive-train")
    parser.add_argument("--steps", type=int, default=100)
    parser.add_argument("--batch", type=int, default=8)
    parser.add_argument("--seq-len", type=int, default=512)
    parser.add_argument("--vocab-size", type=int, default=32000)
    parser.add_argument("--d-model", type=int, default=512)
    parser.add_argument("--n-layers", type=int, default=8)
    parser.add_argument("--n-heads", type=int, default=8)
    parser.add_argument("--n-kv-heads", type=int, default=0,
                        help="GQA shared k/v heads (0 = n_heads, 1 = MQA)")
    parser.add_argument("--d-ff", type=int, default=1408)
    parser.add_argument("--tp", type=int, default=1)
    parser.add_argument("--sp", type=int, default=1)
    parser.add_argument("--fsdp", type=int, default=1)
    parser.add_argument("--pp", type=int, default=1,
                        help="pipeline stages (requires --microbatches)")
    parser.add_argument("--microbatches", type=int, default=0,
                        help="GPipe microbatches; required when --pp > 1")
    parser.add_argument("--ep", type=int, default=1,
                        help="expert-parallel size (with --n-experts)")
    parser.add_argument("--n-experts", type=int, default=0)
    parser.add_argument("--moe-top-k", type=int, default=1)
    parser.add_argument("--moe-zloss", type=float, default=0.0,
                        help="ST-MoE router z-loss weight (0 disables)")
    parser.add_argument("--moe-aux-weight", type=float, default=0.01,
                        help="Switch load-balancing auxiliary-loss weight")
    parser.add_argument("--expert-capacity-factor", type=float, default=1.25,
                        help="MoE expert capacity factor (tokens kept per "
                        "expert relative to an even split)")
    parser.add_argument("--rope-theta", type=float, default=10000.0,
                        help="RoPE base frequency (long-context runs raise "
                        "it; must match at eval/serving time)")
    parser.add_argument("--grad-accum", type=int, default=1,
                        help="gradient-accumulation slices per batch "
                        "(batch must divide evenly)")
    parser.add_argument("--lora-rank", type=int, default=0,
                        help="LoRA fine-tuning: adapter rank on the attention "
                        "projections (0 = full training)")
    parser.add_argument("--lora-mlp", action="store_true",
                        help="extend LoRA adapters to the dense-MLP "
                             "projections (gate/up/down)")
    parser.add_argument("--lora-alpha", type=float, default=16.0,
                        help="LoRA scale (delta = alpha/rank * A B)")
    parser.add_argument("--remat", "--remat-policy", dest="remat",
                        choices=("full", "dots", "none"),
                        default="full",
                        help="layer-scan remat policy (selective remat): "
                             "full recompute (HBM O(1) layers, but the "
                             "recompute is a full extra forward — a direct "
                             "MFU tax), dots (save matmul outputs, replay "
                             "only elementwise work — the MFU-tuned default "
                             "of bench_model.py), none (save everything)")
    parser.add_argument("--overlap", action=argparse.BooleanOptionalAction,
                        default=None,
                        help="--overlap/--no-overlap: the collective-matmul "
                             "tensor-parallel path (sequence-sharded "
                             "residual stream; ppermute-pipelined "
                             "all-gather/reduce-scatter around the "
                             "QKV/out/MLP projections so ICI hops overlap "
                             "MXU work). Default: auto — on whenever "
                             "applicable (tp>1, dense, no LoRA/pipeline); "
                             "--overlap errors if inapplicable; "
                             "HIVED_OVERLAP=0 forces the reference path "
                             "regardless")
    parser.add_argument("--ce-chunk", type=int, default=0,
                        help="chunked cross-entropy: compute lm_head+CE in "
                             "sequence chunks of this size so the "
                             "[B,T,vocab] f32 logits never materialize "
                             "(0 = off; must divide --seq-len; best with "
                             "--sp 1)")
    parser.add_argument("--block-q", type=int, default=128,
                        help="flash-attention q tile (flash/ring_flash/"
                             "ring_zigzag_flash)")
    parser.add_argument("--block-k", type=int, default=128,
                        help="flash-attention k tile (flash/ring_flash/"
                             "ring_zigzag_flash)")
    parser.add_argument("--attn", default=None,
                        help="xla|flash|ring|ring_flash|ring_zigzag|"
                             "ring_zigzag_flash|ulysses "
                             "(default: ring when sp>1)")
    parser.add_argument("--prefetch", type=int, default=2,
                        help="data-loader prefetch depth (batches assembled "
                             "in a background thread — the native gather "
                             "releases the GIL; 0 disables)")
    parser.add_argument("--data", default="",
                        help="packed token file; synthetic corpus when omitted")
    parser.add_argument("--data-dtype", default="uint16",
                        choices=["uint16", "uint32"],
                        help="token dtype of the --data file")
    parser.add_argument("--init-from", default="",
                        help="warm-start params from another run's checkpoint "
                        "(fresh optimizer). With --lora-rank this is the "
                        "pretrained BASE model the adapters fine-tune")
    parser.add_argument("--profile-dir", default="",
                        help="capture a jax.profiler trace (TensorBoard/"
                        "Perfetto format) of steps 2..4 into this directory "
                        "— step 1 is compile and would drown the trace")
    parser.add_argument("--journal-file", default="",
                        help="enable the gang-lifecycle journal "
                        "(obs/journal.py) and append this incarnation's "
                        "resume/rollback events to this JSONL spool")
    parser.add_argument("--goodput-file", default="",
                        help="enable the workload goodput ledger "
                        "(obs/goodput.py) and append this incarnation's "
                        "step-phase records to this JSONL spool; sharing "
                        "one spool across a gang's incarnations makes "
                        "rework classification exact across kills")
    parser.add_argument("--timeline", default="",
                        help="write a per-step JSONL timeline (step, wall_s, "
                        "tokens_per_sec, loss, compile flag) to this path — "
                        "the host-side complement of --profile-dir's device "
                        "trace. Syncs on the loss every step, so per-step "
                        "wall times are true (small dispatch-overlap cost)")
    parser.add_argument("--checkpoint-dir", default="")
    parser.add_argument("--checkpoint-every", type=int, default=50)
    parser.add_argument("--log-every", type=int, default=10)
    parser.add_argument("--data-seed", type=int, default=0,
                        help="data-loader stream seed (the loader's RNG "
                        "state joins every checkpoint, so a preempted run "
                        "resumes the exact uninterrupted stream)")
    parser.add_argument("--elastic", action="store_true",
                        help="elastic mesh mode: treat --tp/--sp/--fsdp/"
                        "--pp/--ep as PREFERENCES and derive a valid mesh "
                        "for whatever slice the scheduler actually offered "
                        "(the device count the bind annotation granted) "
                        "instead of asserting the requested shape. A "
                        "checkpoint saved on one mesh restores onto the "
                        "derived one (cross-topology resume; "
                        "doc/design/elastic.md)")
    parser.add_argument("--min-chips", type=int, default=0,
                        help="with --elastic: the smallest slice this job "
                        "accepts; an offer below it exits nonzero instead "
                        "of training degenerately (recorded in the "
                        "checkpoint metadata as the job's shape ladder "
                        "floor)")
    parser.add_argument("--grace-secs", type=float, default=30.0,
                        help="preemption grace period: SIGTERM/SIGINT "
                        "request checkpoint-and-exit at the next step "
                        "boundary; a shutdown still wedged after this many "
                        "seconds is force-exited (uncommitted checkpoint "
                        "steps stay invisible to restore)")
    parser.add_argument("--watchdog-secs", type=float, default=0.0,
                        help="per-step hang deadline (0 = off): a step "
                        "exceeding it records hived_stall.json in the "
                        "checkpoint dir and exits nonzero so the gang "
                        "restarts instead of wedging (first step gets 10x "
                        "for compile)")
    parser.add_argument("--on-nan", choices=("halt", "rollback", "skip"),
                        default="halt",
                        help="divergence policy for a non-finite loss (or "
                        "spike, see --loss-spike-factor): halt = exit "
                        "nonzero with the last good checkpoint intact; "
                        "rollback = restore the newest committed checkpoint "
                        "and skip past the poisoned batch; skip = drop the "
                        "update inside the jitted step (params/opt_state "
                        "pass through) and continue")
    parser.add_argument("--loss-spike-factor", type=float, default=0.0,
                        help="also treat loss > FACTOR x its EMA as "
                        "divergence (0 = non-finite only; applies to the "
                        "halt/rollback policies)")
    parser.add_argument("--max-rollbacks", type=int, default=3,
                        help="divergence rollback budget before halting "
                        "(--on-nan rollback)")
    parser.add_argument("-v", "--verbose", action="store_true")
    args = parser.parse_args(argv)
    if args.pp > 1 and args.microbatches <= 0:
        parser.error("--pp > 1 requires --microbatches")
    if args.microbatches > 0 and args.pp <= 1:
        parser.error("--microbatches requires --pp > 1")
    if args.on_nan == "skip" and args.lora_rank > 0:
        parser.error("--on-nan skip gates the full train step; with "
                     "--lora-rank use rollback or halt")
    if args.min_chips and not args.elastic:
        parser.error("--min-chips requires --elastic")

    from hivedscheduler_tpu.common import utils as common

    common.init_all(logging.DEBUG if args.verbose else logging.INFO)
    if args.journal_file:
        from hivedscheduler_tpu.obs import journal as obs_journal

        obs_journal.enable(spool_path=args.journal_file)
    # goodput ledger: anchors the process wallclock here (phase `init`),
    # BEFORE the jax import — bring-up is attributed, not leaked
    from hivedscheduler_tpu.obs import goodput as obs_goodput

    if args.goodput_file:
        obs_goodput.enable(spool_path=args.goodput_file)

    # 1. multi-host wiring from the scheduler's gang handoff (no-op when
    #    single-host / not scheduled)
    from hivedscheduler_tpu.parallel.distributed import initialize_from_gang

    rank, world = initialize_from_gang()

    import jax

    from hivedscheduler_tpu.models import transformer as tm
    from hivedscheduler_tpu.parallel import checkpoint as ckpt
    from hivedscheduler_tpu.parallel import topology
    from hivedscheduler_tpu.parallel.train import (
        make_sharded_lora_train_step,
        make_sharded_train_step,
    )

    # 2. mesh over the granted slice. Elastic mode reads the OFFERED slice
    #    (the device count the scheduler's bind actually granted) and
    #    derives a valid mesh for it instead of asserting the requested
    #    shape — the entry-point half of the elastic-resume contract
    #    (doc/design/elastic.md).
    n_devices = len(jax.devices())
    if args.elastic:
        if args.min_chips and n_devices < args.min_chips:
            raise SystemExit(
                f"elastic job floor not met: offered {n_devices} chip(s), "
                f"--min-chips {args.min_chips}"
            )
        axes = topology.elastic_axes(
            n_devices, tp=args.tp, sp=args.sp, fsdp=args.fsdp, pp=args.pp,
            ep=args.ep, n_heads=args.n_heads,
            n_kv_heads=args.n_kv_heads or args.n_heads,
            global_batch=args.batch, seq_len=args.seq_len,
        )
        requested = (args.tp, args.sp, args.fsdp, args.pp, args.ep)
        if (axes.tp, axes.sp, axes.fsdp, axes.pp, axes.ep) != requested:
            log.warning(
                "elastic: requested (tp, sp, fsdp, pp, ep)=%s does not fit "
                "the offered %d-chip slice; derived mesh %s", requested,
                n_devices, axes,
            )
    else:
        axes = topology.infer_axes(n_devices, tp=args.tp, sp=args.sp,
                                   fsdp=args.fsdp, pp=args.pp, ep=args.ep)
    mesh = topology.make_mesh(axes)
    log.info("rank %s/%s: %s devices, mesh %s", rank, world, n_devices, axes)

    attn = args.attn or ("ring" if axes.sp > 1 else "xla")
    cfg = tm.TransformerConfig(
        vocab_size=args.vocab_size,
        d_model=args.d_model,
        n_heads=args.n_heads,
        n_kv_heads=args.n_kv_heads,
        n_layers=args.n_layers,
        d_ff=args.d_ff,
        max_seq_len=args.seq_len,
        attn_impl=attn,
        n_experts=args.n_experts,
        moe_top_k=args.moe_top_k,
        moe_aux_weight=args.moe_aux_weight,
        moe_zloss_weight=args.moe_zloss,
        expert_capacity_factor=args.expert_capacity_factor,
        rope_theta=args.rope_theta,
        # elastic mode may have shrunk pp away: pipelining follows the
        # DERIVED mesh, not the request (a 1-stage pipeline is just the
        # plain layer scan)
        pipeline_microbatches=args.microbatches if axes.pp > 1 else 0,
        lora_rank=args.lora_rank,
        lora_alpha=args.lora_alpha,
        lora_mlp=args.lora_mlp,
        remat=args.remat,
        overlap=args.overlap,
        attn_block_q=args.block_q,
        attn_block_k=args.block_k,
    )
    if args.overlap is not False and os.environ.get("HIVED_OVERLAP") != "0":
        ok, reason = tm.overlap_applicable(cfg, mesh, args.seq_len, args.batch)
        if ok:
            log.info("overlapped collective-matmul path enabled (tp=%s)",
                     args.tp)
        elif args.overlap is True:
            parser.error(f"--overlap requested but inapplicable: {reason}")
        else:
            log.info("overlapped path not applicable (%s); using the "
                     "reference GSPMD path", reason)
    lora_mode = args.lora_rank > 0
    if lora_mode:
        step_fn, init_fn, token_sharding = make_sharded_lora_train_step(
            cfg, mesh, grad_accum=args.grad_accum, ce_chunk=args.ce_chunk
        )
        base_params, lora_params, opt_state = init_fn(jax.random.PRNGKey(0))
        params = tm.combine_lora_params(base_params, lora_params)
    else:
        step_fn, init_fn, token_sharding = make_sharded_train_step(
            cfg, mesh, grad_accum=args.grad_accum, ce_chunk=args.ce_chunk,
            skip_nonfinite=args.on_nan == "skip",
        )
        params, opt_state = init_fn(jax.random.PRNGKey(0))

    # 3. warm start (params only, fresh optimizer) — for LoRA this loads the
    #    frozen pretrained base the adapters are tuned against
    if args.init_from:
        if lora_mode:
            _, base_params = ckpt.restore_params(args.init_from, base_params)
            params = tm.combine_lora_params(base_params, lora_params)
        else:
            _, params = ckpt.restore_params(args.init_from, params)
        log.info("warm-started params from %s", args.init_from)

    import math

    from hivedscheduler_tpu.parallel import supervisor as sup_lib
    from hivedscheduler_tpu.runtime.metrics import REGISTRY as metrics

    def restore_state(params_t, opt_t):
        """Restore the newest committed checkpoint into the given templates;
        returns (step, params, opt_state, loader_metadata). The templates
        carry THIS incarnation's shardings, so a checkpoint written on a
        different (dp, fsdp, pp, ep, tp, sp) mesh reshards on load — the
        metadata gate below has already verified the model geometry and
        data stream match."""
        step_no, p, o = ckpt.restore(args.checkpoint_dir, params_t, opt_t)
        meta = ckpt.read_metadata(args.checkpoint_dir, step_no)
        return step_no, p, o, meta

    # resume if this gang incarnation has a previous checkpoint
    start_step = 0
    resume_meta: dict = {}
    if args.checkpoint_dir:
        last = ckpt.latest_step(args.checkpoint_dir)
        if last is not None:
            source_mesh = ckpt.validate_resume_metadata(
                ckpt.read_metadata(args.checkpoint_dir, last), axes, cfg,
                global_batch=args.batch, seq_len=args.seq_len,
            )
            start_step, params, opt_state, resume_meta = restore_state(
                params, opt_state
            )
            if lora_mode:
                base_params, lora_params = tm.split_lora_params(params)
            metrics.inc("tpu_hive_train_resumes_total")
            from hivedscheduler_tpu.obs import journal as obs_journal
            if obs_journal.JOURNAL.enabled:
                obs_journal.emit("train_resume", "train",
                                 step=start_step,
                                 crossTopology=source_mesh is not None)
            if source_mesh is not None:
                # cross-topology resume: same arrays, different layout —
                # bit-exactness is not promised across reduction orders;
                # the loss trajectory is pinned allclose instead
                # (tests/test_elastic.py)
                metrics.inc("tpu_hive_train_cross_topology_resumes_total")
                log.warning(
                    "cross-topology resume: checkpoint step %s was saved on "
                    "mesh %s, restoring onto %s", start_step, source_mesh,
                    {n: s for n, s in zip(axes.names, axes.shape)},
                )
            log.info("resumed from checkpoint step %s", start_step)

    from hivedscheduler_tpu.parallel import data as data_lib

    if args.data:
        dataset = data_lib.TokenFileDataset(args.data, dtype=args.data_dtype)
        peak = int(dataset.tokens[: 1 << 16].max())
        if peak >= cfg.vocab_size:
            raise SystemExit(
                f"--data contains token id {peak} >= vocab size "
                f"{cfg.vocab_size}; wrong --data-dtype or --vocab-size?"
            )
    else:
        dataset = data_lib.synthetic_dataset(cfg.vocab_size)

    def make_loader(loader_dict, fast_forward_to):
        """The checkpointable batch stream: restored from checkpoint
        metadata when present, else fresh (fast-forwarded for legacy
        checkpoints that predate loader-state-of-record)."""
        if loader_dict:
            return data_lib.CheckpointableBatches.from_dict(
                loader_dict, dataset, args.batch, args.seq_len,
                process_index=jax.process_index(),
                process_count=jax.process_count(),
            )
        loader = data_lib.CheckpointableBatches(
            dataset, args.batch, args.seq_len,
            process_index=jax.process_index(),
            process_count=jax.process_count(),
            seed=args.data_seed,
        )
        if fast_forward_to:
            loader.skip(fast_forward_to)
        return loader

    # data positions the divergence guard decided to skip (rollback policy)
    skip_positions: set = set()

    def stream(loader):
        """Yield (host_batch, loader_state_snapshot): the snapshot rides
        along so checkpoints commit the loader position of the NEXT
        unconsumed batch even while prefetch reads ahead."""
        while True:
            while loader.step in skip_positions:
                log.warning("skipping poisoned data position %s", loader.step)
                loader.skip(1)
            batch = next(loader)
            yield batch, loader.to_dict()

    sup = sup_lib.Supervisor(
        grace_secs=args.grace_secs, watchdog_secs=args.watchdog_secs,
        spike_factor=args.loss_spike_factor,
        max_rollbacks=args.max_rollbacks, record_dir=args.checkpoint_dir,
    )
    loader = make_loader(resume_meta.get("loader"), start_step)
    loader_snap = loader.to_dict()

    t0 = time.perf_counter()
    tokens_per_step = args.batch * args.seq_len
    profiling = False
    timeline = open(args.timeline, "w") if args.timeline else None
    import json

    if timeline is not None:
        from hivedscheduler_tpu.obs import trace as obs_trace
    if args.profile_dir and args.steps - start_step < 2:
        log.warning(
            "--profile-dir needs at least 2 steps to trace (step 1 is "
            "compile); %s step(s) will run — no trace will be written",
            args.steps - start_step,
        )

    # the commit-marker sidecar: loader state of record + the elastic-resume
    # identity (source mesh, model geometry, data stream, shape ladder)
    elastic_meta = None
    if args.elastic:
        elastic_meta = {
            "min_chips": args.min_chips,
            "requested": {"tp": args.tp, "sp": args.sp, "fsdp": args.fsdp,
                          "pp": args.pp, "ep": args.ep},
        }
    train_meta = ckpt.train_metadata(
        axes, cfg, global_batch=args.batch, seq_len=args.seq_len,
        elastic=elastic_meta,
    )

    def save_checkpoint(step_no):
        if not args.checkpoint_dir:
            return
        if ckpt.latest_step(args.checkpoint_dir) == step_no:
            return  # already committed (e.g. preempted right after a save)
        ckpt.save(args.checkpoint_dir, step_no, params, opt_state,
                  extra={"loader": loader_snap, **train_meta})

    preempted = False
    diverged = None
    step = start_step
    loss = None
    with sup:
        batches = data_lib.prefetch(stream(loader), depth=args.prefetch,
                                    stop=sup.preemption.event)
        while step < args.steps:
            if sup.preempt_requested:
                preempted = True
                break
            sup.heartbeat(step)
            # chaos hooks (inert unless HIVED_FAULT_* env vars arm them)
            sup.faults.pace()
            sup.faults.maybe_hang(step + 1)
            if sup.faults.take_nan(step + 1):
                nan = float("nan")
                if lora_mode:
                    lora_params = jax.tree.map(lambda x: x * nan, lora_params)
                else:
                    params = jax.tree.map(lambda x: x * nan, params)
            if args.profile_dir:
                # trace steps 2..4 of this incarnation: past compile, short
                # enough that the Perfetto UI stays responsive
                rel = step - start_step
                if rel == 1 and not profiling:
                    jax.profiler.start_trace(args.profile_dir)
                    profiling = True
                    log.info("profiler trace started -> %s", args.profile_dir)
                elif rel == 4 and profiling:
                    jax.block_until_ready(loss)
                    jax.profiler.stop_trace()
                    profiling = False
                    log.info("profiler trace written to %s", args.profile_dir)
            step_t0 = time.perf_counter()
            obs_goodput.phase("data_wait")
            try:
                local_batch, snap = next(batches)
            except StopIteration:
                # the preemption event woke a consumer blocked on data
                preempted = True
                break
            # compile / rework / step_compute, decided against the step
            # high-water mark (rework = re-doing steps a kill threw away)
            obs_goodput.note_step(step + 1, is_compile=step == start_step)
            tokens = data_lib.device_put_global(
                local_batch, token_sharding, args.batch
            )
            if lora_mode:
                lora_params, opt_state, loss = step_fn(
                    base_params, lora_params, opt_state, tokens
                )
                params = tm.combine_lora_params(base_params, lora_params)
            else:
                params, opt_state, loss = step_fn(params, opt_state, tokens)
            # the supervisor syncs on the loss every step: the watchdog
            # heartbeat must reflect completed device work and the
            # divergence guard must see the value BEFORE the next
            # checkpoint can commit it (small dispatch-overlap cost, same
            # trade --timeline already makes)
            loss_f = float(loss)
            obs_goodput.note_step_done(step + 1)
            loader_snap = snap
            if timeline is not None:
                wall = time.perf_counter() - step_t0
                record = {
                    "step": step + 1,
                    "wall_s": round(wall, 6),
                    "tokens_per_sec": round(tokens_per_step / max(wall, 1e-9), 1),
                    "loss": loss_f,
                    "compile": step == start_step,
                }
                timeline.write(json.dumps(record) + "\n")
                timeline.flush()
                obs_trace.complete("train/step", step_t0, time.perf_counter(),
                                   cat="train", step=step + 1,
                                   compile=step == start_step)
            if args.on_nan == "skip":
                if not math.isfinite(loss_f):
                    # the jitted gate already dropped this update
                    log.warning("non-finite loss at step %s: update skipped",
                                step + 1)
            else:
                reason = sup.check_loss(step + 1, loss_f)
                if reason is not None:
                    can_roll = (
                        args.on_nan == "rollback" and args.checkpoint_dir
                        and ckpt.latest_step(args.checkpoint_dir) is not None
                    )
                    if can_roll and sup.note_rollback():
                        bad_pos = snap["step"] - 1
                        skip_positions.add(bad_pos)
                        batches.close()
                        step, params, opt_state, meta = restore_state(
                            params, opt_state
                        )
                        if lora_mode:
                            base_params, lora_params = tm.split_lora_params(
                                params
                            )
                        loader = make_loader(meta.get("loader"), step)
                        loader_snap = loader.to_dict()
                        batches = data_lib.prefetch(
                            stream(loader), depth=args.prefetch,
                            stop=sup.preemption.event,
                        )
                        log.warning(
                            "divergence (%s): rolled back to checkpoint "
                            "step %s; data position %s will be skipped",
                            reason, step, bad_pos,
                        )
                        continue
                    diverged = reason
                    break
            step += 1
            if step % args.log_every == 0:
                dt = time.perf_counter() - t0
                done = step - start_step
                log.info(
                    "step %s loss %.4f | %.0f tok/s",
                    step, loss_f, done * tokens_per_step / max(dt, 1e-9),
                )
            if args.checkpoint_dir and step % args.checkpoint_every == 0:
                save_checkpoint(step)
        if profiling:
            # fewer than 4 steps ran after the trace started
            jax.block_until_ready(loss)
            jax.profiler.stop_trace()
            log.info("profiler trace written to %s", args.profile_dir)
        if timeline is not None:
            timeline.close()
            log.info("step timeline written to %s", args.timeline)
        obs_goodput.phase("idle")  # loop done; final save spans itself
        if diverged is not None:
            log.error(
                "divergence: %s — halting with the last committed "
                "checkpoint intact (exit %s)", diverged,
                sup_lib.EXIT_DIVERGED,
            )
            return sup_lib.EXIT_DIVERGED
        save_checkpoint(step)
        if preempted:
            log.info(
                "preemption: committed checkpoint at step %s and exiting "
                "cleanly within the %.1fs grace period", step, args.grace_secs
            )
            return 0
    log.info("training complete: %s steps", args.steps)
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
