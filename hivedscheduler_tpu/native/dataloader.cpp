// Native token-window gather for the host-parallel data loader.
//
// The Python loader (parallel/data.py TokenFileDataset.sample) draws random
// window starts and gathers [rows, seq_len] int32 batches from a memory-
// mapped uint16/uint32 token stream. The gather is the bandwidth-heavy part
// (page faults + widening copy on the training thread); this implementation
// moves it native: per-row wraparound handled as at most two contiguous
// widening copies (elementwise modulo only in the degenerate seq_len >
// n_tokens case), rows split across threads, and — because it is entered
// via a ctypes call — the GIL is released for the duration, so the Python
// prefetch thread (parallel/data.prefetch) genuinely overlaps batch N+1
// assembly with step N.
//
// Semantics are bit-identical to the numpy path:
//   idx = (start + arange(seq_len)) % n ; out = int32(tokens[idx])
// (guard: tests/test_data.py::test_native_gather_matches_numpy).

#include <algorithm>
#include <cstdint>
#include <thread>
#include <vector>

namespace {

template <typename T>
void copy_row(const T* src, long long n, long long start, int seq_len,
              int32_t* dst) {
    long long s = start % n;
    if (s < 0) s += n;
    if (seq_len <= n) {
        long long first = std::min<long long>(seq_len, n - s);
        for (long long i = 0; i < first; ++i) dst[i] = (int32_t)src[s + i];
        for (long long i = first; i < seq_len; ++i)
            dst[i] = (int32_t)src[i - first];
    } else {  // degenerate: window longer than the corpus
        for (int i = 0; i < seq_len; ++i) dst[i] = (int32_t)src[(s + i) % n];
    }
}

template <typename T>
void gather_rows(const T* src, long long n, const long long* starts, int row0,
                 int row1, int seq_len, int32_t* out) {
    for (int r = row0; r < row1; ++r)
        copy_row(src, n, starts[r], seq_len, out + (long long)r * seq_len);
}

}  // namespace

extern "C" {

// tokens: uint16 (in_dtype_bytes==2) or uint32 (==4) stream of n_tokens;
// starts: n_rows window starts; out: [n_rows, seq_len] int32 row-major.
// Returns 0 on success, -1 on bad dtype.
int hived_gather_windows(const void* tokens, long long n_tokens,
                         int in_dtype_bytes, const long long* starts,
                         int n_rows, int seq_len, int32_t* out,
                         int n_threads) {
    if (in_dtype_bytes != 2 && in_dtype_bytes != 4) return -1;
    if (n_tokens <= 0 || n_rows <= 0 || seq_len <= 0) return n_rows ? -1 : 0;
    n_threads = std::max(1, std::min(n_threads, n_rows));
    auto run = [&](int row0, int row1) {
        if (in_dtype_bytes == 2)
            gather_rows((const uint16_t*)tokens, n_tokens, starts, row0, row1,
                        seq_len, out);
        else
            gather_rows((const uint32_t*)tokens, n_tokens, starts, row0, row1,
                        seq_len, out);
    };
    if (n_threads == 1) {
        run(0, n_rows);
        return 0;
    }
    std::vector<std::thread> workers;
    int per = (n_rows + n_threads - 1) / n_threads;
    for (int t = 0; t < n_threads; ++t) {
        int row0 = t * per, row1 = std::min(n_rows, row0 + per);
        if (row0 >= row1) break;
        workers.emplace_back(run, row0, row1);
    }
    for (auto& w : workers) w.join();
    return 0;
}

}  // extern "C"
