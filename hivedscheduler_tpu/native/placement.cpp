// Native placement search: best-affinity leaf-cell selection inside a node.
//
// C++ implementation of the backtracking LCA-minimizing search the scheduler
// runs per pod (Python reference: algorithm/topology_aware.py
// find_leaf_cells_in_node; upstream semantics: topology_aware_scheduler.go:
// 309-387). Exposed via a C ABI for ctypes; semantics are identical to the
// Python path and covered by differential tests (tests/test_native.py).
//
// Representation: each available leaf cell is a row of `ancestors`
// ([n_avail x n_levels], row-major), holding an integer id of the cell's
// ancestor at each level (level 1 = the leaf itself at column 0). The LCA of
// a candidate leaf and the running affinity (an ancestor of a previously
// picked leaf at level `aff_level`) is the lowest level >= aff_level at which
// their ancestor ids agree. Lower LCA level = tighter ICI sub-mesh.
//
// Build: g++ -O2 -shared -fPIC -o _placement.so placement.cpp

#include <cstdint>
#include <vector>

namespace {
constexpr int32_t kInfLevel = INT32_MAX;

inline int32_t lca_level(const int32_t* ancestors, int32_t n_levels,
                         int32_t leaf, int32_t ref, int32_t from_level) {
  const int32_t* a = ancestors + static_cast<int64_t>(leaf) * n_levels;
  const int32_t* b = ancestors + static_cast<int64_t>(ref) * n_levels;
  for (int32_t l = from_level; l <= n_levels; ++l) {
    if (a[l - 1] == b[l - 1]) return l;
  }
  return kInfLevel;
}
}  // namespace

extern "C" {

// Returns the best affinity level found (and writes the picked candidate
// indices, ascending, to out_indices), or -1 if no solution exists.
// Mirrors findLeafCellsInNode: candidates scanned in order (free cells before
// preemptible ones), prune when the running LCA exceeds the best seen, early
// stop at optimal_affinity.
int32_t hived_find_leaf_cells(const int32_t* ancestors, int32_t n_avail,
                              int32_t n_levels, int32_t leaf_cell_num,
                              int32_t optimal_affinity, int32_t* out_indices) {
  if (leaf_cell_num <= 0 || n_avail < leaf_cell_num) return -1;
  std::vector<int32_t> current_idx(leaf_cell_num, 0);
  // running affinity per depth: (reference leaf row, LCA level)
  std::vector<int32_t> aff_ref(leaf_cell_num, 0);
  std::vector<int32_t> aff_level(leaf_cell_num, 0);
  std::vector<int32_t> best_idx(leaf_cell_num, 0);
  int32_t best_affinity = kInfLevel;

  int32_t search = 0;
  int32_t avail = 0;
  while (true) {
    while (avail < n_avail) {
      current_idx[search] = avail;
      if (search == 0) {
        aff_ref[0] = avail;
        aff_level[0] = 1;  // a single leaf: affinity is the leaf itself
      } else {
        int32_t lvl = lca_level(ancestors, n_levels, avail,
                                aff_ref[search - 1], aff_level[search - 1]);
        // prune: running LCA already worse than the best seen
        if ((lvl == kInfLevel && best_affinity < kInfLevel) ||
            (lvl != kInfLevel && lvl > best_affinity)) {
          ++avail;
          continue;
        }
        aff_ref[search] = avail;
        aff_level[search] = lvl;
      }
      if (search == leaf_cell_num - 1) {
        int32_t affinity = aff_level[search];
        if (affinity < best_affinity) {
          best_affinity = affinity;
          for (int32_t i = 0; i < leaf_cell_num; ++i) best_idx[i] = current_idx[i];
          if (affinity == optimal_affinity) {
            for (int32_t i = 0; i < leaf_cell_num; ++i) out_indices[i] = best_idx[i];
            return best_affinity;  // early stop: all-buddy solution
          }
        }
      } else {
        ++search;
      }
      ++avail;
    }
    --search;
    if (search < 0) {
      if (best_affinity == kInfLevel) return -1;
      for (int32_t i = 0; i < leaf_cell_num; ++i) out_indices[i] = best_idx[i];
      return best_affinity;
    }
    avail = current_idx[search] + 1;
  }
}

}  // extern "C"
