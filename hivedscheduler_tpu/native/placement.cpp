// Native placement search: best-affinity leaf-cell selection inside a node.
//
// C++ implementation of the backtracking LCA-minimizing search the scheduler
// runs per pod (Python reference: algorithm/topology_aware.py
// find_leaf_cells_in_node; upstream semantics: topology_aware_scheduler.go:
// 309-387). Exposed via a C ABI for ctypes; semantics are identical to the
// Python path and covered by differential tests (tests/test_native.py).
//
// Representation: each available leaf cell is a row of `ancestors`
// ([n_avail x n_levels], row-major), holding an integer id of the cell's
// ancestor at each level (level 1 = the leaf itself at column 0). The LCA of
// a candidate leaf and the running affinity (an ancestor of a previously
// picked leaf at level `aff_level`) is the lowest level >= aff_level at which
// their ancestor ids agree. Lower LCA level = tighter ICI sub-mesh.
//
// Build: g++ -O2 -shared -fPIC -o _placement.so placement.cpp

#include <algorithm>
#include <cstdint>
#include <vector>

namespace {
constexpr int32_t kInfLevel = INT32_MAX;

inline int32_t lca_level(const int32_t* ancestors, int32_t n_levels,
                         int32_t leaf, int32_t ref, int32_t from_level) {
  const int32_t* a = ancestors + static_cast<int64_t>(leaf) * n_levels;
  const int32_t* b = ancestors + static_cast<int64_t>(ref) * n_levels;
  for (int32_t l = from_level; l <= n_levels; ++l) {
    if (a[l - 1] == b[l - 1]) return l;
  }
  return kInfLevel;
}
}  // namespace

extern "C" {

// Returns the best affinity level found (and writes the picked candidate
// indices, ascending, to out_indices), or -1 if no solution exists.
// Mirrors findLeafCellsInNode: candidates scanned in order (free cells before
// preemptible ones), prune when the running LCA exceeds the best seen, early
// stop at optimal_affinity.
int32_t hived_find_leaf_cells(const int32_t* ancestors, int32_t n_avail,
                              int32_t n_levels, int32_t leaf_cell_num,
                              int32_t optimal_affinity, int32_t* out_indices) {
  if (leaf_cell_num <= 0 || n_avail < leaf_cell_num) return -1;
  std::vector<int32_t> current_idx(leaf_cell_num, 0);
  // running affinity per depth: (reference leaf row, LCA level)
  std::vector<int32_t> aff_ref(leaf_cell_num, 0);
  std::vector<int32_t> aff_level(leaf_cell_num, 0);
  std::vector<int32_t> best_idx(leaf_cell_num, 0);
  int32_t best_affinity = kInfLevel;

  int32_t search = 0;
  int32_t avail = 0;
  while (true) {
    while (avail < n_avail) {
      current_idx[search] = avail;
      if (search == 0) {
        aff_ref[0] = avail;
        aff_level[0] = 1;  // a single leaf: affinity is the leaf itself
      } else {
        int32_t lvl = lca_level(ancestors, n_levels, avail,
                                aff_ref[search - 1], aff_level[search - 1]);
        // prune: running LCA already worse than the best seen
        if ((lvl == kInfLevel && best_affinity < kInfLevel) ||
            (lvl != kInfLevel && lvl > best_affinity)) {
          ++avail;
          continue;
        }
        aff_ref[search] = avail;
        aff_level[search] = lvl;
      }
      if (search == leaf_cell_num - 1) {
        int32_t affinity = aff_level[search];
        if (affinity < best_affinity) {
          best_affinity = affinity;
          for (int32_t i = 0; i < leaf_cell_num; ++i) best_idx[i] = current_idx[i];
          if (affinity == optimal_affinity) {
            for (int32_t i = 0; i < leaf_cell_num; ++i) out_indices[i] = best_idx[i];
            return best_affinity;  // early stop: all-buddy solution
          }
        }
      } else {
        ++search;
      }
      ++avail;
    }
    --search;
    if (search < 0) {
      if (best_affinity == kInfLevel) return -1;
      for (int32_t i = 0; i < leaf_cell_num; ++i) out_indices[i] = best_idx[i];
      return best_affinity;
    }
    avail = current_idx[search] + 1;
  }
}

// Cross-node packing for a whole gang in ONE call: stable sort of the
// persistent node order, tightest-enclosure pass, then the flat greedy —
// the single-chain common case of the Python reference
// (algorithm/topology_aware.py _find_nodes_for_pods; upstream semantics:
// topology_aware_scheduler.go:268-306). Inputs are persistent per-scheduler
// buffers in STATIC node order, kept in sync by the incremental cluster
// view's dirty tracking; `order` is the in/out sorted permutation whose tie
// history must match the reference's repeated in-place sort, hence
// std::stable_sort seeded with the previous order.
//
// anc_ids: [n_nodes x n_anc] static ancestor-id matrix, columns = ancestor
// levels ascending (tightest first), -1 where a node has no ancestor at
// that level; ids dense in [0, n_ids).
//
// Returns 0 on success (out_nodes = picked STATIC node indices, one per
// pod), 1 = insufficient capacity, 2 = would need a bad node, 3 = would
// need a non-suggested node (out_fail_node = the offending static index).
int32_t hived_find_nodes_for_pods(
    int32_t n_nodes, int32_t n_anc, int32_t n_ids, const int32_t* anc_ids,
    const int32_t* healthy, const int32_t* suggested,
    const int32_t* used_same, const int32_t* used_higher,
    const int32_t* free_at_p, int32_t pack, int32_t do_sort, int32_t* order,
    const int32_t* pod_nums, int32_t n_pods, int32_t* out_nodes,
    int32_t* out_fail_node) {
  if (n_pods <= 0 || n_nodes <= 0) return 1;
  if (do_sort) {
    const int64_t sign = pack ? -1 : 1;
    std::stable_sort(order, order + n_nodes, [&](int32_t a, int32_t b) {
      // lexicographic (!healthy, !suggested, sign*used_same, used_higher)
      const int32_t ha = !healthy[a], hb = !healthy[b];
      if (ha != hb) return ha < hb;
      const int32_t sa = !suggested[a], sb = !suggested[b];
      if (sa != sb) return sa < sb;
      const int64_t ua = sign * static_cast<int64_t>(used_same[a]);
      const int64_t ub = sign * static_cast<int64_t>(used_same[b]);
      if (ua != ub) return ua < ub;
      return used_higher[a] < used_higher[b];
    });
  }
  // greedy walk over nodes given by ranks into `order` (reference:
  // findNodesForPods inner loop / _greedy_assign): a pod lands on the
  // current node if it still fits; otherwise the accumulated count resets
  // and the walk advances
  auto greedy = [&](const int32_t* ranks, int32_t n_ranks,
                    bool detect_fail, int32_t* fail_code) -> bool {
    int32_t pod = 0;
    int32_t picked_leaf = 0;
    int32_t oi = 0;
    while (oi < n_ranks) {
      const int32_t j = order[ranks[oi]];
      if (free_at_p[j] - picked_leaf >= pod_nums[pod]) {
        if (!healthy[j]) {
          if (detect_fail) { *out_fail_node = j; *fail_code = 2; }
          return false;
        }
        if (!suggested[j]) {
          if (detect_fail) { *out_fail_node = j; *fail_code = 3; }
          return false;
        }
        out_nodes[pod] = j;
        picked_leaf += pod_nums[pod];
        ++pod;
        if (pod == n_pods) return true;
      } else {
        picked_leaf = 0;
        ++oi;
      }
    }
    if (detect_fail) *fail_code = 1;
    return false;
  };

  if (n_pods > 1 && n_anc > 0 && n_ids > 0) {
    int64_t total = 0;
    for (int32_t i = 0; i < n_pods; ++i) total += pod_nums[i];
    std::vector<int32_t> rank(n_nodes);
    for (int32_t r = 0; r < n_nodes; ++r) rank[order[r]] = r;
    // per enclosure (discovered in ascending first-member rank, which
    // matches the reference's (level, first-member) visit order when
    // columns ascend by level): member ranks + usable capacity; only
    // healthy+suggested nodes join an enclosure
    std::vector<int32_t> grp_of(n_ids);
    std::vector<int64_t> grp_cap;
    std::vector<std::vector<int32_t>> grp_ranks;
    for (int32_t col = 0; col < n_anc; ++col) {
      std::fill(grp_of.begin(), grp_of.end(), -1);
      grp_cap.clear();
      grp_ranks.clear();
      for (int32_t r = 0; r < n_nodes; ++r) {
        const int32_t j = order[r];
        if (!healthy[j] || !suggested[j]) continue;
        const int32_t a = anc_ids[static_cast<int64_t>(j) * n_anc + col];
        if (a < 0) continue;
        int32_t gi = grp_of[a];
        if (gi < 0) {
          gi = grp_of[a] = static_cast<int32_t>(grp_cap.size());
          grp_cap.push_back(0);
          grp_ranks.emplace_back();
        }
        grp_cap[gi] += free_at_p[j];
        grp_ranks[gi].push_back(r);
      }
      for (size_t gi = 0; gi < grp_cap.size(); ++gi) {
        if (grp_cap[gi] < total) continue;
        if (greedy(grp_ranks[gi].data(),
                   static_cast<int32_t>(grp_ranks[gi].size()),
                   /*detect_fail=*/false, nullptr)) {
          return 0;
        }
      }
    }
  }
  std::vector<int32_t> flat(n_nodes);
  for (int32_t r = 0; r < n_nodes; ++r) flat[r] = r;
  int32_t fail_code = 1;
  if (greedy(flat.data(), n_nodes, /*detect_fail=*/true, &fail_code)) return 0;
  return fail_code;
}

}  // extern "C"
