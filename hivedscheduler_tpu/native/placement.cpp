// Native placement search: best-affinity leaf-cell selection inside a node.
//
// C++ implementation of the backtracking LCA-minimizing search the scheduler
// runs per pod (Python reference: algorithm/topology_aware.py
// find_leaf_cells_in_node; upstream semantics: topology_aware_scheduler.go:
// 309-387). Exposed via a C ABI for ctypes; semantics are identical to the
// Python path and covered by differential tests (tests/test_native.py).
//
// Representation: each available leaf cell is a row of `ancestors`
// ([n_avail x n_levels], row-major), holding an integer id of the cell's
// ancestor at each level (level 1 = the leaf itself at column 0). The LCA of
// a candidate leaf and the running affinity (an ancestor of a previously
// picked leaf at level `aff_level`) is the lowest level >= aff_level at which
// their ancestor ids agree. Lower LCA level = tighter ICI sub-mesh.
//
// Build: g++ -O2 -shared -fPIC -o _placement.so placement.cpp

#include <algorithm>
#include <cstdint>
#include <vector>

namespace {
constexpr int32_t kInfLevel = INT32_MAX;

inline int32_t lca_level(const int32_t* ancestors, int32_t n_levels,
                         int32_t leaf, int32_t ref, int32_t from_level) {
  const int32_t* a = ancestors + static_cast<int64_t>(leaf) * n_levels;
  const int32_t* b = ancestors + static_cast<int64_t>(ref) * n_levels;
  for (int32_t l = from_level; l <= n_levels; ++l) {
    if (a[l - 1] == b[l - 1]) return l;
  }
  return kInfLevel;
}

// One cluster view's per-node buffers in STATIC node order plus the sorted
// permutation; shared by the full-gang packing entry and the prefix-fit
// walk below.
struct View {
  int32_t n_nodes, n_anc, n_ids;
  const int32_t* anc_ids;    // [n_nodes x n_anc], levels ascending, -1 = none
  const int32_t* healthy;
  const int32_t* suggested;
  const int32_t* free_at_p;
  const int32_t* order;      // sorted permutation: rank -> static index
};

// Greedy walk over nodes given by ranks into `order` (reference:
// findNodesForPods inner loop / _greedy_assign): a pod lands on the current
// node if it still fits; otherwise the accumulated count resets and the walk
// advances. `pod_at(i)` indirection lets callers feed pods in reverse
// (descending member lists evaluated as the ascending sort the reference
// uses).
struct PodSeq {
  const int32_t* nums;
  int32_t n;
  bool reversed;
  inline int32_t at(int32_t i) const {
    return nums[reversed ? n - 1 - i : i];
  }
};

bool greedy_walk(const View& v, const int32_t* ranks, int32_t n_ranks,
                 const PodSeq& pods, int32_t* out_nodes,
                 int32_t* out_fail_node, int32_t* fail_code) {
  int32_t pod = 0;
  int32_t picked_leaf = 0;
  int32_t oi = 0;
  while (oi < n_ranks) {
    const int32_t j = v.order[ranks[oi]];
    if (v.free_at_p[j] - picked_leaf >= pods.at(pod)) {
      if (!v.healthy[j]) {
        if (fail_code != nullptr) { *out_fail_node = j; *fail_code = 2; }
        return false;
      }
      if (!v.suggested[j]) {
        if (fail_code != nullptr) { *out_fail_node = j; *fail_code = 3; }
        return false;
      }
      if (out_nodes != nullptr) out_nodes[pod] = j;
      picked_leaf += pods.at(pod);
      ++pod;
      if (pod == pods.n) return true;
    } else {
      picked_leaf = 0;
      ++oi;
    }
  }
  if (fail_code != nullptr) *fail_code = 1;
  return false;
}

// Scratch buffers reused across enclosure passes (and, in the prefix walk,
// across takes) so the descending-take descent does not reallocate per step.
struct PackScratch {
  std::vector<int32_t> grp_of;
  std::vector<int64_t> grp_cap;
  std::vector<std::vector<int32_t>> grp_ranks;
  std::vector<int32_t> flat;
};

// The whole packing attempt for one pod multiset: the tightest-enclosure
// pass (per ancestor level ascending, groups in ascending first-member rank
// — the reference's (level, first-member) visit order), then the flat
// greedy, which owns the bad/non-suggested failure codes. Returns 0 on
// success (out_nodes = picked static indices per pod), else 1/2/3 exactly
// like the original single entry.
int32_t pack_attempt(const View& v, const PodSeq& pods, PackScratch& s,
                     int32_t* out_nodes, int32_t* out_fail_node) {
  if (pods.n > 1 && v.n_anc > 0 && v.n_ids > 0) {
    int64_t total = 0;
    for (int32_t i = 0; i < pods.n; ++i) total += pods.nums[i];
    s.grp_of.assign(v.n_ids, -1);
    for (int32_t col = 0; col < v.n_anc; ++col) {
      std::fill(s.grp_of.begin(), s.grp_of.end(), -1);
      s.grp_cap.clear();
      s.grp_ranks.clear();
      for (int32_t r = 0; r < v.n_nodes; ++r) {
        const int32_t j = v.order[r];
        if (!v.healthy[j] || !v.suggested[j]) continue;
        const int32_t a = v.anc_ids[static_cast<int64_t>(j) * v.n_anc + col];
        if (a < 0) continue;
        int32_t gi = s.grp_of[a];
        if (gi < 0) {
          gi = s.grp_of[a] = static_cast<int32_t>(s.grp_cap.size());
          s.grp_cap.push_back(0);
          s.grp_ranks.emplace_back();
        }
        s.grp_cap[gi] += v.free_at_p[j];
        s.grp_ranks[gi].push_back(r);
      }
      for (size_t gi = 0; gi < s.grp_cap.size(); ++gi) {
        if (s.grp_cap[gi] < total) continue;
        if (greedy_walk(v, s.grp_ranks[gi].data(),
                        static_cast<int32_t>(s.grp_ranks[gi].size()), pods,
                        out_nodes, nullptr, nullptr)) {
          return 0;
        }
      }
    }
  }
  s.flat.resize(v.n_nodes);
  for (int32_t r = 0; r < v.n_nodes; ++r) s.flat[r] = r;
  int32_t fail_code = 1;
  if (greedy_walk(v, s.flat.data(), v.n_nodes, pods, out_nodes,
                  out_fail_node, &fail_code)) {
    return 0;
  }
  return fail_code;
}

void sort_order(int32_t* order, int32_t n_nodes, const int32_t* healthy,
                const int32_t* suggested, const int32_t* used_same,
                const int32_t* used_higher, int32_t pack) {
  const int64_t sign = pack ? -1 : 1;
  std::stable_sort(order, order + n_nodes, [&](int32_t a, int32_t b) {
    // lexicographic (!healthy, !suggested, sign*used_same, used_higher)
    const int32_t ha = !healthy[a], hb = !healthy[b];
    if (ha != hb) return ha < hb;
    const int32_t sa = !suggested[a], sb = !suggested[b];
    if (sa != sb) return sa < sb;
    const int64_t ua = sign * static_cast<int64_t>(used_same[a]);
    const int64_t ub = sign * static_cast<int64_t>(used_same[b]);
    if (ua != ub) return ua < ub;
    return used_higher[a] < used_higher[b];
  });
}
}  // namespace

extern "C" {

// Returns the best affinity level found (and writes the picked candidate
// indices, ascending, to out_indices), or -1 if no solution exists.
// Mirrors findLeafCellsInNode: candidates scanned in order (free cells before
// preemptible ones), prune when the running LCA exceeds the best seen, early
// stop at optimal_affinity.
int32_t hived_find_leaf_cells(const int32_t* ancestors, int32_t n_avail,
                              int32_t n_levels, int32_t leaf_cell_num,
                              int32_t optimal_affinity, int32_t* out_indices) {
  if (leaf_cell_num <= 0 || n_avail < leaf_cell_num) return -1;
  std::vector<int32_t> current_idx(leaf_cell_num, 0);
  // running affinity per depth: (reference leaf row, LCA level)
  std::vector<int32_t> aff_ref(leaf_cell_num, 0);
  std::vector<int32_t> aff_level(leaf_cell_num, 0);
  std::vector<int32_t> best_idx(leaf_cell_num, 0);
  int32_t best_affinity = kInfLevel;

  int32_t search = 0;
  int32_t avail = 0;
  while (true) {
    while (avail < n_avail) {
      current_idx[search] = avail;
      if (search == 0) {
        aff_ref[0] = avail;
        aff_level[0] = 1;  // a single leaf: affinity is the leaf itself
      } else {
        int32_t lvl = lca_level(ancestors, n_levels, avail,
                                aff_ref[search - 1], aff_level[search - 1]);
        // prune: running LCA already worse than the best seen
        if ((lvl == kInfLevel && best_affinity < kInfLevel) ||
            (lvl != kInfLevel && lvl > best_affinity)) {
          ++avail;
          continue;
        }
        aff_ref[search] = avail;
        aff_level[search] = lvl;
      }
      if (search == leaf_cell_num - 1) {
        int32_t affinity = aff_level[search];
        if (affinity < best_affinity) {
          best_affinity = affinity;
          for (int32_t i = 0; i < leaf_cell_num; ++i) best_idx[i] = current_idx[i];
          if (affinity == optimal_affinity) {
            for (int32_t i = 0; i < leaf_cell_num; ++i) out_indices[i] = best_idx[i];
            return best_affinity;  // early stop: all-buddy solution
          }
        }
      } else {
        ++search;
      }
      ++avail;
    }
    --search;
    if (search < 0) {
      if (best_affinity == kInfLevel) return -1;
      for (int32_t i = 0; i < leaf_cell_num; ++i) out_indices[i] = best_idx[i];
      return best_affinity;
    }
    avail = current_idx[search] + 1;
  }
}

// Cross-node packing for a whole gang in ONE call: stable sort of the
// persistent node order, tightest-enclosure pass, then the flat greedy —
// one chain view of the Python reference
// (algorithm/topology_aware.py _find_nodes_for_pods; upstream semantics:
// topology_aware_scheduler.go:268-306). Inputs are persistent per-scheduler
// buffers in STATIC node order, kept in sync by the incremental cluster
// view's dirty tracking; `order` is the in/out sorted permutation whose tie
// history must match the reference's repeated in-place sort, hence
// std::stable_sort seeded with the previous order.
//
// anc_ids: [n_nodes x n_anc] static ancestor-id matrix, columns = ancestor
// levels ascending (tightest first), -1 where a node has no ancestor at
// that level; ids dense in [0, n_ids).
//
// Returns 0 on success (out_nodes = picked STATIC node indices, one per
// pod), 1 = insufficient capacity, 2 = would need a bad node, 3 = would
// need a non-suggested node (out_fail_node = the offending static index).
int32_t hived_find_nodes_for_pods(
    int32_t n_nodes, int32_t n_anc, int32_t n_ids, const int32_t* anc_ids,
    const int32_t* healthy, const int32_t* suggested,
    const int32_t* used_same, const int32_t* used_higher,
    const int32_t* free_at_p, int32_t pack, int32_t do_sort, int32_t* order,
    const int32_t* pod_nums, int32_t n_pods, int32_t* out_nodes,
    int32_t* out_fail_node) {
  if (n_pods <= 0 || n_nodes <= 0) return 1;
  if (do_sort) {
    sort_order(order, n_nodes, healthy, suggested, used_same, used_higher,
               pack);
  }
  View v{n_nodes, n_anc, n_ids, anc_ids, healthy, suggested, free_at_p,
         order};
  PodSeq pods{pod_nums, n_pods, /*reversed=*/false};
  PackScratch scratch;
  return pack_attempt(v, pods, scratch, out_nodes, out_fail_node);
}

// The multi-chain relax walk's descending-take descent in ONE call
// (Python reference: hived.py _schedule_relaxed_across_chains run_pass):
// `pod_nums` holds member sizes in DESCENDING order (the relax `flat`
// prefix); for take = n_pods..1 the ascending reading of the first `take`
// members (= the reference's per-probe sorted_pod_nums) is packed against
// this view — enclosure pass + flat greedy, identical to
// hived_find_nodes_for_pods — and the largest take that packs is returned
// (0 if none). The caller treats the result as an EXACT upper bound on the
// takes worth running through the full scheduling probe: every take above
// it provably fails this same packing, every take at or below it still
// runs the real probe, so decisions are unchanged. `order` is sorted in
// place when `do_sort` is set — callers pass a SCRATCH copy of the
// persistent order so the probe never perturbs the reference's tie
// history. out_nodes (size n_pods) receives the winning take's picks.
int32_t hived_find_nodes_prefix(
    int32_t n_nodes, int32_t n_anc, int32_t n_ids, const int32_t* anc_ids,
    const int32_t* healthy, const int32_t* suggested,
    const int32_t* used_same, const int32_t* used_higher,
    const int32_t* free_at_p, int32_t pack, int32_t do_sort, int32_t* order,
    const int32_t* pod_nums, int32_t n_pods, int32_t* out_nodes) {
  if (n_pods <= 0 || n_nodes <= 0) return 0;
  if (do_sort) {
    sort_order(order, n_nodes, healthy, suggested, used_same, used_higher,
               pack);
  }
  View v{n_nodes, n_anc, n_ids, anc_ids, healthy, suggested, free_at_p,
         order};
  // usable capacity upper bound: a take whose chip total exceeds the
  // healthy+suggested free sum cannot pack — skip it without a walk
  int64_t usable = 0;
  for (int32_t j = 0; j < n_nodes; ++j) {
    if (v.healthy[j] && v.suggested[j]) usable += v.free_at_p[j];
  }
  int64_t prefix_total = 0;
  for (int32_t i = 0; i < n_pods; ++i) prefix_total += pod_nums[i];
  PackScratch scratch;
  for (int32_t take = n_pods; take > 0; --take) {
    if (prefix_total <= usable) {
      PodSeq pods{pod_nums, take, /*reversed=*/true};
      int32_t fail = -1;
      if (pack_attempt(v, pods, scratch, out_nodes, &fail) == 0) {
        return take;
      }
    }
    prefix_total -= pod_nums[take - 1];
  }
  return 0;
}

}  // extern "C"
