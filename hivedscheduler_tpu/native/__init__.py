"""ctypes loader for the native placement search.

Compiles ``placement.cpp`` with g++ on first use (cached as ``_placement.so``
next to the source) and exposes :func:`find_leaf_cells`. Import failure or a
missing toolchain degrades silently to the pure-Python path — set
``HIVED_NATIVE=0`` to force Python, ``HIVED_NATIVE=1`` to require native.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
from typing import List, Optional

log = logging.getLogger(__name__)

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "placement.cpp")
_SO = os.path.join(_HERE, "_placement.so")

_lib = None
_tried = False


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    if _tried:
        return _lib
    _tried = True
    if os.environ.get("HIVED_NATIVE", "") == "0":
        return None
    try:
        if not os.path.exists(_SO) or os.path.getmtime(_SO) < os.path.getmtime(_SRC):
            subprocess.run(
                ["g++", "-O2", "-shared", "-fPIC", "-o", _SO, _SRC],
                check=True,
                capture_output=True,
            )
        lib = ctypes.CDLL(_SO)
        lib.hived_find_leaf_cells.restype = ctypes.c_int32
        lib.hived_find_leaf_cells.argtypes = [
            ctypes.POINTER(ctypes.c_int32),
            ctypes.c_int32,
            ctypes.c_int32,
            ctypes.c_int32,
            ctypes.c_int32,
            ctypes.POINTER(ctypes.c_int32),
        ]
        _lib = lib
    except Exception as e:  # toolchain missing / compile error
        if os.environ.get("HIVED_NATIVE") == "1":
            raise
        log.info("native placement unavailable, using Python path: %s", e)
        _lib = None
    return _lib


def available() -> bool:
    return _load() is not None


def find_leaf_cells(
    ancestors: "ctypes.Array",
    n_avail: int,
    n_levels: int,
    leaf_cell_num: int,
    optimal_affinity: int,
) -> Optional[List[int]]:
    """Run the native search; returns picked candidate indices (ascending) or
    None when no solution exists. ``ancestors`` is a flat int32 ctypes array
    of shape [n_avail, n_levels]."""
    lib = _load()
    assert lib is not None
    out = (ctypes.c_int32 * leaf_cell_num)()
    best = lib.hived_find_leaf_cells(
        ancestors, n_avail, n_levels, leaf_cell_num, optimal_affinity, out
    )
    if best < 0:
        return None
    return list(out)
