"""ctypes loaders for the native runtime components.

Each .cpp next to this file compiles with g++ on first use (cached as a .so
beside the source): ``placement.cpp`` (best-affinity placement search,
:func:`find_leaf_cells`) and ``dataloader.cpp`` (token-window gather for the
data loader, :func:`gather_windows`). Import failure or a missing toolchain
degrades silently to the pure-Python paths — set ``HIVED_NATIVE=0`` to force
Python, ``HIVED_NATIVE=1`` to require native.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
from typing import List, Optional

log = logging.getLogger(__name__)

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "placement.cpp")
_SO = os.path.join(_HERE, "_placement.so")

_lib = None
_tried = False

# Strict warnings are part of the sanitize build contract: the sources are
# kept -Wall -Wextra -Werror clean (guarded by tests/test_native_asan.py).
_STRICT_FLAGS = ["-Wall", "-Wextra", "-Werror"]
_SANITIZE_FLAGS = ["-O1", "-g", "-fno-omit-frame-pointer",
                   "-fsanitize=address,undefined"]


def sanitize_mode() -> bool:
    """Opt-in ASan/UBSan build mode (``HIVED_NATIVE_SANITIZE=1``): the .so
    compiles with ``-fsanitize=address,undefined`` plus strict warnings and
    loads from a separate ``*.asan.so`` cache. The loading process must
    preload the sanitizer runtimes (see :func:`sanitizer_preload`) — ctypes
    dlopens the library into an uninstrumented CPython, so ASan's runtime
    has to come first via LD_PRELOAD in a fresh process."""
    return os.environ.get("HIVED_NATIVE_SANITIZE", "") == "1"


def sanitizer_preload():
    """LD_PRELOAD value (space-separated libasan/libubsan paths) for a
    process that loads the sanitized .so, or None when the toolchain lacks
    the shared sanitizer runtimes (callers skip cleanly)."""
    paths = []
    for lib in ("libasan.so", "libubsan.so"):
        try:
            out = subprocess.run(
                ["g++", f"-print-file-name={lib}"],
                capture_output=True, text=True, check=True,
            )
        except (OSError, subprocess.CalledProcessError):
            return None
        p = out.stdout.strip()
        if not p or p == lib or not os.path.exists(p):
            return None
        paths.append(p)
    return " ".join(paths)


def _build_and_load(src: str, so: str) -> ctypes.CDLL:
    if sanitize_mode():
        so = so[: -len(".so")] + ".asan.so"
        flags = _SANITIZE_FLAGS + _STRICT_FLAGS
    else:
        flags = ["-O2"]
    if not os.path.exists(so) or os.path.getmtime(so) < os.path.getmtime(src):
        subprocess.run(
            ["g++", *flags, "-shared", "-fPIC", "-o", so, src],
            check=True,
            capture_output=True,
        )
    return ctypes.CDLL(so)


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    if _tried:
        return _lib
    _tried = True
    if os.environ.get("HIVED_NATIVE", "") == "0":
        return None
    try:
        lib = _build_and_load(_SRC, _SO)
        lib.hived_find_leaf_cells.restype = ctypes.c_int32
        lib.hived_find_leaf_cells.argtypes = [
            ctypes.POINTER(ctypes.c_int32),
            ctypes.c_int32,
            ctypes.c_int32,
            ctypes.c_int32,
            ctypes.c_int32,
            ctypes.POINTER(ctypes.c_int32),
        ]
        try:
            p_i32 = ctypes.POINTER(ctypes.c_int32)
            lib.hived_find_nodes_for_pods.restype = ctypes.c_int32
            lib.hived_find_nodes_for_pods.argtypes = [
                ctypes.c_int32, ctypes.c_int32, ctypes.c_int32,  # n, n_anc, n_ids
                p_i32,                                           # anc_ids
                p_i32, p_i32, p_i32, p_i32, p_i32,               # scores
                ctypes.c_int32, ctypes.c_int32,                  # pack, do_sort
                p_i32,                                           # order (in/out)
                p_i32, ctypes.c_int32,                           # pod_nums, n_pods
                p_i32, p_i32,                                    # out_nodes, out_fail
            ]
        except AttributeError:  # stale prebuilt .so: packing entry absent
            pass
        try:
            p_i32 = ctypes.POINTER(ctypes.c_int32)
            lib.hived_find_nodes_prefix.restype = ctypes.c_int32
            lib.hived_find_nodes_prefix.argtypes = [
                ctypes.c_int32, ctypes.c_int32, ctypes.c_int32,  # n, n_anc, n_ids
                p_i32,                                           # anc_ids
                p_i32, p_i32, p_i32, p_i32, p_i32,               # scores
                ctypes.c_int32, ctypes.c_int32,                  # pack, do_sort
                p_i32,                                           # order (scratch)
                p_i32, ctypes.c_int32,                           # pod_nums, n_pods
                p_i32,                                           # out_nodes
            ]
        except AttributeError:  # stale prebuilt .so: prefix entry absent
            pass
        _lib = lib
    except Exception as e:  # toolchain missing / compile error
        if os.environ.get("HIVED_NATIVE") == "1":
            raise
        log.info("native placement unavailable, using Python path: %s", e)
        _lib = None
    return _lib


_DL_SRC = os.path.join(_HERE, "dataloader.cpp")
_DL_SO = os.path.join(_HERE, "_dataloader.so")

_dl_lib = None
_dl_tried = False


def _load_dataloader() -> Optional[ctypes.CDLL]:
    global _dl_lib, _dl_tried
    if _dl_tried:
        return _dl_lib
    _dl_tried = True
    if os.environ.get("HIVED_NATIVE", "") == "0":
        return None
    try:
        lib = _build_and_load(_DL_SRC, _DL_SO)
        lib.hived_gather_windows.restype = ctypes.c_int
        lib.hived_gather_windows.argtypes = [
            ctypes.c_void_p,
            ctypes.c_longlong,
            ctypes.c_int,
            ctypes.POINTER(ctypes.c_longlong),
            ctypes.c_int,
            ctypes.c_int,
            ctypes.POINTER(ctypes.c_int32),
            ctypes.c_int,
        ]
        _dl_lib = lib
    except Exception as e:  # toolchain missing / compile error
        if os.environ.get("HIVED_NATIVE") == "1":
            raise
        log.info("native dataloader unavailable, using numpy path: %s", e)
        _dl_lib = None
    return _dl_lib


def dataloader_available() -> bool:
    return _load_dataloader() is not None


def gather_windows(tokens, starts, seq_len: int, n_threads: int = 4):
    """Native [rows, seq_len] int32 gather from a uint16/uint32 token array
    (numpy or memmap), bit-identical to ``tokens[(starts[:,None]+arange(seq))
    % n]``. The ctypes call releases the GIL, so a prefetch thread overlaps
    the copy with compute. Returns None when the native lib is unavailable
    or the dtype unsupported (callers fall back to numpy)."""
    import numpy as np

    lib = _load_dataloader()
    if (lib is None or tokens.dtype.kind != "u"
            or tokens.dtype.itemsize not in (2, 4)
            or not tokens.dtype.isnative
            or not tokens.flags["C_CONTIGUOUS"]):
        # big-endian (user-supplied --data-dtype '>u2') or strided views
        # would be read wrong through the raw pointer: numpy handles them
        return None
    starts64 = np.ascontiguousarray(starts, dtype=np.int64)
    out = np.empty((len(starts64), seq_len), dtype=np.int32)
    rc = lib.hived_gather_windows(
        tokens.ctypes.data_as(ctypes.c_void_p),
        ctypes.c_longlong(len(tokens)),
        ctypes.c_int(tokens.dtype.itemsize),
        starts64.ctypes.data_as(ctypes.POINTER(ctypes.c_longlong)),
        ctypes.c_int(len(starts64)),
        ctypes.c_int(seq_len),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        ctypes.c_int(n_threads),
    )
    return out if rc == 0 else None


def available() -> bool:
    return _load() is not None


def pack_available() -> bool:
    """True when the cross-node packing entry point is loadable (a stale
    prebuilt .so without the symbol degrades to the Python path)."""
    lib = _load()
    return lib is not None and hasattr(lib, "hived_find_nodes_for_pods")


def prefix_available() -> bool:
    """True when the multi-chain prefix-fit entry point is loadable."""
    lib = _load()
    return lib is not None and hasattr(lib, "hived_find_nodes_prefix")


def find_nodes_prefix(state: dict, pod_nums_desc: List[int], pack: bool,
                      order_scratch) -> int:
    """One-call descending-take feasibility walk for the multi-chain relax
    path: the largest prefix of ``pod_nums_desc`` (member sizes,
    DESCENDING — the relax ``flat`` segment) whose ascending reading packs
    on this view. ``order_scratch`` is a ctypes int32 array seeded with the
    persistent order; it is sorted in place by the call, so the caller's
    real order (and its stable-sort tie history) is never perturbed.
    Returns 0 when no prefix fits."""
    import ctypes

    lib = _load()
    assert lib is not None
    n_pods = len(pod_nums_desc)
    pods_arr = (ctypes.c_int32 * n_pods)(*pod_nums_desc)
    out = (ctypes.c_int32 * n_pods)()
    return lib.hived_find_nodes_prefix(
        state["n"], state["n_anc"], state["n_ids"], state["anc_buf"],
        state["healthy_buf"], state["suggested_buf"], state["same_buf"],
        state["higher_buf"], state["free_buf"],
        1 if pack else 0, 1, order_scratch,
        pods_arr, n_pods, out,
    )


def find_nodes_for_pods(state: dict, pod_nums: List[int], pack: bool,
                        do_sort: int):
    """One-call cross-node gang packing (sort + enclosure pass + greedy).

    ``state`` holds the scheduler's persistent per-node buffers in static
    order (see TopologyAwareScheduler._native_pack_state); ``state[
    "order_buf"]`` is updated in place when ``do_sort`` is set. Returns
    ``(rc, picked_static_indices_or_None, fail_static_index)`` with rc
    codes 0=ok, 1=insufficient, 2=bad node, 3=non-suggested — the caller
    formats the failure strings so they stay identical to the Python
    reference's."""
    import ctypes

    lib = _load()
    assert lib is not None
    n_pods = len(pod_nums)
    pods_arr = (ctypes.c_int32 * n_pods)(*pod_nums)
    out = (ctypes.c_int32 * n_pods)()
    fail = (ctypes.c_int32 * 1)(-1)
    rc = lib.hived_find_nodes_for_pods(
        state["n"], state["n_anc"], state["n_ids"], state["anc_buf"],
        state["healthy_buf"], state["suggested_buf"], state["same_buf"],
        state["higher_buf"], state["free_buf"],
        1 if pack else 0, do_sort, state["order_buf"],
        pods_arr, n_pods, out, fail,
    )
    if rc == 0:
        return 0, list(out), -1
    return rc, None, fail[0]


def find_leaf_cells(
    ancestors: "ctypes.Array",
    n_avail: int,
    n_levels: int,
    leaf_cell_num: int,
    optimal_affinity: int,
) -> Optional[List[int]]:
    """Run the native search; returns picked candidate indices (ascending) or
    None when no solution exists. ``ancestors`` is a flat int32 ctypes array
    of shape [n_avail, n_levels]."""
    lib = _load()
    assert lib is not None
    out = (ctypes.c_int32 * leaf_cell_num)()
    best = lib.hived_find_leaf_cells(
        ancestors, n_avail, n_levels, leaf_cell_num, optimal_affinity, out
    )
    if best < 0:
        return None
    return list(out)
