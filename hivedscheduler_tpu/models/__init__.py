from hivedscheduler_tpu.models.transformer import (  # noqa: F401
    TransformerConfig,
    forward,
    init_params,
)
