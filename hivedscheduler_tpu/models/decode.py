"""Autoregressive decoding for the flagship transformer: KV-cache prefill,
incremental step, and a jit-friendly ``generate`` loop.

The reference is a scheduler with no model runtime; this is part of the
workload runtime built around it. TPU-first choices:

- **Static shapes**: the cache is allocated at ``max_len`` up front and
  attention always scores the full cache with a position mask — no dynamic
  shapes, one compiled step for the whole decode. (The serving engine's
  paged cache, ``serving.advance_paged``, keeps the same static-shape
  contract — the block-table indirection changes the cache *addressing*,
  never the compiled program shapes.)
- **Compact GQA cache**: k/v are cached at ``cfg.kv_heads`` ([L, B, M,
  H_kv, D]) and consumed by grouped einsums, so MQA/GQA cuts cache HBM and
  bandwidth by H/H_kv — the main GQA serving win.
- **One program for prefill and decode**: ``advance`` takes [B, S] tokens at
  any position; prefill is S=prompt_len, decoding is S=1. The layer stack
  runs under ``lax.scan`` over the stacked layer params, updating the
  per-layer cache slices in the scanned carry.

MoE layers decode with NO-DROP capacity (every token reaches its routed
experts): training's capacity factor is a throughput knob whose drop
decisions depend on the chunk length of the forward call, so reproducing it
per decode step would diverge anyway — serving uses the exact mixture
instead. Decoded MoE logits therefore match a training forward exactly iff
nothing overflowed capacity there (guard:
test_decode.py::test_moe_decode_uses_no_drop_capacity).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from hivedscheduler_tpu.models.transformer import (
    TransformerConfig,
    _moe_mlp,
    _rms_norm,
    _rope,
    is_quantized_leaf,
    load_weight,
)
from hivedscheduler_tpu.ops.attention import NEG_INF


class KVCache(NamedTuple):
    """Per-layer key/value cache and the number of tokens already absorbed.

    k/v: [n_layers, B, max_len, kv_heads, head_dim] in the model dtype;
    length: scalar int32 (same for every sequence of the batch — decode
    assumes an unpadded, position-aligned batch)."""

    k: jax.Array
    v: jax.Array
    length: jax.Array


def init_kv_cache(cfg: TransformerConfig, batch: int, max_len: int) -> KVCache:
    shape = (cfg.n_layers, batch, max_len, cfg.kv_heads, cfg.head_dim)
    return KVCache(
        k=jnp.zeros(shape, cfg.dtype),
        v=jnp.zeros(shape, cfg.dtype),
        length=jnp.zeros((), jnp.int32),
    )


def embed_tokens(params: Dict[str, Any], tokens: jax.Array, dtype) -> jax.Array:
    """Token embedding lookup, int8-quantization-aware (shared by the
    uniform decode path and the ragged serving path so the quant handling
    cannot drift between them)."""
    emb = params["embed"]
    if is_quantized_leaf(emb):
        # int8 embedding: gather the rows, then scale per row — the gather
        # itself moves int8 bytes
        return emb["qi8"][tokens].astype(dtype) * emb["scale"][tokens].astype(dtype)
    return emb.astype(dtype)[tokens]


def qkv_proj(lp: Dict[str, Any], h: jax.Array, positions, theta: float, dtype):
    """q/k/v projections + RoPE for one layer (shared decode/serving)."""
    q = jnp.einsum("bsd,dhk->bshk", h, load_weight(lp["wq"], dtype))
    k = jnp.einsum("bsd,dhk->bshk", h, load_weight(lp["wk"], dtype))
    v = jnp.einsum("bsd,dhk->bshk", h, load_weight(lp["wv"], dtype))
    return _rope(q, positions, theta), _rope(k, positions, theta), v


def dense_mlp(lp: Dict[str, Any], h: jax.Array, dtype) -> jax.Array:
    """SwiGLU MLP for one layer (shared decode/serving)."""
    gate = jnp.einsum("bsd,df->bsf", h, load_weight(lp["w_gate"], dtype))
    up = jnp.einsum("bsd,df->bsf", h, load_weight(lp["w_up"], dtype))
    return jnp.einsum(
        "bsf,fd->bsd", jax.nn.silu(gate) * up, load_weight(lp["w_down"], dtype)
    )


def final_logits(params: Dict[str, Any], x: jax.Array, dtype) -> jax.Array:
    """Final RMSNorm + lm_head in f32 (shared decode/serving)."""
    x = _rms_norm(x, params["final_norm"])
    return jnp.einsum(
        "bsd,dv->bsv", x, load_weight(params["lm_head"], dtype)
    ).astype(jnp.float32)


def _cached_attention(q, ck, cv, pos0, scale):
    """q: [B,S,H,D] at absolute positions pos0..pos0+S-1; ck/cv:
    [B,M,H_kv,D] full cache (entries past the live length are masked by the
    causal position test, since they can only sit at positions > pos0+s).
    Returns [B,S,H,D]."""
    b, s_len, h, d = q.shape
    m_len, h_kv = ck.shape[1], ck.shape[2]
    gsz = h // h_kv  # 1 for MHA; the size-1 group dim is free in XLA
    qg = q.reshape(b, s_len, h_kv, gsz, d)
    s = jnp.einsum(
        "bshgd,bmhd->bhgsm", qg, ck, preferred_element_type=jnp.float32
    ) * scale
    key_pos = lax.iota(jnp.int32, m_len)
    q_pos = pos0 + lax.iota(jnp.int32, s_len)
    mask = key_pos[None, :] <= q_pos[:, None]  # [S, M]
    s = jnp.where(mask[None, None, None], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum("bhgsm,bmhd->bshgd", p, cv.astype(jnp.float32))
    return o.reshape(b, s_len, h, d).astype(q.dtype)


def inference_moe_cfg(cfg: TransformerConfig) -> TransformerConfig:
    """No-drop inference capacity: ceil(S*k*E/E) = S*k slots per expert
    covers the worst-case routing skew (see module docstring), so every
    inference path routes exactly — a dropped token would silently change
    the stream. ONE home for the rule: decode.advance and
    serving.advance_ragged must stay routing-identical."""
    if cfg.n_experts <= 0:
        return cfg
    return dataclasses.replace(
        cfg, expert_capacity_factor=float(max(cfg.n_experts, 1))
    )


def advance(
    params: Dict[str, Any],
    cache: KVCache,
    tokens: jax.Array,
    cfg: TransformerConfig,
) -> Tuple[jax.Array, KVCache]:
    """Absorb ``tokens`` [B, S] starting at position ``cache.length`` and
    return (logits [B, S, vocab] f32, updated cache). S=prompt length for
    prefill, S=1 while decoding — same compiled program shape per S."""
    dtype = cfg.dtype
    b, s_len = tokens.shape
    pos0 = cache.length
    x = embed_tokens(params, tokens, dtype)  # [B, S, D]
    positions = (pos0 + lax.iota(jnp.int32, s_len))[None, :]
    scale = 1.0 / math.sqrt(cfg.head_dim)
    cfg = inference_moe_cfg(cfg)

    def layer(x, scanned):
        lp, ck, cv = scanned
        h = _rms_norm(x, lp["attn_norm"])
        q, k_new, v_new = qkv_proj(lp, h, positions, cfg.rope_theta, dtype)
        ck = lax.dynamic_update_slice_in_dim(ck, k_new.astype(ck.dtype), pos0, 1)
        cv = lax.dynamic_update_slice_in_dim(cv, v_new.astype(cv.dtype), pos0, 1)
        attn = _cached_attention(q, ck, cv, pos0, scale)
        x = x + jnp.einsum("bshk,hkd->bsd", attn, load_weight(lp["wo"], dtype))
        h = _rms_norm(x, lp["mlp_norm"])
        if cfg.n_experts > 0:
            moe_out, _ = _moe_mlp(h, lp, cfg, dtype)
            x = x + moe_out
        else:
            x = x + dense_mlp(lp, h, dtype)
        return x, (ck, cv)

    (x, (new_k, new_v)) = lax.scan(
        lambda carry, scanned: layer(carry, scanned),
        x,
        (params["layers"], cache.k, cache.v),
    )
    logits = final_logits(params, x, dtype)
    new_cache = KVCache(k=new_k, v=new_v, length=pos0 + s_len)
    return logits, new_cache


def filter_logits(logits: jax.Array, top_k: int = 0, top_p: float = 1.0) -> jax.Array:
    """Nucleus/top-k filtering on ``logits`` [..., V]: everything outside the
    top-k entries (if ``top_k`` > 0) and outside the smallest prefix of the
    sorted distribution with cumulative probability >= ``top_p`` (if
    ``top_p`` < 1) is masked to -inf. Static-shape, jit-friendly (sort +
    mask, no dynamic vocab slicing); filters compose k-then-p like the
    standard HF sampling processors."""
    if top_k > 0:
        kth = lax.top_k(logits, top_k)[0][..., -1:]
        logits = jnp.where(logits < kth, NEG_INF, logits)
    if top_p < 1.0:
        sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # keep every token up to AND including the one that crosses top_p;
        # the most likely token is always kept (top_p <= 0 would otherwise
        # mask the whole vocabulary)
        keep_sorted = (cum - probs) < top_p
        keep_sorted = keep_sorted.at[..., 0].set(True)
        cutoff = jnp.min(
            jnp.where(keep_sorted, sorted_logits, jnp.inf), axis=-1, keepdims=True
        )
        logits = jnp.where(logits < cutoff, NEG_INF, logits)
    return logits


def generate(
    params: Dict[str, Any],
    prompt: jax.Array,
    cfg: TransformerConfig,
    max_new_tokens: int,
    *,
    temperature: float = 0.0,
    top_k: int = 0,
    top_p: float = 1.0,
    key: Optional[jax.Array] = None,
    max_len: Optional[int] = None,
    decode_steps: int = 1,
) -> jax.Array:
    """Greedy (temperature 0) or sampled continuation of ``prompt`` [B, T],
    with optional top-k / nucleus (top-p) filtering of the sampled
    distribution. Returns [B, max_new_tokens]. The whole decode loop is one
    ``lax.scan`` over a fixed-shape cached step, so it stays inside a single
    jit.

    ``decode_steps``: unroll the scan body by K iterations. The loop is
    already device-resident (no per-token Python dispatch), but each XLA
    while-loop trip still pays its condition/carry bookkeeping and blocks
    cross-iteration scheduling; unrolling lets XLA software-pipeline K
    consecutive token steps (weight prefetch under the previous step's
    tail) at the cost of a K-times-larger loop body to compile. Pure
    schedule change — the emitted tokens are identical for any K (guard:
    test_serving_multistep.py::test_generate_decode_steps_unroll_exact)."""
    b, t = prompt.shape
    total = t + max_new_tokens
    if max_len is None:
        max_len = total
    assert max_len >= total, (max_len, total)
    assert temperature == 0.0 or key is not None, (
        "sampling (temperature > 0) needs a PRNG key"
    )
    cache = init_kv_cache(cfg, b, max_len)
    logits, cache = advance(params, cache, prompt, cfg)
    last = logits[:, -1]

    def pick(logits_b, k):
        if temperature == 0.0:
            return jnp.argmax(logits_b, axis=-1).astype(prompt.dtype)
        return jax.random.categorical(
            k, filter_logits(logits_b / temperature, top_k, top_p), axis=-1
        ).astype(prompt.dtype)

    keys = (
        jax.random.split(key, max_new_tokens)
        if key is not None
        else jnp.zeros((max_new_tokens, 2), jnp.uint32)
    )

    def step(carry, k):
        last_logits, cache = carry
        tok = pick(last_logits, k)
        logits, cache = advance(params, cache, tok[:, None], cfg)
        return (logits[:, -1], cache), tok

    unroll = max(1, min(decode_steps, max_new_tokens))
    (_, _), toks = lax.scan(step, (last, cache), keys, unroll=unroll)
    return jnp.swapaxes(toks, 0, 1)  # [B, max_new]


def serving_shardings(
    cfg: TransformerConfig, mesh, *, require: bool = True, quantized: bool = False
):
    """Validate ``cfg`` against the mesh's tp axis and build the param
    NamedSharding tree (``transformer.sharding_specs`` laid over ``mesh``;
    ``quantized`` uses ``quant.sharding_specs`` for int8 trees). The single
    source of the serving sharding contract: heads, vocab and ff must divide
    tp. ``require=False`` returns None instead of raising when a dim doesn't
    divide (callers then replicate — the speculative draft's fallback)."""
    from jax.sharding import NamedSharding
    from jax.sharding import PartitionSpec as P

    from hivedscheduler_tpu.models import transformer as tm

    tp = dict(zip(mesh.axis_names, mesh.devices.shape)).get("tp", 1)
    if cfg.n_heads % tp or cfg.kv_heads % tp:
        if not require:
            return None
        raise ValueError(
            f"head counts must divide the tp axis: n_heads={cfg.n_heads}, "
            f"kv_heads={cfg.kv_heads}, tp={tp}"
        )
    if cfg.vocab_size % tp or cfg.d_ff % tp:
        # lm_head/MLP shard their wide axis over tp; fail with a clear
        # message instead of device_put's divisibility error
        if not require:
            return None
        raise ValueError(
            f"vocab_size ({cfg.vocab_size}) and d_ff ({cfg.d_ff}) must "
            f"divide the tp axis ({tp})"
        )
    if quantized:
        from hivedscheduler_tpu.models import quant

        specs = quant.sharding_specs(cfg)
    else:
        specs = tm.sharding_specs(cfg)
    return jax.tree.map(
        lambda spec: NamedSharding(mesh, spec), specs,
        is_leaf=lambda x: isinstance(x, P),
    )


def make_sharded_generate(
    cfg: TransformerConfig,
    mesh,
    max_new_tokens: int,
    *,
    temperature: float = 0.0,
    top_k: int = 0,
    top_p: float = 1.0,
    quantized: bool = False,
    decode_steps: int = 1,
):
    """Sharded serving: returns (jitted_generate, param_shardings,
    prompt_sharding). Params laid out by ``transformer.sharding_specs`` —
    or ``quant.sharding_specs`` when ``quantized=True``, for int8 trees from
    ``quant.quantize_params`` — (tp shards heads/ff — the decode einsums
    then run tensor-parallel under GSPMD, with the kv cache sharded over
    the compact head axis), prompts over dp.
    ``jitted_generate(params, prompt, key)`` -> [B, max_new]
    (pass ``key=None`` for greedy)."""
    import functools

    from jax.sharding import NamedSharding
    from jax.sharding import PartitionSpec as P

    param_shardings = serving_shardings(cfg, mesh, quantized=quantized)
    prompt_sharding = NamedSharding(mesh, P(("dp", "fsdp")))

    run = functools.partial(
        generate, cfg=cfg, max_new_tokens=max_new_tokens,
        temperature=temperature, top_k=top_k, top_p=top_p,
        decode_steps=decode_steps,
    )
    from hivedscheduler_tpu.common import compileguard

    jitted = compileguard.jit(
        lambda params, prompt, key=None: run(params, prompt, key=key),
        guard_label="decode.generate",
    )
    return jitted, param_shardings, prompt_sharding
