"""Flagship workload: a decoder-only transformer LM, TPU-first.

Pure-JAX pytree params (no framework dependency), bf16 matmuls on the MXU,
RoPE, RMSNorm, SwiGLU. Layers are stacked and scanned with ``lax.scan`` so
compile time is O(1) in depth and XLA fuses per-layer elementwise work into
the matmuls. Attention implementation is selectable: plain XLA einsum, the
Pallas flash kernel (``ops/attention.py``), or ring/Ulysses sequence
parallelism over a mesh axis (``parallel/ring_attention.py``).

Sharding is annotation-driven (``models.sharding_specs``): tp shards heads
and the MLP hidden dim, fsdp shards the other param axis, dp/sp shard batch
and sequence of activations — XLA inserts the collectives.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 32000
    d_model: int = 512
    n_heads: int = 8
    n_layers: int = 4
    d_ff: int = 1408
    max_seq_len: int = 2048
    rope_theta: float = 10000.0
    dtype: Any = jnp.bfloat16
    # "xla" | "flash" | "ring" | "ulysses"
    attn_impl: str = "xla"

    @property
    def head_dim(self) -> int:
        assert self.d_model % self.n_heads == 0
        return self.d_model // self.n_heads


def init_params(cfg: TransformerConfig, key: jax.Array) -> Dict[str, Any]:
    """Stacked-layer params: arrays carry a leading [n_layers] axis so the
    forward pass can lax.scan over them."""
    k_emb, k_attn, k_mlp, k_out = jax.random.split(key, 4)
    d, h, hd, f, L = cfg.d_model, cfg.n_heads, cfg.head_dim, cfg.d_ff, cfg.n_layers

    def norm_init(k, shape, fan_in):
        return (jax.random.normal(k, shape, jnp.float32) / math.sqrt(fan_in)).astype(
            jnp.float32
        )

    ks = jax.random.split(k_attn, 4)
    km = jax.random.split(k_mlp, 3)
    return {
        "embed": norm_init(k_emb, (cfg.vocab_size, d), d),
        "layers": {
            "attn_norm": jnp.ones((L, d), jnp.float32),
            "wq": norm_init(ks[0], (L, d, h, hd), d),
            "wk": norm_init(ks[1], (L, d, h, hd), d),
            "wv": norm_init(ks[2], (L, d, h, hd), d),
            "wo": norm_init(ks[3], (L, h, hd, d), d),
            "mlp_norm": jnp.ones((L, d), jnp.float32),
            "w_gate": norm_init(km[0], (L, d, f), d),
            "w_up": norm_init(km[1], (L, d, f), d),
            "w_down": norm_init(km[2], (L, f, d), f),
        },
        "final_norm": jnp.ones((d,), jnp.float32),
        "lm_head": norm_init(k_out, (d, cfg.vocab_size), d),
    }


def sharding_specs(cfg: TransformerConfig) -> Dict[str, Any]:
    """PartitionSpecs per param: tp shards heads / ff; fsdp shards the
    complementary axis. Mirror of init_params' tree."""
    return {
        "embed": P(None, "fsdp"),
        "layers": {
            "attn_norm": P(None, None),
            "wq": P(None, "fsdp", "tp", None),
            "wk": P(None, "fsdp", "tp", None),
            "wv": P(None, "fsdp", "tp", None),
            "wo": P(None, "tp", None, "fsdp"),
            "mlp_norm": P(None, None),
            "w_gate": P(None, "fsdp", "tp"),
            "w_up": P(None, "fsdp", "tp"),
            "w_down": P(None, "tp", "fsdp"),
        },
        "final_norm": P(None),
        "lm_head": P("fsdp", "tp"),
    }


def activation_spec() -> P:
    """[batch, seq, ...]: batch over dp(+fsdp), sequence over sp."""
    return P(("dp", "fsdp"), "sp")


def _rms_norm(x: jax.Array, w: jax.Array, eps: float = 1e-6) -> jax.Array:
    xf = x.astype(jnp.float32)
    norm = xf * lax.rsqrt(jnp.mean(xf * xf, axis=-1, keepdims=True) + eps)
    return (norm * w).astype(x.dtype)


def _rope(x: jax.Array, positions: jax.Array, theta: float) -> jax.Array:
    """x: [B, T, H, D]; rotate pairs (even, odd) by position-dependent angles."""
    d = x.shape[-1]
    freqs = jnp.exp(
        -math.log(theta) * (jnp.arange(0, d, 2, dtype=jnp.float32) / d)
    )  # [D/2]
    angles = positions[..., None].astype(jnp.float32) * freqs  # [B?, T, D/2]
    cos = jnp.cos(angles)[..., None, :]  # broadcast over heads: [.., T, 1, D/2]
    sin = jnp.sin(angles)[..., None, :]
    x1 = x[..., 0::2].astype(jnp.float32)
    x2 = x[..., 1::2].astype(jnp.float32)
    out = jnp.stack([x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1)
    return out.reshape(x.shape).astype(x.dtype)


def forward(
    params: Dict[str, Any],
    tokens: jax.Array,
    cfg: TransformerConfig,
    mesh=None,
) -> jax.Array:
    """tokens [B, T] int32 -> logits [B, T, vocab] (f32).

    ``mesh`` is required for the ring/ulysses attention implementations (the
    sequence axis lives on the mesh); the sharded T seen here is global.
    """
    dtype = cfg.dtype
    b, t = tokens.shape
    x = params["embed"].astype(dtype)[tokens]  # [B, T, D]
    positions = jnp.broadcast_to(jnp.arange(t, dtype=jnp.int32), (b, t))

    if cfg.attn_impl == "flash":
        from hivedscheduler_tpu.ops.attention import flash_attention as attn_fn
    elif cfg.attn_impl in ("ring", "ulysses"):
        from hivedscheduler_tpu.parallel import ring_attention as ra

        assert mesh is not None, "ring/ulysses attention requires a mesh"
        attn_fn = (
            ra.ring_attention if cfg.attn_impl == "ring" else ra.ulysses_attention
        )
    else:
        from hivedscheduler_tpu.ops.attention import xla_attention as attn_fn

    def layer(x, lp):
        h = _rms_norm(x, lp["attn_norm"])
        q = jnp.einsum("btd,dhk->bthk", h, lp["wq"].astype(dtype))
        k = jnp.einsum("btd,dhk->bthk", h, lp["wk"].astype(dtype))
        v = jnp.einsum("btd,dhk->bthk", h, lp["wv"].astype(dtype))
        q = _rope(q, positions, cfg.rope_theta)
        k = _rope(k, positions, cfg.rope_theta)
        if cfg.attn_impl in ("ring", "ulysses"):
            attn = attn_fn(q, k, v, mesh, causal=True)
        else:
            attn = attn_fn(q, k, v, causal=True)
        x = x + jnp.einsum("bthk,hkd->btd", attn, lp["wo"].astype(dtype))
        h = _rms_norm(x, lp["mlp_norm"])
        gate = jnp.einsum("btd,df->btf", h, lp["w_gate"].astype(dtype))
        up = jnp.einsum("btd,df->btf", h, lp["w_up"].astype(dtype))
        x = x + jnp.einsum(
            "btf,fd->btd", jax.nn.silu(gate) * up, lp["w_down"].astype(dtype)
        )
        return x, None

    # rematerialize per-layer activations in the backward pass: HBM for the
    # whole stack is O(1) layers instead of O(n_layers), the standard trade
    # for long-context training
    x, _ = lax.scan(jax.checkpoint(layer), x, params["layers"])
    x = _rms_norm(x, params["final_norm"])
    logits = jnp.einsum("btd,dv->btv", x, params["lm_head"].astype(dtype))
    return logits.astype(jnp.float32)
