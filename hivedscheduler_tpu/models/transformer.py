"""Flagship workload: a decoder-only transformer LM, TPU-first.

Pure-JAX pytree params (no framework dependency), bf16 matmuls on the MXU,
RoPE, RMSNorm, SwiGLU. Layers are stacked and scanned with ``lax.scan``
(compile time O(1) in depth) and rematerialized with ``jax.checkpoint``.
Attention implementation is selectable: plain XLA einsum, the Pallas flash
kernel (``ops/attention.py``), or ring/Ulysses sequence parallelism over the
``sp`` mesh axis (``parallel/ring_attention.py``).

Parallelism:
- tp shards heads and MLP hidden, fsdp the complementary param axis, dp/sp
  shard activations (annotation-driven; XLA inserts the collectives);
- ``n_experts > 0`` turns every MLP into a MoE layer (top-1 switch routing
  by default, ``moe_top_k=2`` for renormalized top-2) with the expert
  dimension sharded over ``ep`` (capacity-based dense dispatch, the standard
  GSPMD expert-parallel formulation);
- ``pipeline_microbatches > 0`` runs the layer stack GPipe-pipelined over the
  ``pp`` mesh axis (``parallel/pipeline.py``), layer params sharded by stage.
"""

from __future__ import annotations

import dataclasses
import math
import os
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 32000
    d_model: int = 512
    n_heads: int = 8
    n_layers: int = 4
    d_ff: int = 1408
    max_seq_len: int = 2048
    rope_theta: float = 10000.0
    dtype: Any = jnp.bfloat16
    # "xla" | "flash" | "ring" | "ring_flash" | "ring_zigzag" |
    # "ring_zigzag_flash" | "ulysses"
    attn_impl: str = "xla"
    # switch-MoE: 0 = dense MLP; >0 = experts per MoE layer (ep-sharded)
    n_experts: int = 0
    expert_capacity_factor: float = 1.25
    # experts per token: 1 = switch routing (raw top gate), 2 = top-2 with
    # gates renormalized over the chosen experts
    moe_top_k: int = 1
    # weight of the Switch load-balancing auxiliary loss (router collapse
    # prevention); added to the LM loss by parallel/train.py
    moe_aux_weight: float = 0.01
    # ST-MoE router z-loss weight (penalizes large router logits for
    # numerical stability); 0 disables
    moe_zloss_weight: float = 0.0
    # GPipe microbatches over the pp axis; 0 = no pipelining
    pipeline_microbatches: int = 0

    # rematerialization policy for the layer scan's backward pass:
    # - "full": recompute the whole layer (HBM O(1) layers — the
    #   long-context default, but the recompute is a full extra forward,
    #   which caps MFU at 3/4 of hardware utilization);
    # - "dots": jax.checkpoint with dots_with_no_batch_dims_saveable —
    #   matmul outputs are saved, only elementwise work is recomputed
    #   (near-zero FLOP overhead, activations ~= no-remat);
    # - "none": save everything (fastest when activations fit in HBM).
    remat: str = "full"

    # Pallas flash-attention tile sizes (attn_impl="flash" and
    # "ring_flash", where they tile each per-shard ring block); the
    # sequence length (per-shard for the ring) must divide both. 128/128
    # matches the MXU systolic array; larger k blocks cut grid-loop
    # overhead on long sequences.
    attn_block_q: int = 128
    attn_block_k: int = 128

    # overlapped tensor parallelism (the collective-matmul path): the
    # residual stream is sequence-sharded over (sp, tp) and the
    # all-gather/reduce-scatter around the QKV/out/MLP projections run as
    # lax.ppermute-pipelined chunks inside shard_map, so each ICI hop
    # transfers while the previous chunk multiplies on the MXU.
    # None = auto (on whenever applicable: tp > 1, dense, no LoRA, no
    # pipeline, divisible shapes); False = always the GSPMD reference
    # path; True = require it (raises when inapplicable). The env var
    # HIVED_OVERLAP=0 forces the reference path regardless — the
    # differential-parity contract (tests/test_overlap.py).
    overlap: Optional[bool] = None

    # grouped-query attention: number of shared k/v heads (0 = n_heads,
    # classic MHA; 1 = MQA). q heads are grouped contiguously: q head i
    # attends with k/v head i // (n_heads // n_kv_heads)
    n_kv_heads: int = 0

    # LoRA adapters on the attention projections (q/k/v/o): 0 = off; > 0
    # adds rank-r factors (lora_*_a Gaussian, lora_*_b zero — identity at
    # init) scaled by lora_alpha/lora_rank. Fine-tuning freezes the base
    # weights and trains only the adapters (parallel/train.py:
    # make_sharded_lora_train_step); merge_lora folds them back for serving
    lora_rank: int = 0
    lora_alpha: float = 16.0
    # extend the adapters to the dense-MLP projections (gate/up/down) too;
    # requires lora_rank > 0 and a dense model (MoE experts are not adapted)
    lora_mlp: bool = False

    @property
    def head_dim(self) -> int:
        assert self.d_model % self.n_heads == 0
        return self.d_model // self.n_heads

    @property
    def kv_heads(self) -> int:
        n_kv = self.n_kv_heads or self.n_heads
        assert self.n_heads % n_kv == 0, (
            f"n_heads {self.n_heads} not divisible by n_kv_heads {n_kv}"
        )
        return n_kv


def init_params(cfg: TransformerConfig, key: jax.Array) -> Dict[str, Any]:
    """Stacked-layer params: arrays carry a leading [n_layers] axis so the
    forward pass can lax.scan (or pipeline) over them."""
    k_emb, k_attn, k_mlp, k_out = jax.random.split(key, 4)
    d, h, hd, f, L = cfg.d_model, cfg.n_heads, cfg.head_dim, cfg.d_ff, cfg.n_layers

    def norm_init(k, shape, fan_in):
        return (jax.random.normal(k, shape, jnp.float32) / math.sqrt(fan_in)).astype(
            jnp.float32
        )

    ks = jax.random.split(k_attn, 4)
    km = jax.random.split(k_mlp, 4)
    h_kv = cfg.kv_heads
    layers: Dict[str, Any] = {
        "attn_norm": jnp.ones((L, d), jnp.float32),
        "wq": norm_init(ks[0], (L, d, h, hd), d),
        "wk": norm_init(ks[1], (L, d, h_kv, hd), d),
        "wv": norm_init(ks[2], (L, d, h_kv, hd), d),
        "wo": norm_init(ks[3], (L, h, hd, d), d),
        "mlp_norm": jnp.ones((L, d), jnp.float32),
    }
    if cfg.n_experts > 0:
        E = cfg.n_experts
        layers.update(
            router=norm_init(km[3], (L, d, E), d),
            w_gate=norm_init(km[0], (L, E, d, f), d),
            w_up=norm_init(km[1], (L, E, d, f), d),
            w_down=norm_init(km[2], (L, E, f, d), f),
        )
    else:
        layers.update(
            w_gate=norm_init(km[0], (L, d, f), d),
            w_up=norm_init(km[1], (L, d, f), d),
            w_down=norm_init(km[2], (L, f, d), f),
        )
    if cfg.lora_mlp and cfg.lora_rank <= 0:
        # silently training ALL parameters when the user asked for
        # MLP adapters would defeat the point of the flag
        raise ValueError("lora_mlp requires lora_rank > 0")
    if cfg.lora_rank > 0:
        r = cfg.lora_rank
        kl = jax.random.split(jax.random.fold_in(key, 7), 7)
        layers.update(
            # a ~ N(0, 1/d) like the base projections, b = 0: the adapted
            # model starts exactly equal to the base model
            lora_wq_a=norm_init(kl[0], (L, d, r), d),
            lora_wq_b=jnp.zeros((L, r, h, hd), jnp.float32),
            lora_wk_a=norm_init(kl[1], (L, d, r), d),
            lora_wk_b=jnp.zeros((L, r, h_kv, hd), jnp.float32),
            lora_wv_a=norm_init(kl[2], (L, d, r), d),
            lora_wv_b=jnp.zeros((L, r, h_kv, hd), jnp.float32),
            lora_wo_a=norm_init(kl[3], (L, h, hd, r), d),
            lora_wo_b=jnp.zeros((L, r, d), jnp.float32),
        )
        if cfg.lora_mlp:
            if cfg.n_experts > 0:
                raise ValueError("lora_mlp adapts the dense MLP only "
                                 "(MoE experts are not adapted)")
            layers.update(
                lora_w_gate_a=norm_init(kl[4], (L, d, r), d),
                lora_w_gate_b=jnp.zeros((L, r, f), jnp.float32),
                lora_w_up_a=norm_init(kl[5], (L, d, r), d),
                lora_w_up_b=jnp.zeros((L, r, f), jnp.float32),
                lora_w_down_a=norm_init(kl[6], (L, f, r), f),
                lora_w_down_b=jnp.zeros((L, r, d), jnp.float32),
            )
    return {
        "embed": norm_init(k_emb, (cfg.vocab_size, d), d),
        "layers": layers,
        "final_norm": jnp.ones((d,), jnp.float32),
        "lm_head": norm_init(k_out, (d, cfg.vocab_size), d),
    }


def split_lora_params(params: Dict[str, Any]):
    """Split a LoRA-enabled param tree into (base, adapters) — the two
    arguments of the LoRA train step. Inverse: ``combine_lora_params``."""
    layers = params["layers"]
    lora = {k: v for k, v in layers.items() if k.startswith("lora_")}
    base = dict(params)
    base["layers"] = {k: v for k, v in layers.items() if not k.startswith("lora_")}
    return base, {"layers": lora}


def combine_lora_params(base: Dict[str, Any], lora: Dict[str, Any]) -> Dict[str, Any]:
    out = dict(base)
    out["layers"] = {**base["layers"], **lora["layers"]}
    return out


def merge_lora(params: Dict[str, Any], cfg: TransformerConfig) -> Dict[str, Any]:
    """Fold the adapters into the base weights (W + (alpha/r) A B) and drop
    the lora leaves: the result has the base tree shape, loads into the
    decode/serving path unchanged, and computes the same function (guard:
    tests/test_lora.py::test_merge_matches_adapter_forward)."""
    assert cfg.lora_rank > 0, "merge_lora needs a LoRA config"
    s = cfg.lora_alpha / cfg.lora_rank
    layers = dict(params["layers"])
    bases = [k[len("lora_"):-len("_a")] for k in layers
             if k.startswith("lora_") and k.endswith("_a")]
    for name in bases:
        a = layers.pop(f"lora_{name}_a")
        b = layers.pop(f"lora_{name}_b")
        if name == "wo":
            delta = jnp.einsum("lhkr,lrd->lhkd", a, b)
        elif name in ("wq", "wk", "wv"):
            delta = jnp.einsum("ldr,lrhk->ldhk", a, b)
        else:  # MLP projections: plain 2-D factors
            delta = jnp.einsum("lxr,lry->lxy", a, b)
        layers[name] = (layers[name] + s * delta).astype(params["layers"][name].dtype)
    out = dict(params)
    out["layers"] = layers
    return out


def sharding_specs(cfg: TransformerConfig) -> Dict[str, Any]:
    """PartitionSpecs per param, mirroring init_params' tree. tp shards heads
    and ff, fsdp the complementary axis, ep the expert axis. With pipelining,
    the leading layer axis is sharded over pp; tp is kept (manual
    row-parallel psums in the stage body) and fsdp is kept too (ZeRO-style
    per-use all-gather; see parallel/pipeline.py for the composition
    rules)."""
    # pipelined stages run in manual shard_map mode: tp sharding is kept
    # (row-parallel psums in _apply_layer) and fsdp param sharding is kept
    # too (ZeRO-style all-gather per use inside the stage)
    pl = "pp" if cfg.pipeline_microbatches > 0 else None
    fsdp = "fsdp"
    tp = "tp"
    layers: Dict[str, Any] = {
        "attn_norm": P(pl, None),
        "wq": P(pl, fsdp, tp, None),
        "wk": P(pl, fsdp, tp, None),
        "wv": P(pl, fsdp, tp, None),
        "wo": P(pl, tp, None, fsdp),
        "mlp_norm": P(pl, None),
    }
    if cfg.n_experts > 0:
        layers.update(
            router=P(pl, fsdp, None),
            w_gate=P(pl, "ep", fsdp, tp),
            w_up=P(pl, "ep", fsdp, tp),
            w_down=P(pl, "ep", tp, fsdp),
        )
    else:
        layers.update(
            w_gate=P(pl, fsdp, tp),
            w_up=P(pl, fsdp, tp),
            w_down=P(pl, tp, fsdp),
        )
    if cfg.lora_rank > 0:
        # the rank axis stays replicated (it is tiny); the head/width axes
        # mirror the base projections so the delta einsums stay tp-local
        layers.update(
            lora_wq_a=P(pl, fsdp, None),
            lora_wq_b=P(pl, None, tp, None),
            lora_wk_a=P(pl, fsdp, None),
            lora_wk_b=P(pl, None, tp, None),
            lora_wv_a=P(pl, fsdp, None),
            lora_wv_b=P(pl, None, tp, None),
            lora_wo_a=P(pl, tp, None, None),
            lora_wo_b=P(pl, None, fsdp),
        )
        if cfg.lora_mlp:
            layers.update(
                lora_w_gate_a=P(pl, fsdp, None),
                lora_w_gate_b=P(pl, None, tp),
                lora_w_up_a=P(pl, fsdp, None),
                lora_w_up_b=P(pl, None, tp),
                lora_w_down_a=P(pl, tp, None),
                lora_w_down_b=P(pl, None, fsdp),
            )
    return {
        "embed": P(None, "fsdp"),
        "layers": layers,
        "final_norm": P(None),
        "lm_head": P("fsdp", "tp"),
    }


def activation_spec() -> P:
    """[batch, seq, ...]: batch over dp(+fsdp), sequence over sp."""
    return P(("dp", "fsdp"), "sp")


def is_quantized_leaf(leaf) -> bool:
    """The single structural test for an int8-quantized weight leaf
    (models/quant.py's ``{"qi8", "scale"}`` encoding)."""
    return isinstance(leaf, dict) and "qi8" in leaf


def load_weight(leaf, dtype) -> jax.Array:
    """Cast a weight leaf to the compute dtype, dequantizing transparently
    when it is an int8-quantized ``{"qi8", "scale"}`` pair (models/quant.py).
    The convert-and-scale fuses into the consuming einsum, so quantized
    serving reads int8 bytes from HBM and multiplies in ``dtype``."""
    if is_quantized_leaf(leaf):
        return leaf["qi8"].astype(dtype) * leaf["scale"].astype(dtype)
    return leaf.astype(dtype)


def cast_params(params: Dict[str, Any], dtype) -> Dict[str, Any]:
    """Cast float weight leaves to the serving/compute dtype once, up front.

    Training keeps f32 master weights and casts per use (``load_weight``),
    which is right for the optimizer but makes autoregressive decode stream
    4 bytes/param from HBM per step — decode is bandwidth-bound, so serving
    should hold bf16 (or int8, via models/quant.py) weights instead.
    Quantized ``{"qi8", "scale"}`` leaves pass through untouched; everything
    else float is cast, so ``load_weight(leaf, dtype)`` becomes a no-op at
    decode time."""

    def cast(leaf):
        if is_quantized_leaf(leaf):
            return leaf
        if hasattr(leaf, "dtype") and jnp.issubdtype(leaf.dtype, jnp.floating):
            return leaf.astype(dtype)
        return leaf

    return jax.tree.map(cast, params, is_leaf=is_quantized_leaf)


def _rms_norm(x: jax.Array, w: jax.Array, eps: float = 1e-6) -> jax.Array:
    xf = x.astype(jnp.float32)
    norm = xf * lax.rsqrt(jnp.mean(xf * xf, axis=-1, keepdims=True) + eps)
    return (norm * w).astype(x.dtype)


def _rope(x: jax.Array, positions: jax.Array, theta: float) -> jax.Array:
    """x: [B, T, H, D]; rotate pairs (even, odd) by position-dependent angles."""
    d = x.shape[-1]
    freqs = jnp.exp(
        -math.log(theta) * (jnp.arange(0, d, 2, dtype=jnp.float32) / d)
    )  # [D/2]
    angles = positions[..., None].astype(jnp.float32) * freqs  # [B?, T, D/2]
    cos = jnp.cos(angles)[..., None, :]  # broadcast over heads: [.., T, 1, D/2]
    sin = jnp.sin(angles)[..., None, :]
    x1 = x[..., 0::2].astype(jnp.float32)
    x2 = x[..., 1::2].astype(jnp.float32)
    out = jnp.stack([x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1)
    return out.reshape(x.shape).astype(x.dtype)


def _moe_mlp(
    h: jax.Array, lp: Dict[str, Any], cfg: TransformerConfig, dtype, mesh=None,
    manual_ep_axis=None, manual_tp_axis=None, manual_sp_axis=None,
):
    """Top-k MoE with capacity-based dense dispatch; the expert axis is
    ep-sharded so GSPMD turns the dispatch einsums into all_to_alls. Top-1
    uses the raw switch gate; top-2 renormalizes the gates over the chosen
    experts. Returns (output, aux) where aux is this layer's WEIGHTED
    auxiliary loss: moe_aux_weight * the Switch load-balancing term
    E * sum_e(first_choice_frac_e * mean_prob_e), plus moe_zloss_weight *
    the ST-MoE router z-loss mean(logsumexp(logits)^2).

    ``manual_ep_axis`` (shard_map / pipeline-stage mode): expert weights are
    device-local slices; routing runs on the full expert count (the router is
    replicated), each device computes only its experts' slots, and the
    combine partial-sums are psum'd over the axis.

    ``manual_sp_axis``: the sequence is sharded over that axis, but routing
    reproduces GLOBAL capacity semantics exactly — capacity is computed on
    the global token count, slot positions add an exclusive prefix of
    earlier shards' per-expert counts (an all_gather of [B, E] counts, tiny),
    the load-balance/z-loss statistics are pmean'd to their global values,
    and expert inputs are reduce-scattered over the axis (each shard runs
    the expert FFN on a 1/sp slice of the capacity dim, all_gathered back
    before the combine; psum fallback when capacity is not divisible by sp)
    so every expert sees its tokens from all shards without redundant FLOPs.
    A token therefore overflows capacity iff it would in the unsharded
    computation (guard:
    test_pipeline_moe.py::test_moe_inside_sp_pipeline_matches_dense)."""
    b, t, d = h.shape
    # the router is always full-width: its E dim is the global expert count
    E = lp["router"].shape[-1]
    top_k = max(1, min(cfg.moe_top_k, E))
    sp_size = 1
    if manual_sp_axis is not None:
        # static python int inside the shard_map body — capacity is a shape
        from hivedscheduler_tpu.parallel.shard_utils import axis_size

        sp_size = axis_size(manual_sp_axis)
    # capacity is defined on the GLOBAL sequence length
    capacity = max(
        1, int(math.ceil(t * sp_size * top_k / E * cfg.expert_capacity_factor))
    )
    logits = jnp.einsum("btd,de->bte", h, lp["router"].astype(dtype)).astype(jnp.float32)
    probs = jax.nn.softmax(logits, axis=-1)
    top_gates, top_idx = lax.top_k(probs, top_k)  # [B, T, K]
    if top_k > 1:
        top_gates = top_gates / jnp.sum(top_gates, axis=-1, keepdims=True)
    masks = jax.nn.one_hot(top_idx, E, dtype=jnp.float32)  # [B, T, K, E]
    # aux loss on the first choice (standard Switch load balancing); with a
    # sequence-sharded stage the means are pmean'd to their global values
    # BEFORE the nonlinear product
    mean_mask0 = jnp.mean(masks[:, :, 0, :], axis=(0, 1))
    mean_probs = jnp.mean(probs, axis=(0, 1))
    if manual_sp_axis is not None:
        mean_mask0 = lax.pmean(mean_mask0, manual_sp_axis)
        mean_probs = lax.pmean(mean_probs, manual_sp_axis)
    lb = E * jnp.sum(mean_mask0 * mean_probs)
    aux = cfg.moe_aux_weight * lb
    if cfg.moe_zloss_weight > 0.0:
        # ST-MoE router z-loss: keeps router logits small so the softmax
        # stays in a numerically comfortable range
        z = jnp.mean(jnp.square(jax.nn.logsumexp(logits, axis=-1)))
        if manual_sp_axis is not None:
            z = lax.pmean(z, manual_sp_axis)
        aux = aux + cfg.moe_zloss_weight * z
    # per-expert slot assignment: choice 0 tokens queue first, then choice 1
    combine = jnp.zeros((b, t, E, capacity), jnp.float32)
    counts = jnp.zeros((b, E), jnp.float32)  # global counts of prior choices
    for i in range(top_k):
        m = masks[:, :, i, :]  # [B, T, E]
        local_cum = jnp.cumsum(m, axis=1)
        if manual_sp_axis is not None:
            # global slot position = (this choice's counts on earlier
            # shards) + local cumsum + (all shards' counts of prior choices)
            cnt = jnp.sum(m, axis=1)  # [B, E]
            all_cnt = lax.all_gather(cnt, manual_sp_axis)  # [sp, B, E]
            before = (
                jnp.arange(sp_size) < lax.axis_index(manual_sp_axis)
            ).astype(jnp.float32)
            prefix = jnp.einsum("s,sbe->be", before, all_cnt)
            pos = (local_cum + prefix[:, None, :]) * m - 1.0 + counts[:, None, :] * m
            counts = counts + jnp.sum(all_cnt, axis=0)
        else:
            pos = local_cum * m - 1.0 + counts[:, None, :] * m
            counts = counts + jnp.sum(m, axis=1)
        keep = m * ((pos >= 0) & (pos < capacity)).astype(jnp.float32)
        slot = jax.nn.one_hot(
            jnp.clip(pos, 0, capacity - 1).astype(jnp.int32), capacity,
            dtype=jnp.float32,
        ) * keep[..., None]  # [B, T, E, C]
        combine = combine + slot * top_gates[:, :, i][..., None, None]
    dispatch = (combine > 0.0).astype(jnp.float32)  # [B, T, E, C]
    if manual_ep_axis is not None:
        # manual (pipeline-stage) mode: this device holds E_local experts;
        # compute their slots only and psum the partial combine
        e_local = lp["w_gate"].shape[0]
        start = lax.axis_index(manual_ep_axis) * e_local
        dispatch = lax.dynamic_slice_in_dim(dispatch, start, e_local, axis=2)
        combine = lax.dynamic_slice_in_dim(combine, start, e_local, axis=2)
    expert_in = jnp.einsum("btec,btd->ebcd", dispatch.astype(dtype), h)
    sp_scattered = False
    if manual_sp_axis is not None:
        # each expert's slots aggregate tokens from every sequence shard;
        # scatter the capacity dim across sp so the expert FFN below runs on
        # 1/sp of the slots per shard instead of sp-fold redundantly
        if capacity % sp_size == 0:
            expert_in = lax.psum_scatter(
                expert_in, manual_sp_axis, scatter_dimension=2, tiled=True
            )
            sp_scattered = True
        else:
            expert_in = lax.psum(expert_in, manual_sp_axis)
    if manual_ep_axis is None and mesh is not None:
        from jax.sharding import NamedSharding

        expert_in = lax.with_sharding_constraint(
            expert_in, NamedSharding(mesh, P("ep", ("dp", "fsdp"), None, None))
        )
    g = jnp.einsum("ebcd,edf->ebcf", expert_in, load_weight(lp["w_gate"], dtype))
    u = jnp.einsum("ebcd,edf->ebcf", expert_in, load_weight(lp["w_up"], dtype))
    expert_out = jnp.einsum(
        "ebcf,efd->ebcd", jax.nn.silu(g) * u, load_weight(lp["w_down"], dtype)
    )
    if sp_scattered:
        # reassemble the full capacity dim before the local combine
        expert_out = lax.all_gather(
            expert_out, manual_sp_axis, axis=2, tiled=True
        )
    # `combine` already carries the per-token gate weights per slot
    out = jnp.einsum("btec,ebcd->btd", combine.astype(dtype), expert_out)
    # manual mode: the output is partial over local experts (ep) AND over the
    # tp-local slice of the expert hidden dim — psum both
    manual_axes = tuple(a for a in (manual_ep_axis, manual_tp_axis) if a)
    if manual_axes:
        out = lax.psum(out, manual_axes)
    return out, aux


def _flash_gspmd(q, k, v, mesh, attn_fn):
    """Run the Pallas flash kernel sharded over dp/fsdp (batch) and tp
    (heads) via shard_map. GSPMD treats a pallas_call as opaque and would
    otherwise all-gather q/k/v and run it replicated on every device; batch
    and head sharding need no cross-device communication, so the manual
    wrapper keeps the kernel local. Falls back to the replicated call when
    the shards don't divide (GSPMD then handles it correctly, just slower).
    The sequence axis is gathered (spec None): flash attends over the full
    sequence — sequence-parallel attention is the ring family's job."""
    from hivedscheduler_tpu.parallel.ring_attention import _get_shard_map

    sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
    ndp = sizes.get("dp", 1) * sizes.get("fsdp", 1)
    tp = sizes.get("tp", 1)
    b, _, h, _ = q.shape
    h_kv = k.shape[2]
    if b % ndp or h % tp or h_kv % tp:
        return attn_fn(q, k, v, causal=True)
    spec = P(("dp", "fsdp"), None, "tp", None)
    body = lambda q, k, v: attn_fn(q, k, v, causal=True)
    kw = dict(mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)
    try:
        # the pallas_call's out_shape avals carry no vma info; skip the check
        fn = _get_shard_map()(body, check_vma=False, **kw)
    except TypeError:  # older jax spells it check_rep
        fn = _get_shard_map()(body, check_rep=False, **kw)
    return fn(q, k, v)


def _dispatch_attention(q, k, v, cfg: TransformerConfig, attn_fn, mesh,
                        manual_tp_axis=None, manual_sp_axis=None,
                        manual_ep_axis=None, manual_vma_axes=(),
                        device_local: bool = False):
    """GQA compact-vs-repeat policy + attention implementation dispatch —
    the ONE home shared by the GSPMD layer body, the pipeline-stage manual
    body, and the overlapped collective-matmul body (so the three cannot
    drift). ``device_local=True`` marks q/k/v as already device-local
    slices inside a manual context, which skips the mesh-level tp
    divisibility re-check (the local head counts already divided)."""
    if k.shape[2] != q.shape[2]:
        # GQA. The ring schedules and Ulysses consume compact k/v directly
        # via grouped einsums — the ppermute rotation / k,v all_to_all then
        # ships H_kv/H of the bytes — when the compact head count still
        # shards evenly over tp (the manual pipeline path rejects
        # indivisible kv/tp upfront; Ulysses expands locally if H_kv
        # doesn't split over sp). All other impls (and the indivisible
        # GSPMD case) materialize each shared k/v head for its q-head
        # group here, after RoPE so the rotation runs on the small head
        # count; contiguous grouping keeps groups aligned with tp shards.
        compact_ok = cfg.attn_impl in (
            "ring", "ring_flash", "ring_zigzag", "ring_zigzag_flash",
            "ulysses", "flash",
        )
        if (compact_ok and manual_sp_axis is None and mesh is not None
                and not device_local):
            tp_size = dict(zip(mesh.axis_names, mesh.devices.shape)).get("tp", 1)
            compact_ok = k.shape[2] % tp_size == 0
        if not compact_ok:
            rep = q.shape[2] // k.shape[2]
            k = jnp.repeat(k, rep, axis=2)
            v = jnp.repeat(v, rep, axis=2)
    if manual_sp_axis is not None:
        from hivedscheduler_tpu.parallel.ring_attention import (
            _ring_attention_local,
            _ring_flash_attention_local,
            _ulysses_local,
            _zigzag_flash_attention_local,
            _zigzag_ring_attention_local,
        )

        if cfg.attn_impl == "ulysses":
            attn = _ulysses_local(q, k, v, axis_name=manual_sp_axis, causal=True)
        elif cfg.attn_impl == "ring_zigzag":
            attn = _zigzag_ring_attention_local(
                q, k, v, axis_name=manual_sp_axis, mesh_axes=manual_vma_axes,
            )
        elif cfg.attn_impl == "ring_zigzag_flash":
            attn = _zigzag_flash_attention_local(
                q, k, v, axis_name=manual_sp_axis, mesh_axes=manual_vma_axes,
                block_q=cfg.attn_block_q, block_k=cfg.attn_block_k,
            )
        elif cfg.attn_impl == "ring_flash":
            attn = _ring_flash_attention_local(
                q, k, v, axis_name=manual_sp_axis, causal=True,
                mesh_axes=manual_vma_axes,
                block_q=cfg.attn_block_q, block_k=cfg.attn_block_k,
            )
        else:
            attn = _ring_attention_local(
                q, k, v, axis_name=manual_sp_axis, causal=True,
                mesh_axes=manual_vma_axes,
            )
    elif cfg.attn_impl in RING_FAMILY:
        attn = attn_fn(q, k, v, mesh, causal=True)
    elif cfg.attn_impl == "flash" and mesh is not None:
        if manual_tp_axis is None and manual_ep_axis is None and not device_local:
            attn = _flash_gspmd(q, k, v, mesh, attn_fn)
        else:
            # GSPMD shard_map cannot open inside a manual (pipeline-stage)
            # context (CLAUDE.md shard_map rule): arrays are already
            # device-local, so call the kernel directly — passing the
            # varying axes so its pallas out_shape avals type under the
            # enclosing shard_map's vma checker
            attn = attn_fn(q, k, v, causal=True, vma=manual_vma_axes)
    else:
        attn = attn_fn(q, k, v, causal=True)
    return attn


def _apply_layer_overlapped(x, lp, cfg: TransformerConfig, attn_fn, mesh,
                            tp_axis: str, sp_axis, vma_axes=()):
    """One transformer block in the overlapped tensor-parallel manual mode
    (``cfg.overlap`` / HIVED_OVERLAP — see ``forward_with_aux``).

    The residual stream arrives sequence-sharded over (sp, tp) — the
    Megatron sequence-parallel layout — so the norms and residual adds are
    token-local, and the tp collectives around the projections run as
    collective matmuls (``shard_utils``): QKV and gate/up consume the
    all-gather as a ppermute pipeline (one rotation feeding all fused
    weights), attention-out and MLP-down produce the reduce-scatter as a
    pipelined chunk accumulator. Every ICI hop therefore transfers under
    the previous chunk's MXU work instead of serializing after it.

    Dense layers only: the caller (``_use_overlap``) gates MoE/LoRA/
    pipeline configs back to the GSPMD reference path. Numerics: each
    output element is computed by the same local contractions as the
    reference; only the cross-device reduction order of the row-parallel
    partial sums differs (bit-identical at tp=2 where the two-term sum is
    commutative; guard: tests/test_overlap.py)."""
    from hivedscheduler_tpu.parallel import shard_utils

    dtype = cfg.dtype
    tp_size = shard_utils.axis_size(tp_axis)
    t_gather = x.shape[1] * tp_size
    base = lax.axis_index(sp_axis) * t_gather if sp_axis else 0
    positions = (base + lax.iota(jnp.int32, t_gather))[None, :]

    h = _rms_norm(x, lp["attn_norm"])
    q, k, v = shard_utils.allgather_matmul(
        h,
        [lp["wq"].astype(dtype), lp["wk"].astype(dtype),
         lp["wv"].astype(dtype)],
        tp_axis, "btd,dhk->bthk", vma_axes=vma_axes,
    )
    q = _rope(q, positions, cfg.rope_theta)
    k = _rope(k, positions, cfg.rope_theta)
    attn = _dispatch_attention(
        q, k, v, cfg, attn_fn, mesh,
        manual_tp_axis=tp_axis, manual_sp_axis=sp_axis,
        manual_vma_axes=vma_axes, device_local=True,
    )
    x = x + shard_utils.matmul_reducescatter(
        attn, lp["wo"].astype(dtype), tp_axis, "bthk,hkd->btd"
    )
    h = _rms_norm(x, lp["mlp_norm"])
    gate, up = shard_utils.allgather_matmul(
        h, [lp["w_gate"].astype(dtype), lp["w_up"].astype(dtype)],
        tp_axis, "btd,df->btf", vma_axes=vma_axes,
    )
    mid = jax.nn.silu(gate) * up
    x = x + shard_utils.matmul_reducescatter(
        mid, lp["w_down"].astype(dtype), tp_axis, "btf,fd->btd"
    )
    return x


def _apply_layer(x, lp, positions, cfg: TransformerConfig, attn_fn, mesh,
                 manual_tp_axis=None, manual_sp_axis=None, manual_ep_axis=None,
                 manual_vma_axes=()):
    """One transformer block; lp leaves have no leading layer axis.
    Returns (x, aux) — aux is the layer's weighted MoE auxiliary loss (0 for
    dense layers).

    Manual (shard_map / pipeline-stage) mode:
    - ``manual_tp_axis``: weights tensor-sharded over that axis — heads and
      the MLP hidden dim are device-local, and the two row-parallel
      projections (attention out, MLP down) psum Megatron-style;
    - ``manual_sp_axis``: activations sequence-sharded over that axis — RoPE
      positions are offset by the shard index and attention runs the local
      ring body directly (``manual_vma_axes`` seeds its accumulators'
      device-varying state)."""
    dtype = cfg.dtype

    def row_parallel(out):
        return lax.psum(out, manual_tp_axis) if manual_tp_axis else out

    if manual_sp_axis is not None:
        t_local = x.shape[1]
        positions = (
            lax.axis_index(manual_sp_axis) * t_local
            + lax.iota(jnp.int32, t_local)
        )[None, :]

    h = _rms_norm(x, lp["attn_norm"])
    q = jnp.einsum("btd,dhk->bthk", h, lp["wq"].astype(dtype))
    k = jnp.einsum("btd,dhk->bthk", h, lp["wk"].astype(dtype))
    v = jnp.einsum("btd,dhk->bthk", h, lp["wv"].astype(dtype))
    if "lora_wq_a" in lp:
        # rank-r adapter delta x A B, scaled alpha/r; the rank axis is tiny
        # and replicated, so these ride the MXU as two thin matmuls
        s = cfg.lora_alpha / cfg.lora_rank

        def lora(inp, name):
            z = jnp.einsum("btd,dr->btr", inp, lp[f"{name}_a"].astype(dtype))
            return jnp.einsum("btr,rhk->bthk", z, lp[f"{name}_b"].astype(dtype)) * s

        q = q + lora(h, "lora_wq")
        k = k + lora(h, "lora_wk")
        v = v + lora(h, "lora_wv")
    q = _rope(q, positions, cfg.rope_theta)
    k = _rope(k, positions, cfg.rope_theta)
    attn = _dispatch_attention(
        q, k, v, cfg, attn_fn, mesh,
        manual_tp_axis=manual_tp_axis, manual_sp_axis=manual_sp_axis,
        manual_ep_axis=manual_ep_axis, manual_vma_axes=manual_vma_axes,
    )
    o = jnp.einsum("bthk,hkd->btd", attn, lp["wo"].astype(dtype))
    if "lora_wo_a" in lp:
        # both the base wo and the adapter's A contract the (sharded) head
        # axis, so the partial sums share the row-parallel psum
        zo = jnp.einsum("bthk,hkr->btr", attn, lp["lora_wo_a"].astype(dtype))
        o = o + jnp.einsum("btr,rd->btd", zo, lp["lora_wo_b"].astype(dtype)) * (
            cfg.lora_alpha / cfg.lora_rank
        )
    x = x + row_parallel(o)
    h = _rms_norm(x, lp["mlp_norm"])
    aux = jnp.zeros((), jnp.float32)
    if cfg.n_experts > 0:
        moe_out, aux = _moe_mlp(h, lp, cfg, dtype, mesh,
                                manual_ep_axis=manual_ep_axis,
                                manual_tp_axis=manual_tp_axis,
                                manual_sp_axis=manual_sp_axis)
        x = x + moe_out
    else:
        gate = jnp.einsum("btd,df->btf", h, lp["w_gate"].astype(dtype))
        up = jnp.einsum("btd,df->btf", h, lp["w_up"].astype(dtype))
        if "lora_w_gate_a" in lp:
            s = cfg.lora_alpha / cfg.lora_rank

            def lora_mlp(inp, name):
                z = jnp.einsum("btd,dr->btr", inp, lp[f"{name}_a"].astype(dtype))
                return jnp.einsum(
                    "btr,rf->btf", z, lp[f"{name}_b"].astype(dtype)
                ) * s

            gate = gate + lora_mlp(h, "lora_w_gate")
            up = up + lora_mlp(h, "lora_w_up")
        mid = jax.nn.silu(gate) * up
        down = jnp.einsum("btf,fd->btd", mid, lp["w_down"].astype(dtype))
        if "lora_w_down_a" in lp:
            # contracts the (tp-sharded) hidden dim like the base w_down, so
            # the adapter's partial sums ride the same row-parallel psum
            zd = jnp.einsum("btf,fr->btr", mid, lp["lora_w_down_a"].astype(dtype))
            down = down + jnp.einsum(
                "btr,rd->btd", zd, lp["lora_w_down_b"].astype(dtype)
            ) * (cfg.lora_alpha / cfg.lora_rank)
        x = x + row_parallel(down)
    return x, aux


ATTN_IMPLS = ("xla", "flash", "ring", "ring_flash", "ring_zigzag",
              "ring_zigzag_flash", "ulysses")
# need a mesh + sp axis
RING_FAMILY = ("ring", "ring_flash", "ring_zigzag", "ring_zigzag_flash",
               "ulysses")


def _remat_wrap(fn, cfg: TransformerConfig):
    """Apply cfg.remat to a scanned layer/stage body (see the config field
    docstring for the policy trade-offs)."""
    if cfg.remat == "none":
        return fn
    if cfg.remat == "dots":
        return jax.checkpoint(
            fn, policy=jax.checkpoint_policies.dots_with_no_batch_dims_saveable
        )
    if cfg.remat == "full":
        return jax.checkpoint(fn)
    raise ValueError(
        f"unknown remat policy {cfg.remat!r}; expected 'full', 'dots' or 'none'"
    )


def overlap_applicable(cfg: TransformerConfig, mesh, seq_len=None,
                       batch=None):
    """Can the overlapped collective-matmul path serve (cfg, mesh)?
    Returns (ok, reason) — pure, so CLIs and tests can interrogate the
    gate without tracing. ``seq_len``/``batch`` add the call-shape
    divisibility checks when known."""
    if mesh is None:
        return False, "no mesh"
    shape = dict(zip(mesh.axis_names, mesh.devices.shape))
    tp = shape.get("tp", 1)
    sp = shape.get("sp", 1)
    if tp <= 1:
        return False, "tp axis is 1: no tensor collectives to overlap"
    if cfg.pipeline_microbatches > 0:
        return False, "pipelined stacks run the pipeline's own manual path"
    if cfg.n_experts > 0:
        return False, "MoE dispatch is not on the overlapped path"
    if cfg.lora_rank > 0:
        return False, "LoRA adapters are not on the overlapped path"
    if cfg.n_heads % tp or cfg.kv_heads % tp or cfg.d_ff % tp:
        return False, (
            f"heads/kv/ff must divide tp: n_heads={cfg.n_heads}, "
            f"kv_heads={cfg.kv_heads}, d_ff={cfg.d_ff}, tp={tp}"
        )
    if sp > 1 and cfg.attn_impl not in RING_FAMILY:
        return False, (
            f"sp={sp} needs a ring-family attn_impl, got {cfg.attn_impl!r}"
        )
    if cfg.attn_impl == "ulysses" and sp > 1 and (cfg.n_heads // tp) % sp:
        return False, (
            f"ulysses needs tp-local heads divisible by sp: "
            f"{cfg.n_heads} heads / tp={tp} vs sp={sp}"
        )
    if seq_len is not None and seq_len % (sp * tp):
        return False, (
            f"sequence {seq_len} must divide sp*tp={sp * tp} to "
            "sequence-shard the residual stream"
        )
    if batch is not None and batch % (shape.get("dp", 1) * shape.get("fsdp", 1)):
        return False, (
            f"batch {batch} must divide dp*fsdp="
            f"{shape.get('dp', 1) * shape.get('fsdp', 1)}"
        )
    return True, ""


def _use_overlap(cfg: TransformerConfig, mesh, seq_len, batch) -> bool:
    """The HIVED_OVERLAP / cfg.overlap gate: env 0 always forces the
    GSPMD reference path (the differential-parity contract); cfg.overlap
    False opts out, True requires (raising when inapplicable), None = on
    whenever applicable."""
    if os.environ.get("HIVED_OVERLAP", "") == "0":
        return False
    if cfg.overlap is False:
        return False
    ok, reason = overlap_applicable(cfg, mesh, seq_len, batch)
    if cfg.overlap is True and not ok:
        raise ValueError(
            f"cfg.overlap=True but the overlapped path cannot serve this "
            f"config: {reason}"
        )
    return ok


def _overlapped_stack(x, layers, cfg: TransformerConfig, attn_fn, mesh):
    """Run the whole layer stack in one shard_map: scan over the stacked
    layer params with ``_apply_layer_overlapped`` as the body, the
    residual stream sequence-sharded over (sp, tp) and fsdp weight shards
    all-gathered per use (ZeRO-style — autodiff turns the gathers into
    grad reduce-scatters, exactly like the pipeline stage path)."""
    from hivedscheduler_tpu.parallel.ring_attention import _get_shard_map

    layer_specs = sharding_specs(cfg)["layers"]
    x_spec = P(("dp", "fsdp"), ("sp", "tp"), None)
    manual_sp = "sp" if cfg.attn_impl in RING_FAMILY else None
    vma_axes = ("dp", "fsdp", "tp") + (("sp",) if manual_sp else ())

    def gather_fsdp(lp):
        def gather(leaf, spec):
            # spec's first entry is the (scanned-away) layer axis
            for i, part in enumerate(spec[1:]):
                parts = part if isinstance(part, tuple) else (part,)
                if "fsdp" in parts:
                    return lax.all_gather(leaf, "fsdp", axis=i, tiled=True)
            return leaf

        return jax.tree.map(gather, lp, layer_specs)

    def stacked(xx, stack):
        def scan_body(carry, lp):
            out = _apply_layer_overlapped(
                carry, gather_fsdp(lp), cfg, attn_fn, mesh, "tp", manual_sp,
                vma_axes,
            )
            return out, None

        out, _ = lax.scan(_remat_wrap(scan_body, cfg), xx, stack)
        return out

    kw = dict(mesh=mesh, in_specs=(x_spec, layer_specs), out_specs=x_spec)
    try:
        # the ppermute pipelines and the pallas kernel's out_shape avals
        # don't all type under the vma checker (same stance as
        # _flash_gspmd); numerics are pinned differentially against the
        # HIVED_OVERLAP=0 reference path in tests/test_overlap.py
        fn = _get_shard_map()(stacked, check_vma=False, **kw)
    except TypeError:  # older jax spells it check_rep
        fn = _get_shard_map()(stacked, check_rep=False, **kw)
    from jax.sharding import NamedSharding

    # hand the residual stream back in the reference layout (seq over sp
    # only): the final norm + lm_head then partition exactly as on the
    # HIVED_OVERLAP=0 path — this is what makes the forward parity
    # bit-exact end to end, and GSPMD would gather x for the lm_head
    # contraction anyway
    return lax.with_sharding_constraint(
        fn(x, layers), NamedSharding(mesh, activation_spec())
    )


def _resolve_attn_fn(cfg: TransformerConfig):
    if cfg.attn_impl == "flash":
        import functools

        from hivedscheduler_tpu.ops.attention import flash_attention

        attn_fn = functools.partial(
            flash_attention, block_q=cfg.attn_block_q, block_k=cfg.attn_block_k
        )
    elif cfg.attn_impl in RING_FAMILY:
        from hivedscheduler_tpu.parallel import ring_attention as ra

        if cfg.attn_impl in ("ring_flash", "ring_zigzag_flash"):
            import functools

            attn_fn = functools.partial(
                ra.ring_flash_attention if cfg.attn_impl == "ring_flash"
                else ra.zigzag_ring_flash_attention,
                block_q=cfg.attn_block_q, block_k=cfg.attn_block_k,
            )
        else:
            attn_fn = {
                "ring": ra.ring_attention,
                "ring_zigzag": ra.zigzag_ring_attention,
                "ulysses": ra.ulysses_attention,
            }[cfg.attn_impl]
    elif cfg.attn_impl == "xla":
        from hivedscheduler_tpu.ops.attention import xla_attention as attn_fn
    else:
        # a typo must not silently train with dense attention
        raise ValueError(
            f"unknown attn_impl {cfg.attn_impl!r}; expected one of {ATTN_IMPLS}"
        )
    return attn_fn


def forward_with_aux(
    params: Dict[str, Any],
    tokens: jax.Array,
    cfg: TransformerConfig,
    mesh=None,
    return_hidden: bool = False,
):
    """tokens [B, T] int32 -> (logits [B, T, vocab] f32, weighted MoE aux
    loss f32 — add it to the task loss directly).

    ``mesh`` is required for ring/ulysses attention and for pipelining.
    ``return_hidden=True`` returns the final-norm hidden states [B, T, D]
    (model dtype) instead of logits — the chunked-cross-entropy loss path
    applies the lm_head itself so the [B, T, vocab] f32 tensor is never
    materialized."""
    dtype = cfg.dtype
    b, t = tokens.shape
    x = params["embed"].astype(dtype)[tokens]  # [B, T, D]
    # [1, T] broadcasts against any (micro)batch size, incl. pipeline stages
    positions = jnp.arange(t, dtype=jnp.int32)[None, :]
    attn_fn = _resolve_attn_fn(cfg)
    if cfg.attn_impl in RING_FAMILY or cfg.pipeline_microbatches > 0:
        assert mesh is not None, f"{cfg.attn_impl}/pipeline requires a mesh"

    def layer(x, lp):
        return _apply_layer(x, lp, positions, cfg, attn_fn, mesh)

    aux_total = jnp.zeros((), jnp.float32)
    if cfg.pipeline_microbatches == 0 and _use_overlap(cfg, mesh, t, b):
        # overlapped tensor parallelism: collective-matmul layer stack
        # (dense-only — aux stays 0, which _use_overlap guarantees)
        x = _overlapped_stack(x, params["layers"], cfg, attn_fn, mesh)
    elif cfg.pipeline_microbatches > 0:
        manual_tp = None
        manual_sp = None
        manual_ep = None
        manual_fsdp = None
        if mesh is not None:
            shape = dict(zip(mesh.axis_names, mesh.devices.shape))
            if shape.get("sp", 1) > 1 and cfg.attn_impl not in RING_FAMILY:
                raise ValueError(
                    f"pipeline with mesh sp > 1 requires one of attn_impl "
                    f"{RING_FAMILY} (got {cfg.attn_impl}): the sequence axis "
                    "is sharded inside the stage"
                )
            if cfg.attn_impl in RING_FAMILY and "sp" in shape:
                # always run the manual attention body inside the stage (a
                # GSPMD shard_map cannot open inside the pipeline's manual
                # context; with sp == 1 it degenerates to local attention)
                manual_sp = "sp"
            if "tp" in shape:
                # Megatron-style psums inside the stage; with tp == 1 the
                # psum is free but still normalizes the shard_map vma of the
                # tp-sharded (possibly size-1) weights
                if cfg.kv_heads % shape["tp"]:
                    raise ValueError(
                        f"GQA in pipeline needs kv heads divisible by tp: "
                        f"{cfg.kv_heads} kv heads, tp={shape['tp']}"
                    )
                manual_tp = "tp"
            if cfg.n_experts > 0 and "ep" in shape:
                if cfg.n_experts % shape["ep"]:
                    raise ValueError(
                        f"n_experts {cfg.n_experts} not divisible by mesh "
                        f"ep={shape['ep']} inside the pipeline"
                    )
                manual_ep = "ep"
            if (
                cfg.attn_impl == "ulysses"
                and shape.get("sp", 1) > 1
                and (cfg.n_heads // max(1, shape.get("tp", 1))) % shape["sp"]
            ):
                raise ValueError(
                    f"ulysses in pipeline needs local heads divisible by sp: "
                    f"{cfg.n_heads} heads / tp={shape.get('tp', 1)} not "
                    f"divisible by sp={shape['sp']}"
                )
            if "fsdp" in shape:
                manual_fsdp = "fsdp"
        from hivedscheduler_tpu.parallel.pipeline import pipeline_apply

        layer_specs = sharding_specs(cfg)["layers"]

        def gather_stage_params(lp):
            """ZeRO-style: reconstruct each weight from its fsdp shards at
            use time (autodiff turns this into grad reduce-scatters)."""
            if manual_fsdp is None:
                return lp

            def gather(leaf, spec):
                # spec's first entry is the (scanned-away) layer/pp axis
                for i, part in enumerate(spec[1:]):
                    parts = part if isinstance(part, tuple) else (part,)
                    if "fsdp" in parts:
                        return lax.all_gather(
                            leaf, manual_fsdp, axis=i, tiled=True
                        )
                return leaf

            return jax.tree.map(gather, lp, layer_specs)
        # axes the activations/weights vary over inside the stage body (for
        # the ring accumulators' vma seed): batch + stage + tp-local heads +
        # the sequence shard itself
        vma_axes = ("dp", "fsdp", "pp") + (("tp",) if manual_tp else ()) + (
            ("sp",) if manual_sp else ()
        )

        def stage_block(stage_params, h):
            def stage_layer(carry, lp):
                xx, aux = carry
                lp = gather_stage_params(lp)
                out, layer_aux = _apply_layer(xx, lp, positions, cfg, attn_fn,
                                              mesh,
                                              manual_tp_axis=manual_tp,
                                              manual_sp_axis=manual_sp,
                                              manual_ep_axis=manual_ep,
                                              manual_vma_axes=vma_axes)
                return (out, aux + layer_aux), None

            (hh, aux), _ = lax.scan(
                _remat_wrap(stage_layer, cfg),
                (h, jnp.zeros((), jnp.float32) + 0.0 * jnp.sum(h[..., 0, 0])),
                stage_params,
            )
            return hh, aux

        x, aux_total = pipeline_apply(
            stage_block,
            params["layers"],
            layer_specs,
            x,
            mesh,
            n_micro=cfg.pipeline_microbatches,
            seq_axis=manual_sp,
        )
    else:
        def scan_body(carry, lp):
            x, aux = carry
            x, layer_aux = layer(x, lp)
            return (x, aux + layer_aux), None

        (x, aux_total), _ = lax.scan(
            _remat_wrap(scan_body, cfg), (x, aux_total), params["layers"]
        )
    x = _rms_norm(x, params["final_norm"])
    if return_hidden:
        return x, aux_total
    logits = jnp.einsum("btd,dv->btv", x, params["lm_head"].astype(dtype))
    return logits.astype(jnp.float32), aux_total


def forward(
    params: Dict[str, Any],
    tokens: jax.Array,
    cfg: TransformerConfig,
    mesh=None,
) -> jax.Array:
    """tokens [B, T] int32 -> logits [B, T, vocab] (f32)."""
    return forward_with_aux(params, tokens, cfg, mesh)[0]
