"""Weight-only int8 quantization for serving.

Decode latency on TPU is HBM-bound: each generated token reads every weight
once, so shipping weights as int8 (+ a per-output-channel f32 scale)
halves the bytes vs bf16 and quarters them vs f32 while the matmuls still
run in the model dtype on the MXU (the int8->bf16 convert-and-scale fuses
into the consuming einsum as an elementwise producer; under the stacked-
layer ``lax.scan`` each step slices and dequantizes ONE layer's weights, so
HBM traffic per token is the int8 bytes).

Post-training, symmetric, per-output-channel: q = round(w / s), s =
max|w| / 127 reduced over the input (contraction) axes. Norm weights and
the MoE router stay f32 (tiny, accuracy-critical). The quantized tree
mirrors the base tree except each quantized leaf becomes
``{"qi8": int8, "scale": f32}`` — ``models/decode.py``'s weight loads
dequantize transparently, so generate/speculative serving consume either
tree. Training never quantizes (quantize after training, or after
``merge_lora``).
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp

from hivedscheduler_tpu.models.transformer import TransformerConfig

# leaf name -> input (contraction) axes to reduce the scale over, for the
# per-layer-stacked [L, ...] layout of init_params
_LAYER_CONTRACT_AXES = {
    "wq": (1,),        # [L, d, h, hd] contracts d
    "wk": (1,),
    "wv": (1,),
    "wo": (1, 2),      # [L, h, hd, d] contracts h, hd
    "w_gate": (1,),    # dense [L, d, f] contracts d; MoE [L, E, d, f] -> (2,)
    "w_up": (1,),
    "w_down": (1,),    # dense [L, f, d] contracts f; MoE [L, E, f, d] -> (2,)
}


def _contract_axes(name: str, moe: bool) -> Tuple[int, ...]:
    """Input (contraction) axes for a layer leaf — the single source shared
    by quantize_params and sharding_specs so the scale reduction and the
    scale sharding can never drift apart."""
    if moe and name in ("w_gate", "w_up", "w_down"):
        return (2,)  # [L, E, in, out]: per-expert input
    return _LAYER_CONTRACT_AXES[name]


def _quantize_leaf(w: jax.Array, axes: Tuple[int, ...]) -> Dict[str, jax.Array]:
    scale = jnp.max(jnp.abs(w), axis=axes, keepdims=True) / 127.0
    scale = jnp.maximum(scale, 1e-8).astype(jnp.float32)
    q = jnp.clip(jnp.round(w / scale), -127, 127).astype(jnp.int8)
    return {"qi8": q, "scale": scale}


# the quantized-leaf predicate and the dequantize-or-cast weight load live
# in transformer.py (the decode path and the MoE block share them);
# re-exported here for discoverability
from hivedscheduler_tpu.models.transformer import (  # noqa: E402,F401
    is_quantized_leaf,
    load_weight,
)


def quantize_params(params: Dict[str, Any], cfg: TransformerConfig) -> Dict[str, Any]:
    """Quantize the serving-relevant matmul weights of a base param tree
    (LoRA runs: ``merge_lora`` first — lora_* leaves are rejected here).

    embed is quantized per row (the gather then scales one row per token);
    lm_head per output column; layer projections per output channel."""
    if any(k.startswith("lora_") for k in params["layers"]):
        raise ValueError(
            "quantize after merge_lora: adapters must be folded into the base"
        )
    moe = cfg.n_experts > 0
    out: Dict[str, Any] = {}
    # iterate the actual tree (unknown leaves pass through unchanged) so a
    # new init_params leaf cannot be silently dropped; the key-structure
    # guard is tests/test_quant.py::test_tree_mirrors_init_params
    for name, leaf in params.items():
        if name == "embed":
            out[name] = _quantize_leaf(leaf, (1,))      # per-row (gathered)
        elif name == "lm_head":
            out[name] = _quantize_leaf(leaf, (0,))      # per-output-column
        elif name == "layers":
            layers: Dict[str, Any] = {}
            for lname, w in leaf.items():
                if lname in _LAYER_CONTRACT_AXES:
                    layers[lname] = _quantize_leaf(w, _contract_axes(lname, moe))
                else:
                    layers[lname] = w  # norms, router
            out[name] = layers
        else:
            out[name] = leaf  # final_norm and any future float leaf
    return out


def sharding_specs(cfg: TransformerConfig) -> Dict[str, Any]:
    """PartitionSpecs for a quantized tree: qi8 mirrors the base weight's
    spec; the keepdims scale drops the sharding of every reduced (size-1)
    axis. ``decode.serving_shardings(cfg, mesh, quantized=True)`` lays
    these over a mesh."""
    from jax.sharding import PartitionSpec as P

    from hivedscheduler_tpu.models import transformer as tm

    base = tm.sharding_specs(cfg)
    moe = cfg.n_experts > 0

    def qspec(name: str, spec: P, axes: Tuple[int, ...]) -> Dict[str, Any]:
        scale_spec = P(*[None if i in axes else s for i, s in enumerate(spec)])
        return {"qi8": spec, "scale": scale_spec}

    layers: Dict[str, Any] = {}
    for name, spec in base["layers"].items():
        if name in _LAYER_CONTRACT_AXES:
            layers[name] = qspec(name, spec, _contract_axes(name, moe))
        else:
            layers[name] = spec
    return {
        "embed": qspec("embed", base["embed"], (1,)),
        "layers": layers,
        "final_norm": base["final_norm"],
        "lm_head": qspec("lm_head", base["lm_head"], (0,)),
    }
