"""Speculative decoding: a small draft model proposes ``gamma`` tokens per
round, the target model verifies them in ONE batched forward, and rejection
sampling keeps the output distribution exactly the target's (greedy output is
bit-identical to target-only greedy decoding — guard:
``tests/test_speculative.py::test_greedy_matches_vanilla``).

The reference scheduler has no model runtime; this extends the workload
runtime's serving path (``models/decode.py``). TPU-first choices:

- **Static shapes end to end**: the whole loop is one ``lax.while_loop``;
  each round runs the draft ``gamma+1`` single-token steps and the target one
  ``S=gamma+1`` step — both fixed-shape compiled programs. Variable
  acceptance is handled by rolling the KV-cache ``length`` back (stale cache
  entries beyond ``length`` are masked by the causal position test in
  ``decode._cached_attention``, so rollback is O(1) — no copies).
- **Verification rides the MXU**: the target scores all gamma+1 positions in
  one call, turning gamma sequential target steps into one matmul-batched
  step — the whole point of speculation on hardware whose matmuls are cheap
  and whose per-step latency is HBM-bound.
- **Full-batch**: per-sequence acceptance lengths are aligned by truncating
  every sequence to the round's minimum accepted prefix; truncated-but-
  accepted draft tokens are still emitted verbatim (they passed their own
  acceptance test, so the per-sequence output law is unchanged), which keeps
  one scalar cache length for the whole batch.

Acceptance rule (the standard speculative-sampling one): draft token t_j is
accepted iff u < p_target(t_j)/p_draft(t_j); on rejection the replacement is
sampled from norm(max(p_target - p_draft, 0)); if all gamma are accepted a
bonus token is sampled from the target's gamma+1-th distribution.
Temperature/top-k/top-p filters apply to BOTH models' logits, so exactness
holds w.r.t. the *filtered* target distribution.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from hivedscheduler_tpu.models.decode import (
    KVCache,
    advance,
    filter_logits,
    init_kv_cache,
)
from hivedscheduler_tpu.models.transformer import TransformerConfig


@dataclasses.dataclass(frozen=True)
class SpecDecodeConfig:
    """First-class speculative serving: pass
    ``ServingEngine(..., spec_decode=SpecDecodeConfig(...))`` and the
    engine constructor routes to the speculative engine — composing with
    continuous batching, chunked prefill, the prefix cache and the paged
    KV cache (``page_size``/``num_blocks``), instead of requiring callers
    to pick a separate engine class. ``gamma`` is the number of draft
    proposals per verify round; the per-row acceptance, exactness and
    counter-keyed sampling contracts are documented on
    ``serving.SpeculativeServingEngine``."""

    draft_params: Any
    draft_cfg: TransformerConfig
    gamma: int = 4


class SpecStats(NamedTuple):
    """Per-run speculation counters (all scalars): verification rounds,
    draft tokens proposed, draft tokens accepted. acceptance rate =
    accepted/drafted; tokens per target step ~ emitted/rounds."""

    rounds: jax.Array
    drafted: jax.Array
    accepted: jax.Array


def derive_draft_config(
    cfg: TransformerConfig, draft_layers: int, draft_d_model: int = 0
) -> TransformerConfig:
    """The CLIs' shared draft-model derivation: ~half the target width,
    rounded up so head_dim stays an even integer (RoPE rotates sin/cos
    pairs), dense MLP at 2x width, classic MHA. Raises ValueError when an
    explicit ``draft_d_model`` breaks the even-head_dim requirement."""
    import dataclasses

    quantum = 2 * cfg.n_heads
    d_model = draft_d_model or max(64, cfg.d_model // 2)
    if not draft_d_model:
        d_model = -(-d_model // quantum) * quantum
    if d_model % quantum:
        raise ValueError(
            f"draft d_model {d_model} must be a multiple of 2*n_heads "
            f"({quantum}): RoPE needs an even head_dim"
        )
    return dataclasses.replace(
        cfg, n_layers=draft_layers, d_model=d_model, d_ff=2 * d_model,
        n_experts=0, n_kv_heads=0,
    )


def generate_speculative(
    target_params: Dict[str, Any],
    draft_params: Dict[str, Any],
    prompt: jax.Array,
    target_cfg: TransformerConfig,
    draft_cfg: TransformerConfig,
    max_new_tokens: int,
    *,
    gamma: int = 4,
    temperature: float = 0.0,
    top_k: int = 0,
    top_p: float = 1.0,
    key: Optional[jax.Array] = None,
) -> Tuple[jax.Array, SpecStats]:
    """Speculative continuation of ``prompt`` [B, T] -> ([B, max_new_tokens],
    SpecStats). ``temperature == 0`` decodes greedily (and is bit-identical
    to ``decode.generate``'s greedy output); sampling needs ``key``. The two
    configs must share the vocab; everything else (depth/width/heads) may
    differ."""
    assert target_cfg.vocab_size == draft_cfg.vocab_size, "vocabs must match"
    assert gamma >= 1, gamma
    assert temperature == 0.0 or key is not None, (
        "sampling (temperature > 0) needs a PRNG key"
    )
    b, t = prompt.shape
    vocab = target_cfg.vocab_size
    # headroom: a round may absorb gamma+1 tokens past the accepted prefix
    # before rolling back
    max_len = t + max_new_tokens + gamma + 1
    greedy = temperature == 0.0
    if key is None:
        key = jax.random.PRNGKey(0)  # unused on the greedy path

    def probs_of(logits):
        # filtered, temperature-scaled distribution in f32
        if greedy:
            return jax.nn.softmax(logits, axis=-1)
        return jax.nn.softmax(
            filter_logits(logits / temperature, top_k, top_p), axis=-1
        )

    def pick(p, k):
        if greedy:
            return jnp.argmax(p, axis=-1).astype(jnp.int32)
        return jax.random.categorical(k, jnp.log(p), axis=-1).astype(jnp.int32)

    # prefill both models on the full prompt; invariant from here on: both
    # caches have absorbed the same prefix and last_tok is NOT absorbed yet
    tgt_cache = init_kv_cache(target_cfg, b, max_len)
    dft_cache = init_kv_cache(draft_cfg, b, max_len)
    tgt_logits, tgt_cache = advance(target_params, tgt_cache, prompt, target_cfg)
    _, dft_cache = advance(draft_params, dft_cache, prompt, draft_cfg)
    key, k0 = jax.random.split(key)
    first = pick(probs_of(tgt_logits[:, -1]), k0)

    buf = jnp.zeros((b, max_new_tokens + gamma + 1), jnp.int32)
    buf = buf.at[:, 0].set(first)
    stats0 = SpecStats(
        rounds=jnp.zeros((), jnp.int32),
        drafted=jnp.zeros((), jnp.int32),
        accepted=jnp.zeros((), jnp.int32),
    )

    def round_body(state):
        tgt_cache, dft_cache, last_tok, buf, n_out, key, stats = state
        key, kd, ka, kr = jax.random.split(key, 4)

        # --- draft: propose gamma tokens (gamma single-token steps), plus
        # one extra step so the draft cache absorbs its own last proposal
        # (needed when every proposal is accepted)
        def draft_step(carry, k):
            cache, tok = carry
            logits, cache = advance(draft_params, cache, tok[:, None], draft_cfg)
            p = probs_of(logits[:, -1])
            nxt = pick(p, k)
            return (cache, nxt), (nxt, p)

        (dft_cache, last_draft), (t_draft, p_d) = lax.scan(
            draft_step, (dft_cache, last_tok), jax.random.split(kd, gamma)
        )
        _, dft_cache = advance(
            draft_params, dft_cache, last_draft[:, None], draft_cfg
        )
        t_draft = jnp.swapaxes(t_draft, 0, 1)  # [B, gamma]
        p_d = jnp.swapaxes(p_d, 0, 1)  # [B, gamma, V]

        # --- target: verify all proposals in one S=gamma+1 step
        tgt_in = jnp.concatenate([last_tok[:, None], t_draft], axis=1)
        tgt_logits, tgt_cache = advance(
            target_params, tgt_cache, tgt_in, target_cfg
        )
        p_t = probs_of(tgt_logits)  # [B, gamma+1, V]

        # --- acceptance: n_i = accepted prefix per sequence, n = batch min
        if greedy:
            acc = t_draft == jnp.argmax(p_t[:, :gamma], axis=-1)  # [B, gamma]
        else:
            pt_tok = jnp.take_along_axis(
                p_t[:, :gamma], t_draft[..., None], axis=-1
            )[..., 0]
            pd_tok = jnp.take_along_axis(p_d, t_draft[..., None], axis=-1)[..., 0]
            u = jax.random.uniform(ka, t_draft.shape)
            acc = u * pd_tok < pt_tok
        n_i = jnp.sum(jnp.cumprod(acc.astype(jnp.int32), axis=1), axis=1)  # [B]
        n = jnp.min(n_i)

        # --- emit n accepted draft tokens + one correction/bonus token
        if greedy:
            emit = jnp.argmax(p_t, axis=-1).astype(jnp.int32)  # [B, gamma+1]
        else:
            # residual resample at column n for sequences rejected there;
            # sequences whose own acceptance went past n keep their accepted
            # draft token; p_d padded with zeros at column gamma makes the
            # residual at n == gamma the plain bonus distribution p_t[gamma]
            p_d_pad = jnp.pad(p_d, ((0, 0), (0, 1), (0, 0)))
            p_t_n = p_t[:, n]  # [B, V] (dynamic row gather)
            residual = jnp.maximum(p_t_n - p_d_pad[:, n], 0.0)
            # float-exact draft==target leaves an empty residual; fall back
            # to the target distribution (rejection there has probability 0)
            residual = jnp.where(
                jnp.sum(residual, axis=-1, keepdims=True) > 0, residual, p_t_n
            )
            resample = jax.random.categorical(
                kr, jnp.log(residual), axis=-1
            ).astype(jnp.int32)
            t_pad = jnp.pad(t_draft, ((0, 0), (0, 1)))
            at_n = jnp.where(n_i > n, t_pad[:, n], resample)  # [B]
            cols = lax.iota(jnp.int32, gamma + 1)[None, :]
            emit = jnp.where(cols < n, t_pad, at_n[:, None])
        buf = lax.dynamic_update_slice(buf, emit, (0, n_out))
        new_last = emit[:, n]

        # --- roll both caches back to the accepted prefix (last_tok +
        # t_0..t_{n-1}); stale entries past length are masked by position
        rollback = tgt_cache.length - (gamma + 1) + (n + 1)
        tgt_cache = KVCache(tgt_cache.k, tgt_cache.v, rollback)
        dft_cache = KVCache(dft_cache.k, dft_cache.v, rollback)

        stats = SpecStats(
            rounds=stats.rounds + 1,
            drafted=stats.drafted + gamma,
            accepted=stats.accepted + n,
        )
        return (tgt_cache, dft_cache, new_last, buf, n_out + n + 1, key, stats)

    def cond(state):
        return state[4] < max_new_tokens

    state = (tgt_cache, dft_cache, first, buf, jnp.ones((), jnp.int32), key, stats0)
    (_, _, _, buf, _, _, stats) = lax.while_loop(cond, round_body, state)
    return buf[:, :max_new_tokens].astype(prompt.dtype), stats


def draft_serving_shardings(draft_cfg, mesh):
    """The one home of the draft shard-or-replicate policy: the (small)
    draft shards tensor-parallel when its head counts divide tp and is
    replicated otherwise — a replicated draft costs its tiny weights per
    device but keeps every draft step collective-free (a sharded draft pays
    GSPMD all-reduces per step like any tp model). Returns
    (shardings, sharded: bool)."""
    from jax.sharding import NamedSharding
    from jax.sharding import PartitionSpec as P

    from hivedscheduler_tpu.models import transformer as tm
    from hivedscheduler_tpu.models.decode import serving_shardings

    shardings = serving_shardings(draft_cfg, mesh, require=False)
    if shardings is not None:
        return shardings, True
    replicated = NamedSharding(mesh, P())
    return jax.tree.map(
        lambda spec: replicated, tm.sharding_specs(draft_cfg),
        is_leaf=lambda x: isinstance(x, P),
    ), False


def make_sharded_speculative(
    target_cfg: TransformerConfig,
    draft_cfg: TransformerConfig,
    mesh,
    max_new_tokens: int,
    *,
    gamma: int = 4,
    temperature: float = 0.0,
    top_k: int = 0,
    top_p: float = 1.0,
    quantized_target: bool = False,
):
    """Speculative serving over a dp x tp mesh: the (big) target runs
    tensor-parallel exactly like ``decode.make_sharded_generate``; the
    (small) draft shards the same way when its head counts divide tp and is
    replicated otherwise — a replicated draft costs its tiny weights per
    device and keeps every round's gamma single-token steps collective-free.

    Returns (jitted_run, target_shardings, draft_shardings,
    prompt_sharding); ``jitted_run(target_params, draft_params, prompt,
    key)`` -> ([B, max_new], SpecStats)."""
    import functools

    from jax.sharding import NamedSharding
    from jax.sharding import PartitionSpec as P

    from hivedscheduler_tpu.models import transformer as tm
    from hivedscheduler_tpu.models.decode import serving_shardings

    target_shardings = serving_shardings(
        target_cfg, mesh, quantized=quantized_target
    )
    draft_shardings, _ = draft_serving_shardings(draft_cfg, mesh)
    prompt_sharding = NamedSharding(mesh, P(("dp", "fsdp")))

    run = functools.partial(
        generate_speculative, gamma=gamma, temperature=temperature,
        top_k=top_k, top_p=top_p,
    )

    def wrapped(target_params, draft_params, prompt, key=None):
        return run(
            target_params, draft_params, prompt, target_cfg, draft_cfg,
            max_new_tokens, key=key,
        )

    from hivedscheduler_tpu.common import compileguard

    return (compileguard.jit(wrapped, guard_label="speculative.generate"),
            target_shardings, draft_shardings, prompt_sharding)
