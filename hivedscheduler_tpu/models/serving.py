"""Continuous-batching serving engine: ragged KV cache + slot recycling.

The reference scheduler hands out TPU slices; this is the serving runtime a
slice runs. ``decode.generate`` serves one fixed batch start-to-finish —
real serving traffic arrives continuously, and a static batch wastes the
chip whenever sequences finish early. This engine implements the
continuous-batching pattern (the core of modern LLM servers) TPU-first:

- **Static shapes, ragged content**: one [L, max_batch, max_len, H_kv, D]
  KV cache allocated up front; each row carries its own length. All jitted
  programs have fixed shapes — admission/retirement is Python-side slot
  bookkeeping, never a recompile.
- **Per-row positions**: the decode step advances every active row at its
  own absolute position (RoPE and the causal mask are computed from a
  [B] length vector, not a scalar), so rows at different depths share one
  MXU-batched step.
- **Bucketed prefill**: prompts are right-padded to power-of-two buckets,
  so at most log2(max_len) prefill programs ever compile; each prefill
  writes one row of the shared cache in place (donated).
- **Slot recycling**: a finished row (EOS or budget) frees its slot
  immediately; the next queued request prefills into it while the other
  rows keep decoding — chip occupancy tracks offered load, not the
  slowest request of a static batch.

No paging indirection: a TPU gets no benefit from non-contiguous KV blocks
(there is no per-block allocator to appease, unlike GPU VRAM heaps); the
fixed per-slot arena + recycling achieves the same utilization with dense,
layout-friendly slices.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Dict, List, NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax import lax

from hivedscheduler_tpu.models.decode import (
    dense_mlp,
    embed_tokens,
    filter_logits,
    final_logits,
    qkv_proj,
)
from hivedscheduler_tpu.models.transformer import (
    TransformerConfig,
    _rms_norm,
    load_weight,
)
from hivedscheduler_tpu.ops.attention import NEG_INF


class RaggedCache(NamedTuple):
    """KV cache with a per-row length: k/v [L, B, M, H_kv, D], lengths [B]."""

    k: jax.Array
    v: jax.Array
    lengths: jax.Array  # int32 [B] — tokens absorbed per row


def init_ragged_cache(cfg: TransformerConfig, max_batch: int, max_len: int) -> RaggedCache:
    shape = (cfg.n_layers, max_batch, max_len, cfg.kv_heads, cfg.head_dim)
    return RaggedCache(
        k=jnp.zeros(shape, cfg.dtype),
        v=jnp.zeros(shape, cfg.dtype),
        lengths=jnp.zeros((max_batch,), jnp.int32),
    )


def _ragged_attention(q, ck, cv, positions, scale):
    """q [B,S,H,D] at absolute per-row positions [B,S]; ck/cv [B,M,H_kv,D].
    Causal mask per row: key_pos <= position."""
    b, s_len, h, d = q.shape
    m_len, h_kv = ck.shape[1], ck.shape[2]
    gsz = h // h_kv
    qg = q.reshape(b, s_len, h_kv, gsz, d)
    s = jnp.einsum(
        "bshgd,bmhd->bhgsm", qg, ck, preferred_element_type=jnp.float32
    ) * scale
    key_pos = lax.iota(jnp.int32, m_len)
    mask = key_pos[None, None, :] <= positions[:, :, None]  # [B, S, M]
    s = jnp.where(mask[:, None, None], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum("bhgsm,bmhd->bshgd", p, cv.astype(jnp.float32))
    return o.reshape(b, s_len, h, d).astype(q.dtype)


def advance_ragged(
    params: Dict[str, Any],
    cache: RaggedCache,
    tokens: jax.Array,
    cfg: TransformerConfig,
    row: Optional[jax.Array] = None,
) -> tuple:
    """Absorb ``tokens`` and return (logits [B_t, S, vocab] f32, cache).

    Two modes sharing one implementation:

    - decode (``row is None``): tokens [B, 1], every row advances at its own
      ``cache.lengths[b]`` (rows are masked/ignored by the caller if idle);
    - prefill (``row`` given): tokens [1, S] written into cache row ``row``
      starting at position 0 (the row's previous content is dead — its
      length is reset to the real prompt length by the caller; padded tail
      positions write garbage past ``lengths`` that the causal mask never
      reads).
    """
    dtype = cfg.dtype
    if cfg.n_experts > 0:
        raise NotImplementedError("continuous batching serves dense models")
    b_t, s_len = tokens.shape
    if row is None:
        positions = cache.lengths[:, None] + lax.iota(jnp.int32, s_len)[None, :]
    else:
        positions = lax.iota(jnp.int32, s_len)[None, :]

    x = embed_tokens(params, tokens, dtype)
    scale = 1.0 / math.sqrt(cfg.head_dim)
    n_rows = cache.k.shape[1]

    def layer(x, scanned):
        lp, ck, cv = scanned  # ck/cv [B_rows, M, H_kv, D]
        h = _rms_norm(x, lp["attn_norm"])
        q, k_new, v_new = qkv_proj(lp, h, positions, cfg.rope_theta, dtype)
        if row is None:
            # decode: scatter each row's single token at its own length
            rows = lax.iota(jnp.int32, n_rows)
            ck = ck.at[rows, cache.lengths].set(k_new[:, 0].astype(ck.dtype))
            cv = cv.at[rows, cache.lengths].set(v_new[:, 0].astype(cv.dtype))
            att_k, att_v = ck, cv
        else:
            # prefill: overwrite [row, 0:S]
            ck = lax.dynamic_update_slice(
                ck, k_new.astype(ck.dtype), (row, 0, 0, 0)
            )
            cv = lax.dynamic_update_slice(
                cv, v_new.astype(cv.dtype), (row, 0, 0, 0)
            )
            att_k = lax.dynamic_slice_in_dim(ck, row, 1, axis=0)
            att_v = lax.dynamic_slice_in_dim(cv, row, 1, axis=0)
        attn = _ragged_attention(q, att_k, att_v, positions, scale)
        x = x + jnp.einsum("bshk,hkd->bsd", attn, load_weight(lp["wo"], dtype))
        h = _rms_norm(x, lp["mlp_norm"])
        x = x + dense_mlp(lp, h, dtype)
        return x, (ck, cv)

    x, (new_k, new_v) = lax.scan(
        lambda carry, scanned: layer(carry, scanned),
        x,
        (params["layers"], cache.k, cache.v),
    )
    logits = final_logits(params, x, dtype)
    if row is None:
        lengths = cache.lengths + 1
    else:
        lengths = cache.lengths  # caller sets the row's true prompt length
    return logits, RaggedCache(k=new_k, v=new_v, lengths=lengths)


@dataclasses.dataclass
class Request:
    """One serving request; ``tokens_out`` fills as the engine runs."""

    rid: int
    prompt: List[int]
    max_new_tokens: int
    tokens_out: List[int] = dataclasses.field(default_factory=list)
    done: bool = False


class ServingEngine:
    """Continuous-batching driver around the two jitted programs.

    ``submit()`` enqueues requests at any time; each ``step()`` admits
    queued requests into free slots (bucketed prefill) and advances every
    active slot by one token. ``run_until_drained()`` loops until every
    submitted request finished. Greedy or temperature/top-k/top-p sampling.
    """

    def __init__(
        self,
        params,
        cfg: TransformerConfig,
        max_batch: int = 8,
        max_len: int = 512,
        temperature: float = 0.0,
        top_k: int = 0,
        top_p: float = 1.0,
        eos_id: Optional[int] = None,
        seed: int = 0,
        mesh=None,
    ):
        """``mesh``: lay the engine out over a dp x tp serving mesh —
        params by ``decode.serving_shardings`` (tp shards heads/ff/vocab),
        cache rows over dp, the compact kv-head axis over tp. The jitted
        programs then run under GSPMD with XLA-inserted collectives;
        max_batch must divide the dp axis."""
        self.params = params
        self.cfg = cfg
        self.max_batch = max_batch
        self.max_len = max_len
        self.temperature = temperature
        self.top_k = top_k
        self.top_p = top_p
        self.eos_id = eos_id
        self._key = jax.random.PRNGKey(seed)
        self.cache = init_ragged_cache(cfg, max_batch, max_len)
        self.slots: List[Optional[Request]] = [None] * max_batch
        # host-side staging for the per-row feedback tokens: slots emit into
        # this array and ONE upload per decode step feeds the jitted program
        # (per-slot device scatters would cost B dispatches per step)
        self._last_host = [0] * max_batch
        self._token_sharding = None
        if mesh is not None:
            from jax.sharding import NamedSharding
            from jax.sharding import PartitionSpec as P

            from hivedscheduler_tpu.models.decode import serving_shardings
            from hivedscheduler_tpu.models.transformer import is_quantized_leaf

            quantized = is_quantized_leaf(params["lm_head"])
            sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
            dp = sizes.get("dp", 1) * sizes.get("fsdp", 1)
            if max_batch % dp:
                raise ValueError(
                    f"max_batch {max_batch} must divide the dp axis {dp}"
                )
            self.params = jax.device_put(
                params, serving_shardings(cfg, mesh, quantized=quantized)
            )
            row = ("dp", "fsdp")
            kv_sh = NamedSharding(mesh, P(None, row, None, "tp", None))
            self.cache = jax.device_put(self.cache, RaggedCache(
                k=kv_sh, v=kv_sh, lengths=NamedSharding(mesh, P(row)),
            ))
            self._token_sharding = NamedSharding(mesh, P(row))
        self.queue: List[Request] = []
        self._next_rid = 0
        self.steps = 0  # decode steps executed (for occupancy stats)
        self.slot_steps = 0  # sum of active slots over decode steps

        def decode_step(params, cache, last_tokens):
            logits, cache = advance_ragged(params, cache, last_tokens[:, None], cfg)
            return logits[:, 0], cache

        def prefill(params, cache, tokens, row):
            logits, cache = advance_ragged(params, cache, tokens, cfg, row=row)
            return logits[0], cache

        self._decode = jax.jit(decode_step, donate_argnums=(1,))
        # one compile per prompt bucket (tokens' S is static per call shape)
        self._prefill = jax.jit(prefill, donate_argnums=(1,))

    # -- request lifecycle -------------------------------------------------
    def submit(self, prompt: List[int], max_new_tokens: int) -> Request:
        if not prompt:
            raise ValueError("empty prompt")
        if max_new_tokens < 1:
            # the engine always emits the prefill token; a <1 budget would
            # silently over-deliver
            raise ValueError(f"max_new_tokens must be >= 1, got {max_new_tokens}")
        if len(prompt) + max_new_tokens > self.max_len:
            raise ValueError(
                f"prompt {len(prompt)} + max_new {max_new_tokens} exceeds "
                f"max_len {self.max_len}"
            )
        req = Request(self._next_rid, list(prompt), max_new_tokens)
        self._next_rid += 1
        self.queue.append(req)
        return req

    def _bucket(self, n: int) -> int:
        return min(self.max_len, 1 << max(1, (n - 1).bit_length()))

    def _admit(self) -> None:
        for slot in range(self.max_batch):
            if not self.queue:
                return
            if self.slots[slot] is not None:
                continue
            req = self.queue.pop(0)
            tokens = jnp.asarray(
                req.prompt + [0] * (self._bucket(len(req.prompt)) - len(req.prompt)),
                jnp.int32,
            )[None, :]
            logits, self.cache = self._prefill(
                self.params, self.cache, tokens, jnp.int32(slot)
            )
            # the row's true length is the unpadded prompt (padded tail
            # positions are never attended: mask keys > length-1)
            self.cache = self.cache._replace(
                lengths=self.cache.lengths.at[slot].set(len(req.prompt))
            )
            tok = self._pick(logits[len(req.prompt) - 1])
            self._emit(req, slot, tok)
            self.slots[slot] = None if req.done else req

    def _pick(self, logits_row) -> int:
        if self.temperature == 0.0:
            return int(jnp.argmax(logits_row))
        self._key, sub = jax.random.split(self._key)
        return int(jax.random.categorical(
            sub, filter_logits(logits_row / self.temperature, self.top_k, self.top_p)
        ))

    def _pick_batch(self, logits):
        """Pick for every row with ONE host transfer per decode step."""
        if self.temperature == 0.0:
            return jax.device_get(jnp.argmax(logits, axis=-1))
        self._key, sub = jax.random.split(self._key)
        return jax.device_get(jax.random.categorical(
            sub, filter_logits(logits / self.temperature, self.top_k, self.top_p),
            axis=-1,
        ))

    def _emit(self, req: Request, slot: int, tok: int) -> None:
        req.tokens_out.append(tok)
        self._last_host[slot] = tok
        if len(req.tokens_out) >= req.max_new_tokens or tok == self.eos_id:
            req.done = True

    # -- engine ticks ------------------------------------------------------
    def step(self) -> bool:
        """Admit + one decode step for all active slots. Returns whether any
        work remains (active slots or queued requests)."""
        self._admit()
        active = [s for s in range(self.max_batch) if self.slots[s] is not None]
        if active:
            last = jnp.asarray(self._last_host, jnp.int32)
            if self._token_sharding is not None:
                last = jax.device_put(last, self._token_sharding)
            logits, self.cache = self._decode(self.params, self.cache, last)
            self.steps += 1
            self.slot_steps += len(active)
            picked = self._pick_batch(logits)
            for slot in active:
                req = self.slots[slot]
                self._emit(req, slot, int(picked[slot]))
                if req.done:
                    self.slots[slot] = None  # recycle immediately
        return bool(self.queue) or any(s is not None for s in self.slots)

    def run_until_drained(self, max_steps: int = 100_000) -> None:
        for _ in range(max_steps):
            if not self.step():
                return
        raise RuntimeError(f"serving did not drain in {max_steps} steps")

    @property
    def occupancy(self) -> float:
        """Mean fraction of slots doing useful work per decode step."""
        return self.slot_steps / (self.steps * self.max_batch) if self.steps else 0.0
