"""Continuous-batching serving engine: ragged KV cache + slot recycling.

The reference scheduler hands out TPU slices; this is the serving runtime a
slice runs. ``decode.generate`` serves one fixed batch start-to-finish —
real serving traffic arrives continuously, and a static batch wastes the
chip whenever sequences finish early. This engine implements the
continuous-batching pattern (the core of modern LLM servers) TPU-first:

- **Static shapes, ragged content**: one [L, max_batch, max_len, H_kv, D]
  KV cache allocated up front; each row carries its own length. All jitted
  programs have fixed shapes — admission/retirement is Python-side slot
  bookkeeping, never a recompile.
- **Per-row positions**: the decode step advances every active row at its
  own absolute position (RoPE and the causal mask are computed from a
  [B] length vector, not a scalar), so rows at different depths share one
  MXU-batched step.
- **Bucketed prefill**: prompts are right-padded to power-of-two buckets,
  so at most log2(max_len) prefill programs ever compile; each prefill
  writes one row of the shared cache in place (donated).
- **Slot recycling**: a finished row (EOS or budget) frees its slot
  immediately; the next queued request prefills into it while the other
  rows keep decoding — chip occupancy tracks offered load, not the
  slowest request of a static batch.
- **Chunked prefill** (``prefill_chunk > 0``): prompts absorb at most
  that many tokens per engine step via offset prefills, so one long
  prompt's prefill interleaves with everyone else's decode steps instead
  of stalling them — bounded work per step, bit-exact streams.
- **Prefix caching** (``prefix_cache_size > 0``): the KV of recent prompts
  stays device-resident in an LRU; a new prompt that extends a cached one
  restores the prefix KV with one dynamic_update_slice and prefills only
  the tail — shared system prompts skip their prefill FLOPs entirely,
  bit-exactly (restored KV is identical to recomputation).

- **Paged KV cache** (``page_size > 0``): instead of one dense max-length
  slab per slot, KV lives in a device-resident block pool
  ([L, n_blocks, block, H_kv, D]) shared by every stream, with a host-side
  per-slot block table, free-list allocator and refcounts. Admission is
  gated on *block availability* rather than slot count, so concurrency per
  chip tracks the actual token footprint of the traffic, not the
  worst-case sequence length. The prefix cache is rekeyed on block-aligned
  token chunks: shared system prompts become reference-counted shared
  blocks (no copies), with copy-on-write the moment a stream writes into a
  shared block. ``HIVED_PAGED_KV=0`` forces the dense ragged path — the
  differential reference every paged stream must match token-exactly
  (guard: tests/test_serving_paged.py).

The dense ragged path (the default) remains the layout XLA likes best when
slots are short-lived and uniformly sized; paging is the lever for
mixed-length production traffic where dense slabs strand HBM on the
worst-case length.

Observability: every finished request publishes per-priority-class
queue-wait/TTFT/TPOT histograms into the shared Prometheus registry, and —
when ``obs.trace`` is enabled — queued/prefill/decode spans on its own
timeline lane (tid = rid) for the Perfetto export. See
doc/design/observability.md.
"""

from __future__ import annotations

import dataclasses
import math
import os
import time
from typing import Any, Dict, List, NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from hivedscheduler_tpu.common import compileguard
from hivedscheduler_tpu.models.decode import (
    dense_mlp,
    embed_tokens,
    filter_logits,
    final_logits,
    inference_moe_cfg,
    qkv_proj,
)
from hivedscheduler_tpu.models.transformer import (
    TransformerConfig,
    _moe_mlp,
    _rms_norm,
    load_weight,
)
from hivedscheduler_tpu.obs import goodput as obs_goodput
from hivedscheduler_tpu.obs import journal as obs_journal
from hivedscheduler_tpu.obs import trace as obs_trace
from hivedscheduler_tpu.ops.attention import NEG_INF, block_coords, gather_block_kv
from hivedscheduler_tpu.runtime.metrics import REGISTRY as metrics


def _stream_key(base_key, rid, count, tag: int = 0):
    """The engine's counter-based sampling key for (request, emitted
    position): fold_in(fold_in(base, rid), count), optionally folded with
    a purpose ``tag``. ONE home on purpose: the plain sampler (tag 0) and
    the speculative engine's proposal (0) / accept (1) / residual (2)
    draws MUST derive keys identically — the perfect-draft bit-exactness
    guarantee (a proposal is drawn with the very key the plain engine
    would use at that position) is structural only while they share this
    function."""
    k = jax.random.fold_in(jax.random.fold_in(base_key, rid), count)
    return jax.random.fold_in(k, tag) if tag else k


class RaggedCache(NamedTuple):
    """KV cache with a per-row length: k/v [L, B, M, H_kv, D], lengths [B].

    With int8 KV (``init_ragged_cache(kv_dtype="int8")``) k/v hold the
    quantized values and ``k_scale``/``v_scale`` [L, B, M, H_kv] the
    per-(position, head) symmetric absmax scales — decode then streams
    half the KV bytes from HBM (the long-context decode bottleneck), and
    the scales factor OUT of both attention einsums (score rows and
    probability columns), so no dequantized cache copy ever materializes
    — the int8->compute-dtype convert fuses into the cache read.
    Quantization happens once at scatter time; every engine composition
    (chunking, prefix cache, speculation) re-reads the same quantized
    entries, so int8 engines are BIT-EXACT among themselves — only the
    int8-vs-float comparison is approximate (bounded by absmax/127 per
    element; guard: tests/test_serving_int8kv.py)."""

    k: jax.Array
    v: jax.Array
    lengths: jax.Array  # int32 [B] — tokens absorbed per row
    k_scale: Optional[jax.Array] = None
    v_scale: Optional[jax.Array] = None

    @property
    def quantized(self) -> bool:
        return self.k_scale is not None


def init_ragged_cache(cfg: TransformerConfig, max_batch: int, max_len: int,
                      kv_dtype: Optional[str] = None) -> RaggedCache:
    shape = (cfg.n_layers, max_batch, max_len, cfg.kv_heads, cfg.head_dim)
    if kv_dtype == "int8":
        sshape = shape[:-1]
        return RaggedCache(
            k=jnp.zeros(shape, jnp.int8),
            v=jnp.zeros(shape, jnp.int8),
            lengths=jnp.zeros((max_batch,), jnp.int32),
            k_scale=jnp.zeros(sshape, jnp.float32),
            v_scale=jnp.zeros(sshape, jnp.float32),
        )
    if kv_dtype is not None:
        raise ValueError(f"kv_dtype must be None or 'int8', got {kv_dtype!r}")
    return RaggedCache(
        k=jnp.zeros(shape, cfg.dtype),
        v=jnp.zeros(shape, cfg.dtype),
        lengths=jnp.zeros((max_batch,), jnp.int32),
    )


def _quant_kv(x):
    """Symmetric per-(token, head) absmax int8: x [..., H_kv, D] ->
    (int8 values, f32 scales [..., H_kv])."""
    absmax = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=-1)
    scale = jnp.maximum(absmax, 1e-8) / 127.0
    q = jnp.clip(jnp.round(x.astype(jnp.float32) / scale[..., None]),
                 -127, 127).astype(jnp.int8)
    return q, scale


def _ragged_attention(q, ck, cv, positions, scale, ck_scale=None,
                      cv_scale=None):
    """q [B,S,H,D] at absolute per-row positions [B,S]; ck/cv [B,M,H_kv,D].
    Causal mask per row: key_pos <= position. With int8 KV the per-key
    scales multiply the score rows (k) and weight the probability columns
    (v) — algebraically identical to dequantizing the cache, without ever
    materializing a dequantized copy."""
    b, s_len, h, d = q.shape
    m_len, h_kv = ck.shape[1], ck.shape[2]
    gsz = h // h_kv
    qg = q.reshape(b, s_len, h_kv, gsz, d)
    s = jnp.einsum(
        "bshgd,bmhd->bhgsm", qg, ck.astype(qg.dtype),
        preferred_element_type=jnp.float32
    ) * scale
    if ck_scale is not None:
        s = s * jnp.transpose(ck_scale, (0, 2, 1))[:, :, None, None, :]
    key_pos = lax.iota(jnp.int32, m_len)
    mask = key_pos[None, None, :] <= positions[:, :, None]  # [B, S, M]
    s = jnp.where(mask[:, None, None], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    if cv_scale is not None:
        p = p * jnp.transpose(cv_scale, (0, 2, 1))[:, :, None, None, :]
    o = jnp.einsum("bhgsm,bmhd->bshgd", p, cv.astype(jnp.float32))
    return o.reshape(b, s_len, h, d).astype(q.dtype)


def advance_ragged(
    params: Dict[str, Any],
    cache: RaggedCache,
    tokens: jax.Array,
    cfg: TransformerConfig,
    row: Optional[jax.Array] = None,
    start: Optional[jax.Array] = None,
) -> tuple:
    """Absorb ``tokens`` and return (logits [B_t, S, vocab] f32, cache).

    Two modes sharing one implementation:

    - decode (``row is None``): tokens [B, 1], every row advances at its own
      ``cache.lengths[b]`` (rows are masked/ignored by the caller if idle);
    - prefill (``row`` given): tokens [1, S] written into cache row ``row``
      starting at position ``start`` (0 when omitted — a fresh prompt; a
      prefix-cache hit restores the prefix KV and prefills only the tail
      from ``start=prefix_len``). The row's previous content past the
      restored prefix is dead — its length is reset to the real prompt
      length by the caller; padded tail positions write garbage past
      ``lengths`` that the causal mask never reads.
    """
    dtype = cfg.dtype
    cfg = inference_moe_cfg(cfg)  # routing-exact: no-drop capacity
    b_t, s_len = tokens.shape
    if row is None:
        positions = cache.lengths[:, None] + lax.iota(jnp.int32, s_len)[None, :]
    else:
        offset = jnp.int32(0) if start is None else start
        positions = offset + lax.iota(jnp.int32, s_len)[None, :]

    x = embed_tokens(params, tokens, dtype)
    scale = 1.0 / math.sqrt(cfg.head_dim)
    n_rows = cache.k.shape[1]
    quantized = cache.quantized  # static: fixed by the cache's pytree shape

    def layer(x, scanned):
        if quantized:
            lp, ck, cv, cks, cvs = scanned  # + scales [B_rows, M, H_kv]
        else:
            lp, ck, cv = scanned  # ck/cv [B_rows, M, H_kv, D]
            cks = cvs = None
        h = _rms_norm(x, lp["attn_norm"])
        q, k_new, v_new = qkv_proj(lp, h, positions, cfg.rope_theta, dtype)
        if quantized:
            k_q, k_s = _quant_kv(k_new)
            v_q, v_s = _quant_kv(v_new)
        else:
            k_q, v_q = k_new, v_new
        if row is None:
            # decode: scatter each row's S tokens at its own length offset
            # (S=1 plain decode; S=gamma+1 speculative verify)
            rows = lax.iota(jnp.int32, n_rows)
            if s_len == 1:
                ck = ck.at[rows, cache.lengths].set(k_q[:, 0].astype(ck.dtype))
                cv = cv.at[rows, cache.lengths].set(v_q[:, 0].astype(cv.dtype))
                if quantized:
                    cks = cks.at[rows, cache.lengths].set(k_s[:, 0])
                    cvs = cvs.at[rows, cache.lengths].set(v_s[:, 0])
            else:
                # `positions` (built at entry) IS the scatter index set
                ck = ck.at[rows[:, None], positions].set(k_q.astype(ck.dtype))
                cv = cv.at[rows[:, None], positions].set(v_q.astype(cv.dtype))
                if quantized:
                    cks = cks.at[rows[:, None], positions].set(k_s)
                    cvs = cvs.at[rows[:, None], positions].set(v_s)
            att_k, att_v, att_ks, att_vs = ck, cv, cks, cvs
        else:
            # prefill: overwrite [row, start:start+S] (start is 0 for a
            # fresh prompt; the prefix-cache tail prefill offsets past the
            # restored prefix)
            off = jnp.int32(0) if start is None else start
            ck = lax.dynamic_update_slice(
                ck, k_q.astype(ck.dtype), (row, off, 0, 0)
            )
            cv = lax.dynamic_update_slice(
                cv, v_q.astype(cv.dtype), (row, off, 0, 0)
            )
            att_k = lax.dynamic_slice_in_dim(ck, row, 1, axis=0)
            att_v = lax.dynamic_slice_in_dim(cv, row, 1, axis=0)
            att_ks = att_vs = None
            if quantized:
                cks = lax.dynamic_update_slice(cks, k_s, (row, off, 0))
                cvs = lax.dynamic_update_slice(cvs, v_s, (row, off, 0))
                att_ks = lax.dynamic_slice_in_dim(cks, row, 1, axis=0)
                att_vs = lax.dynamic_slice_in_dim(cvs, row, 1, axis=0)
        attn = _ragged_attention(q, att_k, att_v, positions, scale,
                                 att_ks, att_vs)
        x = x + jnp.einsum("bshk,hkd->bsd", attn, load_weight(lp["wo"], dtype))
        h = _rms_norm(x, lp["mlp_norm"])
        if cfg.n_experts > 0:
            moe_out, _ = _moe_mlp(h, lp, cfg, dtype)
            x = x + moe_out
        else:
            x = x + dense_mlp(lp, h, dtype)
        if quantized:
            return x, (ck, cv, cks, cvs)
        return x, (ck, cv)

    if quantized:
        xs = (params["layers"], cache.k, cache.v, cache.k_scale,
              cache.v_scale)
    else:
        xs = (params["layers"], cache.k, cache.v)
    x, scanned_out = lax.scan(
        lambda carry, scanned: layer(carry, scanned), x, xs
    )
    if quantized:
        new_k, new_v, new_ks, new_vs = scanned_out
    else:
        new_k, new_v = scanned_out
        new_ks = new_vs = None
    logits = final_logits(params, x, dtype)
    if row is None:
        # all S tokens absorbed; a speculative verify caller rolls rows back
        # to its per-row accepted counts afterwards (stale tail entries are
        # rewritten by the next contiguous window before any query reaches
        # them — see SpeculativeServingEngine). Clamp at the arena size:
        # idle rows (retired slots, parked chunked prefills at max_len-1)
        # advance with every shared step too, and without the clamp their
        # lengths — and hence their RoPE positions and scatter indices —
        # would drift unboundedly past the arena. Rows pinned AT the clamp
        # still scatter at index max_len each step, which relies on JAX
        # dropping exactly that one out-of-bounds index (don't "harden"
        # these scatters with mode='promise_in_bounds'); the clamp bounds
        # the drift, it does not eliminate the drop-OOB reliance.
        lengths = jnp.minimum(cache.lengths + s_len, cache.k.shape[2])
    else:
        lengths = cache.lengths  # caller sets the row's true prompt length
    return logits, RaggedCache(k=new_k, v=new_v, lengths=lengths,
                               k_scale=new_ks, v_scale=new_vs)


class PagedKVPool(NamedTuple):
    """Paged KV: one block pool per layer, k/v [L, n_blocks, block, H_kv,
    D], shared by every stream. Block 0 is the reserved TRASH block: every
    unassigned block-table entry points at it, so clamped/idle scatters and
    padded-prefill garbage land somewhere no live position maps to.
    Lengths and block tables are HOST state (the engine owns the
    allocator); the pool itself carries no per-row bookkeeping. With int8
    KV the ``k_scale``/``v_scale`` pools [L, n_blocks, block, H_kv] travel
    with their blocks — a shared or COW-copied block is bit-identical to
    the original, values and scales together, so every exactness argument
    of :class:`RaggedCache` int8 mode carries over block-wise."""

    k: jax.Array
    v: jax.Array
    k_scale: Optional[jax.Array] = None
    v_scale: Optional[jax.Array] = None

    @property
    def quantized(self) -> bool:
        return self.k_scale is not None


def init_paged_pool(cfg: TransformerConfig, n_blocks: int, block: int,
                    kv_dtype: Optional[str] = None) -> PagedKVPool:
    shape = (cfg.n_layers, n_blocks, block, cfg.kv_heads, cfg.head_dim)
    if kv_dtype == "int8":
        return PagedKVPool(
            k=jnp.zeros(shape, jnp.int8),
            v=jnp.zeros(shape, jnp.int8),
            k_scale=jnp.zeros(shape[:-1], jnp.float32),
            v_scale=jnp.zeros(shape[:-1], jnp.float32),
        )
    if kv_dtype is not None:
        raise ValueError(f"kv_dtype must be None or 'int8', got {kv_dtype!r}")
    return PagedKVPool(
        k=jnp.zeros(shape, cfg.dtype),
        v=jnp.zeros(shape, cfg.dtype),
    )


def advance_paged(
    params: Dict[str, Any],
    pool: PagedKVPool,
    tokens: jax.Array,
    cfg: TransformerConfig,
    table: jax.Array,
    lengths: Optional[jax.Array] = None,
    row: Optional[jax.Array] = None,
    start: Optional[jax.Array] = None,
) -> tuple:
    """Paged twin of :func:`advance_ragged`: absorb ``tokens`` through the
    block-table indirection and return (logits [B_t, S, vocab] f32, pool).

    Same two modes: decode (``row is None``; tokens [B, S], per-row
    positions from ``lengths`` [B]) and prefill (``row`` given; tokens
    [1, S] written through ``table[row]`` from position ``start``). New
    k/v scatter at :func:`ops.attention.block_coords` (clamped — idle and
    parked rows write garbage that is rewritten before any query can
    attend it, the dense path's own invariant); the attention read is
    :func:`ops.attention.gather_block_kv` over the row's table, whose
    axis-1 index IS the logical position, so `_ragged_attention` and its
    int8-scale algebra apply unchanged. The transformer body (norms, QKV +
    RoPE, grouped attention, MoE/dense MLP) is the SAME shared helpers the
    dense path uses; the only divergence surface is the cache addressing,
    and the paged-vs-dense token-exactness differential
    (tests/test_serving_paged.py) pins that to zero.

    Length bookkeeping is the CALLER's (the engine's host-side allocator
    advances its own lengths); the returned pool is the only device-state
    change."""
    dtype = cfg.dtype
    cfg = inference_moe_cfg(cfg)  # routing-exact: no-drop capacity
    b_t, s_len = tokens.shape
    block = pool.k.shape[2]
    if row is None:
        positions = lengths[:, None] + lax.iota(jnp.int32, s_len)[None, :]
        tbl = table
    else:
        offset = jnp.int32(0) if start is None else start
        positions = (offset + lax.iota(jnp.int32, s_len))[None, :]
        tbl = lax.dynamic_slice_in_dim(table, row, 1, axis=0)  # [1, nbs]
    wblk, woff = block_coords(positions, tbl, block)

    x = embed_tokens(params, tokens, dtype)
    scale = 1.0 / math.sqrt(cfg.head_dim)
    quantized = pool.quantized  # static: fixed by the pool's pytree shape

    def layer(x, scanned):
        if quantized:
            lp, pk, pv, pks, pvs = scanned
        else:
            lp, pk, pv = scanned  # pk/pv [n_blocks, block, H_kv, D]
            pks = pvs = None
        h = _rms_norm(x, lp["attn_norm"])
        q, k_new, v_new = qkv_proj(lp, h, positions, cfg.rope_theta, dtype)
        if quantized:
            k_q, k_s = _quant_kv(k_new)
            v_q, v_s = _quant_kv(v_new)
        else:
            k_q, v_q = k_new, v_new
        # scatter BEFORE the gather/attention, exactly like the dense path:
        # the gathered view must include this call's own tokens
        pk = pk.at[wblk, woff].set(k_q.astype(pk.dtype))
        pv = pv.at[wblk, woff].set(v_q.astype(pv.dtype))
        if quantized:
            pks = pks.at[wblk, woff].set(k_s)
            pvs = pvs.at[wblk, woff].set(v_s)
        att_k = gather_block_kv(pk, tbl)
        att_v = gather_block_kv(pv, tbl)
        att_ks = gather_block_kv(pks, tbl) if quantized else None
        att_vs = gather_block_kv(pvs, tbl) if quantized else None
        attn = _ragged_attention(q, att_k, att_v, positions, scale,
                                 att_ks, att_vs)
        x = x + jnp.einsum("bshk,hkd->bsd", attn, load_weight(lp["wo"], dtype))
        h = _rms_norm(x, lp["mlp_norm"])
        if cfg.n_experts > 0:
            moe_out, _ = _moe_mlp(h, lp, cfg, dtype)
            x = x + moe_out
        else:
            x = x + dense_mlp(lp, h, dtype)
        if quantized:
            return x, (pk, pv, pks, pvs)
        return x, (pk, pv)

    if quantized:
        xs = (params["layers"], pool.k, pool.v, pool.k_scale, pool.v_scale)
    else:
        xs = (params["layers"], pool.k, pool.v)
    x, scanned_out = lax.scan(
        lambda carry, scanned: layer(carry, scanned), x, xs
    )
    if quantized:
        new_k, new_v, new_ks, new_vs = scanned_out
    else:
        new_k, new_v = scanned_out
        new_ks = new_vs = None
    logits = final_logits(params, x, dtype)
    return logits, PagedKVPool(k=new_k, v=new_v, k_scale=new_ks,
                               v_scale=new_vs)


class EngineDraining(RuntimeError):
    """Raised by ``submit()`` once ``begin_drain()`` was called: the engine
    finishes in-flight work but admits nothing new. The serving front-end
    maps this to HTTP 503 + ``Retry-After`` (the preempted-replica
    admission contract; see doc/design/fault-model.md)."""


@dataclasses.dataclass
class Request:
    """One serving request; ``tokens_out`` fills as the engine runs."""

    rid: int
    prompt: List[int]
    max_new_tokens: int
    tokens_out: List[int] = dataclasses.field(default_factory=list)
    done: bool = False
    # why the request finished: "eos" (stop token), "length" (budget
    # exhausted), "shed" (queue-wait deadline exceeded before admission —
    # the request never ran; tokens_out is empty), or "preempted" (the
    # engine's drain deadline expired before this request finished; its
    # stream is truncated at whatever was emitted)
    finish_reason: Optional[str] = None
    # admission priority: higher jumps the queue (FIFO within a level) —
    # the engine-level analogue of the scheduler's guaranteed-vs-
    # opportunistic ordering. Scheduling-only: a request's STREAM is
    # unaffected (greedy exactness and the counter-based sampled keys
    # depend on rid/prompt, not admission order).
    #
    # STARVATION CAVEAT: this is strict priority with no aging by default.
    # A sustained stream of higher-priority submissions keeps inserting
    # ahead of priority-0 waiters, which then never reach the queue head —
    # there is no bounded-wait guarantee for low-priority traffic. Callers
    # that need one can opt into bounded-wait aging
    # (``ServingEngine(age_boost_secs=...)`` / ``serve --age-boost-secs``:
    # one effective priority level per age_boost_secs waited), bound the
    # high-priority offered load themselves (or
    # periodically resubmit aged work at a boosted priority); the per-class
    # TTFT/queue-wait histograms (tpu_hive_serve_*_seconds{priority=...})
    # make starvation visible. ``queue_timeout_s`` converts unbounded
    # starvation into bounded, *observable* load shedding: an expired waiter
    # finishes with finish_reason="shed" (counted per class in
    # tpu_hive_serve_shed_total) instead of waiting forever — under
    # sustained overload the starved low-priority work is shed first, which
    # is the documented graceful degradation of strict priority.
    priority: int = 0
    # wall-clock bookkeeping (perf_counter): queue wait = admitted - submitted;
    # time-to-first-token = queue wait + prefill (the latency prefix caching
    # attacks); time-per-output-token = decode span / (tokens - 1)
    submitted_at: float = 0.0
    admitted_at: Optional[float] = None
    first_token_at: Optional[float] = None
    done_at: Optional[float] = None
    # request-flight recording (obs/journal.py REQUEST_LEGS): the journal
    # key this request's admission/first-token marks attribute into.
    # ``flight_decode`` picks the first-token leg name (``first_decode``
    # for a post-handoff decode leg, ``prefill`` otherwise);
    # ``flight_local`` means THIS engine owns the terminal (self-installed
    # via ``record_flights`` — fleet-installed flights are terminated by
    # the router, which outlives any one leg).
    flight: Optional[str] = None
    flight_decode: bool = False
    flight_local: bool = False

    @property
    def ttft_s(self) -> Optional[float]:
        if self.first_token_at is None:
            return None
        return self.first_token_at - self.submitted_at

    @property
    def queue_wait_s(self) -> Optional[float]:
        if self.admitted_at is None:
            return None
        return self.admitted_at - self.submitted_at

    @property
    def tpot_s(self) -> Optional[float]:
        """Mean seconds per output token after the first (None until done
        or when only one token was emitted)."""
        if self.done_at is None or self.first_token_at is None:
            return None
        n = len(self.tokens_out) - 1
        if n <= 0:
            return None
        return (self.done_at - self.first_token_at) / n


class ServingEngine:
    """Continuous-batching driver around the two jitted programs.

    ``submit()`` enqueues requests at any time; each ``step()`` admits
    queued requests into free slots (bucketed prefill) and advances every
    active slot by one token. ``run_until_drained()`` loops until every
    submitted request finished. Greedy or temperature/top-k/top-p sampling;
    sampled streams use counter-based keys (seed x rid x position), so they
    are reproducible across batch interleavings and arrival churn — greedy
    remains the bit-exact-vs-vanilla mode.

    ``ServingEngine(..., spec_decode=SpecDecodeConfig(...))`` constructs the
    speculative engine (:class:`SpeculativeServingEngine`) — speculative
    serving is a first-class mode of THIS constructor, composing with
    continuous batching, chunked prefill, the prefix cache and the paged
    KV cache, not a separate side engine.
    """

    # opt-in request-flight recording for SINGLE-engine serving: submit()
    # then opens a serve/<rid> flight in the journal and the engine owns
    # its terminal (serve.py flips this with --journal-file/HIVED_JOURNAL;
    # fleet-routed engines leave it False — the router installs fleet/<fid>
    # flights on the legs it dispatches)
    record_flights = False

    def __new__(cls, *args, **kw):
        # first-class speculative mode: spec_decode= routes construction to
        # the speculative subclass, whose __init__ then receives the same
        # arguments (Python calls __init__ on whatever __new__ returned)
        if cls is ServingEngine and kw.get("spec_decode") is not None:
            return super().__new__(SpeculativeServingEngine)
        return super().__new__(cls)

    def __init__(
        self,
        params,
        cfg: TransformerConfig,
        max_batch: int = 8,
        max_len: int = 512,
        temperature: float = 0.0,
        top_k: int = 0,
        top_p: float = 1.0,
        eos_id: Optional[int] = None,
        seed: int = 0,
        mesh=None,
        prefix_cache_size: int = 0,
        prefill_chunk: int = 0,
        kv_dtype: Optional[str] = None,
        queue_timeout_s: Optional[float] = None,
        age_boost_secs: Optional[float] = None,
        decode_steps: int = 1,
        page_size: int = 0,
        num_blocks: int = 0,
        spec_decode=None,
        clock=time.perf_counter,
    ):
        """``mesh``: lay the engine out over a dp x tp serving mesh —
        params by ``decode.serving_shardings`` (tp shards heads/ff/vocab),
        cache rows over dp, the compact kv-head axis over tp. The jitted
        programs then run under GSPMD with XLA-inserted collectives;
        max_batch must divide the dp axis.

        ``prefix_cache_size``: keep the KV of up to this many past prompts
        (device-resident, LRU) and, when a new prompt starts with a cached
        one, restore that prefix and prefill only the tail — the standard
        shared-system-prompt win. 0 disables (no extra HBM). Exactness is
        unaffected: restored KV is bit-identical to recomputation (guard:
        tests/test_serving_prefix.py).

        ``prefill_chunk``: absorb prompts at most this many tokens per
        engine step (0 = whole prompt at admission). A long prompt then
        cannot stall the decoding rows for its full prefill: each step runs
        one bounded chunk (offset prefill into the row) and one decode —
        the chunked-prefill fairness pattern. Exact: chunks write the same
        KV a monolithic prefill would (guard: tests/test_serving_chunked.py).

        ``kv_dtype``: ``"int8"`` stores the KV cache quantized (symmetric
        per-token-per-head absmax scales) — decode streams half the KV
        bytes from HBM; see RaggedCache. int8 engines are bit-exact among
        themselves under every composition; int8-vs-float differs by the
        bounded quantization error.

        ``queue_timeout_s``: per-request admission deadline. A queued
        request whose wait exceeds it is SHED before the next admission
        sweep — finished with ``finish_reason="shed"``, no tokens, counted
        per priority class in ``tpu_hive_serve_shed_total`` — bounding the
        strict-priority starvation caveat with observable load shedding
        instead of unbounded waits. ``None`` (default) never sheds.

        ``age_boost_secs``: bounded-wait aging for the strict-priority
        queue (see the starvation caveat on ``submit``/``Request.priority``).
        When set, a waiter's EFFECTIVE priority at admission time is
        ``priority + floor(wait / age_boost_secs)`` — every
        ``age_boost_secs`` seconds in queue buys one priority level, so any
        waiter eventually outranks a sustained stream of higher-priority
        arrivals and wait is bounded by
        ``(p_high - p_low) * age_boost_secs`` plus one admission sweep.
        Ties keep FIFO order within an effective level. ``None`` (default)
        keeps strict priority exactly as before.

        ``decode_steps``: run up to this many decode iterations inside ONE
        jitted ``lax.scan`` per engine step (sampling fused on device, the
        picked token fed straight back into the next iteration, cache
        donated through the carry) — the per-token Python dispatch + host
        sync then amortizes over the window, which is the decode tick's
        dominant cost for small models. The emitted streams are EXACT for
        any window size: greedy/sampled picks per row depend only on that
        row's logits and its counter-based key, rows that hit EOS or
        their budget inside a window have their surplus tokens
        computed-then-discarded (bounded waste, K-1 tokens), and the
        window adaptively collapses to 1 when a slot may finish by length
        inside it, when chunked prefills are mid-flight, or when EOS
        retirement could free a slot queued work is waiting on (see
        ``_fused_window``). 1 (default) = the step-by-step engine.
        Guard: tests/test_serving_multistep.py.

        ``page_size``/``num_blocks``: paged KV cache. ``page_size > 0``
        replaces the per-slot dense slab with one shared block pool of
        ``num_blocks`` blocks of ``page_size`` tokens (default
        ``max_batch * ceil(max_len/page_size) + 1`` — capacity parity with
        the dense slabs; size it SMALLER with a larger ``max_batch`` to get
        more concurrent streams out of the same KV HBM, which is the whole
        point). A host-side free-list allocator + per-slot block tables map
        logical positions to pool blocks; admission is gated on block
        availability (prompt-tail blocks + first-decode headroom) instead
        of slot count, the prefix cache shares reference-counted blocks at
        block-chunk granularity with copy-on-write on divergence, and pool
        exhaustion degrades in documented order: reclaim LRU cached prefix
        blocks, then preempt the youngest lowest-priority stream
        (``finish_reason="preempted"``). Streams are token-exact vs the
        dense path (``HIVED_PAGED_KV=0`` forces dense — the differential
        reference; guard: tests/test_serving_paged.py). Block 0 is the
        reserved trash block. With a mesh, the pool shards over tp on the
        kv-head axis; blocks cannot shard over dp (any block may back any
        slot), so paged + dp>1 raises.

        ``spec_decode``: a ``models.speculative.SpecDecodeConfig`` —
        constructs the speculative engine (see ``__new__``); None (default)
        is the plain engine.

        ``clock``: the engine's wall-clock source (``time.perf_counter``);
        injectable so overload/deadline behavior is testable
        deterministically."""
        if spec_decode is not None and type(self) is ServingEngine:
            raise ValueError("spec_decode requires the speculative engine "
                             "(ServingEngine.__new__ routes it; do not "
                             "bypass with a direct __init__ call)")
        self.params = params
        self.cfg = cfg
        self.queue_timeout_s = queue_timeout_s
        self.age_boost_secs = age_boost_secs
        self._clock = clock
        self.max_batch = max_batch
        self.max_len = max_len
        # read-only after construction: the jitted sampler closes over
        # them (mutating these attributes would NOT change sampling)
        self.temperature = temperature
        self.top_k = top_k
        self.top_p = top_p
        self.eos_id = eos_id
        # Counter-based sampling keys: each sampled token uses
        # fold_in(fold_in(seed_key, rid), n_emitted), so a request's
        # sampled stream is a pure function of (seed, rid, prompt) —
        # independent of batch interleaving, slot assignment, and arrival
        # order (a split-per-step chain would make sampled output depend
        # on scheduling churn). Greedy (temperature=0) stays the bit-exact
        # mode either way.
        base_key = jax.random.PRNGKey(seed)
        self._base_key = base_key  # subclasses derive per-row keys from it

        def sample_rows(logits, rids, counts):
            filtered = filter_logits(
                logits / temperature if temperature > 0.0 else logits,
                top_k, top_p,
            )
            keys = jax.vmap(
                lambda r, c: _stream_key(base_key, r, c))(rids, counts)
            return jax.vmap(jax.random.categorical)(keys, filtered)

        self._sample = compileguard.jit(sample_rows, guard_label="serve.sample")
        self.kv_dtype = kv_dtype
        # -- paged KV cache state (host-side allocator; see class docstring)
        self.page_size = max(0, page_size)
        self.paged = (self.page_size > 0
                      and os.environ.get("HIVED_PAGED_KV", "1") != "0")
        self._repl_sharding = None
        if self.paged:
            self._blocks_per_slot = -(-max_len // self.page_size)
            if num_blocks <= 0:
                # capacity parity with the dense slabs (+ the trash block)
                num_blocks = max_batch * self._blocks_per_slot + 1
            if num_blocks < self._blocks_per_slot + 1:
                raise ValueError(
                    f"num_blocks {num_blocks} cannot back one max_len "
                    f"stream: need >= ceil(max_len/page_size) + 1 "
                    f"(= {self._blocks_per_slot + 1}, incl. the reserved "
                    f"trash block)"
                )
            self.num_blocks = num_blocks
            # parked/idle rows write at the last addressable position; like
            # the dense sentinel it is at/past every live row's length, so
            # the garbage is rewritten before any query attends it
            self._park_pos = self._blocks_per_slot * self.page_size - 1
            self.pool = init_paged_pool(cfg, num_blocks, self.page_size,
                                        kv_dtype=kv_dtype)
            self._table = np.zeros((max_batch, self._blocks_per_slot),
                                   np.int32)
            self._host_len = np.full((max_batch,), self._park_pos, np.int32)
            self._slot_bids: List[List[int]] = [[] for _ in range(max_batch)]
            self._free: List[int] = list(range(1, num_blocks))
            self._ref = np.zeros((num_blocks,), np.int64)
            self.blocks_cow = 0
            self.pool_preempted = 0
            self.prefix_block_hits = 0
            self.cache = None
        else:
            self.pool = None
            self.cache = init_ragged_cache(cfg, max_batch, max_len,
                                           kv_dtype=kv_dtype)
        self.slots: List[Optional[Request]] = [None] * max_batch
        # host-side staging for the per-row feedback tokens: slots emit into
        # this array and ONE upload per decode step feeds the jitted program
        # (per-slot device scatters would cost B dispatches per step)
        self._last_host = [0] * max_batch
        self._token_sharding = None
        self._len_sharding = None
        if mesh is not None:
            from jax.sharding import NamedSharding
            from jax.sharding import PartitionSpec as P

            from hivedscheduler_tpu.models.decode import serving_shardings
            from hivedscheduler_tpu.models.transformer import is_quantized_leaf

            quantized = is_quantized_leaf(params["lm_head"])
            sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
            dp = sizes.get("dp", 1) * sizes.get("fsdp", 1)
            if max_batch % dp:
                raise ValueError(
                    f"max_batch {max_batch} must divide the dp axis {dp}"
                )
            self.params = jax.device_put(
                params, serving_shardings(cfg, mesh, quantized=quantized)
            )
            row = ("dp", "fsdp")
            self._len_sharding = NamedSharding(mesh, P(row))
            if self.paged:
                # blocks are fungible across slots, so the pool cannot shard
                # over a batch axis — only the compact kv-head axis over tp
                if dp != 1:
                    raise ValueError(
                        f"paged KV cache cannot shard blocks over dp/fsdp "
                        f"(axis size {dp}): any block may back any slot; "
                        f"use tp"
                    )
                pool_sh = NamedSharding(mesh, P(None, None, None, "tp", None))
                scale_sh = NamedSharding(mesh, P(None, None, None, "tp"))
                self.pool = jax.device_put(
                    self.pool,
                    PagedKVPool(
                        k=pool_sh, v=pool_sh,
                        k_scale=scale_sh if self.kv_dtype == "int8" else None,
                        v_scale=scale_sh if self.kv_dtype == "int8" else None,
                    ),
                )
                self._repl_sharding = NamedSharding(mesh, P())
            else:
                kv_sh = NamedSharding(mesh, P(None, row, None, "tp", None))
                self.cache = jax.device_put(
                    self.cache,
                    self._cache_shardings(kv_sh, self._len_sharding))
            self._token_sharding = NamedSharding(mesh, P(row))
        self.mesh = mesh
        self.queue: List[Request] = []
        self.draining = False  # see begin_drain()
        self._next_rid = 0
        self.steps = 0  # decode steps executed (for occupancy stats)
        self.slot_steps = 0  # sum of active slots over decode steps
        self.prefill_chunk = max(0, prefill_chunk)
        # slot -> (tail tokens, plen offset, pos absorbed): in-flight
        # chunked prefills; these slots are occupied but not yet decoding
        self._prefilling: Dict[int, tuple] = {}
        self.prefill_chunks_done = 0

        def decode_step(params, cache, last_tokens):
            logits, cache = advance_ragged(params, cache, last_tokens[:, None], cfg)
            return logits[:, 0], cache

        def prefill(params, cache, tokens, row, start):
            logits, cache = advance_ragged(params, cache, tokens, cfg, row=row,
                                           start=start)
            return logits[0], cache

        self._decode = compileguard.jit(
            decode_step, guard_label="serve.decode", donate_argnums=(1,))
        # one compile per prompt bucket (tokens' S is static per call shape)
        self._prefill = compileguard.jit(
            prefill, guard_label="serve.prefill", donate_argnums=(1,))

        if decode_steps < 1:
            raise ValueError(f"decode_steps must be >= 1, got {decode_steps}")
        self.decode_steps = decode_steps
        self.fused_windows = 0  # multi-step windows executed (k > 1)

        def decode_multi(params, cache, last_tokens, rids, counts, k):
            """``k`` fused decode iterations in one scan: the pick (argmax
            or the counter-keyed sampler — identical math to sample_rows)
            runs on device and feeds straight back, so the host sees one
            dispatch + one [B, k] transfer per window instead of k."""

            def body(carry, i):
                cache, last = carry
                logits, cache = advance_ragged(params, cache, last[:, None],
                                               cfg)
                row_logits = logits[:, 0]
                if temperature == 0.0:
                    tok = jnp.argmax(row_logits, axis=-1)
                else:
                    filtered = filter_logits(
                        row_logits / temperature, top_k, top_p
                    )
                    step_i = i.astype(counts.dtype)
                    keys = jax.vmap(
                        lambda r, c: _stream_key(base_key, r, c + step_i)
                    )(rids, counts)
                    tok = jax.vmap(jax.random.categorical)(keys, filtered)
                tok = tok.astype(jnp.int32)
                return (cache, tok), tok

            (cache, _), toks = lax.scan(
                body, (cache, last_tokens), jnp.arange(k)
            )
            return jnp.swapaxes(toks, 0, 1), cache  # toks [B, k]

        # one compile per distinct window size (bounded by _fused_window's
        # power-of-two bucketing)
        self._decode_multi = compileguard.jit(
            decode_multi, guard_label="serve.decode_multi",
            static_argnums=(5,), donate_argnums=(1,))

        # -- paged twins of the three programs (block table + host lengths
        # travel as arguments; the pool is donated like the dense cache) ---
        if self.paged:
            park = self._park_pos

            def paged_decode(params, pool, last_tokens, table, lengths):
                logits, pool = advance_paged(params, pool,
                                             last_tokens[:, None], cfg,
                                             table, lengths)
                return logits[:, 0], pool

            def paged_prefill(params, pool, tokens, table, row, start):
                logits, pool = advance_paged(params, pool, tokens, cfg,
                                             table, row=row, start=start)
                return logits[0], pool

            def paged_decode_multi(params, pool, last_tokens, table,
                                   lengths, rids, counts, k):
                """Paged fused window: same pick math as decode_multi, with
                the per-iteration lengths carried in the scan (the host
                advances its own copy by k afterwards). Idle rows clamp at
                the park sentinel — their writes stay in trash."""

                def body(carry, i):
                    pool, last, lens = carry
                    logits, pool = advance_paged(params, pool, last[:, None],
                                                 cfg, table, lens)
                    row_logits = logits[:, 0]
                    if temperature == 0.0:
                        tok = jnp.argmax(row_logits, axis=-1)
                    else:
                        filtered = filter_logits(
                            row_logits / temperature, top_k, top_p
                        )
                        step_i = i.astype(counts.dtype)
                        keys = jax.vmap(
                            lambda r, c: _stream_key(base_key, r, c + step_i)
                        )(rids, counts)
                        tok = jax.vmap(jax.random.categorical)(keys, filtered)
                    tok = tok.astype(jnp.int32)
                    lens = jnp.minimum(lens + 1, jnp.int32(park))
                    return (pool, tok, lens), tok

                (pool, _, _), toks = lax.scan(
                    body, (pool, last_tokens, lengths), jnp.arange(k)
                )
                return jnp.swapaxes(toks, 0, 1), pool  # toks [B, k]

            quant_pool = kv_dtype == "int8"

            def copy_block(pool, src, dst):
                """COW: duplicate block ``src`` into the freshly allocated
                ``dst`` across every layer (values AND scales — the copy is
                bit-identical, so a diverging stream's history matches the
                shared original exactly up to its divergence point)."""

                def cp(a):
                    return a.at[:, dst].set(a[:, src])

                upd = dict(k=cp(pool.k), v=cp(pool.v))
                if quant_pool:
                    upd["k_scale"] = cp(pool.k_scale)
                    upd["v_scale"] = cp(pool.v_scale)
                return pool._replace(**upd)

            def import_block(pool, dst, *parts):
                """Fleet KV handoff: write one shipped block's contents
                (values + scales for int8) into freshly-allocated block
                ``dst``. Block-shaped, so ONE program serves any prefix
                length — the per-block loop in import_prefix never
                recompiles."""
                kc, vc = parts[0], parts[1]
                upd = dict(k=pool.k.at[:, dst].set(kc.astype(pool.k.dtype)),
                           v=pool.v.at[:, dst].set(vc.astype(pool.v.dtype)))
                if quant_pool:
                    upd["k_scale"] = pool.k_scale.at[:, dst].set(parts[2])
                    upd["v_scale"] = pool.v_scale.at[:, dst].set(parts[3])
                return pool._replace(**upd)

            self._import_block = compileguard.jit(
                import_block, guard_label="serve.import_block",
                donate_argnums=(0,))
            self._paged_decode = compileguard.jit(
                paged_decode, guard_label="serve.paged_decode",
                donate_argnums=(1,))
            self._paged_prefill = compileguard.jit(
                paged_prefill, guard_label="serve.paged_prefill",
                donate_argnums=(1,))
            self._paged_decode_multi = compileguard.jit(
                paged_decode_multi, guard_label="serve.paged_decode_multi",
                static_argnums=(7,), donate_argnums=(1,)
            )
            self._copy_block = compileguard.jit(
                copy_block, guard_label="serve.copy_block",
                donate_argnums=(0,))

        # -- prompt prefix cache (LRU over device-resident KV rows) --------
        from collections import OrderedDict

        self.prefix_cache_size = max(0, prefix_cache_size)
        # prompt tuple -> (payload, true_len); payload is whatever
        # _prefix_extract returns — treat it as OPAQUE: (k [L, Pb, H_kv,
        # D], v) for a float cache, (k, v, k_scale, v_scale) for int8,
        # and the speculative engine nests target+draft payloads. Pb is
        # the prompt's prefill bucket, so restores compile once per bucket
        self._prefix_cache: "OrderedDict[tuple, tuple]" = OrderedDict()
        self.prefix_hits = 0
        self.prefix_tokens_reused = 0

        quant_kv = kv_dtype == "int8"

        def restore_prefix(cache, payload, row):
            """Write a cached prefix payload into slot ``row`` at [0:Pb]
            (values + scales for a quantized cache — a restored quantized
            prefix is bit-identical to the stored one)."""
            pk, pv = payload[0], payload[1]
            k = lax.dynamic_update_slice(cache.k, pk[:, None], (0, row, 0, 0, 0))
            v = lax.dynamic_update_slice(cache.v, pv[:, None], (0, row, 0, 0, 0))
            upd = dict(k=k, v=v)
            if quant_kv:
                upd["k_scale"] = lax.dynamic_update_slice(
                    cache.k_scale, payload[2][:, None], (0, row, 0, 0))
                upd["v_scale"] = lax.dynamic_update_slice(
                    cache.v_scale, payload[3][:, None], (0, row, 0, 0))
            return cache._replace(**upd)

        def extract_prefix(cache, row, pb):
            """Copy slot ``row``'s [0:pb] KV out as a standalone prefix row."""
            l_, _, _, h_kv, hd = cache.k.shape
            k = lax.dynamic_slice(cache.k, (0, row, 0, 0, 0),
                                  (l_, 1, pb, h_kv, hd))[:, 0]
            v = lax.dynamic_slice(cache.v, (0, row, 0, 0, 0),
                                  (l_, 1, pb, h_kv, hd))[:, 0]
            if quant_kv:
                ks = lax.dynamic_slice(cache.k_scale, (0, row, 0, 0),
                                       (l_, 1, pb, h_kv))[:, 0]
                vs = lax.dynamic_slice(cache.v_scale, (0, row, 0, 0),
                                       (l_, 1, pb, h_kv))[:, 0]
                return k, v, ks, vs
            return k, v

        self._restore_prefix = compileguard.jit(
            restore_prefix, guard_label="serve.restore_prefix",
            donate_argnums=(0,))
        self._extract_prefix = compileguard.jit(
            extract_prefix, guard_label="serve.extract_prefix",
            static_argnums=(2,))

    def _cache_shardings(self, kv_sh, len_sh):
        """Sharding pytree matching this engine's cache structure. The
        scale sharding is the kv spec with the head-dim axis dropped, so
        target and draft caches stay on one layout rule."""
        if self.kv_dtype != "int8":
            return RaggedCache(k=kv_sh, v=kv_sh, lengths=len_sh)
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as P

        scale_sh = NamedSharding(kv_sh.mesh, P(*kv_sh.spec[:-1]))
        return RaggedCache(k=kv_sh, v=kv_sh, lengths=len_sh,
                           k_scale=scale_sh, v_scale=scale_sh)

    # -- paged block allocator (host-side; device state is only the pool) --
    #
    # Invariants (pinned by chaos.invariants.check_block_pool):
    # - block 0 (trash) is never allocated, never refcounted;
    # - every other block is either on the free list (ref 0) or referenced
    #   (ref = #slots holding it in their block table + #prefix-cache
    #   entries naming it) — no leak, no double-alloc;
    # - a slot's table row is exactly its owned/shared bids then trash;
    # - a block a stream WRITES into has ref 1 (copy-on-write splits any
    #   shared block before the write reaches it).

    @property
    def blocks_in_use(self) -> int:
        return (self.num_blocks - 1 - len(self._free)) if self.paged else 0

    def _table_dev(self):
        t = jnp.asarray(self._table)
        if self._repl_sharding is not None:
            t = jax.device_put(t, self._repl_sharding)
        return t

    def _len_dev(self):
        ln = jnp.asarray(self._host_len)
        if self._repl_sharding is not None:
            ln = jax.device_put(ln, self._repl_sharding)
        return ln

    def _blocks_admit(self, req: Request, hit) -> bool:
        """Admission control by block availability: the prompt needs
        ``cover - full_shared`` new blocks (fresh tail blocks, plus the COW
        replacement of a partially-shared boundary block), and one spare
        when the first decode token starts a fresh block. LRU cached prefix
        blocks are reclaimed to make room (the matched entry is protected);
        False leaves the waiter queued — head-of-line, so admission order
        is preserved."""
        bs = self.page_size
        plen = hit[1][1] if hit is not None else 0
        cover = -(-len(req.prompt) // bs)
        want = cover - plen // bs
        if len(req.prompt) % bs == 0:
            want += 1
        protect = hit[0] if hit is not None else None
        while len(self._free) < want and self._reclaim_cache_block(protect):
            pass
        return len(self._free) >= want

    def _reclaim_cache_block(self, protect=None) -> bool:
        """Evict ONE LRU prefix-cache entry (never ``protect``) under pool
        pressure. Returns whether an entry was evicted — its blocks only
        actually free when no live stream still shares them."""
        for key in list(self._prefix_cache):  # OrderedDict: LRU first
            if key == protect:
                continue
            payload, _plen = self._prefix_cache.pop(key)
            self._drop_entry(payload)
            return True
        return False

    def _preempt_for_blocks(self, protect_slot: Optional[int]) -> bool:
        """Last-resort pool-pressure relief: truncate the youngest stream
        of the lowest priority class (never ``protect_slot``) with
        ``finish_reason="preempted"`` and free its blocks. The shed
        ordering mirrors queue shedding: low-priority work degrades first,
        observably (tpu_hive_serve_pool_preempted_total)."""
        victims = [s for s in range(self.max_batch)
                   if s != protect_slot and self.slots[s] is not None
                   and self._slot_bids[s]]
        if not victims:
            return False
        victim = max(victims, key=lambda s: (
            -self.slots[s].priority, self.slots[s].admitted_at or 0.0))
        req = self.slots[victim]
        req.done = True
        req.done_at = self._clock()
        req.finish_reason = "preempted"
        if obs_journal.JOURNAL.enabled:
            obs_journal.emit("serve_preempt", f"serve/{req.rid}",
                             slot=victim, priority=req.priority)
        self._observe_request(req)
        metrics.inc("tpu_hive_serve_pool_preempted_total")
        self.pool_preempted += 1
        self._retire(victim)
        return True

    def _alloc_block(self, protect_slot: Optional[int] = None) -> int:
        while not self._free:
            if self._reclaim_cache_block():
                continue
            if not self._preempt_for_blocks(protect_slot):
                raise RuntimeError(
                    "paged KV pool exhausted with nothing reclaimable — "
                    "num_blocks cannot back even one stream (constructor "
                    "validation should have caught this)"
                )
        bid = self._free.pop()
        self._ref[bid] = 1
        return bid

    def _decref(self, bid: int) -> None:
        self._ref[bid] -= 1
        assert self._ref[bid] >= 0, f"negative refcount on block {bid}"
        if self._ref[bid] == 0:
            self._free.append(bid)

    def _ensure_writable(self, slot: int, lo: int, hi: int) -> None:
        """Make positions [lo, hi] of ``slot`` writable: allocate blocks up
        to hi's cover, and copy-on-write any block in the write range that
        is still shared (ref > 1). Every engine write path runs through
        this first — admission/tail prefill, the decode boundary, fused
        windows, speculative verify — so a shared block is never written."""
        bs = self.page_size
        bids = self._slot_bids[slot]
        hi_cover = min(hi // bs + 1, self._blocks_per_slot)
        while len(bids) < hi_cover:
            bid = self._alloc_block(slot)
            self._table[slot, len(bids)] = bid
            bids.append(bid)
        for j in range(max(0, lo // bs), hi_cover):
            if self._ref[bids[j]] > 1:
                dst = self._alloc_block(slot)
                self.pool = self._copy_block(self.pool, jnp.int32(bids[j]),
                                             jnp.int32(dst))
                self._decref(bids[j])
                bids[j] = dst
                self._table[slot, j] = dst
                self.blocks_cow += 1
                metrics.inc("tpu_hive_serve_block_cow_total")

    def _trim_blocks(self, slot: int, keep_tokens: int) -> None:
        """Roll the block table back past ``keep_tokens`` (speculative
        rollback: rejected-tail blocks return to the pool; NO cache copy —
        kept blocks' stale tail entries are rewritten by the next
        contiguous window before any query reaches them)."""
        keep = -(-keep_tokens // self.page_size)
        bids = self._slot_bids[slot]
        while len(bids) > keep:
            bid = bids.pop()
            self._table[slot, len(bids)] = 0
            self._decref(bid)

    def _release_blocks(self, slot: int) -> None:
        for bid in self._slot_bids[slot]:
            self._decref(bid)
        self._slot_bids[slot] = []
        self._table[slot, :] = 0
        self._host_len[slot] = self._park_pos

    def _retire(self, slot: int) -> None:
        """Free the slot (request finished or preempted): ONE home for the
        recycle so the paged allocator cannot leak a retired row's blocks."""
        self.slots[slot] = None
        self._prefilling.pop(slot, None)
        if self.paged:
            self._release_blocks(slot)

    def _set_row_length(self, slot: int, n: int) -> None:
        if self.paged:
            self._host_len[slot] = n
        else:
            self.cache = self.cache._replace(
                lengths=self.cache.lengths.at[slot].set(n)
            )

    def _run_prefill(self, slot: int, tokens, start: int):
        """Dispatch one (possibly offset) prefill through the active cache
        backend; returns the [S, vocab] logits."""
        if self.paged:
            logits, self.pool = self._paged_prefill(
                self.params, self.pool, tokens, self._table_dev(),
                jnp.int32(slot), jnp.int32(start)
            )
            return logits
        logits, self.cache = self._prefill(
            self.params, self.cache, tokens, jnp.int32(slot),
            jnp.int32(start)
        )
        return logits

    def _store_payload(self, slot: int, bids, plen: int):
        """Paged prefix-cache entry payload for ``slot``'s first ``plen``
        tokens (the speculative engine bundles a draft-KV copy alongside
        the shared target block ids)."""
        return tuple(bids)

    def _drop_entry(self, payload) -> None:
        """Release one evicted prefix-cache entry's block references."""
        if self.paged:
            for bid in self._entry_bids(payload):
                self._decref(bid)

    def _entry_bids(self, payload):
        return payload

    # -- request lifecycle -------------------------------------------------
    def submit(self, prompt: List[int], max_new_tokens: int,
               priority: int = 0) -> Request:
        """Enqueue a request. ``priority``: higher is admitted first when
        slots free up (FIFO within a level; running rows are never
        preempted — admission ordering only, so every request's stream is
        unchanged).

        Strict priority, NO aging by default: a sustained stream of
        higher-priority submissions starves lower-priority waiters
        indefinitely (each new high-priority request inserts ahead of
        them). If bounded wait matters, construct the engine with
        ``age_boost_secs`` (one priority level per ``age_boost_secs``
        seconds waited — ``serve --age-boost-secs``), cap the
        high-priority offered load, or re-submit aged requests at a
        boosted priority — see ``Request.priority``."""
        if self.draining:
            metrics.inc("tpu_hive_serve_drain_rejected_total")
            raise EngineDraining(
                "engine is draining (preemption requested): new requests "
                "are rejected — retry on another replica"
            )
        if not prompt:
            raise ValueError("empty prompt")
        if max_new_tokens < 1:
            # the engine always emits the prefill token; a <1 budget would
            # silently over-deliver
            raise ValueError(f"max_new_tokens must be >= 1, got {max_new_tokens}")
        if len(prompt) + max_new_tokens > self.max_len:
            raise ValueError(
                f"prompt {len(prompt)} + max_new {max_new_tokens} exceeds "
                f"max_len {self.max_len}"
            )
        req = Request(self._next_rid, list(prompt), max_new_tokens,
                      priority=priority, submitted_at=self._clock())
        self._next_rid += 1
        if self.record_flights and obs_journal.JOURNAL.enabled:
            # single-engine flight (serve CLI): this engine owns the whole
            # request path, terminal included. Fleet legs instead carry
            # the router-installed fleet/<fid> flight (req.flight set by
            # FleetRouter after this submit returns).
            req.flight = f"serve/{req.rid}"
            req.flight_local = True
            obs_journal.note_request_submit(
                req.flight, at=req.submitted_at, priority=priority,
                promptTokens=len(req.prompt))
        # stable insertion keeps FIFO within a priority level: insert
        # before the first strictly-lower-priority waiter
        at = len(self.queue)
        for i, w in enumerate(self.queue):
            if w.priority < priority:
                at = i
                break
        self.queue.insert(at, req)
        if obs_journal.JOURNAL.enabled:
            obs_journal.emit("serve_submit", f"serve/{req.rid}",
                             priority=priority,
                             promptTokens=len(req.prompt))
        return req

    def _bucket(self, n: int) -> int:
        return min(self.max_len, 1 << max(1, (n - 1).bit_length()))

    def _match_prefix(self, prompt: List[int]):
        """Longest cached prompt that strictly prefixes ``prompt`` (strict:
        the tail prefill needs >= 1 token to produce the next-token logits).
        The offset tail write must also stay inside the arena — a bucketed
        tail that would clamp against max_len falls back to a full prefill."""
        best = None
        for key, entry in self._prefix_cache.items():
            plen = entry[1]
            if plen >= len(prompt) or (best is not None and plen <= best[1][1]):
                continue
            if list(key) == prompt[:len(key)]:
                if plen + self._bucket(len(prompt) - plen) > self.max_len:
                    continue
                best = (key, entry)
        if best is not None:
            self._prefix_cache.move_to_end(best[0])  # LRU touch
        return best

    def _prefix_extract(self, slot: int, pb: int):
        """Copy slot ``slot``'s [0:pb] KV out as an opaque prefix payload
        (subclasses with auxiliary caches extract those too)."""
        return self._extract_prefix(self.cache, jnp.int32(slot), pb)

    def _prefix_restore(self, slot: int, payload) -> None:
        """Write a cached payload back into slot ``slot``. Paged: the
        payload IS the shared block ids — the slot takes a reference on
        each and points its table at them; no device copy (divergence
        copies later, on write, via _ensure_writable's COW)."""
        if self.paged:
            # payload here is the bids tuple itself (the speculative
            # override unpacks its bundled draft copy before delegating)
            bids = list(payload)
            assert not self._slot_bids[slot], "restore into an occupied row"
            for j, bid in enumerate(bids):
                self._ref[bid] += 1
                self._table[slot, j] = bid
            self._slot_bids[slot] = bids
            self.prefix_block_hits += len(bids)
            metrics.inc("tpu_hive_serve_prefix_block_hits_total", len(bids))
            return
        self.cache = self._restore_prefix(self.cache, payload, jnp.int32(slot))

    def _store_prefix(self, slot: int, prompt: List[int]) -> None:
        """Cache the row's KV under the full prompt AND interior
        boundaries below it: two prompts sharing only a system prompt
        never prefix each other wholly, but they match at chunk
        granularity. The dense path snapshots power-of-two boundaries
        (each entry is a real device copy, so the count must stay
        logarithmic); the paged path registers EVERY full-block boundary —
        an entry is just refcounts on the live blocks (O(1), no copy), and
        block-aligned chunk keys are exactly what block-granular sharing
        can serve. ``prefix_cache_size`` counts entries either way."""
        pl = len(prompt)
        lens = {pl}
        if self.paged:
            pb = self.page_size
            while pb < pl:
                lens.add(pb)
                pb += self.page_size
        else:
            pb = 2
            while pb < pl:
                lens.add(pb)
                pb <<= 1
        # ascending, capped at capacity: the LONGEST prefixes insert last so
        # LRU eviction discards the short (least valuable) entries first,
        # and entries this very batch would evict are never extracted (each
        # dense extraction is a real [L, Pb, H_kv, D] x2 device copy)
        for plen in sorted(lens)[-self.prefix_cache_size:]:
            key = tuple(prompt[:plen])
            if key in self._prefix_cache:
                self._prefix_cache.move_to_end(key)
                continue
            if self.paged:
                bids = self._slot_bids[slot][: -(-plen // self.page_size)]
                for bid in bids:
                    self._ref[bid] += 1
                payload = self._store_payload(slot, bids, plen)
            else:
                payload = self._prefix_extract(slot, self._bucket(plen))
            self._prefix_cache[key] = (payload, plen)
        while len(self._prefix_cache) > self.prefix_cache_size:
            _, (payload, _plen) = self._prefix_cache.popitem(last=False)
            self._drop_entry(payload)  # paged: drop the block references

    # -- cross-replica prefix shipping (the fleet tier's KV handoff;
    # doc/design/fleet.md) -------------------------------------------------
    def export_prefix(self, prompt: List[int]):
        """Host-side copy of the longest cached STRICT prefix of
        ``prompt`` — the ship leg of the fleet KV handoff. Returns None on
        a cache miss, else ``(key, plen, data)`` where ``data`` is an
        opaque host payload consumable by :meth:`import_prefix` on a
        config-identical engine (same TransformerConfig, page_size and
        kv_dtype). Exactness: the shipped bytes are bit-identical copies
        of this engine's cached KV, which is itself bit-identical to what
        the importing replica would compute for the same prompt prefix
        (same params, deterministic prefill) — so a decode leg resumed
        from an imported prefix is token-exact vs local serving (guard:
        tests/test_fleet_router.py)."""
        hit = self._match_prefix(list(prompt))
        if hit is None:
            return None
        key, (payload, plen) = hit
        if self.paged:
            idx = jnp.asarray(list(self._entry_bids(payload)), jnp.int32)
            data = {"k": np.asarray(self.pool.k[:, idx]),
                    "v": np.asarray(self.pool.v[:, idx])}
            if self.kv_dtype == "int8":
                data["k_scale"] = np.asarray(self.pool.k_scale[:, idx])
                data["v_scale"] = np.asarray(self.pool.v_scale[:, idx])
        else:
            data = tuple(np.asarray(a) for a in payload)
        return key, plen, data

    def import_prefix(self, key, plen: int, data) -> bool:
        """Install a shipped prefix payload (from :meth:`export_prefix` on
        a config-identical engine) into this engine's prefix cache: the
        receive leg of the fleet KV handoff. The next ``submit()`` of a
        prompt extending ``key`` restores the imported KV and prefills
        only the tail — the ordinary prefix-hit path, so every exactness
        and accounting argument of the local cache carries over (paged:
        the imported blocks are allocated from this pool and refcounted
        exactly like locally-stored entries; check_block_pool covers
        them). Returns False when the key is already cached (LRU-touched,
        nothing written). May reclaim LRU cache entries or preempt
        streams under pool pressure, like any allocation."""
        if self.prefix_cache_size <= 0:
            raise ValueError(
                "import_prefix needs prefix_cache_size > 0 — the imported "
                "payload lives in the prefix cache"
            )
        key = tuple(key)
        if len(key) != plen or plen <= 0:
            raise ValueError(f"prefix key length {len(key)} != plen {plen}")
        if key in self._prefix_cache:
            self._prefix_cache.move_to_end(key)
            return False
        if self.paged:
            nb = -(-plen // self.page_size)
            if data["k"].shape[1] != nb or data["k"].shape[2] != self.page_size:
                raise ValueError(
                    f"shipped payload shape {data['k'].shape} does not "
                    f"cover {plen} tokens at page_size {self.page_size} — "
                    f"handoff requires config-identical engines"
                )
            bids: List[int] = []
            try:
                for j in range(nb):
                    bid = self._alloc_block()
                    bids.append(bid)
                    parts = [jnp.asarray(data["k"][:, j]),
                             jnp.asarray(data["v"][:, j])]
                    if self.kv_dtype == "int8":
                        parts += [jnp.asarray(data["k_scale"][:, j]),
                                  jnp.asarray(data["v_scale"][:, j])]
                    self.pool = self._import_block(
                        self.pool, jnp.int32(bid), *parts)
            except RuntimeError:
                for bid in bids:
                    self._decref(bid)
                raise
            payload = tuple(bids)
        else:
            payload = tuple(jnp.asarray(a) for a in data)
        self._prefix_cache[key] = (payload, plen)
        while len(self._prefix_cache) > self.prefix_cache_size:
            _k, (pl, _n) = self._prefix_cache.popitem(last=False)
            self._drop_entry(pl)
        return True

    def _shed_expired(self) -> None:
        """Queue-wait deadline: finish expired waiters with
        ``finish_reason="shed"`` before admission. Under strict priority the
        longest waiters are the lowest classes, so sustained overload sheds
        low-priority work first — bounded, observable degradation (see the
        starvation caveat on ``Request.priority``)."""
        if self.queue_timeout_s is None or not self.queue:
            return
        now = self._clock()
        kept: List[Request] = []
        for req in self.queue:
            if now - req.submitted_at > self.queue_timeout_s:
                req.done = True
                req.done_at = now
                req.finish_reason = "shed"
                metrics.inc("tpu_hive_serve_shed_total",
                            priority=str(req.priority))
                if obs_journal.JOURNAL.enabled:
                    if req.flight_local:
                        obs_journal.note_request_done(
                            req.flight, "shed", at=now,
                            priority=req.priority)
                    # shed closes the request's episode (it never ran)
                    obs_journal.note_phase(
                        f"serve/{req.rid}", "closed", "serve_shed",
                        priority=req.priority)
            else:
                kept.append(req)
        self.queue = kept

    def _next_waiter_index(self) -> int:
        """Index of the next request to admit: queue head under strict
        priority (the insertion order), or the max-effective-priority
        waiter under ``age_boost_secs`` aging (ties keep FIFO: the queue is
        already priority-then-FIFO ordered, and a stable max scan returns
        the earliest of equals). Peek-only — the paged admission gate must
        inspect the candidate BEFORE committing to pop it."""
        if self.age_boost_secs is None or len(self.queue) <= 1:
            return 0
        now = self._clock()
        boost = self.age_boost_secs
        best_i = 0
        best_eff = None
        for i, w in enumerate(self.queue):
            eff = w.priority + int((now - w.submitted_at) / boost)
            if best_eff is None or eff > best_eff:
                best_i, best_eff = i, eff
        return best_i

    def _admit(self) -> None:
        self._shed_expired()
        for slot in range(self.max_batch):
            if not self.queue:
                return
            if self.slots[slot] is not None:
                continue
            at = self._next_waiter_index()
            req = self.queue[at]
            hit = self._match_prefix(req.prompt) if self._prefix_cache else None
            if self.paged and not self._blocks_admit(req, hit):
                # admission by BLOCK availability, not slot count: the
                # waiter stays queued (head-of-line — admission order is
                # never reshuffled by footprint) until retirements or
                # cache reclaim free enough blocks
                return
            self.queue.pop(at)
            req.admitted_at = self._clock()
            if obs_journal.JOURNAL.enabled:
                obs_journal.emit("serve_admit", f"serve/{req.rid}",
                                 slot=slot, priority=req.priority)
                if req.flight is not None:
                    obs_journal.note_leg(req.flight, "admission_wait",
                                         at=req.admitted_at, slot=slot)
            if hit is not None:
                payload, plen = hit[1]
                self.prefix_hits += 1
                self.prefix_tokens_reused += plen
                self._prefix_restore(slot, payload)
                tail = req.prompt[plen:]
            else:
                plen, tail = 0, req.prompt
            if self.paged:
                # allocate the prompt's whole block cover up front (the
                # admission gate counted it) and COW a partially-shared
                # boundary block the tail will write into mid-block
                self._ensure_writable(slot, plen, len(req.prompt) - 1)
            if self.prefill_chunk > 0 and len(tail) > self.prefill_chunk:
                # chunked admission: the slot is occupied but decodes only
                # after its chunks complete (one per step). Park the device
                # length at max_len-1: the shared decode step scatters k/v
                # at lengths[row] for EVERY row, and the parked position is
                # (a) outside any prompt region (prompt <= max_len - budget)
                # and (b) rewritten by the row's own scatter before any
                # query can attend it, so the garbage is never read.
                self.slots[slot] = req
                self._prefilling[slot] = (tail, plen, 0)
                self._park(slot)
                continue
            tokens = self._padded_tokens(tail)
            logits = self._run_prefill(slot, tokens, plen)
            self._on_prefill(slot, tokens, len(req.prompt), plen)
            # the row's true length is the unpadded prompt (padded tail
            # positions are never attended: mask keys > length-1)
            self.slots[slot] = req
            self._finish_prefill(req, slot, logits, len(tail) - 1)

    def _park(self, slot: int) -> None:
        """Pin the slot's length at the parked sentinel while its chunked
        prefill is in flight (see the invariant note in _admit); the paged
        sentinel is the last table-addressable position, which maps to the
        trash block for unassigned entries. Subclasses with auxiliary
        caches park those rows too — an unparked auxiliary row would let
        concurrent decode/verify scatters land at the slot's STALE length,
        possibly inside the prompt region being chunked in."""
        self._set_row_length(
            slot, self._park_pos if self.paged else self.max_len - 1)

    def _finish_prefill(self, req: Request, slot: int, logits,
                        last_idx: int) -> None:
        """Shared post-prefill tail of the monolithic and chunked paths:
        set the row's true length, store the prefix (after _on_prefill has
        populated subclass caches), pick + emit the first token."""
        self._set_row_length(slot, len(req.prompt))
        self._on_ready(slot, len(req.prompt))
        if self.prefix_cache_size > 0:
            # store even on a hit: the row now holds valid KV for the FULL
            # prompt, so a future prompt extending it further can reuse
            # more than the shorter cached entry
            self._store_prefix(slot, req.prompt)
        tok = self._pick(logits[last_idx], req)
        self._emit(req, slot, tok)
        if req.done:
            self._retire(slot)

    def _padded_tokens(self, toks: List[int]):
        """Right-pad to the prefill bucket — ONE home for the padding rule
        so the monolithic and chunked paths cannot drift."""
        return jnp.asarray(
            toks + [0] * (self._bucket(len(toks)) - len(toks)), jnp.int32
        )[None, :]

    def _prefill_chunk_tick(self, slot: Optional[int] = None) -> None:
        """Advance one in-flight chunked prefill by one chunk — the per-step
        prefill budget that keeps decode latency bounded."""
        if not self._prefilling:
            return
        if slot is None:
            slot = next(iter(self._prefilling))  # insertion order: true FIFO
        tail, plen, pos = self._prefilling[slot]
        req = self.slots[slot]
        # the padded bucket write [off, off+bucket) must stay inside the
        # arena: dynamic_update_slice CLAMPS an out-of-bounds start, which
        # would silently shift the chunk over earlier KV. Shrink the chunk
        # so its bucket fits (room >= 2 always: prompt+budget <= max_len).
        off = plen + pos
        room = self.max_len - off
        size = min(self.prefill_chunk, len(tail) - pos)
        while self._bucket(size) > room:
            size = self._bucket(size) // 2
        chunk = tail[pos: pos + size]
        tokens = self._padded_tokens(chunk)
        logits = self._run_prefill(slot, tokens, off)
        self._on_prefill(slot, tokens, len(req.prompt), off)
        self.prefill_chunks_done += 1
        pos += len(chunk)
        if pos < len(tail):
            self._prefilling[slot] = (tail, plen, pos)
            return
        del self._prefilling[slot]
        # the final chunk holds the prompt's last position: its logits row
        # len(chunk)-1 is exactly what a monolithic prefill would pick from
        self._finish_prefill(req, slot, logits, len(chunk) - 1)

    def _on_prefill(self, slot: int, tokens, prompt_len: int,
                    start: int = 0) -> None:
        """Hook for subclasses that keep auxiliary per-slot state (the
        speculative engine prefills its draft cache here). On a prefix-cache
        hit ``tokens`` is the bucketed TAIL only and ``start`` its offset.
        Called once per monolithic prefill and once per CHUNK on the
        chunked path — implementations must only write KV at [start, ...)
        and leave length bookkeeping to ``_on_ready``."""

    def _on_ready(self, slot: int, prompt_len: int) -> None:
        """Hook: the slot's prefill just completed (its true length is set
        and it will decode from the next step). Subclasses sync auxiliary
        cache lengths here — NOT in _on_prefill, which fires mid-chunking
        while the slot must stay parked."""

    def _sample_coords(self, reqs):
        """Per-row (rid, emitted-count) arrays for the keyed sampler; idle
        rows get zeros (their sampled values are never read)."""
        rids = np.zeros(len(reqs), np.uint32)
        counts = np.zeros(len(reqs), np.uint32)
        for i, r in enumerate(reqs):
            if r is not None:
                rids[i], counts[i] = r.rid, len(r.tokens_out)
        return jnp.asarray(rids), jnp.asarray(counts)

    def _pick(self, logits_row, req: Request) -> int:
        if self.temperature == 0.0:
            return int(jnp.argmax(logits_row))
        rids, counts = self._sample_coords([req])
        return int(self._sample(logits_row[None], rids, counts)[0])

    def _pick_batch(self, logits, reqs):
        """Pick for every row with ONE host transfer per decode step.
        ``reqs``: the slot->Request list aligned with logits rows."""
        if self.temperature == 0.0:
            return jax.device_get(jnp.argmax(logits, axis=-1))
        return jax.device_get(self._sample(logits, *self._sample_coords(reqs)))

    def _emit(self, req: Request, slot: int, tok: int) -> None:
        if req.first_token_at is None:
            req.first_token_at = self._clock()
            if req.flight is not None and obs_journal.JOURNAL.enabled:
                # the first-token mark closes the flight's TTFT window —
                # new request-path code between admission and here must
                # emit its own leg or the sum-to-ttft assertion trips
                if req.flight_decode:
                    obs_journal.note_leg(req.flight, "first_decode",
                                         at=req.first_token_at)
                else:
                    obs_journal.note_leg(req.flight, "prefill",
                                         at=req.first_token_at)
        req.tokens_out.append(tok)
        self._last_host[slot] = tok
        if len(req.tokens_out) >= req.max_new_tokens or tok == self.eos_id:
            req.done = True
            req.finish_reason = "eos" if tok == self.eos_id else "length"
            req.done_at = self._clock()
            self._observe_request(req)

    def _observe_request(self, req: Request) -> None:
        """Publish one finished request's lifecycle: per-priority-class
        histograms into the Prometheus registry and (when tracing is on)
        queued -> admitted -> prefill -> decode spans on the request's own
        timeline lane (tid = rid). Registry and tracer are both locked —
        safe when engines run on worker threads."""
        prio = str(req.priority)
        metrics.inc("tpu_hive_serve_requests_total", priority=prio)
        if obs_journal.JOURNAL.enabled:
            if req.flight_local:
                obs_journal.note_request_done(
                    req.flight, req.finish_reason,
                    first_token_at=req.first_token_at, at=req.done_at,
                    tokensOut=len(req.tokens_out))
            obs_journal.note_phase(
                f"serve/{req.rid}", "closed", "serve_finish",
                finishReason=req.finish_reason,
                tokensOut=len(req.tokens_out))
        if req.queue_wait_s is not None:
            metrics.observe("tpu_hive_serve_queue_wait_seconds",
                            req.queue_wait_s, priority=prio)
        if req.ttft_s is not None:
            metrics.observe("tpu_hive_serve_ttft_seconds", req.ttft_s,
                            priority=prio)
        if req.tpot_s is not None:
            metrics.observe("tpu_hive_serve_tpot_seconds", req.tpot_s,
                            priority=prio)
        if not obs_trace.enabled():
            return
        args = {"rid": req.rid, "priority": req.priority,
                "prompt_tokens": len(req.prompt),
                "new_tokens": len(req.tokens_out)}
        tid = req.rid
        if req.admitted_at is not None:
            obs_trace.TRACER.complete("request/queued", req.submitted_at,
                                      req.admitted_at, cat="serving",
                                      tid=tid, args=args)
            if req.first_token_at is not None:
                obs_trace.TRACER.complete("request/prefill", req.admitted_at,
                                          req.first_token_at, cat="serving",
                                          tid=tid, args=args)
        if req.first_token_at is not None and req.done_at is not None:
            obs_trace.TRACER.complete("request/decode", req.first_token_at,
                                      req.done_at, cat="serving",
                                      tid=tid, args=args)
        obs_trace.TRACER.instant("request/done", cat="serving", tid=tid,
                                 at=req.done_at, args=args)

    # -- engine ticks ------------------------------------------------------
    def _tick_prefills(self) -> List[int]:
        """Shared per-step chunk scheduling: one bounded chunk while any
        row is decoding (fairness budget protects decode latency), ALL
        in-flight prefills when nothing is (a burst of long prompts must
        not serialize against a budget with nothing to be fair to).
        Returns the slots ready to decode/speculate this step."""
        decoding = any(
            s is not None and i not in self._prefilling
            for i, s in enumerate(self.slots)
        )
        if decoding:
            self._prefill_chunk_tick()
        else:
            for slot in list(self._prefilling):
                if slot in self._prefilling:  # a tick may finish the slot
                    self._prefill_chunk_tick(slot)
        return [s for s in range(self.max_batch)
                if self.slots[s] is not None and s not in self._prefilling]

    def _fused_window(self, active) -> int:
        """How many decode iterations may run device-side before the host
        must look again: bounded by the ``decode_steps`` knob and every
        active row's remaining budget (length-exactness — a window never
        overruns a budget), and collapsed to 1 while chunked prefills are
        in flight (their chunk ticks are per engine step) or when EOS
        retirement could free a slot that QUEUED work is waiting for
        (admission latency). Rows may still hit EOS inside a window
        (inherently unpredictable): their surplus tokens are computed and
        discarded — the emitted stream stays exact, the waste is bounded
        by K-1 tokens per retiring row. Below-knob windows are rounded
        down to a power of two so at most log2(decode_steps) + 1 programs
        ever compile."""
        if self.decode_steps <= 1 or self._prefilling:
            return 1
        if self.eos_id is not None and self.queue:
            return 1
        rem = min(
            self.slots[s].max_new_tokens - len(self.slots[s].tokens_out)
            for s in active
        )
        if rem >= self.decode_steps:
            return self.decode_steps
        return 1 << (rem.bit_length() - 1)

    def step(self) -> bool:
        """Admit + tick chunked prefills (one bounded chunk while anyone
        is decoding, else all — see _tick_prefills) + one decode step —
        or one fused multi-step window (``decode_steps`` > 1, see
        ``_fused_window``) — for all decoding slots. Returns whether any
        work remains (active slots, in-flight chunked prefills, or queued
        requests)."""
        self._admit()
        active = self._tick_prefills()
        if active and self.paged:
            k_plan = self._fused_window(active)
            for slot in active:
                if self.slots[slot] is None:
                    continue  # retired by an earlier slot's pool preemption
                lo = int(self._host_len[slot])
                self._ensure_writable(slot, lo, lo + k_plan - 1)
            # block-pressure preemption inside _ensure_writable may have
            # retired another active slot; a SMALLER window than planned is
            # always exact, so re-filter rather than re-plan
            active = [s for s in active if self.slots[s] is not None]
        if active:
            last = jnp.asarray(self._last_host, jnp.int32)
            if self._token_sharding is not None:
                last = jax.device_put(last, self._token_sharding)
            k = self._fused_window(active) if not self.paged else k_plan
            if k == 1:
                if self.paged:
                    logits, self.pool = self._paged_decode(
                        self.params, self.pool, last, self._table_dev(),
                        self._len_dev()
                    )
                else:
                    logits, self.cache = self._decode(self.params,
                                                      self.cache, last)
                self.steps += 1
                self.slot_steps += len(active)
                picked = self._pick_batch(logits, self.slots)
                if self.paged:
                    for slot in active:
                        self._host_len[slot] += 1
                for slot in active:
                    req = self.slots[slot]
                    self._emit(req, slot, int(picked[slot]))
                    if req.done:
                        self._retire(slot)  # recycle immediately
            else:
                rids, counts = self._sample_coords(self.slots)
                if self._token_sharding is not None:
                    rids = jax.device_put(rids, self._token_sharding)
                    counts = jax.device_put(counts, self._token_sharding)
                if self.paged:
                    toks_d, self.pool = self._paged_decode_multi(
                        self.params, self.pool, last, self._table_dev(),
                        self._len_dev(), rids, counts, k
                    )
                else:
                    toks_d, self.cache = self._decode_multi(
                        self.params, self.cache, last, rids, counts, k
                    )
                self.fused_windows += 1
                metrics.inc("tpu_hive_serve_fused_decode_windows_total")
                toks = jax.device_get(toks_d)  # ONE [B, k] transfer
                self.steps += k
                self.slot_steps += len(active) * k
                if self.paged:
                    for slot in active:
                        self._host_len[slot] += k
                for slot in active:
                    req = self.slots[slot]
                    for j in range(k):
                        self._emit(req, slot, int(toks[slot, j]))
                        if req.done:
                            break  # surplus window tokens are discarded
                    if req.done:
                        self._retire(slot)
        if self.paged:
            metrics.set_gauge(
                "tpu_hive_serve_block_pool_occupancy",
                self.blocks_in_use / max(1, self.num_blocks - 1),
            )
        return bool(self.queue) or any(s is not None for s in self.slots)

    def run_until_drained(self, max_steps: int = 100_000) -> None:
        for _ in range(max_steps):
            if not self.step():
                return
        raise RuntimeError(f"serving did not drain in {max_steps} steps")

    # -- graceful preemption (work-preserving drain) -----------------------
    def begin_drain(self) -> None:
        """Flip admission off: every later ``submit()`` raises
        :class:`EngineDraining` (counted in
        ``tpu_hive_serve_drain_rejected_total``; the HTTP front-end's 503 +
        ``Retry-After``). Requests already in the system — queued waiters
        and decoding slots — are in-flight and keep running; use
        :meth:`drain` to finish them under a deadline."""
        if not self.draining:
            self.draining = True

    def end_drain(self) -> None:
        """Re-arm admission after a COMPLETED drain — a drained replica
        returning to a warm standby pool (the fleet autoscaler's
        scale-down/regrow cycle must not pay a fresh engine build). Only
        legal once idle: re-arming with work still in flight would turn
        the drain's 503 contract into silent re-admission."""
        if self.queue or any(s is not None for s in self.slots):
            raise RuntimeError(
                "end_drain with work still in flight — finish the drain "
                "(step until idle) before re-arming admission"
            )
        self.draining = False

    def drain(self, deadline_s: Optional[float] = None,
              max_steps: int = 100_000) -> bool:
        """Finish all in-flight work, bounded by ``deadline_s``.

        Calls :meth:`begin_drain` then steps the engine until nothing is
        queued or active. Returns True when fully drained; when the
        deadline expires first, every still-unfinished request is finalized
        with ``finish_reason="preempted"`` (its stream truncated at what
        was emitted) and the engine state is cleared — the bounded-exit
        guarantee a preempting scheduler needs (SIGTERM must not wait on an
        unbounded decode tail)."""
        self.begin_drain()
        # goodput: finishing admitted work while refusing new is its own
        # badput phase (the elastic preemption handshake's workload cost)
        obs_goodput.phase("drain")
        try:
            t0 = self._clock()
            steps = 0
            while self.step():
                steps += 1
                expired = (deadline_s is not None
                           and self._clock() - t0 > deadline_s)
                if expired or steps >= max_steps:
                    now = self._clock()
                    leftovers = list(self.queue) + [
                        r for r in self.slots if r is not None
                    ]
                    for req in leftovers:
                        req.done = True
                        req.done_at = now
                        req.finish_reason = "preempted"
                        if req.flight_local and obs_journal.JOURNAL.enabled:
                            obs_journal.note_request_done(
                                req.flight, "preempted",
                                first_token_at=req.first_token_at, at=now)
                    self.queue.clear()
                    for slot in range(self.max_batch):
                        if self.slots[slot] is not None:
                            self._retire(slot)  # paged: return the blocks
                    self._prefilling.clear()
                    return False
            return True
        finally:
            obs_goodput.phase("idle")

    @property
    def occupancy(self) -> float:
        """Mean fraction of slots doing useful work per decode step."""
        return self.slot_steps / (self.steps * self.max_batch) if self.steps else 0.0


class SpeculativeServingEngine(ServingEngine):
    """Continuous batching + speculative decoding with PER-ROW acceptance.

    ``models.speculative`` verifies a uniform batch and must advance every
    sequence by the BATCH MINIMUM accepted prefix (one slow row drags all).
    The ragged cache removes that barrier: each engine step drafts ``gamma``
    greedy proposals per row (one scanned jit), verifies them in a single
    S=gamma+1 target pass at per-row offsets, and each row keeps its OWN
    accepted prefix + correction token — rows at different acceptance rates
    emit 1..gamma+1 tokens per step independently.

    Cache-consistency argument (per row, both caches): a round absorbs the
    contiguous window [len, len+gamma] and rolls back to len+1+a; the stale
    tail [len+1+a, len+gamma] is strictly inside the NEXT round's window
    (which starts at the rolled-back length), and advance_ragged scatters
    new k/v before attention in every layer, so no query ever attends a
    stale entry — the same invariant models/speculative.py relies on,
    applied per row. Greedy speculation is exact: every row's stream equals
    vanilla greedy decode (guard: test_serving_speculative.py).

    Sampled speculation (temperature > 0) does per-row residual
    resampling (accept x_i ~ q with prob min(1, p(x_i)/q(x_i)); on reject
    sample from norm(max(p-q, 0)); on full acceptance a bonus token from
    p), so sampled output is distributed exactly as the target model's —
    the standard speculative-sampling guarantee — while each row still
    advances independently. All draws use the engine's counter-based keys
    (seed x rid x emitted-position, tagged per purpose), which makes
    sampled speculative streams reproducible across batch interleavings
    AND makes a perfect draft (draft == target) reproduce the plain
    sampled engine's stream bit-exactly: every proposal is drawn with the
    SAME key the plain engine would use at that position, acceptance is
    then certain, and the bonus token uses the plain key too (guard:
    test_serving_speculative_sampled.py). Greedy (temperature 0) remains
    bit-exact vs vanilla greedy decode.

    ``decode_steps`` does not apply here: a speculative round already
    amortizes the host round-trip over up to gamma+1 tokens, and fusing
    rounds would defeat the per-row acceptance bookkeeping. The knob is
    accepted (shared constructor) and ignored by this engine's ``step``.

    Composes with chunked prefill (``prefill_chunk > 0``): prompt chunks
    absorb into BOTH caches per engine step (the shared chunk tick's
    ``_on_prefill`` hook mirrors every chunk into the draft), while the
    other rows keep speculating. Both rows are parked at max_len-1 during
    chunking (see ``_park``) so concurrent verify/draft scatters never
    touch the prompt region being built. Exactness guard:
    tests/test_serving_chunked.py + the chunked speculative fuzz."""

    def __init__(self, params, cfg, draft_params=None, draft_cfg=None, *,
                 gamma: int = 4, spec_decode=None, **kw):
        if spec_decode is not None:
            # first-class construction: ServingEngine(spec_decode=...)
            # routed here via __new__ — unpack the config
            if draft_params is not None or draft_cfg is not None:
                raise ValueError(
                    "pass either spec_decode= or explicit draft_params/"
                    "draft_cfg, not both"
                )
            draft_params = spec_decode.draft_params
            draft_cfg = spec_decode.draft_cfg
            gamma = spec_decode.gamma
        if draft_params is None or draft_cfg is None:
            raise ValueError(
                "speculative serving needs a draft model: pass "
                "spec_decode=SpecDecodeConfig(...) (or legacy positional "
                "draft_params/draft_cfg)"
            )
        if cfg.vocab_size != draft_cfg.vocab_size:
            raise ValueError("target and draft vocabs must match")
        if gamma < 1:
            raise ValueError(f"gamma must be >= 1, got {gamma}")
        super().__init__(params, cfg, **kw)
        self.draft_params = draft_params
        self.draft_cfg = draft_cfg
        self.gamma = gamma
        self.draft_cache = init_ragged_cache(draft_cfg, self.max_batch,
                                             self.max_len,
                                             kv_dtype=self.kv_dtype)
        if self.mesh is not None:
            # one shared policy with make_sharded_speculative (see
            # draft_serving_shardings for the shard-vs-replicate trade-off).
            # Cache rows always shard over dp; the kv-head axis only when
            # the draft itself shards.
            from jax.sharding import NamedSharding
            from jax.sharding import PartitionSpec as P

            from hivedscheduler_tpu.models.speculative import (
                draft_serving_shardings,
            )

            dsh, sharded = draft_serving_shardings(draft_cfg, self.mesh)
            head_ax = "tp" if sharded else None
            self.draft_params = jax.device_put(draft_params, dsh)
            dkv_sh = NamedSharding(
                self.mesh, P(None, ("dp", "fsdp"), None, head_ax, None)
            )
            self.draft_cache = jax.device_put(
                self.draft_cache,
                self._cache_shardings(dkv_sh, self._len_sharding),
            )
        self.drafted = 0
        self.accepted = 0

        def draft_prefill(dparams, dcache, tokens, row, start):
            _, dcache = advance_ragged(dparams, dcache, tokens, draft_cfg,
                                       row=row, start=start)
            return dcache

        def make_spec_round(paged: bool):
            """Greedy speculative round; the draft side is identical for
            both cache backends (the draft stays a dense slab — it is a
            fraction of the target's size), only the target verify pass
            addresses its cache differently. Paged callers append the
            block table + host lengths."""

            def spec_round(tparams, dparams, tcache, dcache, last, *extra):
                def draft_step(carry, _):
                    dc, tok = carry
                    logits, dc = advance_ragged(dparams, dc, tok[:, None],
                                                draft_cfg)
                    nxt = jnp.argmax(logits[:, 0], axis=-1).astype(jnp.int32)
                    return (dc, nxt), nxt

                (dcache, last_d), props = jax.lax.scan(
                    draft_step, (dcache, last), None, length=gamma
                )
                # extra absorb so the draft cache holds its last proposal
                # when a row accepts everything (models/speculative.py:128-143)
                _, dcache = advance_ragged(dparams, dcache, last_d[:, None],
                                           draft_cfg)
                props = jnp.swapaxes(props, 0, 1)  # [B, gamma]
                tgt_in = jnp.concatenate([last[:, None], props], axis=1)
                if paged:
                    table, lengths = extra
                    tlogits, tcache = advance_paged(tparams, tcache, tgt_in,
                                                    cfg, table, lengths)
                else:
                    tlogits, tcache = advance_ragged(tparams, tcache, tgt_in,
                                                     cfg)
                emit = jnp.argmax(tlogits, axis=-1).astype(jnp.int32)
                return tcache, dcache, props, emit  # emit [B, g+1]

            return spec_round

        self._draft_prefill = compileguard.jit(
            draft_prefill, guard_label="serve.draft_prefill",
            donate_argnums=(1,))
        self._spec_round = compileguard.jit(
            make_spec_round(False), guard_label="serve.spec_round",
            donate_argnums=(2, 3))
        if self.paged:
            self._spec_round_paged = compileguard.jit(
                make_spec_round(True), guard_label="serve.spec_round_paged",
                donate_argnums=(2, 3))

        if self.temperature > 0.0:
            temp, topk, topp = self.temperature, self.top_k, self.top_p
            base_key = self._base_key

            def row_key(r, c, tag):
                # shared _stream_key: tag 0 is BIT-IDENTICAL to the plain
                # engine's sampling key (perfect-draft exactness); tags
                # 1/2 are independent streams for accept/residual draws
                return _stream_key(base_key, r, c, tag)

            def spec_round_sampled(tparams, dparams, tcache, dcache, last,
                                   rids, counts, *extra):
                # paged callers append (table, lengths) and pass the block
                # pool as tcache; the presence of the extras is part of the
                # jit trace signature, so this branch is static
                def fdist(logits):
                    return filter_logits(logits / temp, topk, topp)

                def draft_step(carry, i):
                    dc, tok = carry
                    logits, dc = advance_ragged(dparams, dc, tok[:, None],
                                                draft_cfg)
                    f = fdist(logits[:, 0])
                    keys = jax.vmap(
                        lambda r, c: row_key(r, c + i, 0))(rids, counts)
                    nxt = jax.vmap(jax.random.categorical)(keys, f)
                    return (dc, nxt.astype(jnp.int32)), (nxt, f)

                (dcache, last_d), (props, qf) = jax.lax.scan(
                    draft_step, (dcache, last), jnp.arange(gamma)
                )
                # extra absorb so the draft cache holds its last proposal
                # when a row accepts everything (greedy round does the same)
                _, dcache = advance_ragged(dparams, dcache, last_d[:, None],
                                           draft_cfg)
                props = jnp.swapaxes(props, 0, 1).astype(jnp.int32)  # [B,g]
                qf = jnp.swapaxes(qf, 0, 1)                      # [B,g,V]
                tgt_in = jnp.concatenate([last[:, None], props], axis=1)
                if extra:
                    table, lengths = extra
                    tlogits, tcache = advance_paged(tparams, tcache, tgt_in,
                                                    cfg, table, lengths)
                else:
                    tlogits, tcache = advance_ragged(tparams, tcache, tgt_in,
                                                     cfg)
                pf = fdist(tlogits)                              # [B,g+1,V]
                p = jax.nn.softmax(pf, axis=-1)
                q = jax.nn.softmax(qf, axis=-1)
                b_rows = props.shape[0]
                rows = jnp.arange(b_rows)
                gidx = jnp.arange(gamma)
                # accept proposal i iff u_i < p_i(x_i)/q_i(x_i)
                px = p[rows[:, None], gidx[None, :], props]
                qx = q[rows[:, None], gidx[None, :], props]
                u = jax.vmap(
                    lambda r, c: jax.vmap(
                        lambda i: jax.random.uniform(row_key(r, c + i, 1))
                    )(gidx)
                )(rids, counts)
                # u < p/q, NOT u*q < p: with a perfect draft (px == qx
                # bitwise) the ratio is exactly 1.0 and u in [0,1) always
                # accepts, whereas fl(u*qx) can round UP to qx for u near
                # 1 and spuriously reject — breaking the perfect-draft
                # bit-exactness guarantee through the residual path. qx>0
                # is guaranteed: the proposal was sampled from q (filtered
                # logits keep their top token, so no -inf argmax).
                accept = u < px / qx
                acc = jnp.sum(
                    jnp.cumprod(accept.astype(jnp.int32), axis=1), axis=1
                )
                # the token at position acc: residual resample on a reject,
                # bonus sample from the target's extra position on full
                # acceptance (with the PLAIN tag-0 key and the RAW filtered
                # logits — bit-matching the plain engine's categorical)
                p_at = p[rows, acc]
                q_at = jnp.where(
                    (acc < gamma)[:, None],
                    q[rows, jnp.minimum(acc, gamma - 1)], 0.0,
                )
                resid = jnp.maximum(p_at - q_at, 0.0)
                degenerate = jnp.sum(resid, axis=-1, keepdims=True) <= 0.0
                resid = jnp.where(degenerate, p_at, resid)
                res_keys = jax.vmap(
                    lambda r, c, a: row_key(r, c + a, 2))(rids, counts, acc)
                corr_res = jax.vmap(jax.random.categorical)(
                    res_keys, jnp.log(jnp.maximum(resid, 1e-30)))
                bonus_keys = jax.vmap(
                    lambda r, c, a: row_key(r, c + a, 0))(rids, counts, acc)
                corr_bonus = jax.vmap(jax.random.categorical)(
                    bonus_keys, pf[rows, acc])
                corr = jnp.where(acc == gamma, corr_bonus,
                                 corr_res).astype(jnp.int32)
                # accepted proposals with the correction spliced at `acc`
                # (positions past acc are never read by the host)
                emit = jnp.where(
                    jnp.arange(gamma + 1)[None, :] == acc[:, None],
                    corr[:, None],
                    jnp.concatenate([props, props[:, -1:]], axis=1),
                )
                return tcache, dcache, emit, acc

            self._spec_round_sampled = compileguard.jit(
                spec_round_sampled, guard_label="serve.spec_round_sampled",
                donate_argnums=(2, 3)
            )

    def _park(self, slot: int) -> None:
        # park the draft row too: while the slot's chunks are in flight,
        # concurrent spec rounds scatter draft k/v at lengths[slot] — left
        # at the previous occupant's stale length that write could land
        # INSIDE the prompt region being chunked in. The parked sentinel
        # sends it to max_len-1, which no query ever attends (spec queries
        # top out at max_len-2: submit reserves gamma+1 headroom).
        super()._park(slot)
        self.draft_cache = self.draft_cache._replace(
            lengths=self.draft_cache.lengths.at[slot].set(self.max_len - 1)
        )

    def _on_prefill(self, slot: int, tokens, prompt_len: int,
                    start: int = 0) -> None:
        # KV only; the draft length stays parked until _on_ready (chunked
        # path) — setting it early would unpark the row mid-chunking
        self.draft_cache = self._draft_prefill(
            self.draft_params, self.draft_cache, tokens, jnp.int32(slot),
            jnp.int32(start)
        )

    def _on_ready(self, slot: int, prompt_len: int) -> None:
        self.draft_cache = self.draft_cache._replace(
            lengths=self.draft_cache.lengths.at[slot].set(prompt_len)
        )

    def _prefix_extract(self, slot: int, pb: int):
        """Target AND draft KV travel together in one payload: a restored
        prefix must leave both caches exactly as a full prefill would."""
        return (
            super()._prefix_extract(slot, pb),
            self._extract_prefix(self.draft_cache, jnp.int32(slot), pb),
        )

    def _prefix_restore(self, slot: int, payload) -> None:
        tgt, dft = payload
        super()._prefix_restore(slot, tgt)
        self.draft_cache = self._restore_prefix(
            self.draft_cache, dft, jnp.int32(slot)
        )

    def _store_payload(self, slot: int, bids, plen: int):
        # paged target prefix = shared block ids (refcounted, no copy); the
        # draft has no paged pool, so bundle a dense draft-KV copy — a
        # restored prefix must leave BOTH models exactly as a full prefill
        # would, which is what the paged differential pins
        return (tuple(bids),
                self._extract_prefix(self.draft_cache, jnp.int32(slot),
                                     self._bucket(plen)))

    def _entry_bids(self, payload):
        return payload[0]

    def export_prefix(self, prompt):
        raise RuntimeError(
            "KV shipping across replicas does not support the speculative "
            "engine (its prefix payloads bundle a draft-cache copy); run "
            "the fleet with HIVED_FLEET_KV_SHIP=0 (re-prefill-on-miss)"
        )

    def import_prefix(self, key, plen: int, data) -> bool:
        raise RuntimeError(
            "KV shipping across replicas does not support the speculative "
            "engine (its prefix payloads bundle a draft-cache copy); run "
            "the fleet with HIVED_FLEET_KV_SHIP=0 (re-prefill-on-miss)"
        )

    def submit(self, prompt, max_new_tokens: int,
               priority: int = 0) -> Request:
        # a verify round writes up to gamma past the accepted prefix before
        # rolling back: reserve that headroom in the arena
        if prompt and len(prompt) + max_new_tokens + self.gamma + 1 > self.max_len:
            raise ValueError(
                f"prompt {len(prompt)} + max_new {max_new_tokens} + "
                f"speculation headroom {self.gamma + 1} exceeds max_len "
                f"{self.max_len}"
            )
        return super().submit(prompt, max_new_tokens, priority=priority)

    def step(self) -> bool:
        self._admit()
        active = self._tick_prefills()
        if active and self.paged:
            # a verify round writes [len, len+gamma]: allocate/COW that
            # cover up front ("accepted draft tokens append blocks"); the
            # rejected tail's blocks roll back via _trim_blocks below
            for slot in active:
                if self.slots[slot] is None:
                    continue  # retired by an earlier slot's pool preemption
                lo = int(self._host_len[slot])
                self._ensure_writable(slot, lo, lo + self.gamma)
            active = [s for s in active if self.slots[s] is not None]
        if active:
            last = jnp.asarray(self._last_host, jnp.int32)
            if self._token_sharding is not None:
                last = jax.device_put(last, self._token_sharding)
            if self.paged:
                lengths_before = self._host_len.copy()
                extra = (self._table_dev(), self._len_dev())
            else:
                lengths_before = jax.device_get(self.cache.lengths)
                extra = ()
            if self.temperature > 0.0:
                rids, counts = self._sample_coords(self.slots)
                if self._token_sharding is not None:
                    rids = jax.device_put(rids, self._token_sharding)
                    counts = jax.device_put(counts, self._token_sharding)
                if self.paged:
                    self.pool, self.draft_cache, emit_d, acc_d = (
                        self._spec_round_sampled(
                            self.params, self.draft_params, self.pool,
                            self.draft_cache, last, rids, counts, *extra,
                        ))
                else:
                    self.cache, self.draft_cache, emit_d, acc_d = (
                        self._spec_round_sampled(
                            self.params, self.draft_params, self.cache,
                            self.draft_cache, last, rids, counts,
                        ))
                emit, acc_row = jax.device_get((emit_d, acc_d))
                props = None  # device already resolved per-row acceptance
            else:
                if self.paged:
                    self.pool, self.draft_cache, props_d, emit_d = (
                        self._spec_round_paged(
                            self.params, self.draft_params, self.pool,
                            self.draft_cache, last, *extra,
                        ))
                else:
                    self.cache, self.draft_cache, props_d, emit_d = (
                        self._spec_round(
                            self.params, self.draft_params, self.cache,
                            self.draft_cache, last,
                        ))
                props, emit = jax.device_get((props_d, emit_d))
            self.steps += 1
            self.slot_steps += len(active)
            # every slot's final length is derived from lengths_before below
            # (active: +1+acc; idle: pinned), so no second device fetch
            new_len = np.array(lengths_before)
            for slot in active:
                req = self.slots[slot]
                if props is None:
                    acc = int(acc_row[slot])
                else:
                    acc = 0
                    while acc < self.gamma and props[slot, acc] == emit[slot, acc]:
                        acc += 1
                self.drafted += self.gamma
                self.accepted += acc
                metrics.observe("tpu_hive_serve_spec_acceptance_ratio",
                                acc / self.gamma)
                # emit accepted prefix + correction, respecting budget/eos
                for tok in emit[slot, : acc + 1]:
                    self._emit(req, slot, int(tok))
                    if req.done:
                        break
                # roll the row back to feedback + accepted prefix; idle rows
                # keep lengths_before (their absorbed garbage never advances)
                new_len[slot] = lengths_before[slot] + 1 + acc
                if self.paged and not req.done:
                    # speculative rollback, block form: keep the accepted
                    # cover, return the rejected tail's blocks to the pool
                    self._host_len[slot] = new_len[slot]
                    self._trim_blocks(slot, int(new_len[slot]))
                if req.done:
                    self._retire(slot)
            # two distinct buffers: both caches are donated to the next
            # round, and donating one shared lengths array twice is an error
            def upload(arr):
                arr = jnp.array(arr, jnp.int32)
                if self._len_sharding is not None:
                    arr = jax.device_put(arr, self._len_sharding)
                return arr

            if not self.paged:
                self.cache = self.cache._replace(lengths=upload(new_len))
            self.draft_cache = self.draft_cache._replace(
                lengths=upload(new_len))
        if self.paged:
            metrics.set_gauge(
                "tpu_hive_serve_block_pool_occupancy",
                self.blocks_in_use / max(1, self.num_blocks - 1),
            )
        return bool(self.queue) or any(s is not None for s in self.slots)

    @property
    def acceptance(self) -> float:
        return self.accepted / self.drafted if self.drafted else 0.0
