"""ICI-mesh geometry: contiguous sub-mesh cells, tiling, chain expansion.

This is the TPU-first replacement for the reference's generic child-count cell
hierarchy (``pkg/algorithm/config.go:45-108``). A cell in a mesh chain is a
*contiguous sub-mesh* identified by (origin, shape) inside the chain's full ICI
topology. Buddy split = tiling a cell by the next-lower level's shape; buddy
merge = rejoining all tiles of one parent. Because every level's shape tiles
the next level's shape exactly (validated here), contiguity of every allocated
slice is a construction-time guarantee instead of an emergent property — this
is what yields zero ICI-mesh fragmentation for aligned requests.

The expansion produces the same ``cellChainElement``-style level table the rest
of the algorithm consumes (level, childNumber, hasNode, isMultiNodes,
leafCellType, leafCellNumber), so VC-safety accounting and buddy allocation
carry over from the reference unchanged.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Iterator, List, Optional, Tuple

from hivedscheduler_tpu.api.types import MeshSpec

Coord = Tuple[int, ...]
Shape = Tuple[int, ...]


def volume(shape: Shape) -> int:
    return math.prod(shape)


def tiles(child: Shape, parent: Shape) -> bool:
    """True iff a grid of `child`-shaped sub-meshes exactly tiles `parent`."""
    return len(child) == len(parent) and all(p % c == 0 for c, p in zip(child, parent))


def tile_origins(parent_origin: Coord, parent_shape: Shape, child_shape: Shape) -> List[Coord]:
    """Origins of the `child_shape` tiles inside the parent sub-mesh, in
    row-major order (last axis fastest). Deterministic order makes buddy
    split/merge and golden tests stable."""
    assert tiles(child_shape, parent_shape), (child_shape, parent_shape)
    counts = [p // c for p, c in zip(parent_shape, child_shape)]
    out: List[Coord] = []

    def rec(dim: int, prefix: List[int]) -> None:
        if dim == len(counts):
            out.append(tuple(o + i * c for o, i, c in zip(parent_origin, prefix, child_shape)))
            return
        for i in range(counts[dim]):
            rec(dim + 1, prefix + [i])

    rec(0, [])
    return out


def submesh_coords(origin: Coord, shape: Shape) -> Iterator[Coord]:
    """All chip coordinates inside the sub-mesh, row-major."""
    for o in tile_origins(origin, shape, (1,) * len(shape)):
        yield o


def coord_str(coord: Coord) -> str:
    return "-".join(str(c) for c in coord)


def row_major_index(coord: Coord, origin: Coord, shape: Shape) -> int:
    """Flat index of `coord` within the sub-mesh (used for in-host chip
    indices handed to TPU_VISIBLE_CHIPS)."""
    idx = 0
    for c, o, s in zip(coord, origin, shape):
        assert o <= c < o + s, (coord, origin, shape)
        idx = idx * s + (c - o)
    return idx


@dataclass(frozen=True)
class MeshLevel:
    """One level of an expanded mesh chain (ascending from chip = level 1)."""

    level: int
    cell_type: str
    shape: Shape
    child_number: int  # tiles of the level below per cell (0 at chip level)
    is_node_level: bool  # shape == hostShape: maps 1:1 to a K8s node/host
    at_or_higher_than_node: bool
    is_multi_nodes: bool
    leaf_cell_number: int


class MeshChain:
    """Expanded level table of an ICI-mesh cell chain.

    Built from a ``MeshSpec``: chip level and host level are auto-inserted if
    not among the named levels; the chain's own name is the top level with
    shape == topology."""

    def __init__(self, chain_name: str, spec: MeshSpec):
        self.chain_name = chain_name
        self.spec = spec
        dims = len(spec.topology)
        if len(spec.host_shape) != dims:
            raise ValueError(
                f"mesh chain {chain_name}: hostShape rank {len(spec.host_shape)} != "
                f"topology rank {dims}"
            )
        if not tiles(spec.host_shape, spec.topology):
            raise ValueError(
                f"mesh chain {chain_name}: hostShape {spec.host_shape} does not tile "
                f"topology {spec.topology}"
            )

        # Collect (name, shape) ascending: chip, [host], named..., top.
        shapes: List[Tuple[str, Shape]] = [(spec.chip_type, (1,) * dims)]
        named = [(lv.name, lv.shape) for lv in spec.levels]
        host_named = any(s == spec.host_shape for _, s in named)
        if not host_named and spec.host_shape != (1,) * dims and spec.host_shape != spec.topology:
            named.append((f"{chain_name}-host", spec.host_shape))
        named = [nv for nv in named if nv[1] != (1,) * dims and nv[1] != spec.topology]
        named.sort(key=lambda nv: volume(nv[1]))
        shapes.extend(named)
        shapes.append((chain_name, spec.topology))

        host_vol = volume(spec.host_shape)
        self.levels: List[MeshLevel] = []
        for i, (name, shape) in enumerate(shapes):
            if i > 0:
                prev = shapes[i - 1][1]
                if not tiles(prev, shape) or volume(shape) <= volume(prev):
                    raise ValueError(
                        f"mesh chain {chain_name}: level {name} shape {shape} is not an "
                        f"exact super-tile of {shapes[i - 1][0]} shape {prev}"
                    )
            vol = volume(shape)
            self.levels.append(
                MeshLevel(
                    level=i + 1,
                    cell_type=name,
                    shape=shape,
                    child_number=0 if i == 0 else vol // volume(shapes[i - 1][1]),
                    is_node_level=shape == spec.host_shape,
                    at_or_higher_than_node=vol >= host_vol,
                    is_multi_nodes=vol > host_vol,
                    leaf_cell_number=vol,
                )
            )
        names = [lv.cell_type for lv in self.levels]
        if len(set(names)) != len(names):
            raise ValueError(f"mesh chain {chain_name}: duplicate level names {names}")

    @property
    def top_level(self) -> int:
        return len(self.levels)

    @property
    def host_level(self) -> int:
        for lv in self.levels:
            if lv.is_node_level:
                return lv.level
        return self.top_level  # single-host chain (hostShape == topology)

    def level_of_type(self, cell_type: str) -> Optional[int]:
        for lv in self.levels:
            if lv.cell_type == cell_type:
                return lv.level
        return None

    def level(self, level: int) -> MeshLevel:
        return self.levels[level - 1]

    def node_name(self, top_address: str, host_origin: Coord) -> str:
        """Stable node name for the host whose sub-mesh starts at
        host_origin. Default format ``{cell}/{coords}`` (e.g. ``pod-a/2-0-0``)
        for simulation; real deployments set ``spec.hostNameFormat`` to a
        K8s-legal pattern matching their actual hostnames (see MeshSpec)."""
        fmt = self.spec.host_name_format or "{cell}/{coords}"
        return fmt.format(cell=top_address, coords=coord_str(host_origin))

    def host_origin_of(self, coord: Coord) -> Coord:
        return tuple((c // h) * h for c, h in zip(coord, self.spec.host_shape))

    def chip_index_in_host(self, coord: Coord) -> int:
        """In-host chip index handed off via TPU_VISIBLE_CHIPS."""
        return row_major_index(coord, self.host_origin_of(coord), self.spec.host_shape)
