"""Spec → cell-tree constructors.

TPU-native analogue of the reference's ``pkg/algorithm/config.go``:

- ``cellTypeConstructor`` (``config.go:45-108``) → ``build_chain_levels``:
  per-chain level tables (level, childNumber, hasNode, isMultiNodes,
  leafCellType, leafCellNumber), built either from the generic child-count
  cellTypes or from an ICI-mesh declaration (``algorithm/mesh.py``);
- ``physicalCellConstructor`` (``config.go:110-235``) → ``PhysicalTreeBuilder``:
  instantiates PhysicalCell trees; node-level cells pass their address down as
  the node name, multi-node cells merge child node lists; mesh chains generate
  the whole tree geometrically from the top cell's (origin, shape);
- ``virtualCellConstructor`` (``config.go:237-413``) → ``VirtualTreeBuilder``:
  per-VC virtual trees from ``virtualCells`` (``chain.type`` path syntax) and
  ``pinnedCells``, computing ``vcFreeCellNum``;
- ``ParseConfig`` (``config.go:442-477``) → ``parse_config`` returning the
  same bundle of maps consumed by HivedAlgorithm.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from hivedscheduler_tpu.api import types as api
from hivedscheduler_tpu.api.config import Config
from hivedscheduler_tpu.algorithm.cell import CellChain, CellLevel, PhysicalCell, VirtualCell
from hivedscheduler_tpu.algorithm.mesh import MeshChain, coord_str, tile_origins
from hivedscheduler_tpu.algorithm.constants import LOWEST_LEVEL
from hivedscheduler_tpu.algorithm.types import CellList, ChainCellList


@dataclass
class ChainLevel:
    """One level of a chain's level table (reference: cellChainElement,
    config.go:34-43)."""

    level: CellLevel
    cell_type: str
    child_cell_type: str = ""
    child_number: int = 0
    has_node: bool = False  # at or higher than node level
    is_multi_nodes: bool = False
    leaf_cell_type: str = ""
    leaf_cell_number: int = 1
    shape: Optional[Tuple[int, ...]] = None  # mesh chains only

    @property
    def is_node_level(self) -> bool:
        return self.has_node and not self.is_multi_nodes


@dataclass
class ParsedConfig:
    """Output bundle (reference: ParseConfig's 10 return values,
    config.go:442-477, plus the chain level tables and mesh geometries)."""

    physical_full_list: Dict[CellChain, ChainCellList] = field(default_factory=dict)
    physical_free_list: Dict[CellChain, ChainCellList] = field(default_factory=dict)
    vc_free_cell_num: Dict[str, Dict[CellChain, Dict[CellLevel, int]]] = field(default_factory=dict)
    virtual_non_pinned_full: Dict[str, Dict[CellChain, ChainCellList]] = field(default_factory=dict)
    virtual_non_pinned_free: Dict[str, Dict[CellChain, ChainCellList]] = field(default_factory=dict)
    virtual_pinned_cells: Dict[str, Dict[str, ChainCellList]] = field(default_factory=dict)
    physical_pinned_cells: Dict[str, Dict[str, PhysicalCell]] = field(default_factory=dict)
    cell_level_to_leaf_cell_num: Dict[CellChain, Dict[CellLevel, int]] = field(default_factory=dict)
    leaf_cell_type_to_chain: Dict[str, List[CellChain]] = field(default_factory=dict)
    cell_level_to_type: Dict[CellChain, Dict[CellLevel, str]] = field(default_factory=dict)
    chain_levels: Dict[CellChain, List[ChainLevel]] = field(default_factory=dict)
    mesh_chains: Dict[CellChain, MeshChain] = field(default_factory=dict)


# ---------------------------------------------------------------------------
# Level tables
# ---------------------------------------------------------------------------


def _build_generic_levels(
    top_type: str, cell_types: Dict[str, api.CellTypeSpec]
) -> List[ChainLevel]:
    """Walk a generic cellTypes chain from its top type down to the leaf
    (reference: cellTypeConstructor.addCellChain, config.go:59-102)."""
    path: List[Tuple[str, api.CellTypeSpec]] = []
    ct: Optional[str] = top_type
    seen = set()
    while ct is not None and ct in cell_types and cell_types[ct].mesh is None:
        if ct in seen:
            raise ValueError(f"cellTypes cycle detected at {ct}")
        seen.add(ct)
        spec = cell_types[ct]
        path.append((ct, spec))
        ct = spec.child_cell_type
    leaf_type = ct if ct is not None else top_type
    levels: List[ChainLevel] = [
        ChainLevel(
            level=LOWEST_LEVEL,
            cell_type=leaf_type,
            leaf_cell_type=leaf_type,
            leaf_cell_number=1,
        )
    ]
    for name, spec in reversed(path):
        below = levels[-1]
        levels.append(
            ChainLevel(
                level=below.level + 1,
                cell_type=name,
                child_cell_type=below.cell_type,
                child_number=spec.child_cell_number,
                has_node=below.has_node or spec.is_node_level,
                is_multi_nodes=below.has_node,
                leaf_cell_type=below.leaf_cell_type,
                leaf_cell_number=below.leaf_cell_number * spec.child_cell_number,
            )
        )
    return levels


def _build_mesh_levels(mesh_chain: MeshChain) -> List[ChainLevel]:
    levels: List[ChainLevel] = []
    for lv in mesh_chain.levels:
        levels.append(
            ChainLevel(
                level=lv.level,
                cell_type=lv.cell_type,
                child_cell_type="" if lv.level == 1 else levels[-1].cell_type,
                child_number=lv.child_number,
                has_node=lv.at_or_higher_than_node,
                is_multi_nodes=lv.is_multi_nodes,
                leaf_cell_type=mesh_chain.spec.chip_type,
                leaf_cell_number=lv.leaf_cell_number,
                shape=lv.shape,
            )
        )
    return levels


def build_chain_levels(
    chain: CellChain,
    cell_types: Dict[str, api.CellTypeSpec],
    mesh_chains: Dict[CellChain, MeshChain],
) -> List[ChainLevel]:
    spec = cell_types.get(chain)
    if spec is not None and spec.mesh is not None:
        if chain not in mesh_chains:
            mesh_chains[chain] = MeshChain(chain, spec.mesh)
        return _build_mesh_levels(mesh_chains[chain])
    return _build_generic_levels(chain, cell_types)


def _level_of_type(levels: List[ChainLevel], cell_type: str) -> Optional[ChainLevel]:
    for lv in levels:
        if lv.cell_type == cell_type:
            return lv
    return None


# ---------------------------------------------------------------------------
# Physical tree builder
# ---------------------------------------------------------------------------


class PhysicalTreeBuilder:
    """Reference: physicalCellConstructor, config.go:110-235."""

    def __init__(self, cell_types: Dict[str, api.CellTypeSpec]):
        self.cell_types = cell_types
        self.full_list: Dict[CellChain, ChainCellList] = {}
        self.free_list: Dict[CellChain, ChainCellList] = {}
        self.pinned_cells: Dict[str, PhysicalCell] = {}
        self.chain_levels: Dict[CellChain, List[ChainLevel]] = {}
        self.mesh_chains: Dict[CellChain, MeshChain] = {}
        # node name -> cellAddress per mesh chain, to reject two physical
        # cells deriving the same node (double-counted chip capacity)
        self._mesh_chain_nodes: Dict[CellChain, Dict[str, str]] = {}

    def build(self, specs: List[api.PhysicalCellSpec]) -> None:
        for spec in specs:
            chain = spec.cell_type
            levels = self.chain_levels.get(chain)
            if levels is None:
                levels = build_chain_levels(chain, self.cell_types, self.mesh_chains)
                self.chain_levels[chain] = levels
            top = levels[-1]
            if top.cell_type != chain:
                raise ValueError(f"physicalCells top cellType {chain} is not a chain top")
            if not top.has_node:
                raise ValueError(f"top cell must be node-level or above: {chain}")
            if chain in self.mesh_chains:
                root = self._build_mesh_cell(
                    chain, self.mesh_chains[chain], spec, top.level,
                    (0,) * len(self.mesh_chains[chain].spec.topology),
                )
                seen = self._mesh_chain_nodes.setdefault(chain, {})
                for n in root.nodes:
                    if n in seen:
                        raise ValueError(
                            f"physical cells {seen[n]!r} and "
                            f"{spec.cell_address!r} of chain {chain} derive "
                            f"the same node name {n!r}; include {{cell}} in "
                            "hostNameFormat so hosts stay distinct"
                        )
                    seen[n] = spec.cell_address
            else:
                root = self._build_generic_cell(chain, levels, spec, top, "")
            root.api_status.leaf_cell_type = top.leaf_cell_type
            free = self.free_list.setdefault(chain, ChainCellList.new(top.level))
            free[root.level].append(root)

    def _register(
        self,
        chain: CellChain,
        lv: ChainLevel,
        pid: str,
        address: str,
        mesh_origin: Optional[Tuple[int, ...]] = None,
    ) -> PhysicalCell:
        """Reference: physicalCellConstructor.addCell, config.go:186-204."""
        cell = PhysicalCell(
            chain=chain,
            level=lv.level,
            at_or_higher_than_node=lv.has_node,
            total_leaf_cell_num=lv.leaf_cell_number,
            cell_type=lv.cell_type,
            address=address,
            is_node_level=lv.is_node_level,
            mesh_origin=mesh_origin,
            mesh_shape=lv.shape,
        )
        full = self.full_list.setdefault(chain, ChainCellList())
        full.setdefault(lv.level, []).append(cell)
        if pid:
            self.pinned_cells[pid] = cell
            cell.pinned = True
        return cell

    # -- generic chains ------------------------------------------------------

    def _build_generic_cell(
        self,
        chain: CellChain,
        levels: List[ChainLevel],
        spec: api.PhysicalCellSpec,
        lv: ChainLevel,
        current_node: str,
    ) -> PhysicalCell:
        """Reference: buildChildCell, config.go:140-183."""
        last = spec.cell_address.split("/")[-1]
        if lv.is_node_level:
            current_node = last
        cell = self._register(chain, lv, spec.pinned_cell_id, spec.cell_address)
        if lv.level == LOWEST_LEVEL:
            cell.set_physical_resources([current_node], [int(last)])
            return cell
        child_lv = levels[lv.level - 2]
        nodes: List[str] = []
        leaf_indices: List[int] = []
        children: CellList = []
        for child_spec in spec.cell_children:
            child = self._build_generic_cell(chain, levels, child_spec, child_lv, current_node)
            child.parent = cell
            children.append(child)
            if lv.is_multi_nodes:
                nodes.extend(child.nodes)
            else:
                leaf_indices.extend(child.leaf_cell_indices)
        cell.set_children(children)
        if lv.is_multi_nodes:
            leaf_indices = [-1]
        else:
            nodes = [current_node]
        cell.set_physical_resources(nodes, leaf_indices)
        return cell

    # -- mesh chains ---------------------------------------------------------

    def _mesh_pin_lookup(
        self, spec: api.PhysicalCellSpec, mesh_chain: MeshChain
    ) -> Dict[Tuple[int, Tuple[int, ...]], str]:
        """Pinned sub-cells of a mesh chain are declared as cellChildren with a
        named level type and an origin coordinate address (``x-y-z``)."""
        pins: Dict[Tuple[int, Tuple[int, ...]], str] = {}
        for child in spec.cell_children:
            level = mesh_chain.level_of_type(child.cell_type)
            if level is None:
                raise ValueError(
                    f"pinned cell type {child.cell_type} is not a level of mesh chain "
                    f"{mesh_chain.chain_name}"
                )
            origin = tuple(int(x) for x in child.cell_address.split("/")[-1].split("-"))
            lv = mesh_chain.level(level)
            dims = len(mesh_chain.spec.topology)
            if (
                len(origin) != dims
                or any(o % s for o, s in zip(origin, lv.shape))
                or any(o + s > t for o, s, t in zip(origin, lv.shape, mesh_chain.spec.topology))
            ):
                raise ValueError(
                    f"pinned cell origin {origin} is not an aligned in-bounds {lv.shape} tile "
                    f"origin in mesh chain {mesh_chain.chain_name}"
                )
            pins[(level, origin)] = child.pinned_cell_id
        return pins

    def _build_mesh_cell(
        self,
        chain: CellChain,
        mesh_chain: MeshChain,
        spec: api.PhysicalCellSpec,
        top_level: int,
        top_origin: Tuple[int, ...],
    ) -> PhysicalCell:
        pins = self._mesh_pin_lookup(spec, mesh_chain)
        top_address = spec.cell_address
        levels = self.chain_levels[chain]
        if mesh_chain.spec.host_name_format is not None:
            # a custom format exists to target a REAL control plane: derived
            # node names must be legal K8s (DNS-1123 subdomain) names and
            # must vary with the host coordinate
            fmt = mesh_chain.spec.host_name_format
            if "{coords}" not in fmt:
                raise ValueError(
                    f"hostNameFormat {fmt!r} must contain {{coords}} so each "
                    "host gets a distinct node name"
                )
            try:
                sample = mesh_chain.node_name(
                    top_address, tuple(0 for _ in mesh_chain.spec.topology)
                )
            except (KeyError, IndexError) as e:
                raise ValueError(
                    f"hostNameFormat {fmt!r} has an unknown placeholder "
                    f"({e}); only {{cell}} and {{coords}} are available"
                ) from None
            # real DNS-1123: <=253 chars total, dot-separated labels each
            # <=63 chars of [a-z0-9-] with alphanumeric ends
            label = r"[a-z0-9]([-a-z0-9]{0,61}[a-z0-9])?"
            if len(sample) > 253 or not all(
                re.fullmatch(label, part) for part in sample.split(".")
            ):
                raise ValueError(
                    f"hostNameFormat {fmt!r} yields {sample!r}, not a legal "
                    "K8s node name (lowercase DNS-1123 subdomain)"
                )

        def rec(level: int, origin: Tuple[int, ...], current_node: str) -> PhysicalCell:
            lv = levels[level - 1]
            if lv.is_node_level:
                address = mesh_chain.node_name(top_address, origin)
                current_node = address
            elif level == top_level:
                address = top_address
            elif lv.has_node:
                address = f"{top_address}/s{coord_str(origin)}"
            elif level == LOWEST_LEVEL:
                address = f"{current_node}/{mesh_chain.chip_index_in_host(origin)}"
            else:
                address = f"{current_node}/m{coord_str(origin)}"
            pid = spec.pinned_cell_id if level == top_level else pins.get((level, origin), "")
            cell = self._register(chain, lv, pid, address, mesh_origin=origin)
            if level == LOWEST_LEVEL:
                cell.set_physical_resources(
                    [current_node], [mesh_chain.chip_index_in_host(origin)]
                )
                return cell
            child_lv = levels[level - 2]
            nodes: List[str] = []
            leaf_indices: List[int] = []
            children: CellList = []
            for child_origin in tile_origins(origin, lv.shape, child_lv.shape):
                child = rec(level - 1, child_origin, current_node)
                child.parent = cell
                children.append(child)
                if lv.is_multi_nodes:
                    nodes.extend(child.nodes)
                else:
                    leaf_indices.extend(child.leaf_cell_indices)
            cell.set_children(children)
            if lv.is_multi_nodes:
                leaf_indices = [-1]
            else:
                nodes = [current_node]
            cell.set_physical_resources(nodes, leaf_indices)
            return cell

        return rec(top_level, top_origin, "")


# ---------------------------------------------------------------------------
# Virtual tree builder
# ---------------------------------------------------------------------------


class VirtualTreeBuilder:
    """Reference: virtualCellConstructor, config.go:237-413."""

    def __init__(
        self,
        cell_types: Dict[str, api.CellTypeSpec],
        chain_levels: Dict[CellChain, List[ChainLevel]],
        mesh_chains: Dict[CellChain, MeshChain],
        raw_pinned_physical: Dict[str, PhysicalCell],
    ):
        self.cell_types = cell_types
        self.chain_levels = chain_levels
        self.mesh_chains = mesh_chains
        self.raw_pinned_physical = raw_pinned_physical
        self.vc_free_cell_num: Dict[str, Dict[CellChain, Dict[CellLevel, int]]] = {}
        self.non_pinned_full: Dict[str, Dict[CellChain, ChainCellList]] = {}
        self.non_pinned_free: Dict[str, Dict[CellChain, ChainCellList]] = {}
        self.pinned_list: Dict[str, Dict[str, ChainCellList]] = {}
        self.pinned_physical: Dict[str, Dict[str, PhysicalCell]] = {}

    def _levels_for(self, chain: CellChain) -> List[ChainLevel]:
        levels = self.chain_levels.get(chain)
        if levels is None:
            levels = build_chain_levels(chain, self.cell_types, self.mesh_chains)
            self.chain_levels[chain] = levels
        return levels

    def build(self, specs: Dict[str, api.VirtualClusterSpec]) -> None:
        for vc, spec in specs.items():
            self.vc_free_cell_num[vc] = {}
            self.non_pinned_full[vc] = {}
            self.non_pinned_free[vc] = {}
            self.pinned_list[vc] = {}
            self.pinned_physical[vc] = {}
            num_cells = 0
            for vcell in spec.virtual_cells:
                parts = vcell.cell_type.split(".")
                chain = parts[0]
                root_type = parts[-1]
                levels = self._levels_for(chain)
                root_lv = _level_of_type(levels, root_type)
                if root_lv is None:
                    raise ValueError(
                        f"cellType {vcell.cell_type} in VC {vc} not found in chain {chain}"
                    )
                self.vc_free_cell_num[vc].setdefault(chain, {})
                self.vc_free_cell_num[vc][chain][root_lv.level] = (
                    self.vc_free_cell_num[vc][chain].get(root_lv.level, 0) + vcell.cell_number
                )
                for _ in range(vcell.cell_number):
                    root = self._build_tree(
                        vc, chain, levels, root_lv, f"{vc}/{num_cells}", pid=""
                    )
                    free = self.non_pinned_free[vc].setdefault(chain, ChainCellList())
                    free.setdefault(root.level, []).append(root)
                    num_cells += 1
            for pcell in spec.pinned_cells:
                pid = pcell.pinned_cell_id
                pc = self.raw_pinned_physical.get(pid)
                if pc is None:
                    raise ValueError(
                        f"pinned cell not found in physicalCells: VC: {vc}, ID: {pid}"
                    )
                self.pinned_physical[vc][pid] = pc
                levels = self._levels_for(pc.chain)
                root_lv = levels[pc.level - 1]
                self.vc_free_cell_num[vc].setdefault(pc.chain, {})
                self.vc_free_cell_num[vc][pc.chain][pc.level] = (
                    self.vc_free_cell_num[vc][pc.chain].get(pc.level, 0) + 1
                )
                self._build_tree(vc, pc.chain, levels, root_lv, f"{vc}/{num_cells}", pid=pid)
                num_cells += 1

    def _build_tree(
        self,
        vc: str,
        chain: CellChain,
        levels: List[ChainLevel],
        root_lv: ChainLevel,
        address: str,
        pid: str,
    ) -> VirtualCell:
        root_holder: List[Optional[VirtualCell]] = [None]

        def rec(lv: ChainLevel, addr: str) -> VirtualCell:
            cell = VirtualCell(
                vc=vc,
                chain=chain,
                level=lv.level,
                at_or_higher_than_node=lv.has_node,
                total_leaf_cell_num=lv.leaf_cell_number,
                preassigned_cell=None,
                cell_type=lv.cell_type,
                address=addr,
                is_node_level=lv.is_node_level,
            )
            if pid:
                plist = self.pinned_list[vc].setdefault(pid, ChainCellList())
                plist.setdefault(lv.level, []).append(cell)
                cell.set_pinned_cell_id(pid)
            else:
                full = self.non_pinned_full[vc].setdefault(chain, ChainCellList())
                full.setdefault(lv.level, []).append(cell)
            if root_holder[0] is None:
                root_holder[0] = cell
            cell.preassigned_cell = root_holder[0]
            if lv.level == LOWEST_LEVEL:
                return cell
            # Child addresses carry flat indices within the preassigned cell:
            # offset resets to 0 under the root (reference: config.go:326-333).
            parts = addr.split("/")
            offset = 0 if len(parts) == 2 else int(parts[-1]) * lv.child_number
            children: CellList = []
            child_lv = levels[lv.level - 2]
            for i in range(lv.child_number):
                child = rec(child_lv, f"{addr}/{offset + i}")
                child.parent = cell
                children.append(child)
            cell.set_children(children)
            return cell

        root = rec(root_lv, address)
        root.api_status.leaf_cell_type = root_lv.leaf_cell_type
        return root


# ---------------------------------------------------------------------------
# Top level
# ---------------------------------------------------------------------------


def parse_config(config: Config) -> ParsedConfig:
    """Reference: ParseConfig, config.go:442-477."""
    cell_types = config.physical_cluster.cell_types
    pb = PhysicalTreeBuilder(cell_types)
    pb.build(config.physical_cluster.physical_cells)

    vb = VirtualTreeBuilder(cell_types, pb.chain_levels, pb.mesh_chains, pb.pinned_cells)
    vb.build(config.virtual_clusters)

    out = ParsedConfig(
        physical_full_list=pb.full_list,
        physical_free_list=pb.free_list,
        vc_free_cell_num=vb.vc_free_cell_num,
        virtual_non_pinned_full=vb.non_pinned_full,
        virtual_non_pinned_free=vb.non_pinned_free,
        virtual_pinned_cells=vb.pinned_list,
        physical_pinned_cells=vb.pinned_physical,
        chain_levels=pb.chain_levels,
        mesh_chains=pb.mesh_chains,
    )
    for chain in pb.full_list:
        levels = pb.chain_levels[chain]
        out.cell_level_to_leaf_cell_num[chain] = {
            lv.level: lv.leaf_cell_number for lv in levels
        }
        out.cell_level_to_type[chain] = {lv.level: lv.cell_type for lv in levels}
        leaf_type = levels[-1].leaf_cell_type
        out.leaf_cell_type_to_chain.setdefault(leaf_type, []).append(chain)
    return out
