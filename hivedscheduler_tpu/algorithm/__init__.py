"""Scheduling algorithm core: cell model, placement search, buddy allocation.

TPU-native analogue of the reference's ``pkg/algorithm``.
"""

from hivedscheduler_tpu.algorithm.hived import HivedAlgorithm  # noqa: F401
