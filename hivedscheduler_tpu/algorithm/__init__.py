"""Scheduling algorithm core: cell model, placement search, buddy allocation.

TPU-native analogue of the reference's ``pkg/algorithm``.
"""
