"""Buddy allocation, safe-relaxed splitting, virtual<->physical cell mapping,
and cell binding primitives.

TPU-native analogue of the reference's ``pkg/algorithm/cell_allocation.go``.
On a mesh chain, a buddy split is a mesh tiling (children of a cell are the
sub-mesh tiles of the next-lower level), so every allocation these routines
hand out is a contiguous ICI sub-mesh by construction; the backtracking exists
only because cells can be bad or outside K8s suggested nodes
(reference comment: ``cell_allocation.go:36-41``).
"""

from __future__ import annotations

import logging
from typing import Dict, List, Optional, Set, Tuple

from hivedscheduler_tpu.algorithm.cell import (
    Cell,
    CellLevel,
    CellPriority,
    PhysicalCell,
    VirtualCell,
)
from hivedscheduler_tpu.algorithm.constants import (
    FREE_PRIORITY,
    LOWEST_LEVEL,
    MAX_GUARANTEED_PRIORITY,
    OPPORTUNISTIC_PRIORITY,
)
from hivedscheduler_tpu.algorithm.types import CellBindingPathVertex, CellList, ChainCellList

log = logging.getLogger(__name__)


class VCSafetyBroken(AssertionError):
    """Raised when an operation would violate the VC-safety invariant."""


def _top_level(ccl: ChainCellList) -> CellLevel:
    return max(ccl) if ccl else LOWEST_LEVEL


def buddy_alloc(
    cell: CellBindingPathVertex,
    free_list: ChainCellList,
    current_level: CellLevel,
    suggested_nodes: Set[str],
    ignore_suggested_nodes: bool,
    bindings: Dict[str, PhysicalCell],
) -> bool:
    """Backtracking buddy allocation of a free physical cell for a preassigned
    virtual cell; splits a higher-level cell when the current level is empty
    (reference: buddyAlloc, cell_allocation.go:42-80). On mesh chains a split
    is a mesh bisection/tiling, keeping every free cell contiguous."""
    if current_level == cell.cell.level:
        ok, picked = map_virtual_cells_to_physical(
            [cell], free_list[current_level], suggested_nodes, ignore_suggested_nodes,
            bindings, return_picked=True,
        )
        if ok:
            for c in picked:
                free_list.remove(c, current_level)
            return True
        return False
    free_cells = get_usable_physical_cells(
        free_list[current_level], 1, suggested_nodes, ignore_suggested_nodes
    )
    if free_cells is None:
        return False
    for c in free_cells:
        free_list[current_level - 1] = free_list[current_level - 1] + list(c.children)
        if buddy_alloc(
            cell, free_list, current_level - 1, suggested_nodes, ignore_suggested_nodes, bindings
        ):
            free_list.remove(c, current_level)
            return True
        free_list[current_level - 1] = []
    return False


def safe_relaxed_buddy_alloc(
    cell: CellBindingPathVertex,
    free_list: ChainCellList,
    free_cell_num: Dict[CellLevel, int],
    current_level: CellLevel,
    suggested_nodes: Set[str],
    ignore_suggested_nodes: bool,
    bindings: Dict[str, PhysicalCell],
) -> bool:
    """When buddy alloc fails (bad cells / non-suggested nodes), split
    higher-level cells *without* violating VC safety: a level may only donate
    ``len(freeList[l]) - freeCellNum[l]`` cells, where ``freeCellNum`` is the
    number other VCs may still claim at that level (reference:
    safeRelaxedBuddyAlloc, cell_allocation.go:84-150)."""
    top = _top_level(free_list)
    splittable_cell: Optional[Cell] = None
    splittable_num: Dict[CellLevel, int] = {}
    for i in range(top, current_level, -1):
        splittable_num[i] = len(free_list[i]) - free_cell_num.get(i, 0)
        if i < top and splittable_cell is not None:
            splittable_num[i] += splittable_num[i + 1] * len(splittable_cell.children)
        if splittable_cell is None and len(free_list[i]) > 0:
            splittable_cell = free_list[i][0]
        elif splittable_cell is not None:
            splittable_cell = splittable_cell.children[0]
        if splittable_num[i] < 0:
            raise VCSafetyBroken(
                f"VC Safety Broken: level {i} cell with free list {free_list[i]} is "
                f"unsplittable, splittableNum={splittable_num[i]}"
            )

    for l in range(current_level + 1, top + 1):
        cell_num = min(len(free_list[l]), splittable_num[l])
        if cell_num > 0:
            split_list: CellList = []
            for _ in range(cell_num):
                split_list.append(free_list[l][0])
                free_list.remove(free_list[l][0], l)
            splittable_num[l] -= cell_num
            for _ in range(l, current_level, -1):
                split_list = [child for sc in split_list for child in sc.children]
            free_list[current_level] = split_list + free_list[current_level]
            ok, picked = map_virtual_cells_to_physical(
                [cell], free_list[current_level], suggested_nodes, ignore_suggested_nodes,
                bindings, return_picked=True,
            )
            if ok:
                for c in picked:
                    free_list.remove(c, current_level)
                return True
    return False


def get_lowest_free_cell_level(free_list: ChainCellList, level: CellLevel) -> CellLevel:
    """Reference: getLowestFreeCellLevel, cell_allocation.go:153-161."""
    top = _top_level(free_list)
    for l in range(level, top + 1):
        if len(free_list[l]) != 0:
            return l
    raise VCSafetyBroken(
        f"VC Safety Broken: free cell not found even split to the highest level {top}"
    )


def map_virtual_placement_to_physical(
    preassigned_cells: List[CellBindingPathVertex],
    non_preassigned_cells: List[List[CellBindingPathVertex]],
    free_list: ChainCellList,
    free_cell_num: Dict[CellLevel, int],
    suggested_nodes: Set[str],
    ignore_suggested_nodes: bool,
    bindings: Dict[str, PhysicalCell],
) -> bool:
    """Map a VC placement to the physical cluster: preassigned cells via buddy
    alloc, non-preassigned cells following the preassigned cell's physical
    topology (reference: mapVirtualPlacementToPhysical,
    cell_allocation.go:166-197)."""
    for c in preassigned_cells:
        if not buddy_alloc(
            c, free_list, get_lowest_free_cell_level(free_list, c.cell.level),
            suggested_nodes, ignore_suggested_nodes, bindings,
        ):
            log.info("Buddy allocation failed due to bad cells, trying to split higher-level cells")
            if not safe_relaxed_buddy_alloc(
                c, free_list, free_cell_num, c.cell.level,
                suggested_nodes, ignore_suggested_nodes, bindings,
            ):
                log.info("Cannot split higher level cells")
                return False
        else:
            free_cell_num[c.cell.level] = free_cell_num.get(c.cell.level, 0) - 1
    for cells in non_preassigned_cells:
        parent = cells[0].cell.parent
        assert isinstance(parent, VirtualCell) and parent.physical_cell is not None
        ok, _ = map_virtual_cells_to_physical(
            cells, parent.physical_cell.children, suggested_nodes, ignore_suggested_nodes,
            bindings, return_picked=False,
        )
        if not ok:
            return False
    return True


def get_usable_physical_cells(
    candidates: CellList,
    num_needed: int,
    suggested_nodes: Set[str],
    ignore_suggested_nodes: bool,
) -> Optional[CellList]:
    """Filter out bound cells, bad single-node cells, and cells entirely
    outside suggested nodes; sort by fewest opportunistic pods to reduce
    preemption (reference: getUsablePhysicalCells, cell_allocation.go:200-243)."""
    usable: List[PhysicalCell] = []
    for cand in candidates:
        if cand.virtual_cell is not None:
            continue
        nodes = cand.nodes  # == get_physical_placement()[0]
        if len(nodes) == 1 and not cand.healthy:
            continue
        if not ignore_suggested_nodes:
            if all(n not in suggested_nodes for n in nodes):
                continue
        usable.append(cand)
    if len(usable) < num_needed:
        return None
    if len(usable) > 1:
        usable.sort(
            key=lambda c: c.used_leaf_cell_num_at_priorities.get(OPPORTUNISTIC_PRIORITY, 0)
        )
    return usable


def map_virtual_cells_to_physical(
    cells: List[CellBindingPathVertex],
    candidates: CellList,
    suggested_nodes: Set[str],
    ignore_suggested_nodes: bool,
    bindings: Dict[str, PhysicalCell],
    return_picked: bool,
) -> Tuple[bool, Optional[CellList]]:
    """Backtracking assignment of virtual cells to physical candidates, level
    by level; children candidates are the picked cell's children, preserving
    topology equivalence inside the preassigned cell (reference:
    mapVirtualCellsToPhysical, cell_allocation.go:252-315)."""
    usable = get_usable_physical_cells(
        candidates, len(cells), suggested_nodes, ignore_suggested_nodes
    )
    if usable is None:
        return False, None
    cell_index = 0
    picked_candidate_indices = [0] * len(cells)
    picked_index_set: Set[int] = set()
    while cell_index >= 0:
        candidate_index = picked_candidate_indices[cell_index]
        while candidate_index < len(usable):
            if candidate_index in picked_index_set:
                candidate_index += 1
                continue
            candidate = usable[candidate_index]
            assert isinstance(candidate, PhysicalCell)
            if candidate.level == LOWEST_LEVEL:
                picked = True
                bindings[cells[cell_index].cell.address] = candidate
            else:
                picked, _ = map_virtual_cells_to_physical(
                    cells[cell_index].children_to_bind,
                    candidate.children,
                    suggested_nodes,
                    ignore_suggested_nodes,
                    bindings,
                    return_picked=False,
                )
            if picked:
                picked_candidate_indices[cell_index] = candidate_index
                picked_index_set.add(candidate_index)
                if cell_index == len(cells) - 1:
                    if not return_picked:
                        return True, None
                    return True, [usable[i] for i in picked_candidate_indices]
                break
            candidate_index += 1
        if candidate_index == len(usable):
            cell_index -= 1
            if cell_index >= 0:
                picked_index_set.discard(picked_candidate_indices[cell_index])
                picked_candidate_indices[cell_index] += 1
        else:
            cell_index += 1
    return False, None


def map_physical_cell_to_virtual(
    c: PhysicalCell,
    vccl: ChainCellList,
    preassigned_level: CellLevel,
    p: CellPriority,
) -> Tuple[Optional[VirtualCell], str]:
    """Inverse mapping used during recovery of allocated pods (reference:
    mapPhysicalCellToVirtual, cell_allocation.go:320-346)."""
    if c.virtual_cell is not None:
        return c.virtual_cell, ""
    if c.level == preassigned_level:
        pre = get_lowest_priority_virtual_cell(vccl[preassigned_level], p)
        if pre is None:
            return None, (
                f"insufficient free cell in the VC at the preassigned level ({preassigned_level})"
            )
        return pre, ""
    if c.parent is None:
        return None, (
            f"physical and virtual cell hierarchies not match "
            f"(cannot reach the preassigned level {preassigned_level} in physical)"
        )
    assert isinstance(c.parent, PhysicalCell)
    parent_virtual, message = map_physical_cell_to_virtual(
        c.parent, vccl, preassigned_level, p
    )
    if parent_virtual is None:
        return None, message
    return get_lowest_priority_virtual_cell(parent_virtual.children, p), ""


def get_lowest_priority_virtual_cell(cl: CellList, p: CellPriority) -> Optional[VirtualCell]:
    """Lowest-priority virtual cell among those with priority < p. A free cell
    with a binding is skipped — such a binding (e.g., for a doomed bad cell)
    cannot be preempted (reference: getLowestPriorityVirtualCell,
    cell_allocation.go:352-372)."""
    lowest_priority = MAX_GUARANTEED_PRIORITY
    lowest_cell: Optional[VirtualCell] = None
    for c in cl:
        assert isinstance(c, VirtualCell)
        priority = c.priority
        if priority == FREE_PRIORITY:
            if c.physical_cell is None:
                return c
            continue
        if priority < p and priority < lowest_priority:
            lowest_priority = priority
            lowest_cell = c
    return lowest_cell


def get_unbound_virtual_cell(cl: CellList) -> Optional[VirtualCell]:
    """Reference: getUnboundVirtualCell, cell_allocation.go:375-382."""
    for c in cl:
        assert isinstance(c, VirtualCell)
        if c.physical_cell is None:
            return c
    return None


def bind_cell(pc: PhysicalCell, vc: VirtualCell) -> None:
    """Bind a virtual cell chainward up-tree, starting from leaf level
    (reference: bindCell, cell_allocation.go:386-398)."""
    log_on = log.isEnabledFor(logging.INFO)  # one bind per cell of a gang
    while vc.physical_cell is None:
        pc.set_virtual_cell(vc)
        vc.set_physical_cell(pc)
        if log_on:
            log.info("Virtual cell %s is bound to physical cell %s", vc.address, pc.address)
        if vc.parent is None:
            break
        vc = vc.parent  # type: ignore[assignment]
        pc = pc.parent  # type: ignore[assignment]


def unbind_cell(c: PhysicalCell) -> None:
    """Unbind up-tree until an ancestor is pinned or still has bound children
    (reference: unbindCell, cell_allocation.go:402-420)."""
    bound_virtual = c.virtual_cell
    log_on = log.isEnabledFor(logging.INFO)  # one unbind per cell of a gang
    while not bound_virtual.physical_cell.pinned:
        bound_physical = bound_virtual.physical_cell
        if log_on:
            log.info(
                "Virtual cell %s is unbound from physical cell %s",
                bound_virtual.address, bound_physical.address,
            )
        bound_virtual.set_physical_cell(None)
        bound_physical.set_virtual_cell(None)
        if bound_virtual.parent is None:
            return
        for cc in bound_virtual.parent.children:
            assert isinstance(cc, VirtualCell)
            if cc.physical_cell is not None:
                return
        bound_virtual = bound_virtual.parent  # type: ignore[assignment]


def set_cell_priority(c: Cell, p: CellPriority) -> None:
    """Set priority keeping the invariant parent = max(children) (reference:
    setCellPriority, cell_allocation.go:425-441)."""
    original_priority = c.priority
    c.set_priority(p)
    parent = c.parent
    if parent is not None:
        if p > parent.priority:
            set_cell_priority(parent, p)
        elif original_priority == parent.priority and p < original_priority:
            max_buddy_priority = FREE_PRIORITY
            for buddy in parent.children:
                if buddy.priority > max_buddy_priority:
                    max_buddy_priority = buddy.priority
            set_cell_priority(parent, max_buddy_priority)


def update_used_leaf_cell_num_at_priority(c: Optional[Cell], p: CellPriority, increase: bool) -> None:
    """Reference: updateUsedLeafCellNumAtPriority, cell_allocation.go:445-454.

    Inlined dict update: this walk runs once per leaf per alloc/release on
    both cell trees, making it the hottest loop in gang bookkeeping."""
    delta = 1 if increase else -1
    while c is not None:
        d = c.used_leaf_cell_num_at_priorities
        n = d.get(p, 0) + delta
        if n == 0:
            d.pop(p, None)
        else:
            d[p] = n
        c.view_gen += 1
        c = c.parent


class UsedCountBatch:
    """Deferred used-leaf-cell-count updates for whole-gang bookkeeping.

    A 256-leaf gang runs one leaf->root walk per leaf per tree; the count
    half of those walks writes the same ancestor dicts 256 times.  Group
    lifecycle operations (create/delete allocated or preempting groups, lazy
    preemption) instead collect per-leaf ``(cell, priority, delta)`` records
    and :meth:`flush` applies the *sums* bottom-up, one dict update per
    distinct ancestor — O(distinct cells) instead of O(leaves x depth).

    Deferral is observationally safe because nothing inside those loops reads
    ``used_leaf_cell_num_at_priorities``: the readers (cluster-view sorting in
    ``topology_aware``, candidate ranking in ``get_usable_physical_cells``,
    multi-chain capacity ranking, inspect) all run outside an open batch
    window, and priority/binding/free-list state keeps updating per leaf.
    Guard: ``tests/test_walk_fusion.py::test_batched_walks_match_composition``.
    """

    __slots__ = ("_groups",)

    def __init__(self) -> None:
        # priority -> {cell: signed count} — cells hash by identity (no
        # __eq__), so keying by the object itself skips the id() indirection;
        # merged at add time, so N same-priority ops on one leaf collapse to
        # a single entry
        self._groups: Dict[CellPriority, Dict[Cell, int]] = {}

    def add(self, c: Cell, p: CellPriority, delta: int) -> None:
        g = self._groups.get(p)
        if g is None:
            g = self._groups[p] = {}
        g[c] = g.get(c, 0) + delta

    def flush(self) -> None:
        if not self._groups:
            return
        groups, self._groups = self._groups, {}
        for p, frontier in groups.items():
            # propagate strictly by level so a parent receives every child's
            # contribution before its own dict is touched (virtual and
            # physical cells mix freely: parent chains are independent);
            # zero net contributions (alloc+release merged in one batch)
            # are dropped instead of propagated
            by_level: Dict[CellLevel, Dict[Cell, int]] = {}
            for c, n in frontier.items():
                if not n:
                    continue
                lv = by_level.get(c.level)
                if lv is None:
                    lv = by_level[c.level] = {}
                lv[c] = n
            while by_level:
                l = min(by_level)
                for c, n in by_level.pop(l).items():
                    if not n:  # children's contributions cancelled
                        continue
                    counts = c.used_leaf_cell_num_at_priorities
                    m = counts.get(p, 0) + n
                    if m == 0:
                        counts.pop(p, None)
                    else:
                        counts[p] = m
                    c.view_gen += 1
                    parent = c.parent
                    if parent is not None:
                        lv = by_level.get(parent.level)
                        if lv is None:
                            lv = by_level[parent.level] = {}
                        lv[parent] = lv.get(parent, 0) + n


def allocate_cell_walk(
    c: Cell, p: CellPriority, batch: Optional[UsedCountBatch] = None
) -> None:
    """Fused ``set_cell_priority(c, p)`` + ``update_used_leaf_cell_num_at_priority
    (c, p, True)`` in one leaf->root walk — the leaf-allocation hot path runs
    both over the same ancestor chain, and the two touch disjoint state
    (priority + api mirrors vs. the used-count dicts), so interleaving them is
    observationally identical (guard: ``tests/test_walk_fusion.py``).

    The fast path assumes a pure priority *raise* (``p >= c.priority`` — always
    true when allocating a free leaf); anything else falls back to the exact
    two-step composition.

    With ``batch``, the count half is deferred to ``batch.flush()`` and the
    priority half is exactly ``set_cell_priority`` (which early-exits as soon
    as an ancestor already holds priority >= p, so the 2nd..Nth leaf of a
    gang stops after a step or two)."""
    if batch is not None:
        batch.add(c, p, 1)
        if p < c.priority:
            set_cell_priority(c, p)
        else:
            # inline raise-only set_cell_priority: with p >= c.priority only
            # the raise branch can fire, stopping at the first ancestor
            # already holding >= p (the 2nd..Nth leaf of a gang stops after
            # a step or two) — saves a recursive call per leaf on the
            # gang-create hot path
            cur: Optional[Cell] = c
            first = True
            while cur is not None and (first or p > cur.priority):
                cur.set_priority(p)
                first = False
                cur = cur.parent
        return
    if p < c.priority:
        set_cell_priority(c, p)
        update_used_leaf_cell_num_at_priority(c, p, True)
        return
    cur: Optional[Cell] = c
    raising = True
    first = True
    while cur is not None:
        if raising:
            if first or p > cur.priority:
                cur.set_priority(p)
            else:
                # invariant parent = max(children): priorities are monotone
                # non-decreasing up the path, so no higher ancestor needs a
                # raise either
                raising = False
        d = cur.used_leaf_cell_num_at_priorities
        d[p] = d.get(p, 0) + 1
        cur.view_gen += 1
        first = False
        cur = cur.parent


def release_cell_walk(
    c: Cell, old_p: CellPriority, batch: Optional[UsedCountBatch] = None
) -> None:
    """Fused ``update_used_leaf_cell_num_at_priority(c, old_p, False)`` +
    ``set_cell_priority(c, FREE_PRIORITY)`` in one leaf->root walk (the
    leaf-release hot path); same disjoint-state argument as
    ``allocate_cell_walk``, guarded by ``tests/test_walk_fusion.py``.

    With ``batch``, the count half is deferred to ``batch.flush()`` and the
    priority half is exactly ``set_cell_priority(c, FREE_PRIORITY)`` (which
    stops as soon as the downgrade no longer changes an ancestor)."""
    if batch is not None:
        batch.add(c, old_p, -1)
        set_cell_priority(c, FREE_PRIORITY)
        return
    target = FREE_PRIORITY
    prio_active = True
    cur: Optional[Cell] = c
    while cur is not None:
        d = cur.used_leaf_cell_num_at_priorities
        n = d.get(old_p, 0) - 1
        if n == 0:
            d.pop(old_p, None)
        else:
            d[old_p] = n
        cur.view_gen += 1
        if prio_active:
            original = cur.priority
            cur.set_priority(target)
            parent = cur.parent
            if parent is None:
                prio_active = False
            elif target > parent.priority:
                pass  # mirror set_cell_priority's raise branch (unreachable
                # on release: target <= original <= parent.priority)
            elif original == parent.priority and target < original:
                max_buddy_priority = FREE_PRIORITY
                for buddy in parent.children:
                    if buddy.priority > max_buddy_priority:
                        max_buddy_priority = buddy.priority
                target = max_buddy_priority
            else:
                prio_active = False
        cur = cur.parent
