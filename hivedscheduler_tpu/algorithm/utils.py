"""Result generation, victim collection, recovery helpers.

TPU-native analogue of the reference's ``pkg/algorithm/utils.go``.
"""

from __future__ import annotations

import logging
import random
from typing import Dict, List, Optional, Set, Tuple

from hivedscheduler_tpu.api import types as api
from hivedscheduler_tpu.algorithm.cell import CellChain, CellLevel, PhysicalCell, VirtualCell, cell_equal
from hivedscheduler_tpu.algorithm.constants import (
    CELL_RESERVED,
    CELL_RESERVING,
    CELL_USED,
    GROUP_PREEMPTING,
    OPPORTUNISTIC_PRIORITY,
)
from hivedscheduler_tpu.algorithm.types import (
    AlgoAffinityGroup,
    ChainCellList,
    GroupPhysicalPlacement,
    GroupVirtualPlacement,
)
from hivedscheduler_tpu.k8s.types import Pod
from hivedscheduler_tpu.runtime import utils as internal
from hivedscheduler_tpu.runtime.types import (
    PodPreemptInfo,
    PodScheduleResult,
    PodWaitInfo,
)

log = logging.getLogger(__name__)


def generate_pod_schedule_result(
    group_physical_placement: Optional[GroupPhysicalPlacement],
    group_virtual_placement: Optional[GroupVirtualPlacement],
    preemption_victims: Dict[str, Dict[str, Pod]],
    wait_reason: str,
    cell_level_to_type: Dict[CellChain, Dict[CellLevel, str]],
    current_leaf_cell_num: int,
    current_pod_index: int,
    group: Optional[AlgoAffinityGroup],
    group_name: str,
    suggested_nodes: Set[str],
    pod: Pod,
) -> PodScheduleResult:
    """wait | preempt | bind (reference: generatePodScheduleResult,
    utils.go:38-79)."""
    if group_physical_placement is None:
        log.info("[%s]: Pod needs to wait, reason: %s", internal.key(pod), wait_reason)
        return PodScheduleResult(pod_wait_info=PodWaitInfo(reason=wait_reason))
    if preemption_victims:
        return PodScheduleResult(
            pod_preempt_info=generate_pod_preempt_info(preemption_victims, pod)
        )
    # find the selected node only after preemption is done — victims may cause
    # the selected node to be excluded from the suggested nodes
    (bind_info, selected_node, selected_indices, cell_chain,
     encoded_group) = generate_affinity_group_bind_info(
        group_physical_placement, group_virtual_placement, cell_level_to_type,
        current_leaf_cell_num, current_pod_index, group, group_name,
    )
    log.info(
        "[%s]: pod is decided to be scheduled to node %s, leaf cells %s",
        internal.key(pod), selected_node, selected_indices,
    )
    result_info = api.PodBindInfo(
        node=selected_node,
        leaf_cell_isolation=selected_indices,
        cell_chain=cell_chain,
        affinity_group_bind_info=bind_info,
    )
    # version-keyed pre-encoded fragment for new_binding_pod's serializer
    result_info._encoded_group = encoded_group
    return PodScheduleResult(pod_bind_info=result_info)


def generate_pod_preempt_info(
    preemption_victims: Dict[str, Dict[str, Pod]], pod: Pod
) -> PodPreemptInfo:
    """Victims on ONE random node per call — K8s preempts one node at a time;
    randomness spreads different preemptors over different nodes (reference:
    generatePodPreemptInfo, utils.go:82-103)."""
    nodes_having_victims = sorted(preemption_victims)
    node_to_preempt = nodes_having_victims[random.randrange(len(nodes_having_victims))]
    victim_pods = list(preemption_victims[node_to_preempt].values())
    log.info("[%s]: need to preempt pods %s", internal.key(pod),
             [internal.key(v) for v in victim_pods])
    return PodPreemptInfo(victim_pods=victim_pods)


def generate_affinity_group_bind_info(
    group_physical_placement: GroupPhysicalPlacement,
    group_virtual_placement: Optional[GroupVirtualPlacement],
    cell_level_to_type: Dict[CellChain, Dict[CellLevel, str]],
    current_leaf_cell_num: int,
    current_pod_index: int,
    group: Optional[AlgoAffinityGroup],
    group_name: str,
):
    """Placement → wire format, incl. PreassignedCellTypes needed for recovery
    (reference: generateAffinityGroupBindInfo, utils.go:108-171). Returns
    (bind_info, selected_node, selected_indices, chain, encoded_group)."""
    cached = group._bind_info_cache if group is not None else None
    if cached is not None and cached[0] == group.placement_version:
        bind_info, chain = cached[1], cached[2]
        for mbi_cached in bind_info:
            if len(mbi_cached.pod_placements[0].physical_leaf_cell_indices) == current_leaf_cell_num:
                # cell chain is per POD: a multi-chain-relaxed group spans
                # chains, so derive it from the current pod's own placement
                p_cell = group_physical_placement[current_leaf_cell_num][current_pod_index][0]
                if p_cell is not None:
                    chain = p_cell.chain
                return (
                    bind_info,
                    mbi_cached.pod_placements[current_pod_index].physical_node,
                    mbi_cached.pod_placements[current_pod_index].physical_leaf_cell_indices,
                    chain,
                    cached[3],  # pre-encoded gang fragment
                )
    bind_info: List[api.AffinityGroupMemberBindInfo] = []
    selected_node = ""
    selected_indices: List[int] = []
    chain = ""
    for pod_leaf_cell_num, pod_physical_placements in group_physical_placement.items():
        mbi = api.AffinityGroupMemberBindInfo(
            pod_placements=[
                api.PodPlacementInfo(
                    physical_node="",
                    physical_leaf_cell_indices=[0] * pod_leaf_cell_num,
                    preassigned_cell_types=[""] * pod_leaf_cell_num,
                )
                for _ in pod_physical_placements
            ]
        )
        for pod_index in range(len(pod_physical_placements)):
            for leaf_cell_index in range(pod_leaf_cell_num):
                p_leaf_cell = pod_physical_placements[pod_index][leaf_cell_index]
                if p_leaf_cell is None:
                    if group is None or group.state == GROUP_PREEMPTING:
                        raise AssertionError(
                            f"The first pod in group {group_name} was allocated invalid resource"
                        )
                    # placement invalid (e.g., removed by reconfiguration):
                    # insist the decision by retrieving it from peer pods
                    mbi.pod_placements[pod_index], chain = retrieve_missing_pod_placement(
                        group, pod_leaf_cell_num, pod_index
                    )
                    log.warning(
                        "pod placement has been invalid and is retrieved from annotation "
                        "of other pods: node %s, leaf cells %s",
                        mbi.pod_placements[pod_index].physical_node,
                        mbi.pod_placements[pod_index].physical_leaf_cell_indices,
                    )
                else:
                    assert isinstance(p_leaf_cell, PhysicalCell)
                    nodes, leaf_cell_indices = p_leaf_cell.get_physical_placement()
                    if mbi.pod_placements[pod_index].physical_node == "":
                        mbi.pod_placements[pod_index].physical_node = nodes[0]
                    mbi.pod_placements[pod_index].physical_leaf_cell_indices[leaf_cell_index] = (
                        leaf_cell_indices[0]
                    )
                    if group_virtual_placement is not None:
                        v_leaf_cell = group_virtual_placement[pod_leaf_cell_num][pod_index][
                            leaf_cell_index
                        ]
                        assert isinstance(v_leaf_cell, VirtualCell)
                        mbi.pod_placements[pod_index].preassigned_cell_types[leaf_cell_index] = (
                            cell_level_to_type[v_leaf_cell.chain][
                                v_leaf_cell.preassigned_cell.level
                            ]
                        )
                    else:
                        mbi.pod_placements[pod_index].preassigned_cell_types[leaf_cell_index] = ""
        if pod_leaf_cell_num == current_leaf_cell_num:
            selected_node = mbi.pod_placements[current_pod_index].physical_node
            selected_indices = mbi.pod_placements[current_pod_index].physical_leaf_cell_indices
            p_leaf_cell = group_physical_placement[current_leaf_cell_num][current_pod_index][0]
            if p_leaf_cell is not None:
                chain = p_leaf_cell.chain
        bind_info.append(mbi)
    # pre-encode the gang fragment once per placement version; every pod's
    # bind annotation splices it instead of re-serializing the whole gang
    encoded_group = internal.encode_group_fragment(bind_info)
    if group is not None:
        group._bind_info_cache = (
            group.placement_version, bind_info, chain, encoded_group
        )
    return bind_info, selected_node, selected_indices, chain, encoded_group


def collect_bad_or_non_suggested_nodes(
    placement: GroupPhysicalPlacement,
    suggested_nodes: Set[str],
    ignore_suggested_nodes: bool,
) -> Set[str]:
    """Reference: collectBadOrNonSuggestedNodes, utils.go:175-197."""
    bad_or_non_suggested: Set[str] = set()
    for pod_placements in placement.values():
        for pod_placement in pod_placements:
            for leaf_cell in pod_placement:
                if leaf_cell is None:
                    continue
                assert isinstance(leaf_cell, PhysicalCell)
                nodes, _ = leaf_cell.get_physical_placement()
                if not leaf_cell.healthy or (
                    not ignore_suggested_nodes and nodes[0] not in suggested_nodes
                ):
                    bad_or_non_suggested.add(nodes[0])
    return bad_or_non_suggested


def collect_preemption_victims(
    placement: GroupPhysicalPlacement,
) -> Tuple[Dict[str, Dict[str, Pod]], List[AlgoAffinityGroup]]:
    """Gang preemption: any Used/Reserving cell pulls in ALL pods of the using
    group; also returns overlapping preemptor groups whose preemption must be
    canceled (reference: collectPreemptionVictims, utils.go:202-235).

    Victims are keyed node -> {pod uid -> pod}."""
    victim_pods: Dict[str, Dict[str, Pod]] = {}
    overlapping_preemptors: List[AlgoAffinityGroup] = []
    for pod_placements in placement.values():
        for pod_placement in pod_placements:
            for leaf_cell in pod_placement:
                if leaf_cell is None:
                    continue
                assert isinstance(leaf_cell, PhysicalCell)
                state = leaf_cell.state
                if state in (CELL_USED, CELL_RESERVING):
                    for pods in leaf_cell.using_group.allocated_pods.values():
                        for v in pods:
                            if v is not None:
                                victim_pods.setdefault(v.node_name, {})[v.uid] = v
                if state in (CELL_RESERVING, CELL_RESERVED):
                    g = leaf_cell.reserving_or_reserved_group
                    if g is not None and all(o is not g for o in overlapping_preemptors):
                        overlapping_preemptors.append(g)
    return victim_pods, overlapping_preemptors


def retrieve_missing_pod_placement(
    g: AlgoAffinityGroup, leaf_cell_num: int, pod_index: int
) -> Tuple[api.PodPlacementInfo, str]:
    """Reference: retrieveMissingPodPlacement, utils.go:250-265."""
    for pods in g.allocated_pods.values():
        for p in pods:
            if p is not None:
                info = internal.extract_pod_bind_info(p)
                for mbi in info.affinity_group_bind_info:
                    if leaf_cell_num == len(mbi.pod_placements[0].physical_leaf_cell_indices):
                        return mbi.pod_placements[pod_index], info.cell_chain
    raise AssertionError(
        f"No allocated pod found in an allocated group {g.name} when retrieving placement "
        f"for pod {pod_index} with leaf cell number {leaf_cell_num}"
    )


def retrieve_virtual_cell(
    physical_placement: GroupPhysicalPlacement,
    virtual_placement: GroupVirtualPlacement,
    p_leaf_cell: PhysicalCell,
) -> Optional[VirtualCell]:
    """Reference: retrieveVirtualCell, utils.go:269-283."""
    for leaf_cell_num, pod_placements in physical_placement.items():
        for pod_index, pod_placement in enumerate(pod_placements):
            for leaf_cell_index, leaf_cell in enumerate(pod_placement):
                if leaf_cell is not None and cell_equal(leaf_cell, p_leaf_cell):
                    return virtual_placement[leaf_cell_num][pod_index][leaf_cell_index]
    return None


def get_new_pod_index(pods: List[Optional[Pod]], start: int = 0) -> int:
    """Reference: getNewPodIndex, utils.go:286-295.

    ``start`` is a caller-maintained watermark (every slot below it is
    known non-None — see AlgoAffinityGroup.pod_index_watermark), keeping
    the "first None index" result exact while skipping the filled prefix."""
    for i in range(start, len(pods)):
        if pods[i] is None:
            return i
    return -1


def get_allocated_pod_index(info: api.PodBindInfo, leaf_cell_num: int) -> int:
    """Reference: getAllocatedPodIndex, utils.go:298-310.

    The (node, chip) -> pod-index map is memoized on the member-bind-info
    object: a gang replay calls this once per pod against the same shared
    group list (see extract_pod_bind_info's fragment memo), so the naive scan
    is O(gang^2) across the gang while the mapped lookup is O(gang)."""
    if not info.leaf_cell_isolation:
        return -1
    first_chip = info.leaf_cell_isolation[0]
    for gms in info.affinity_group_bind_info:
        if len(gms.pod_placements[0].physical_leaf_cell_indices) == leaf_cell_num:
            index_map = getattr(gms, "_pod_index_map", None)
            if index_map is None:
                index_map = {}
                for pod_index, placement in enumerate(gms.pod_placements):
                    for chip in placement.physical_leaf_cell_indices:
                        # first writer wins, like the scan's first match
                        index_map.setdefault(
                            (placement.physical_node, chip), pod_index
                        )
                gms._pod_index_map = index_map
            pod_index = index_map.get((info.node, first_chip))
            if pod_index is not None:
                return pod_index
    return -1


def all_pods_released(allocated_pods: Dict[int, List[Optional[Pod]]]) -> bool:
    """Reference: allPodsReleased, utils.go:313-321."""
    return all(p is None for pods in allocated_pods.values() for p in pods)


def build_leaf_cell_index(
    full_cell_list: Dict[CellChain, ChainCellList],
) -> Dict[CellChain, Dict[Tuple[str, int], PhysicalCell]]:
    """Static (node, in-node index) -> leaf cell map per chain; the cell
    topology never changes after construction, so lookups during recovery are
    O(1) instead of scanning every leaf cell."""
    index: Dict[CellChain, Dict[Tuple[str, int], PhysicalCell]] = {}
    for chain, ccl in full_cell_list.items():
        chain_index: Dict[Tuple[str, int], PhysicalCell] = {}
        for c in ccl.get(1, []):
            assert isinstance(c, PhysicalCell)
            nodes, leaf_cell_indices = c.get_physical_placement()
            for n in nodes:
                for i in leaf_cell_indices:
                    chain_index[(n, i)] = c
        index[chain] = chain_index
    return index


def find_physical_leaf_cell(
    full_cell_list: Dict[CellChain, ChainCellList],
    chain: CellChain,
    node: str,
    leaf_cell_index: int,
    leaf_cell_index_map: Optional[Dict[CellChain, Dict[Tuple[str, int], PhysicalCell]]] = None,
) -> Optional[PhysicalCell]:
    """Find a leaf cell by (node, index); falls back to other chains on
    reconfiguration (reference: findPhysicalLeafCell, utils.go:326-345)."""
    if leaf_cell_index_map is not None and leaf_cell_index >= 0:
        # a negative index is a wildcard "any cell on the node" (legacy
        # annotations): only the scan path below supports it
        found = leaf_cell_index_map.get(chain, {}).get((node, leaf_cell_index))
        if found is not None:
            return found
        for c, chain_index in leaf_cell_index_map.items():
            if c != chain:
                found = chain_index.get((node, leaf_cell_index))
                if found is not None:
                    log.warning("Leaf cell %s on node %s has been moved to chain %s",
                                leaf_cell_index, node, c)
                    return found
        return None
    if leaf_cell_index_map is not None:
        leaf_cell_index = -1  # normalize wildcard for the scan path
    found = _find_physical_leaf_cell_in_chain(full_cell_list, chain, node, leaf_cell_index)
    if found is None:
        for c in full_cell_list:
            if c != chain:
                found = _find_physical_leaf_cell_in_chain(full_cell_list, c, node, leaf_cell_index)
                if found is not None:
                    log.warning(
                        "Leaf cell %s on node %s has been moved to chain %s",
                        leaf_cell_index, node, c,
                    )
                    return found
        return None
    return found


def _find_physical_leaf_cell_in_chain(
    full_cell_list: Dict[CellChain, ChainCellList],
    chain: CellChain,
    node: str,
    leaf_cell_index: int,
) -> Optional[PhysicalCell]:
    """Reference: findPhysicalLeafCellInChain, utils.go:350-378."""
    for c in full_cell_list.get(chain, {}).get(1, []):
        assert isinstance(c, PhysicalCell)
        nodes, leaf_cell_indices = c.get_physical_placement()
        if node in nodes:
            if leaf_cell_index < 0 or leaf_cell_index in leaf_cell_indices:
                return c
    return None


def in_free_cell_list(c: PhysicalCell) -> bool:
    """True iff the cell or an ancestor is in the global free list (reference:
    inFreeCellList, utils.go:381-391)."""
    while True:
        if c.virtual_cell is not None or c.split:
            return False
        if c.parent is None or c.parent.split:  # type: ignore[union-attr]
            return True
        c = c.parent  # type: ignore[assignment]


def set_cell_state(c: PhysicalCell, s: str) -> None:
    """Set state up-tree: a parent is Used if ANY child is Used; it takes the
    other states only when ALL children share them (reference: setCellState,
    utils.go:397-405).

    Used-path early stop: set_state(s) always writes the cell AND its bound
    virtual cell's mirrors together, so an ancestor whose own state and bound
    virtual cell's state both already read Used was fully synced by the walk
    that made it Used — by induction everything above it is consistent too
    (fresh binds arrive with the virtual cell in Free state, which fails the
    check and forces the walk to continue). Saves a root walk per chip when
    allocating many chips under the same host."""
    while True:
        c.set_state(s)
        parent = c.parent
        if parent is None:
            return
        assert isinstance(parent, PhysicalCell)
        if s == CELL_USED:
            if parent.state == CELL_USED and (
                parent.virtual_cell is None or parent.virtual_cell.state == CELL_USED
            ):
                return
        elif not all_children_same_state(parent, s):
            return
        c = parent


def all_children_same_state(c: PhysicalCell, s: str) -> bool:
    return all(child.state == s for child in c.children)


def generate_ot_virtual_cell(pc: api.PhysicalCellStatus) -> api.VirtualCellStatus:
    """Fake '-opp' virtual cell exposing opportunistic usage in the VC status
    (reference: generateOTVirtualCell, utils.go:419-432)."""
    return api.VirtualCellStatus(
        leaf_cell_type=pc.leaf_cell_type,
        cell_type=pc.cell_type,
        cell_address=pc.cell_address + "-opp",
        cell_state=CELL_USED,
        cell_healthiness=pc.cell_healthiness,
        cell_priority=OPPORTUNISTIC_PRIORITY,
        physical_cell=pc,
    )


def delete_ot_virtual_cell(
    status_list: List[api.VirtualCellStatus], addr: str
) -> List[api.VirtualCellStatus]:
    """Reference: deleteOTVirtualCell, utils.go:436-452."""
    for i, ovc in enumerate(status_list):
        if ovc.physical_cell is not None and ovc.physical_cell.cell_address == addr:
            status_list[i] = status_list[-1]
            status_list.pop()
            return status_list
    log.error(
        "trying to delete an opportunistic virtual cell that does not exist, "
        "physical cell address: %s", addr,
    )
    return status_list
