"""Internal priorities, levels, cell and affinity-group states.

Reference: ``pkg/algorithm/constants.go:30-80``. The state semantics are
documented in the reference's ``doc/design/state-machine.md`` (AG events e0-e8,
cell events e0-e9); our port of that doc lives in ``doc/design/state-machine.md``.
"""

from hivedscheduler_tpu.api import constants as api_constants

# --- internal cell priorities ----------------------------------------------
MAX_GUARANTEED_PRIORITY = api_constants.MAX_GUARANTEED_PRIORITY
MIN_GUARANTEED_PRIORITY = api_constants.MIN_GUARANTEED_PRIORITY
OPPORTUNISTIC_PRIORITY = api_constants.OPPORTUNISTIC_PRIORITY
FREE_PRIORITY = OPPORTUNISTIC_PRIORITY - 1

# --- levels -----------------------------------------------------------------
LOWEST_LEVEL = 1
HIGHEST_LEVEL = 2**31 - 1

# --- cell healthiness (re-exported api wire values) -------------------------
from hivedscheduler_tpu.api.types import CELL_BAD as CELL_BAD_H  # noqa: E402
from hivedscheduler_tpu.api.types import CELL_HEALTHY as CELL_HEALTHY_H  # noqa: E402

# --- cell states ------------------------------------------------------------
# No group is using, reserving, or has reserved the cell. A Free cell's
# priority must be FREE_PRIORITY. (A Free cell may still be *bound* when it is
# a doomed bad cell; such cells must not be picked for new bindings.)
CELL_FREE = "Free"
# A group is using this cell; nobody is reserving it.
CELL_USED = "Used"
# A group is using this cell AND another group is reserving it (preemption in
# flight). The cell's priority is the *reserving* group's, so non-higher
# priority groups cannot take it.
CELL_RESERVING = "Reserving"
# No group is using this cell and a group has reserved it (victims already
# gone, preemptor not yet allocated).
CELL_RESERVED = "Reserved"

# --- affinity group states --------------------------------------------------
# All cells of the group are Used.
GROUP_ALLOCATED = "Allocated"
# The group is preempting others; its cells are Reserving or Reserved.
GROUP_PREEMPTING = "Preempting"
# The group is being preempted; its cells are Used or Reserving.
GROUP_BEING_PREEMPTED = "BeingPreempted"
