"""HivedAlgorithm: the top-level scheduling algorithm.

TPU-native analogue of the reference's ``pkg/algorithm/hived_algorithm.go``:
VC-safety accounting (``totalLeftCellNum >= allVCFreeCellNum`` at every chain
level), gang scheduling of affinity groups, guaranteed/opportunistic
priorities, intra/inter-VC preemption with Reserving/Reserved cell states,
lazy preemption, bad-hardware awareness with doomed-bad-cell binding, and
annotation-driven crash recovery.

Concurrency: all mutating entry points take the algorithm lock; the runtime
additionally serializes scheduling via its own lock (reference contract:
``internal/types.go:59-75``).
"""

from __future__ import annotations

import logging
from datetime import datetime, timezone
from typing import Dict, List, Optional, Set, Tuple

from hivedscheduler_tpu.api import types as api
from hivedscheduler_tpu.api.config import Config
from hivedscheduler_tpu.common import lockcheck
from hivedscheduler_tpu.algorithm.cell import (
    CellChain,
    CellLevel,
    CellPriority,
    PhysicalCell,
    VirtualCell,
)
from hivedscheduler_tpu.algorithm.cell_allocation import (
    UsedCountBatch,
    allocate_cell_walk,
    bind_cell,
    get_unbound_virtual_cell,
    map_physical_cell_to_virtual,
    map_virtual_placement_to_physical,
    release_cell_walk,
    unbind_cell,
)
from hivedscheduler_tpu.algorithm.config_parser import parse_config
from hivedscheduler_tpu.algorithm.constants import (
    CELL_BAD_H,
    CELL_FREE,
    CELL_HEALTHY_H,
    CELL_RESERVED,
    CELL_RESERVING,
    CELL_USED,
    FREE_PRIORITY,
    GROUP_ALLOCATED,
    GROUP_BEING_PREEMPTED,
    GROUP_PREEMPTING,
    LOWEST_LEVEL,
    MIN_GUARANTEED_PRIORITY,
    OPPORTUNISTIC_PRIORITY,
)
from hivedscheduler_tpu.algorithm.intra_vc import IntraVCScheduler
from hivedscheduler_tpu.algorithm.topology_aware import TopologyAwareScheduler
from hivedscheduler_tpu.algorithm.types import (
    AlgoAffinityGroup,
    ChainCellList,
    GroupPhysicalPlacement,
    GroupVirtualPlacement,
    SchedulingRequest,
    to_binding_paths,
    virtual_to_physical_placement,
)
from hivedscheduler_tpu.algorithm.utils import (
    all_pods_released,
    collect_preemption_victims,
    delete_ot_virtual_cell,
    find_physical_leaf_cell,
    generate_ot_virtual_cell,
    generate_pod_schedule_result,
    get_allocated_pod_index,
    get_new_pod_index,
    in_free_cell_list,
    retrieve_virtual_cell,
    set_cell_state,
)
from hivedscheduler_tpu.k8s.types import Node, Pod
from hivedscheduler_tpu.obs import decisions as obs_decisions
from hivedscheduler_tpu.obs import journal as obs_journal
from hivedscheduler_tpu.obs import ledger as obs_ledger
from hivedscheduler_tpu.runtime import types as internal
from hivedscheduler_tpu.runtime import utils as internal_utils
from hivedscheduler_tpu.runtime.types import PodScheduleResult, SchedulerAlgorithm

log = logging.getLogger(__name__)


class HivedAlgorithm(SchedulerAlgorithm):
    """Reference: HivedAlgorithm, hived_algorithm.go:40-105."""

    def __init__(self, config: Config):
        parsed = parse_config(config)
        self.vc_schedulers: Dict[str, IntraVCScheduler] = {}
        self.opportunistic_schedulers: Dict[CellChain, TopologyAwareScheduler] = {}
        self.full_cell_list = parsed.physical_full_list
        self.free_cell_list = parsed.physical_free_list
        self.affinity_groups: Dict[str, AlgoAffinityGroup] = {}
        self.vc_free_cell_num = parsed.vc_free_cell_num
        self.all_vc_free_cell_num: Dict[CellChain, Dict[CellLevel, int]] = {}
        self.total_left_cell_num: Dict[CellChain, Dict[CellLevel, int]] = {}
        self.bad_free_cells: Dict[CellChain, ChainCellList] = {}
        self.vc_doomed_bad_cells: Dict[str, Dict[CellChain, ChainCellList]] = {}
        self.all_vc_doomed_bad_cell_num: Dict[CellChain, Dict[CellLevel, int]] = {}
        self.bad_nodes: Set[str] = set()
        self.cell_chains = parsed.leaf_cell_type_to_chain
        self.cell_types = parsed.cell_level_to_type
        self.leaf_cell_nums = parsed.cell_level_to_leaf_cell_num
        self.mesh_chains = parsed.mesh_chains
        self.api_cluster_status = api.ClusterStatus()
        self.algorithm_lock = lockcheck.make_rlock("algorithm_lock")
        # Live-placement handoff: the optimistic AddAllocatedPod that follows
        # a Schedule under the same scheduler lock re-derives the placement
        # from the annotation (reference behavior). When NOTHING has happened
        # in between (consecutive op sequence numbers) and the annotation's
        # gang fragment is byte-identical to what Schedule encoded, the
        # re-derivation provably picks the same cells — so Schedule stashes
        # its placement objects and the create path reuses them. Any other
        # interleaving (bind retries, recovery, node events) falls back to
        # the annotation-driven slow path.
        self._op_seq = 0
        self._live_stash: Optional[tuple] = None
        # Per-chain mutation counters (allocate/release of leaf or
        # preassigned cells, node health transitions) keying the
        # multi-chain-relax infeasibility cache: a waiting gang re-probed
        # every cycle skips BOTH relax passes when nothing touched the
        # involved chains since its last failed attempt.
        self._chain_gen: Dict[CellChain, int] = {}
        # group name -> (request sig, chain-gen token, suggested set or
        # None, failed reason); see _schedule_relaxed_across_chains
        self._relax_infeasible: Dict[str, tuple] = {}
        # In-flight decision trace (obs.decisions): non-None only inside
        # schedule() when recording is enabled. Single-threaded by the
        # algorithm-lock contract, so a plain attribute is safe.
        self._decision: Optional[obs_decisions.Decision] = None

        for vc_name in parsed.virtual_non_pinned_full:
            self.vc_schedulers[vc_name] = IntraVCScheduler(
                parsed.virtual_non_pinned_full[vc_name],
                parsed.virtual_non_pinned_free[vc_name],
                parsed.virtual_pinned_cells[vc_name],
                parsed.cell_level_to_leaf_cell_num,
                policy=config.virtual_clusters[vc_name].scheduling_policy,
            )
        for chain, ccl in self.full_cell_list.items():
            self.opportunistic_schedulers[chain] = TopologyAwareScheduler(
                ccl, parsed.cell_level_to_leaf_cell_num[chain], cross_priority_pack=False
            )
        from hivedscheduler_tpu.algorithm.utils import build_leaf_cell_index

        self._leaf_cell_index = build_leaf_cell_index(self.full_cell_list)
        # node name -> leaf cells, in full_cell_list iteration order (same
        # order the reference's per-event leaf scan visits, setBadNode,
        # hived_algorithm.go:467-481); health events become O(leaves-per-node)
        self._leaves_by_node: Dict[str, List[PhysicalCell]] = {}
        for ccl in self.full_cell_list.values():
            for leaf_cell in ccl[1]:
                assert isinstance(leaf_cell, PhysicalCell)
                self._leaves_by_node.setdefault(leaf_cell.nodes[0], []).append(leaf_cell)
        # capacity ledger (obs/ledger.py): register every leaf cell before
        # _init_bad_nodes flips them bad; no-op while the ledger is off,
        # idempotent across crash-restarts
        obs_ledger.register_cluster(self)
        self._init_cell_nums()
        self._init_api_cluster_status()
        self._init_pinned_cells(parsed.physical_pinned_cells)
        self._init_bad_nodes()

    # ------------------------------------------------------------------
    # init
    # ------------------------------------------------------------------

    def _init_cell_nums(self) -> None:
        """Validates VC assignment fits the physical cluster and initializes
        usage/badness tracking (reference: initCellNums,
        hived_algorithm.go:369-409)."""
        for vc, vc_free in self.vc_free_cell_num.items():
            self.vc_doomed_bad_cells[vc] = {}
            for chain, chain_free in vc_free.items():
                self.vc_doomed_bad_cells[vc][chain] = ChainCellList()
                self.all_vc_free_cell_num.setdefault(chain, {})
                for level, num in chain_free.items():
                    self.all_vc_free_cell_num[chain][level] = (
                        self.all_vc_free_cell_num[chain].get(level, 0) + num
                    )
        for chain, chain_free in self.all_vc_free_cell_num.items():
            ccl = self.full_cell_list.get(chain)
            if ccl is None:
                raise AssertionError(
                    f"Illegal initial VC assignment: Chain {chain} does not exist "
                    f"in physical cluster"
                )
            top = max(ccl)
            available = len(ccl[top])
            self.total_left_cell_num[chain] = {top: available}
            self.bad_free_cells[chain] = ChainCellList()
            self.all_vc_doomed_bad_cell_num[chain] = {}
            for l in range(top, LOWEST_LEVEL - 1, -1):
                left = available - chain_free.get(l, 0)
                if left < 0:
                    raise AssertionError(
                        f"Illegal initial VC assignment: Insufficient physical cells "
                        f"at chain {chain} level {l}: {chain_free.get(l, 0)} needed, "
                        f"{available} available"
                    )
                if l > LOWEST_LEVEL:
                    child_num = len(ccl[l][0].children)
                    available = left * child_num
                    self.total_left_cell_num[chain][l - 1] = (
                        self.total_left_cell_num[chain][l] * child_num
                    )

    def _init_api_cluster_status(self) -> None:
        """Reference: initAPIClusterStatus, hived_algorithm.go:412-436."""
        for ccl in self.full_cell_list.values():
            for c in ccl[max(ccl)]:
                assert isinstance(c, PhysicalCell)
                self.api_cluster_status.physical_cluster.append(c.api_status)
        for vc, vcs in self.vc_schedulers.items():
            status_list: List[api.VirtualCellStatus] = []
            for ccl in vcs.non_pinned_preassigned_cells.values():
                for cl in ccl.values():
                    for c in cl:
                        assert isinstance(c, VirtualCell)
                        status_list.append(c.api_status)
            for ccl in vcs.pinned_cells.values():
                for c in ccl[max(ccl)]:
                    assert isinstance(c, VirtualCell)
                    status_list.append(c.api_status)
            self.api_cluster_status.virtual_clusters[vc] = status_list

    def _init_pinned_cells(
        self, pinned: Dict[str, Dict[str, PhysicalCell]]
    ) -> None:
        """Static bindings for pinned cells; removes them from the free list
        (reference: initPinnedCells, hived_algorithm.go:439-450)."""
        for vcn, vc_pinned in pinned.items():
            for pid, pinned_physical in vc_pinned.items():
                self._allocate_preassigned_cell(pinned_physical, vcn, doomed_bad=False)
                virtual_list = self.vc_schedulers[vcn].pinned_cells[pid]
                pinned_virtual = virtual_list[max(virtual_list)][0]
                assert isinstance(pinned_virtual, VirtualCell)
                bind_cell(pinned_physical, pinned_virtual)

    def _init_bad_nodes(self) -> None:
        """All nodes start bad until K8s informs otherwise (reference:
        initBadNodes, hived_algorithm.go:453-464)."""
        log.info("Init all nodes defined in the config to bad first, and wait for "
                 "node informs (add_node) to mark the healthy ones")
        for ccl in self.full_cell_list.values():
            for c in ccl[max(ccl)]:
                assert isinstance(c, PhysicalCell)
                nodes, _ = c.get_physical_placement()
                for n in nodes:
                    self._set_bad_node(n)

    # ------------------------------------------------------------------
    # node events
    # ------------------------------------------------------------------

    def add_node(self, node: Node) -> None:
        lockcheck.assert_serialized(self)
        with self.algorithm_lock:
            self._op_seq += 1
            if not internal_utils.is_node_healthy(node):
                self._set_bad_node(node.name)
            else:
                self._set_healthy_node(node.name)

    def update_node(self, old_node: Node, new_node: Node) -> None:
        lockcheck.assert_serialized(self)
        with self.algorithm_lock:
            self._op_seq += 1
            old_healthy = internal_utils.is_node_healthy(old_node)
            if old_healthy != internal_utils.is_node_healthy(new_node):
                if old_healthy:
                    self._set_bad_node(new_node.name)
                else:
                    self._set_healthy_node(new_node.name)

    def delete_node(self, node: Node) -> None:
        lockcheck.assert_serialized(self)
        with self.algorithm_lock:
            self._op_seq += 1
            self._set_bad_node(node.name)

    def _bump_chain_gen(self, chain: CellChain) -> None:
        self._chain_gen[chain] = self._chain_gen.get(chain, 0) + 1

    def _set_bad_node(self, node_name: str) -> None:
        """Reference: setBadNode, hived_algorithm.go:467-481."""
        if node_name in self.bad_nodes:
            return
        self.bad_nodes.add(node_name)
        if obs_ledger.LEDGER.enabled:
            # chip-state books: the node's chips burn as bad_hardware
            # until recovery (pre-bad states shadow and restore)
            obs_ledger.LEDGER.set_node_bad(node_name, True)
        for leaf_cell in self._leaves_by_node.get(node_name, []):
            self._bump_chain_gen(leaf_cell.chain)
            self._set_bad_cell(leaf_cell)

    def _set_healthy_node(self, node_name: str) -> None:
        """Reference: setHealthyNode, hived_algorithm.go:484-498."""
        if node_name not in self.bad_nodes:
            return
        self.bad_nodes.discard(node_name)
        if obs_ledger.LEDGER.enabled:
            obs_ledger.LEDGER.set_node_bad(node_name, False)
        for leaf_cell in self._leaves_by_node.get(node_name, []):
            self._bump_chain_gen(leaf_cell.chain)
            self._set_healthy_cell(leaf_cell)

    def _set_bad_cell(self, c: PhysicalCell) -> None:
        """Mark bad up-tree; bind to a virtual cell if an ancestor is bound so
        the VC scheduler sees the failure (reference: setBadCell,
        hived_algorithm.go:503-521)."""
        if not c.healthy:
            return
        c.set_healthiness(CELL_BAD_H)
        if c.parent is not None:
            self._set_bad_cell(c.parent)  # type: ignore[arg-type]
        if in_free_cell_list(c):
            self._add_bad_free_cell(c)
        elif c.virtual_cell is None and not c.split:
            parent = c.parent
            assert isinstance(parent, PhysicalCell) and parent.virtual_cell is not None
            vc = get_unbound_virtual_cell(parent.virtual_cell.children)
            c.set_virtual_cell(vc)
            vc.set_physical_cell(c)
            log.info("Virtual cell %s is bound to physical cell %s", vc.address, c.address)

    def _reclaim_doomed_cell(self, pc: PhysicalCell, vcn: str) -> None:
        """Delist a doomed-bad cell and release its preassigned allocation —
        the single bookkeeping trio shared by the heal, unbind and
        release-time reclaim paths."""
        self.vc_doomed_bad_cells[vcn][pc.chain].remove(pc, pc.level)
        self.all_vc_doomed_bad_cell_num[pc.chain][pc.level] -= 1
        self._release_preassigned_cell(pc, vcn, doomed_bad=True)

    def _reclaim_doomed_overlapping(self, top: PhysicalCell) -> None:
        """Reclaim every doomed-bad binding overlapping ``top`` — inside its
        subtree OR on its ancestor path (any VC): doomed bindings mark
        FREE-but-bad capacity, so a recovered allocation that needs the
        cell trumps them — the inequality that doomed them re-evaluates on
        later events."""

        def contains(outer: PhysicalCell, inner: PhysicalCell) -> bool:
            c: Optional[PhysicalCell] = inner
            while c is not None and c is not outer:
                c = c.parent  # type: ignore[assignment]
            return c is outer

        for vc_name, chains in self.vc_doomed_bad_cells.items():
            ccl = chains.get(top.chain)
            if ccl is None:
                continue
            for level in sorted(ccl):
                for pc in list(ccl[level]):
                    assert isinstance(pc, PhysicalCell)
                    if pc.priority >= MIN_GUARANTEED_PRIORITY:
                        # in real use: a genuine conflict, not a marker —
                        # the caller's allocatability guard lazy-preempts
                        continue
                    if not (
                        contains(top, pc) if level <= top.level else contains(pc, top)
                    ):
                        continue
                    fvc = pc.virtual_cell
                    if fvc is not None:
                        fvc.set_physical_cell(None)
                        pc.set_virtual_cell(None)
                    log.warning(
                        "Doomed-bad binding on %s (VC %s) reclaimed: a "
                        "recovered allocation needs overlapping cell %s",
                        pc.address, vc_name, top.address,
                    )
                    self._reclaim_doomed_cell(pc, vc_name)

    def _set_healthy_cell(self, c: PhysicalCell) -> None:
        """Reference: setHealthyCell, hived_algorithm.go:526-560."""
        if c.healthy:
            return
        c.set_healthiness(CELL_HEALTHY_H)
        if in_free_cell_list(c):
            self._remove_bad_free_cell(c)
        elif c.virtual_cell is not None:
            vc = c.virtual_cell
            if not c.pinned and c.priority < MIN_GUARANTEED_PRIORITY:
                # binding existed only because the cell was bad; drop it
                c.set_virtual_cell(None)
                vc.set_physical_cell(None)
                log.info("Virtual cell %s is unbound from physical cell %s",
                         vc.address, c.address)
                if vc.parent is None:
                    # a preassigned cell: must be a doomed bad cell
                    self._reclaim_doomed_cell(c, vc.vc)
        if c.parent is None:
            return
        for buddy in c.parent.children:
            assert isinstance(buddy, PhysicalCell)
            if not buddy.healthy:
                return
        self._set_healthy_cell(c.parent)  # type: ignore[arg-type]

    def _add_bad_free_cell(self, c: PhysicalCell) -> None:
        """Reference: addBadFreeCell, hived_algorithm.go:564-581."""
        chain, level = c.chain, c.level
        self.bad_free_cells[chain][level].append(c)
        if self.all_vc_free_cell_num.get(chain, {}).get(level, 0) > (
            self.total_left_cell_num[chain][level] - len(self.bad_free_cells[chain][level])
        ):
            log.warning(
                "Cell type %s (chain %s level %s) now has fewer healthy cells (%s) than "
                "the total free cells of all the VCs (%s). Certain VCs' cells may be "
                "doomed to be bad.",
                self.cell_types[chain][level], chain, level,
                self.total_left_cell_num[chain][level] - len(self.bad_free_cells[chain][level]),
                self.all_vc_free_cell_num[chain][level]
                + self.all_vc_doomed_bad_cell_num[chain].get(level, 0),
            )
            self._try_bind_doomed_bad_cell(chain, level)

    def _remove_bad_free_cell(self, c: PhysicalCell) -> None:
        """Reference: removeBadFreeCell, hived_algorithm.go:584-600."""
        chain, level = c.chain, c.level
        self.bad_free_cells[chain].remove(c, level)
        self._try_unbind_doomed_bad_cell(chain, level)

    def _try_bind_doomed_bad_cell(self, chain: CellChain, level: CellLevel) -> None:
        """If a VC's free cells exceed the healthy free physical cells, some of
        its cells are doomed bad: bind them so the VC scheduler avoids them
        (reference: tryBindDoomedBadCell, hived_algorithm.go:604-628).

        Deviation (PARITY.md, chaos defrag-v1 seeds 2/23): outstanding doomed
        conditions at HIGHER levels are satisfied first. A doomed bind at
        ``level`` splits free ancestors, so with several nodes bad at once it
        can consume the only bad free cell able to back a higher level's
        excess — the higher level's condition then has no cell left to bind
        and ``total_left < all_vc_free`` materializes. The reference assumes
        at most one outstanding level at a time; the top-down sweep makes the
        multi-level case converge (every extra call no-ops when the books are
        consistent)."""
        higher = sorted(
            (lv for lv in self.total_left_cell_num.get(chain, {}) if lv > level),
            reverse=True,
        )
        for lv in higher:
            self._try_bind_doomed_bad_cell_at(chain, lv)
        self._try_bind_doomed_bad_cell_at(chain, level)

    def _try_bind_doomed_bad_cell_at(self, chain: CellChain, level: CellLevel) -> None:
        """The reference per-level bind loop (see _try_bind_doomed_bad_cell
        for the ordering wrapper)."""
        for vc_name, vc_free in self.vc_free_cell_num.items():
            if chain not in vc_free:
                continue
            while vc_free[chain].get(level, 0) > (
                self.total_left_cell_num[chain][level] - len(self.bad_free_cells[chain][level])
            ):
                # the reference binds bad_free[0] unconditionally; under
                # multi-bad-node layouts the list can hold cells meanwhile
                # taken into real guaranteed use (the Preempting phase
                # admits bad nodes), so only a genuinely free candidate is
                # bindable (deviation, PARITY.md)
                pc = next(
                    (
                        c
                        for c in self.bad_free_cells[chain][level]
                        if c.priority < MIN_GUARANTEED_PRIORITY
                        and in_free_cell_list(c)
                    ),
                    None,
                )
                if pc is None:
                    # no bindable bad free cell at this level: the condition
                    # stays outstanding and is retried as later events
                    # re-shape the free lists — better than the reference's
                    # index-out-of-range here
                    log.warning(
                        "VC %s has %s free cells at chain %s level %s beyond "
                        "healthy capacity but no bindable bad free cell is "
                        "available to doom-bind; deferring",
                        vc_name, vc_free[chain].get(level, 0), chain, level,
                    )
                    break
                assert isinstance(pc, PhysicalCell)
                vc = get_unbound_virtual_cell(
                    self.vc_schedulers[vc_name].non_pinned_preassigned_cells[chain][level]
                )
                pc.set_virtual_cell(vc)
                vc.set_physical_cell(pc)
                log.warning(
                    "Cell %s is doomed to be bad and bound to %s (VC %s)",
                    vc.address, pc.address, vc_name,
                )
                self.vc_doomed_bad_cells[vc_name][chain][level].append(pc)
                self.all_vc_doomed_bad_cell_num[chain][level] = (
                    self.all_vc_doomed_bad_cell_num[chain].get(level, 0) + 1
                )
                self._allocate_preassigned_cell(pc, vc_name, doomed_bad=True)

    def _try_unbind_doomed_bad_cell(self, chain: CellChain, level: CellLevel) -> None:
        """Reference: tryUnbindDoomedBadCell, hived_algorithm.go:632-653.

        Documented deviation (PARITY.md): only doomed cells NOT in real use
        (priority below guaranteed) are unbound. A gang may legally land on
        the healed part of a doomed-bound cell (the Preempting phase admits
        bad nodes); the reference unbinds ``vcDoomedBadCells[0]`` regardless
        and returns the in-use cell to the free list, where buddy merges
        then bury a running guaranteed gang inside "free" cells — VC safety
        is broken from that point on. Found by
        tests/test_invariant_fuzz.py's free-list invariant. In-use doomed
        cells stay bound; they become eligible here once released."""
        for vc_name, vc_free in self.vc_free_cell_num.items():
            if chain not in vc_free:
                continue
            while vc_free[chain].get(level, 0) < (
                self.total_left_cell_num[chain][level] - len(self.bad_free_cells[chain][level])
            ):
                pc = next(
                    (
                        c
                        for c in self.vc_doomed_bad_cells[vc_name][chain][level]
                        if c.priority < MIN_GUARANTEED_PRIORITY
                    ),
                    None,
                )
                if pc is None:
                    break
                assert isinstance(pc, PhysicalCell)
                if pc.virtual_cell is not None:
                    log.info(
                        "Cell %s is no longer doomed to be bad and is unbound from %s",
                        pc.virtual_cell.address, pc.address,
                    )
                    pc.virtual_cell.set_physical_cell(None)
                    pc.set_virtual_cell(None)
                else:
                    # the binding was already stripped by unbind_cell when the
                    # group using the (healed) doomed cell released — the
                    # reference nil-derefs here; we just reclaim the cell
                    log.info("Doomed cell %s (already unbound) reclaimed", pc.address)
                self._reclaim_doomed_cell(pc, vc_name)

    # ------------------------------------------------------------------
    # scheduling entry
    # ------------------------------------------------------------------

    def schedule(
        self, pod: Pod, suggested_nodes: List[str], phase: str
    ) -> PodScheduleResult:
        """Reference: Schedule, hived_algorithm.go:180-224.

        When decision recording is enabled (``obs.decisions``), every call
        additionally produces a structured explanation of the placement
        attempts made — the disabled path pays one bool check."""
        lockcheck.assert_serialized(self)
        with self.algorithm_lock:
            rec = obs_decisions.RECORDER
            jr = obs_journal.JOURNAL
            if not rec.enabled and not jr.enabled:
                return self._schedule_locked(pod, suggested_nodes, phase)
            dec = rec.begin(internal_utils.key(pod), phase)
            self._decision = dec
            try:
                result = self._schedule_locked(pod, suggested_nodes, phase)
            except Exception as e:
                if dec is not None:
                    dec.finish("error", reason=str(e))
                    rec.commit(dec)
                raise
            finally:
                self._decision = None
            if dec is not None:
                if result.pod_bind_info is not None:
                    dec.finish("bind", node=result.pod_bind_info.node)
                elif result.pod_preempt_info is not None:
                    dec.finish(
                        "preempt",
                        victims=[internal_utils.key(v)
                                 for v in result.pod_preempt_info.victim_pods],
                    )
                else:
                    dec.finish(
                        "wait",
                        reason=(result.pod_wait_info.reason
                                if result.pod_wait_info is not None else ""),
                    )
                rec.commit(dec)
            if jr.enabled:
                self._journal_schedule(pod, result)
            return result

    def _journal_schedule(self, pod: Pod, result: PodScheduleResult) -> None:
        """Gang-lifecycle journal hook (obs/journal.py): one event per gang
        *transition* — the first member bind of an incarnation opens its
        running episode, a preemption or wait opens/re-attributes a wait
        interval (same bucket = the interval just continues, no event)."""
        s = internal_utils.extract_pod_scheduling_spec(pod)
        gang = s.affinity_group.name
        if result.pod_bind_info is not None:
            obs_journal.note_phase(
                gang, "running", "bind", node=result.pod_bind_info.node,
                vc=s.virtual_cluster, priority=s.priority)
        elif result.pod_preempt_info is not None:
            obs_journal.note_wait(
                gang, "priority", etype="preempt_planned",
                detail="waiting on victim preemption",
                victims=[internal_utils.key(v)
                         for v in result.pod_preempt_info.victim_pods],
                vc=s.virtual_cluster)
        else:
            reason = (result.pod_wait_info.reason
                      if result.pod_wait_info is not None else "")
            obs_journal.note_wait(
                gang, obs_journal.classify_wait(reason), detail=reason,
                vc=s.virtual_cluster)

    def _schedule_locked(
        self, pod: Pod, suggested_nodes: List[str], phase: str
    ) -> PodScheduleResult:
        with self.algorithm_lock:
            self._op_seq += 1
            log.info("[%s]: Scheduling pod in %s phase...", internal_utils.key(pod), phase)
            s = internal_utils.extract_pod_scheduling_spec(pod)
            if self._decision is not None:
                self._decision.group = s.affinity_group.name
                self._decision.vc = s.virtual_cluster
                self._decision.priority = s.priority
                self._decision.suggested_nodes = len(suggested_nodes)
            # built lazily: the existing-ALLOCATED-group fast path (every
            # pod of a gang after the first) never reads the set, and
            # materializing thousands of node names per pod dominates that
            # path's cost at the 4096-chip scale point
            suggested_node_set: Optional[Set[str]] = None
            group_physical: Optional[GroupPhysicalPlacement] = None
            group_virtual: Optional[GroupVirtualPlacement] = None
            preemption_victims: Dict[str, Dict[str, Pod]] = {}
            wait_reason = ""
            pod_index = 0

            g = self.affinity_groups.get(s.affinity_group.name)
            if g is not None:
                if not (g.ignore_k8s_suggested_nodes and not self.bad_nodes):
                    suggested_node_set = set(suggested_nodes)
                (group_physical, group_virtual, preemption_victims, pod_index) = (
                    self._schedule_pod_from_existing_group(
                        g, s, suggested_node_set or set(), phase, pod
                    )
                )
            # the group may have been a preempting group deleted just above
            if self.affinity_groups.get(s.affinity_group.name) is None:
                if suggested_node_set is None:
                    suggested_node_set = set(suggested_nodes)
                (group_physical, group_virtual, preemption_victims, wait_reason) = (
                    self._schedule_pod_from_new_group(s, suggested_node_set, phase, pod)
                )
            result = generate_pod_schedule_result(
                group_physical,
                group_virtual,
                preemption_victims,
                wait_reason,
                self.cell_types,
                s.leaf_cell_number,
                pod_index,
                self.affinity_groups.get(s.affinity_group.name),
                s.affinity_group.name,
                suggested_node_set or set(),
                pod,
            )
            if (
                result.pod_bind_info is not None
                and s.affinity_group.name not in self.affinity_groups
                and group_physical is not None
            ):
                self._live_stash = (
                    self._op_seq,
                    s.affinity_group.name,
                    result.pod_bind_info._encoded_group,
                    group_physical,
                    group_virtual,
                )
            return result

    def add_unallocated_pod(self, pod: Pod) -> None:
        lockcheck.assert_serialized(self)

    def delete_unallocated_pod(self, pod: Pod) -> None:
        """Cancels a preemption when its last preempting pod dies (reference:
        DeleteUnallocatedPod, hived_algorithm.go:229-245)."""
        lockcheck.assert_serialized(self)
        with self.algorithm_lock:
            self._op_seq += 1
            s = internal_utils.extract_pod_scheduling_spec(pod)
            g = self.affinity_groups.get(s.affinity_group.name)
            if g is not None and g.state == GROUP_PREEMPTING:
                if g.preempting_pods and pod.uid in g.preempting_pods:
                    log.info("[%s]: Deleting preempting pod from affinity group %s...",
                             internal_utils.key(pod), g.name)
                    del g.preempting_pods[pod.uid]
                if not g.preempting_pods:
                    log.info(
                        "[%s]: Canceling affinity group %s's preemption because its pods "
                        "are all deleted", internal_utils.key(pod), g.name,
                    )
                    self._delete_preempting_affinity_group(g, pod)

    def add_allocated_pod(self, pod: Pod) -> None:
        """Reference: AddAllocatedPod, hived_algorithm.go:247-269."""
        lockcheck.assert_serialized(self)
        with self.algorithm_lock:
            stash, self._live_stash = self._live_stash, None
            self._op_seq += 1
            s = internal_utils.extract_pod_scheduling_spec(pod)
            info = internal_utils.extract_pod_bind_info(pod)
            log.info("[%s]: Adding allocated pod to affinity group %s (node %s, leaf cells %s)",
                     internal_utils.key(pod), s.affinity_group.name, info.node,
                     info.leaf_cell_isolation)
            pod_index = 0
            g = self.affinity_groups.get(s.affinity_group.name)
            if g is not None:
                if g.state == GROUP_PREEMPTING:
                    self._allocate_preempting_affinity_group(g, pod)
                pod_index = get_allocated_pod_index(info, s.leaf_cell_number)
                if pod_index == -1:
                    log.error(
                        "[%s]: Pod placement not found in group %s: node %s, leaf cells %s",
                        internal_utils.key(pod), s.affinity_group.name, info.node,
                        info.leaf_cell_isolation,
                    )
                    return
            else:
                live = None
                if (
                    stash is not None
                    and stash[0] == self._op_seq - 1
                    and stash[1] == s.affinity_group.name
                    and stash[2] == getattr(info, "_frag", None)
                ):
                    live = (stash[3], stash[4])
                self._create_allocated_affinity_group(s, info, pod, live=live)
                if live is not None:
                    # seed the bind-info cache from the annotation this very
                    # placement was encoded into: the first peer pod's
                    # generate_affinity_group_bind_info then skips a full
                    # O(gang) rebuild of what Schedule already produced
                    new_g = self.affinity_groups.get(s.affinity_group.name)
                    if new_g is not None and new_g._bind_info_cache is None:
                        new_g._bind_info_cache = (
                            new_g.placement_version,
                            info.affinity_group_bind_info,
                            info.cell_chain,
                            stash[2],
                        )
            g = self.affinity_groups[s.affinity_group.name]
            pods_list = g.allocated_pods[s.leaf_cell_number]
            pods_list[pod_index] = pod
            w = g.pod_index_watermark.get(s.leaf_cell_number, 0)
            while w < len(pods_list) and pods_list[w] is not None:
                w += 1
            g.pod_index_watermark[s.leaf_cell_number] = w
            if obs_ledger.LEDGER.enabled:
                # capacity ledger: the pod's chips turn busy (flavor from
                # the runtime's backfill hint, else priority class);
                # idempotent on recovery replays, probe-suppressed
                obs_ledger.LEDGER.transition(
                    info.node, info.leaf_cell_isolation,
                    obs_ledger.LEDGER.busy_state(
                        s.affinity_group.name, s.priority),
                    vc=s.virtual_cluster, gang=s.affinity_group.name)

    def delete_allocated_pod(self, pod: Pod) -> None:
        """Reference: DeleteAllocatedPod, hived_algorithm.go:272-296."""
        lockcheck.assert_serialized(self)
        with self.algorithm_lock:
            self._op_seq += 1
            s = internal_utils.extract_pod_scheduling_spec(pod)
            info = internal_utils.extract_pod_bind_info(pod)
            log.info(
                "[%s]: Deleting allocated pod from affinity group %s (node %s, leaf cells %s)",
                internal_utils.key(pod), s.affinity_group.name, info.node,
                info.leaf_cell_isolation,
            )
            g = self.affinity_groups.get(s.affinity_group.name)
            if g is None:
                log.error("[%s]: Group %s not found when deleting pod",
                          internal_utils.key(pod), s.affinity_group.name)
                return
            pod_index = get_allocated_pod_index(info, s.leaf_cell_number)
            if pod_index == -1 or s.leaf_cell_number not in g.allocated_pods:
                log.error(
                    "[%s]: Pod placement not found in group %s: node %s, leaf cells %s",
                    internal_utils.key(pod), s.affinity_group.name, info.node,
                    info.leaf_cell_isolation,
                )
                return
            g.allocated_pods[s.leaf_cell_number][pod_index] = None
            if pod_index < g.pod_index_watermark.get(s.leaf_cell_number, 0):
                g.pod_index_watermark[s.leaf_cell_number] = pod_index
            if obs_ledger.LEDGER.enabled:
                # capacity ledger: the pod's chips return to idle (the
                # reservation hold state when its node is held, else the
                # current idle diagnosis)
                obs_ledger.LEDGER.release(info.node,
                                          info.leaf_cell_isolation)
            if all_pods_released(g.allocated_pods):
                self._delete_allocated_affinity_group(g, pod)
                if (obs_journal.JOURNAL.enabled
                        and s.affinity_group.name
                        not in self.affinity_groups):
                    # the gang's allocation is fully gone (complete, evicted
                    # or preempted — the cause chain says which): close its
                    # journal episode
                    obs_journal.note_phase(
                        s.affinity_group.name, "closed", "released")

    # ------------------------------------------------------------------
    # inspect
    # ------------------------------------------------------------------

    def get_all_affinity_groups(self) -> List[api.AffinityGroup]:
        with self.algorithm_lock:
            return [g.to_affinity_group() for g in self.affinity_groups.values()]

    def get_affinity_group(self, name: str) -> api.AffinityGroup:
        with self.algorithm_lock:
            g = self.affinity_groups.get(name)
            if g is not None:
                return g.to_affinity_group()
            raise api.WebServerError(
                404,
                f"Affinity group {name} does not exist since it is not allocated or preempting",
            )

    def get_cluster_status(self) -> api.ClusterStatus:
        with self.algorithm_lock:
            return api.ClusterStatus(
                physical_cluster=[s.deep_copy() for s in self.api_cluster_status.physical_cluster],
                virtual_clusters={
                    vcn: [s.deep_copy() for s in vcs]
                    for vcn, vcs in self.api_cluster_status.virtual_clusters.items()
                },
            )

    # -- copy-on-read inspect: to_dict IS the snapshot -----------------
    #
    # The deep_copy() variants above clone the whole status forest per
    # request only for the webserver to immediately serialize the clone and
    # throw it away. These build the JSON-ready dicts directly under the
    # lock — to_dict() produces fresh dicts/lists with no references back
    # into live objects, so it is itself the copy, and only the requested
    # subtree is materialized. The object-returning variants stay for
    # callers that want to hold a snapshot.

    def get_cluster_status_dict(self) -> dict:
        with self.algorithm_lock:
            return self.api_cluster_status.to_dict()

    def get_physical_cluster_status_dict(self) -> list:
        with self.algorithm_lock:
            return [s.to_dict() for s in self.api_cluster_status.physical_cluster]

    def get_all_virtual_clusters_status_dict(self) -> dict:
        with self.algorithm_lock:
            return {
                vcn: [s.to_dict() for s in vcs]
                for vcn, vcs in self.api_cluster_status.virtual_clusters.items()
            }

    def get_virtual_cluster_status_dict(self, vcn: str) -> list:
        with self.algorithm_lock:
            if vcn in self.api_cluster_status.virtual_clusters:
                return [
                    s.to_dict()
                    for s in self.api_cluster_status.virtual_clusters[vcn]
                ]
            raise api.WebServerError(404, f"VC {vcn} not found")

    def get_physical_cluster_status(self) -> List[api.PhysicalCellStatus]:
        with self.algorithm_lock:
            return [s.deep_copy() for s in self.api_cluster_status.physical_cluster]

    def get_all_virtual_clusters_status(self) -> Dict[str, List[api.VirtualCellStatus]]:
        with self.algorithm_lock:
            return {
                vcn: [s.deep_copy() for s in vcs]
                for vcn, vcs in self.api_cluster_status.virtual_clusters.items()
            }

    def get_virtual_cluster_status(self, vcn: str) -> List[api.VirtualCellStatus]:
        with self.algorithm_lock:
            if vcn in self.api_cluster_status.virtual_clusters:
                return [s.deep_copy() for s in self.api_cluster_status.virtual_clusters[vcn]]
            raise api.WebServerError(404, f"VC {vcn} not found")

    # ------------------------------------------------------------------
    # scheduling internals
    # ------------------------------------------------------------------

    def _schedule_pod_from_existing_group(
        self,
        g: AlgoAffinityGroup,
        s: api.PodSchedulingSpec,
        suggested_nodes: Set[str],
        phase: str,
        pod: Pod,
    ) -> Tuple[
        Optional[GroupPhysicalPlacement],
        Optional[GroupVirtualPlacement],
        Dict[str, Dict[str, Pod]],
        int,
    ]:
        """Reference: schedulePodFromExistingGroup, hived_algorithm.go:658-712."""
        group_physical: Optional[GroupPhysicalPlacement] = None
        group_virtual: Optional[GroupVirtualPlacement] = None
        preemption_victims: Dict[str, Dict[str, Pod]] = {}
        pod_index = 0
        # hot path: one scan per pod of every existing group. When the group
        # ignores suggested nodes and no node is bad, every cell is healthy
        # (leaf healthiness is driven solely by set_bad_node/set_healthy_node
        # under this lock), so the scan can only return empty — skip it.
        # Otherwise scan the group's DISTINCT node names (cached per
        # placement version) instead of every leaf cell: a leaf is unhealthy
        # exactly when its node is in bad_nodes (same single-writer
        # argument), so the per-node check is equivalent to
        # collect_bad_or_non_suggested_nodes over the full placement.
        if g.ignore_k8s_suggested_nodes and not self.bad_nodes:
            bad_or_non_suggested: Set[str] = set()
        else:
            bad_or_non_suggested = {
                n for n in g.placement_node_names()
                if n in self.bad_nodes
                or (not g.ignore_k8s_suggested_nodes and n not in suggested_nodes)
            }
        if g.state == GROUP_ALLOCATED:
            log.info("[%s]: Pod is from an affinity group that is already allocated: %s",
                     internal_utils.key(pod), s.affinity_group.name)
            if self._decision is not None:
                self._decision.attempt(
                    f"group {g.name}", "existing-allocated", "placed")
            group_physical = g.physical_leaf_cell_placement
            group_virtual = g.virtual_leaf_cell_placement
            if bad_or_non_suggested:
                # insist the previous decision even if some nodes went bad
                log.warning(
                    "[%s]: Some nodes allocated to affinity group %s are no longer "
                    "healthy and within K8s suggested nodes: %s",
                    internal_utils.key(pod), g.name, bad_or_non_suggested,
                )
            pod_index = get_new_pod_index(
                g.allocated_pods.get(s.leaf_cell_number, []),
                g.pod_index_watermark.get(s.leaf_cell_number, 0),
            )
            if pod_index == -1:
                raise api.as_bad_request(
                    f"Requesting more pods than the configured number for "
                    f"{s.leaf_cell_number} leaf cells "
                    f"({g.total_pod_nums.get(s.leaf_cell_number)} pods) in affinity group "
                    f"{s.affinity_group.name}"
                )
        else:  # GROUP_PREEMPTING
            log.info("[%s]: Pod is from an affinity group that is preempting others: %s",
                     internal_utils.key(pod), s.affinity_group.name)
            if phase == internal.PREEMPTING_PHASE and bad_or_non_suggested:
                # cancel the preemption so the group can reschedule elsewhere;
                # only Preempting-phase suggested nodes consider preemption
                log.info(
                    "[%s]: Canceling affinity group %s's preemption because its placement "
                    "is no longer fully healthy and within Preempting-phase suggested "
                    "nodes: %s", internal_utils.key(pod), g.name, bad_or_non_suggested,
                )
                if self._decision is not None:
                    self._decision.attempt(
                        f"group {g.name}", "existing-preempting", "failed",
                        "preemption canceled: placement no longer healthy "
                        "and within suggested nodes",
                    )
                self._delete_preempting_affinity_group(g, pod)
            else:
                if self._decision is not None:
                    self._decision.attempt(
                        f"group {g.name}", "existing-preempting", "placed")
                group_physical = g.physical_leaf_cell_placement
                group_virtual = g.virtual_leaf_cell_placement
                preemption_victims, _ = collect_preemption_victims(group_physical)
                if not preemption_victims:
                    log.info(
                        "Preemption victims have been cleaned up for the preemptor "
                        "affinity group %s", g.name,
                    )
                g.preempting_pods[pod.uid] = pod
        return group_physical, group_virtual, preemption_victims, pod_index

    def _schedule_pod_from_new_group(
        self,
        s: api.PodSchedulingSpec,
        suggested_nodes: Set[str],
        phase: str,
        pod: Pod,
    ) -> Tuple[
        Optional[GroupPhysicalPlacement],
        Optional[GroupVirtualPlacement],
        Dict[str, Dict[str, Pod]],
        str,
    ]:
        """Reference: schedulePodFromNewGroup, hived_algorithm.go:716-752."""
        group_physical, group_virtual, wait_reason = self._schedule_new_affinity_group(
            pod, s, suggested_nodes
        )
        if group_physical is None:
            return None, None, {}, wait_reason
        preemption_victims, overlapping_preemptors = collect_preemption_victims(group_physical)
        if phase == internal.PREEMPTING_PHASE:
            # cancel preemptions of lower-priority groups we further preempt
            for preemptor in overlapping_preemptors:
                log.info(
                    "[%s]: Canceling affinity group %s's preemption because it is further "
                    "preempted by a higher-priority affinity group %s",
                    internal_utils.key(pod), preemptor.name, s.affinity_group.name,
                )
                self._delete_preempting_affinity_group(preemptor, pod)
            if preemption_victims:
                # reserve now to avoid contention among multiple preemptors
                self._create_preempting_affinity_group(
                    s, group_physical, group_virtual, pod
                )
        elif preemption_victims:
            log.info(
                "[%s]: Found preemption victims in non-Preempting phase, skipping",
                internal_utils.key(pod),
            )
        return group_physical, group_virtual, preemption_victims, wait_reason

    def _schedule_new_affinity_group(
        self,
        pod: Pod,
        s: api.PodSchedulingSpec,
        suggested_nodes: Set[str],
    ) -> Tuple[
        Optional[GroupPhysicalPlacement], Optional[GroupVirtualPlacement], str
    ]:
        """Reference: scheduleNewAffinityGroup, hived_algorithm.go:756-796."""
        log.info("[%s]: Scheduling new affinity group %s",
                 internal_utils.key(pod), s.affinity_group.name)
        sr = SchedulingRequest(
            vc=s.virtual_cluster,
            pinned_cell_id=s.pinned_cell_id,
            priority=s.priority,
            affinity_group_name=s.affinity_group.name,
            suggested_nodes=suggested_nodes,
            ignore_suggested_nodes=s.ignore_k8s_suggested_nodes,
            multi_chain_relax=s.multi_chain_relax_enable,
            multi_chain_relax_policy=s.multi_chain_relax_policy,
        )
        for m in s.affinity_group.members:
            sr.affinity_group_pod_nums[m.leaf_cell_number] = (
                sr.affinity_group_pod_nums.get(m.leaf_cell_number, 0) + m.pod_number
            )
        self._validate_scheduling_request(sr, pod)
        if sr.pinned_cell_id:
            log.info("Using pinned cell %s", sr.pinned_cell_id)
            return self._handle_scheduling_request(sr)
        if s.leaf_cell_type:
            if s.leaf_cell_type not in self.cell_chains:
                raise api.as_bad_request(
                    f"[{internal_utils.key(pod)}]: Pod requesting leaf cell type "
                    f"{s.leaf_cell_type} which the whole cluster does not have"
                )
            log.info("Using specified leaf cell type %s", s.leaf_cell_type)
            return self._schedule_affinity_group_for_leaf_cell_type(
                sr, s.leaf_cell_type, pod, type_specified=True
            )
        return self._schedule_affinity_group_for_any_leaf_cell_type(sr, pod)

    def _schedule_affinity_group_for_leaf_cell_type(
        self,
        sr: SchedulingRequest,
        leaf_cell_type: str,
        pod: Pod,
        type_specified: bool,
        relax_allowed: bool = True,
        single_chain_allowed: bool = True,
    ) -> Tuple[
        Optional[GroupPhysicalPlacement], Optional[GroupVirtualPlacement], str
    ]:
        """Reference: scheduleAffinityGroupForLeafCellType,
        hived_algorithm.go:800-829.

        The any-type caller splits the work into two passes via
        ``single_chain_allowed`` / ``relax_allowed`` so that relaxation never
        preempts another leaf type's whole-gang placement."""
        vc_has_type = False
        failed_reason = ""
        candidate_chains: List[CellChain] = []
        for chain in self.cell_chains[leaf_cell_type]:
            if (
                sr.priority < MIN_GUARANTEED_PRIORITY
                or chain in self.vc_schedulers[sr.vc].non_pinned_preassigned_cells
            ):
                vc_has_type = True
                candidate_chains.append(chain)
                if not single_chain_allowed:
                    continue
                log.info("Searching chain %s", chain)
                sr.chain = chain
                physical, virtual, failed_reason = self._handle_scheduling_request(sr)
                if physical is not None:
                    return physical, virtual, ""
        if len(candidate_chains) > 1 and sr.multi_chain_relax and relax_allowed:
            # no single chain fits the whole gang: relax it across chains of
            # the same leaf type (closes the reference TODO at
            # intra_vc_scheduler.go:52); opt out per group via
            # multiChainRelaxEnable: false
            physical, virtual, relax_reason = self._schedule_relaxed_across_chains(
                sr, candidate_chains
            )
            if physical is not None:
                return physical, virtual, ""
            if relax_reason:
                failed_reason = relax_reason
        if type_specified and sr.priority >= MIN_GUARANTEED_PRIORITY and not vc_has_type:
            raise api.as_bad_request(
                f"[{internal_utils.key(pod)}]: Pod requesting leaf cell type "
                f"{leaf_cell_type} which VC {sr.vc} does not have"
            )
        return None, None, failed_reason

    def _schedule_affinity_group_for_any_leaf_cell_type(
        self, sr: SchedulingRequest, pod: Pod
    ) -> Tuple[
        Optional[GroupPhysicalPlacement], Optional[GroupVirtualPlacement], str
    ]:
        """Reference: scheduleAffinityGroupForAnyLeafCellType,
        hived_algorithm.go:833-853.

        Two passes: every type's single-chain attempts run before ANY type is
        relaxed across chains — a whole-gang placement on some other leaf
        type always beats splitting the gang."""
        failed_reason = ""
        for relax in (False, True) if sr.multi_chain_relax else (False,):
            for leaf_cell_type in self.cell_chains:
                log.info("Searching leaf cell type %s (relax=%s)", leaf_cell_type, relax)
                physical, virtual, type_failed_reason = (
                    self._schedule_affinity_group_for_leaf_cell_type(
                        sr, leaf_cell_type, pod, type_specified=False,
                        relax_allowed=relax, single_chain_allowed=not relax,
                    )
                )
                if physical is not None:
                    return physical, virtual, ""
                if type_failed_reason:
                    failed_reason = type_failed_reason
        return None, None, failed_reason

    def _schedule_relaxed_across_chains(
        self, sr: SchedulingRequest, chains: List[CellChain]
    ) -> Tuple[
        Optional[GroupPhysicalPlacement], Optional[GroupVirtualPlacement], str
    ]:
        """Multi-chain relaxation: split one affinity group across several
        chains of the same leaf cell type when no single chain can host it.

        Closes the reference's TODO (intra_vc_scheduler.go:52: "Support an
        affinity group can relax to be allocated across multiple chains").
        Greedy partition, largest usable capacity first: chains are probed
        in descending order of usable leaf-cell capacity (for guaranteed
        requests the VC's quota minus same-or-higher-priority usage, so
        lazily-preemptible lower-priority cells count; the physical free
        list for opportunistic ones; ties broken by config order for
        determinism), and each chain takes
        the largest prefix of the remaining pods (largest members first) it
        accepts. Largest-capacity-first minimizes the number of chains a gang
        is split across — fewer cross-chain (DCN) boundaries inside the gang
        — and on the success path leaves full chains unprobed (an
        unplaceable gang still probes every chain before giving up, since
        the ranking is an estimate, not a guarantee). Each sub-request
        runs the normal per-chain path, so VC-safety accounting is preserved
        chain by chain. All-or-nothing: if pods remain after the last chain,
        every committed lazy preemption is reverted and the group waits.

        ``multiChainRelaxPolicy: balanced`` keeps the same minimal chain
        set but water-fills the gang's chips across it (bounded by each
        chain's largest AVAILABLE cell — a sub-request is buddy-enclosed in
        one cell), minimizing the largest sub-gang: a hierarchical
        (ICI-then-DCN) collective is then paced by comparable-size ICI
        phases instead of one oversized sub-gang. Targets are enforced as
        CUMULATIVE allowances, so a shortfall on one chain rolls forward
        into the next chain's budget in the same single pass — each chain
        is still probed at most once (a re-probe would hand out the same
        uncommitted cells twice).
        Per-pod cell chains are recorded in the bind info, and recovery
        relies on find_physical_leaf_cell's cross-chain fallback.

        Infeasibility cache (ADVICE.md round 5): a gang that failed to relax
        waits and is re-probed every scheduling cycle, re-running both the
        balanced and the fewest pass each time. When NOTHING has touched the
        involved chains since the last failed attempt (per-chain mutation
        counters ``_chain_gen``; invalidated by any allocate/release —
        including the attempt's own lazy-preempt commits and reverts, since
        the token is captured after the revert — plus health transitions),
        the same request against the same cell state re-fails
        deterministically, so the cached wait reason is returned without
        probing. ``HIVED_RELAX_CACHE=0`` disables it.
        """
        import os as _os

        guaranteed_req = sr.priority >= MIN_GUARANTEED_PRIORITY
        cache_on = _os.environ.get("HIVED_RELAX_CACHE", "1") != "0"
        req_sig = (
            tuple(sorted(sr.affinity_group_pod_nums.items())), sr.priority,
            sr.vc, sr.multi_chain_relax_policy, tuple(chains),
            sr.ignore_suggested_nodes,
        )
        if cache_on:
            cached = self._relax_infeasible.get(sr.affinity_group_name)
            if cached is not None:
                c_req, c_token, c_sugg, c_reason = cached
                if (
                    c_req == req_sig
                    and c_token == tuple(
                        self._chain_gen.get(c, 0) for c in chains
                    )
                    and (c_sugg is None or c_sugg == sr.suggested_nodes)
                ):
                    if self._decision is not None:
                        self._decision.attempt(
                            "relax[" + ",".join(str(c) for c in chains) + "]",
                            "multi-chain-relax", "failed",
                            c_reason + " (cached infeasibility)",
                        )
                    return None, None, c_reason
                del self._relax_infeasible[sr.affinity_group_name]

        def root_available(chain: CellChain) -> List[int]:
            """Per-preassigned-root available leaf counts for a guaranteed
            request: quota minus same-or-higher-priority usage, so
            lazily-preemptible lower-priority cells count — free cells
            alone would under-rank chains full of preemptible pods and
            smear the gang across more chains. Roots at every level (a VC
            may mix whole-pod and sub-cell quotas in one chain);
            descendants are skipped to avoid double counting. ONE home for
            this accounting: the chain ranking sums it, the balanced
            policy's contiguity estimate maxes it."""
            full = self.vc_schedulers[sr.vc].non_pinned_full_cell_list.get(chain)
            if not full:
                return []
            return [
                c.total_leaf_cell_num
                - sum(
                    n
                    for q, n in c.used_leaf_cell_num_at_priorities.items()
                    if q >= sr.priority
                )
                for level in full
                for c in full[level]
                if c.preassigned_cell is c
            ]

        def free_leaf_capacity(chain: CellChain) -> int:
            if guaranteed_req:
                return sum(root_available(chain))
            leaf_num = self.leaf_cell_nums[chain]
            return sum(
                len(cells) * leaf_num[l]
                for l, cells in self.free_cell_list[chain].items()
            )

        config_order = {c: i for i, c in enumerate(chains)}
        chains = sorted(
            chains, key=lambda c: (-free_leaf_capacity(c), config_order[c])
        )
        flat: List[int] = []
        for ln in sorted(sr.affinity_group_pod_nums, reverse=True):
            flat.extend([ln] * sr.affinity_group_pod_nums[ln])

        def contiguous_capacity(chain: CellChain) -> int:
            """Largest single sub-gang this chain could host contiguously —
            a sub-request is buddy-enclosed in ONE cell, so this is the
            largest available cell, not the capacity sum. Optimistic
            estimate only: the probe loop verifies with real placements."""
            if guaranteed_req:
                return max(root_available(chain), default=0)
            leaf_num = self.leaf_cell_nums[chain]
            return max(
                (leaf_num[l] for l, cells in self.free_cell_list[chain].items()
                 if cells),
                default=0,
            )

        # Cumulative chip allowance per chain position. INVARIANT: each
        # chain is probed at most ONCE per relax call — probes compute
        # placements from uncommitted cell state, so a second probe of the
        # same chain would hand out the SAME physical cells again
        # (double-booking). "fewest" allows every chain the whole gang;
        # "balanced" water-fills the gang's chips over the minimal chain
        # set whose contiguous capacities cover it (minimizing the largest
        # sub-gang: every sub-gang then runs its ICI collective phase at a
        # comparable size instead of one oversized sub-gang straggling the
        # hierarchical ICI-then-DCN collective), and any shortfall against
        # the estimated targets rolls FORWARD into later chains' allowance
        # — feasibility degrades gracefully without ever re-probing.
        total = sum(flat)
        allowance = [total] * len(chains)
        if sr.multi_chain_relax_policy == "balanced":
            caps = [contiguous_capacity(c) for c in chains]
            k, acc = 0, 0
            for cap in caps:
                if acc >= total:
                    break
                k += 1
                acc += cap
            if acc >= total:
                # minimize the max target subject to target_i <= cap_i
                # (smallest caps pinned first, remainder over the rest);
                # caps are true per-probe upper bounds (a sub-request is
                # enclosed in one cell <= the largest available), so when
                # even their sum can't cover the gang we keep the plain
                # fewest allowances and let the round fail honestly
                targets = {}
                remaining, left = total, k
                for i in sorted(range(k), key=lambda i: caps[i]):
                    targets[i] = min(caps[i], -(-remaining // left))
                    remaining -= targets[i]
                    left -= 1
                cum = 0
                for i in range(len(chains)):
                    # chains beyond the chosen k carry the full remaining
                    # allowance (pure fallback: they only see pods the
                    # chosen set failed to absorb)
                    cum = cum + targets[i] if i < k else total
                    allowance[i] = cum

        def run_pass(allow: List[int]):
            """One partition attempt under cumulative allowances ``allow``.
            Probes commit nothing to cell state except lazy preemptions
            (returned for the caller to keep or revert), so a failed pass
            leaves the cluster exactly as found once those are reverted."""
            merged_phys: GroupPhysicalPlacement = {}
            merged_virt: GroupVirtualPlacement = {}
            committed_lazy: Dict[str, GroupVirtualPlacement] = {}
            idx = 0
            placed_chips = 0
            try:
                for pos, chain in enumerate(chains):
                    if idx >= len(flat):
                        break
                    # chip-count upper bound: no point probing prefixes
                    # that hold more chips than the whole chain (keeps the
                    # descent linear overall instead of O(pods) probes per
                    # small chain); the balanced policy further caps it at
                    # this chain's cumulative allowance minus what's
                    # already placed
                    chain_chips = sum(
                        c.total_leaf_cell_num
                        for c in self.full_cell_list[chain][max(self.full_cell_list[chain])]
                    )
                    limit = min(chain_chips, allow[pos] - placed_chips)
                    max_take = 0
                    chips = 0
                    for ln in flat[idx:]:
                        if chips + ln > limit:
                            break
                        chips += ln
                        max_take += 1
                    if max_take > 0:
                        # native prefix-fit pre-filter: one C call per
                        # probe phase replaces the O(take) full probes the
                        # descent would burn on prefixes that provably
                        # cannot pack on this chain (exact upper bound —
                        # every surviving take still runs the real probe,
                        # so decisions are unchanged; no-op without the
                        # native fast path)
                        max_take = min(max_take, self._relax_prefix_bound(
                            sr, chain, flat[idx:idx + max_take]))
                    for take in range(max_take, 0, -1):
                        if idx == 0 and take == len(flat):
                            # the whole-group attempt on this chain already
                            # ran (and failed, self-reverting) in the
                            # single-chain pass; re-probing it verbatim is
                            # pure waste
                            continue
                        counts: Dict[int, int] = {}
                        for ln in flat[idx:idx + take]:
                            counts[ln] = counts.get(ln, 0) + 1
                        sr.chain = chain
                        sr.affinity_group_pod_nums = counts
                        physical, virtual, _ = self._handle_scheduling_request(
                            sr, collect_lazy=committed_lazy
                        )
                        if physical is not None:
                            for ln, podps in physical.items():
                                merged_phys.setdefault(ln, []).extend(podps)
                            if virtual is not None:
                                for ln, podps in virtual.items():
                                    merged_virt.setdefault(ln, []).extend(podps)
                            placed_chips += sum(flat[idx:idx + take])
                            idx += take
                            log.info(
                                "Relaxed %s pod(s) of group %s onto chain %s",
                                take, sr.affinity_group_name, chain,
                            )
                            break
            finally:
                sr.affinity_group_pod_nums = original_pod_nums
            return idx, merged_phys, merged_virt, committed_lazy

        def revert_lazy(committed_lazy: Dict[str, GroupVirtualPlacement]):
            for group_name, placement in committed_lazy.items():
                g = self.affinity_groups.get(group_name)
                if g is not None:
                    self._revert_lazy_preempt(g, placement)

        original_pod_nums = sr.affinity_group_pod_nums
        idx, merged_phys, merged_virt, committed_lazy = run_pass(allowance)
        if idx < len(flat) and any(a != total for a in allowance):
            # the balanced targets are optimistic ESTIMATES (a chain's
            # achievable contiguous take can undershoot root_available —
            # e.g. higher-priority chips scattered across its cells): when
            # the balanced partition comes up short, revert its lazy
            # commits and rerun the whole pass under plain fewest-chains
            # allowances so feasibility never regresses vs `fewest`.
            # Probes committed nothing else, so the retry sees pristine
            # state — no cell is ever handed out twice.
            revert_lazy(committed_lazy)
            idx, merged_phys, merged_virt, committed_lazy = run_pass(
                [total] * len(chains)
            )
        relax_where = "relax[" + ",".join(str(c) for c in chains) + "]"
        if idx < len(flat):
            revert_lazy(committed_lazy)
            reason = (
                "insufficient capacity even after relaxing the affinity group "
                "across cell chains"
            )
            if cache_on:
                # token captured AFTER the reverts: it describes the state
                # the next identical attempt would start from
                if len(self._relax_infeasible) >= 256:
                    self._relax_infeasible.clear()
                self._relax_infeasible[sr.affinity_group_name] = (
                    req_sig,
                    tuple(self._chain_gen.get(c, 0) for c in req_sig[4]),
                    None if sr.ignore_suggested_nodes else set(sr.suggested_nodes),
                    reason,
                )
            if self._decision is not None:
                self._decision.attempt(
                    relax_where, "multi-chain-relax", "failed",
                    f"placed {idx}/{len(flat)} pods before running out of chains",
                )
            return None, None, reason
        log.info("Affinity group %s relaxed across chains: %s pods placed",
                 sr.affinity_group_name, len(flat))
        if self._decision is not None:
            self._decision.attempt(relax_where, "multi-chain-relax", "placed")
        return merged_phys, (merged_virt if guaranteed_req else None), ""

    def _relax_prefix_bound(
        self, sr: SchedulingRequest, chain: CellChain, flat_segment: List[int]
    ) -> int:
        """Exact upper bound on the relax descent's feasible takes for
        ``chain``: the native prefix-fit walk on the same view the real
        probe would search (the VC's virtual view for guaranteed requests,
        the physical opportunistic view otherwise). Returns
        ``len(flat_segment)`` — no pruning — when the native fast path is
        not engaged (see TopologyAwareScheduler.max_feasible_prefix)."""
        if sr.priority >= MIN_GUARANTEED_PRIORITY:
            vcs = self.vc_schedulers.get(sr.vc)
            scheduler = (None if vcs is None
                         else vcs.non_pinned_cell_schedulers.get(chain))
        else:
            scheduler = self.opportunistic_schedulers.get(chain)
        if scheduler is None:
            return len(flat_segment)
        return scheduler.max_feasible_prefix(
            flat_segment, sr.priority, sr.suggested_nodes,
            sr.ignore_suggested_nodes)

    def _validate_scheduling_request(self, sr: SchedulingRequest, pod: Pod) -> None:
        """Reference: validateSchedulingRequest, hived_algorithm.go:857-871."""
        message = ""
        if sr.vc not in self.vc_schedulers:
            message = f"VC {sr.vc} does not exist!"
        elif sr.pinned_cell_id:
            if sr.pinned_cell_id not in self.vc_schedulers[sr.vc].pinned_cells:
                message = f"VC {sr.vc} does not have pinned cell {sr.pinned_cell_id}"
            elif sr.priority == OPPORTUNISTIC_PRIORITY:
                message = (
                    f"opportunistic pod not supported to use pinned cell {sr.pinned_cell_id}"
                )
        if message:
            raise api.as_bad_request(f"[{internal_utils.key(pod)}]: {message}")

    def _handle_scheduling_request(
        self, sr: SchedulingRequest, collect_lazy: Optional[Dict] = None
    ) -> Tuple[
        Optional[GroupPhysicalPlacement], Optional[GroupVirtualPlacement], str
    ]:
        """Reference: handleSchedulingRequest, hived_algorithm.go:873-896."""
        where = f"pinned cell {sr.pinned_cell_id}" if sr.pinned_cell_id else f"chain {sr.chain}"
        log.info("Processing scheduling request: %s, leaf cell numbers %s, priority %s",
                 where, sr.affinity_group_pod_nums, sr.priority)
        if sr.priority >= MIN_GUARANTEED_PRIORITY:
            path = "guaranteed"
            physical, virtual, failed_reason = self._schedule_guaranteed_affinity_group(
                sr, collect_lazy
            )
        else:
            path = "opportunistic"
            physical, failed_reason = self._schedule_opportunistic_affinity_group(sr)
            virtual = None
        if self._decision is not None:
            self._decision.attempt(
                where, path, "failed" if physical is None else "placed",
                failed_reason if physical is None else "",
            )
        if physical is None:
            log.info("Cannot find placement in %s: %s", where, failed_reason)
            return None, None, failed_reason
        log.info("Found placement in %s", where)
        return physical, virtual, ""

    def _schedule_guaranteed_affinity_group(
        self, sr: SchedulingRequest, collect_lazy: Optional[Dict] = None
    ) -> Tuple[
        Optional[GroupPhysicalPlacement], Optional[GroupVirtualPlacement], str
    ]:
        """VC placement → binding paths → lazy preempt → map to physical
        (reference: scheduleGuaranteedAffinityGroup, hived_algorithm.go:900-942).

        ``collect_lazy`` (multi-chain relaxation): on success, the lazy
        preemptions this attempt committed are recorded there so the caller
        can revert them if the overall relaxed placement later fails."""
        virtual_placement, failed_reason = self.vc_schedulers[sr.vc].schedule(sr)
        if virtual_placement is None:
            return None, None, failed_reason
        if sr.pinned_cell_id and not sr.chain:
            # infer the chain from the pinned placement for the physical mapping
            any_leaf = next(iter(virtual_placement.values()))[0][0]
            sr.chain = any_leaf.chain
        bindings: Dict[str, PhysicalCell] = {}
        leaf_cell_nums = sorted(sr.affinity_group_pod_nums)
        lazy_preempted_groups = self._try_lazy_preempt(
            virtual_placement, leaf_cell_nums, sr.affinity_group_name
        )
        preassigned, non_preassigned = to_binding_paths(
            virtual_placement, leaf_cell_nums, bindings
        )
        free_cell_num_copy = dict(self.all_vc_free_cell_num[sr.chain])
        if map_virtual_placement_to_physical(
            preassigned,
            non_preassigned,
            self.free_cell_list[sr.chain].shallow_copy(),
            free_cell_num_copy,
            sr.suggested_nodes,
            sr.ignore_suggested_nodes,
            bindings,
        ):
            if collect_lazy is not None:
                for group_name, placement in lazy_preempted_groups.items():
                    collect_lazy.setdefault(group_name, placement)
            return (
                virtual_to_physical_placement(virtual_placement, bindings, leaf_cell_nums),
                virtual_placement,
                "",
            )
        for group_name, placement in lazy_preempted_groups.items():
            g = self.affinity_groups.get(group_name)
            if g is not None:
                self._revert_lazy_preempt(g, placement)
        failed_node_type = "bad" if sr.ignore_suggested_nodes else "bad or non-suggested"
        return None, None, (
            f"Mapping the virtual placement would need to use at least one "
            f"{failed_node_type} node"
        )

    def _try_lazy_preempt(
        self,
        p: GroupVirtualPlacement,
        leaf_cell_nums: List[int],
        group_name: str,
    ) -> Dict[str, GroupVirtualPlacement]:
        """Reference: tryLazyPreempt, hived_algorithm.go:945-963."""
        preempted: Dict[str, GroupVirtualPlacement] = {}
        for pod_leaf_cell_num in leaf_cell_nums:
            for pod_placement in p[pod_leaf_cell_num]:
                for leaf_cell in pod_placement:
                    assert isinstance(leaf_cell, VirtualCell)
                    p_leaf_cell = leaf_cell.physical_cell
                    if p_leaf_cell is not None and p_leaf_cell.state == CELL_USED:
                        using = p_leaf_cell.using_group
                        if using is not None and using.lazy_preemption_enable:
                            preempted[using.name] = self._lazy_preempt_affinity_group(
                                using, group_name
                            )
        return preempted

    def _schedule_opportunistic_affinity_group(
        self, sr: SchedulingRequest
    ) -> Tuple[Optional[GroupPhysicalPlacement], str]:
        """Reference: scheduleOpportunisticAffinityGroup,
        hived_algorithm.go:966-977."""
        placement, failed_reason = self.opportunistic_schedulers[sr.chain].schedule(
            sr.affinity_group_pod_nums,
            OPPORTUNISTIC_PRIORITY,
            sr.suggested_nodes,
            sr.ignore_suggested_nodes,
        )
        if placement is None:
            return None, f"{failed_reason} when scheduling in physical cluster"
        return placement, ""

    # ------------------------------------------------------------------
    # group lifecycle
    # ------------------------------------------------------------------

    def _create_allocated_affinity_group(
        self,
        s: api.PodSchedulingSpec,
        info: api.PodBindInfo,
        pod: Pod,
        live: Optional[tuple] = None,
    ) -> None:
        """Recovery path with the tolerance ladder: missing cells ignored;
        missing virtual placement or safety violation → lazy preempt
        (reference: createAllocatedAffinityGroup, hived_algorithm.go:982-1041).

        ``live`` carries the (physical, virtual) placement objects Schedule
        just computed, when add_allocated_pod proved nothing changed in
        between — the annotation-driven lookup then provably re-derives these
        exact cells (guard: test_live_placement_equivalence), so the lookup
        is skipped. Allocation, binding and safety accounting are unchanged."""
        log.info("[%s]: Creating new allocated affinity group: %s",
                 internal_utils.key(pod), s.affinity_group.name)
        new_group = AlgoAffinityGroup(
            s.affinity_group, s.virtual_cluster, s.lazy_preemption_enable,
            s.ignore_k8s_suggested_nodes, s.priority, GROUP_ALLOCATED,
        )
        should_lazy_preempt = False
        batch = UsedCountBatch()
        for gms in info.affinity_group_bind_info:
            leaf_cell_number = len(gms.pod_placements[0].physical_leaf_cell_indices)
            for pod_index in range(len(gms.pod_placements)):
                node = gms.pod_placements[pod_index].physical_node
                if live is not None:
                    # per-pod row hoists for the live (stash) path: the
                    # [leaf_cell_number][pod_index] indexing otherwise
                    # repeats per leaf of a gang-sized create
                    live_gp, live_gv = live
                    live_prow = live_gp[leaf_cell_number][pod_index]
                    live_vrow = (None if live_gv is None
                                 else live_gv[leaf_cell_number][pod_index])
                for leaf_cell_index in range(
                    len(gms.pod_placements[pod_index].physical_leaf_cell_indices)
                ):
                    if live is not None:
                        p_leaf_cell = live_prow[leaf_cell_index]
                        if live_vrow is None:
                            v_leaf_cell, lazy_preempt = None, None
                        else:
                            v_leaf_cell = live_vrow[leaf_cell_index]
                            lazy_preempt = False
                    else:
                        p_leaf_cell, v_leaf_cell, lazy_preempt = self._find_allocated_leaf_cell(
                            leaf_cell_index,
                            gms.pod_placements[pod_index].physical_leaf_cell_indices,
                            gms.pod_placements[pod_index].preassigned_cell_types,
                            info.cell_chain,
                            node,
                            should_lazy_preempt,
                            s,
                            new_group,
                            pod,
                        )
                    if p_leaf_cell is None:
                        # leaf cell not in the spec: ignore it, let the pod run
                        continue
                    new_group.physical_leaf_cell_placement[leaf_cell_number][pod_index][
                        leaf_cell_index
                    ] = p_leaf_cell
                    if lazy_preempt is None:
                        new_group.virtual_leaf_cell_placement = None
                    elif v_leaf_cell is not None:
                        new_group.virtual_leaf_cell_placement[leaf_cell_number][pod_index][
                            leaf_cell_index
                        ] = v_leaf_cell
                        if (
                            in_free_cell_list(p_leaf_cell)
                            and v_leaf_cell.preassigned_cell.priority > FREE_PRIORITY
                        ):
                            # binding the cell to a virtual cell whose preassigned
                            # cell is already bound (e.g., shrunk VC after
                            # reconfiguration): destroy the old binding by lazy
                            # preempting the groups in the preassigned cell
                            self._lazy_preempt_cell(
                                v_leaf_cell.preassigned_cell, new_group.name
                            )
                    else:
                        should_lazy_preempt = should_lazy_preempt or lazy_preempt
                    safety_ok, reason = self._allocate_leaf_cell(
                        p_leaf_cell, v_leaf_cell, s.priority, new_group.vc, batch
                    )
                    p_leaf_cell.add_using_group(new_group)
                    set_cell_state(p_leaf_cell, CELL_USED)
                    if not safety_ok:
                        should_lazy_preempt = True
                        log.warning("[%s]: %s", internal_utils.key(pod), reason)
        batch.flush()
        if should_lazy_preempt:
            self._lazy_preempt_affinity_group(new_group, new_group.name)
        self.affinity_groups[s.affinity_group.name] = new_group
        log.info("[%s]: New allocated affinity group created: %s",
                 internal_utils.key(pod), s.affinity_group.name)

    def _delete_allocated_affinity_group(self, g: AlgoAffinityGroup, pod: Pod) -> None:
        """Reference: deleteAllocatedAffinityGroup, hived_algorithm.go:1045-1070."""
        log.info("[%s]: All pods complete, deleting allocated affinity group: %s",
                 internal_utils.key(pod), g.name)
        batch = UsedCountBatch()
        for pod_placements in g.physical_leaf_cell_placement.values():
            for pod_placement in pod_placements:
                for leaf_cell in pod_placement:
                    if leaf_cell is None:
                        continue
                    assert isinstance(leaf_cell, PhysicalCell)
                    leaf_cell.delete_using_group(g)
                    if leaf_cell.state == CELL_USED:
                        self._release_leaf_cell(leaf_cell, g.vc, batch)
                        set_cell_state(leaf_cell, CELL_FREE)
                    else:  # Reserving: already allocated to the reserving group
                        set_cell_state(leaf_cell, CELL_RESERVED)
        batch.flush()
        del self.affinity_groups[g.name]
        log.info("[%s]: Allocated affinity group deleted: %s",
                 internal_utils.key(pod), g.name)

    def _create_preempting_affinity_group(
        self,
        s: api.PodSchedulingSpec,
        physical_placement: GroupPhysicalPlacement,
        virtual_placement: GroupVirtualPlacement,
        pod: Pod,
    ) -> None:
        """Resources are reserved immediately, before the victims die, to
        avoid preemptor deadlock (reference: createPreemptingAffinityGroup,
        hived_algorithm.go:1076-1112)."""
        log.info("[%s]: Creating new preempting affinity group: %s",
                 internal_utils.key(pod), s.affinity_group.name)
        new_group = AlgoAffinityGroup(
            s.affinity_group, s.virtual_cluster, s.lazy_preemption_enable,
            s.ignore_k8s_suggested_nodes, s.priority, GROUP_PREEMPTING,
        )
        new_group.physical_leaf_cell_placement = physical_placement
        new_group.virtual_leaf_cell_placement = virtual_placement
        batch = UsedCountBatch()
        for leaf_cell_num, pod_placements in physical_placement.items():
            for pod_index, pod_placement in enumerate(pod_placements):
                for leaf_cell_index, leaf_cell in enumerate(pod_placement):
                    assert isinstance(leaf_cell, PhysicalCell)
                    v_leaf_cell = virtual_placement[leaf_cell_num][pod_index][leaf_cell_index]
                    assert isinstance(v_leaf_cell, VirtualCell)
                    if leaf_cell.state == CELL_USED:
                        using_group = leaf_cell.using_group
                        self._release_leaf_cell(leaf_cell, using_group.vc, batch)
                        using_group.state = GROUP_BEING_PREEMPTED
                    self._allocate_leaf_cell(
                        leaf_cell, v_leaf_cell, s.priority, new_group.vc, batch
                    )
                    leaf_cell.add_reserving_or_reserved_group(new_group)
                    # cell is Used or Free here (Reserving/Reserved preemptors
                    # were canceled before in schedule())
                    if leaf_cell.state == CELL_USED:
                        set_cell_state(leaf_cell, CELL_RESERVING)
                    else:
                        set_cell_state(leaf_cell, CELL_RESERVED)
        batch.flush()
        new_group.preempting_pods[pod.uid] = pod
        self.affinity_groups[s.affinity_group.name] = new_group
        log.info("[%s]: New preempting affinity group created: %s",
                 internal_utils.key(pod), new_group.name)

    def _delete_preempting_affinity_group(self, g: AlgoAffinityGroup, pod: Pod) -> None:
        """Revoke a preemption; Reserving cells return to the being-preempted
        group (reference: deletePreemptingAffinityGroup,
        hived_algorithm.go:1116-1144)."""
        batch = UsedCountBatch()
        for pod_placements in g.physical_leaf_cell_placement.values():
            for pod_placement in pod_placements:
                for leaf_cell in pod_placement:
                    assert isinstance(leaf_cell, PhysicalCell)
                    self._release_leaf_cell(leaf_cell, g.vc, batch)
                    leaf_cell.delete_reserving_or_reserved_group(
                        leaf_cell.reserving_or_reserved_group
                    )
                    if leaf_cell.state == CELL_RESERVING:
                        set_cell_state(leaf_cell, CELL_USED)
                        being_preempted = leaf_cell.using_group
                        being_preempted_v: Optional[VirtualCell] = None
                        if being_preempted.virtual_leaf_cell_placement is not None:
                            being_preempted_v = retrieve_virtual_cell(
                                being_preempted.physical_leaf_cell_placement,
                                being_preempted.virtual_leaf_cell_placement,
                                leaf_cell,
                            )
                        self._allocate_leaf_cell(
                            leaf_cell, being_preempted_v, being_preempted.priority,
                            being_preempted.vc, batch,
                        )
                    else:  # Reserved
                        set_cell_state(leaf_cell, CELL_FREE)
        batch.flush()
        del self.affinity_groups[g.name]
        log.info("[%s]: Preempting affinity group %s deleted",
                 internal_utils.key(pod), g.name)

    def _allocate_preempting_affinity_group(self, g: AlgoAffinityGroup, pod: Pod) -> None:
        """Reference: allocatePreemptingAffinityGroup, hived_algorithm.go:1148-1162."""
        for pod_placements in g.physical_leaf_cell_placement.values():
            for pod_placement in pod_placements:
                for leaf_cell in pod_placement:
                    assert isinstance(leaf_cell, PhysicalCell)
                    leaf_cell.delete_reserving_or_reserved_group(g)
                    leaf_cell.add_using_group(g)
                    set_cell_state(leaf_cell, CELL_USED)
        g.state = GROUP_ALLOCATED
        g.preempting_pods = None
        log.info("[%s]: Preempting affinity group %s transitioned to allocated",
                 internal_utils.key(pod), g.name)

    def _lazy_preempt_affinity_group(
        self, victim: AlgoAffinityGroup, preemptor: str
    ) -> Optional[GroupVirtualPlacement]:
        """Demote a group to opportunistic (reference:
        lazyPreemptAffinityGroup, hived_algorithm.go:1166-1189)."""
        batch = UsedCountBatch()
        for pod_virtual_placements in (victim.virtual_leaf_cell_placement or {}).values():
            for pod_virtual_placement in pod_virtual_placements:
                for leaf_cell in pod_virtual_placement:
                    if leaf_cell is not None:
                        assert isinstance(leaf_cell, VirtualCell)
                        p_leaf_cell = leaf_cell.physical_cell
                        self._release_leaf_cell(p_leaf_cell, victim.vc, batch)
                        self._allocate_leaf_cell(
                            p_leaf_cell, None, OPPORTUNISTIC_PRIORITY, victim.vc, batch
                        )
        batch.flush()
        original = victim.virtual_leaf_cell_placement
        victim.virtual_leaf_cell_placement = None
        victim.placement_version += 1
        victim.lazy_preemption_status = api.LazyPreemptionStatus(
            preemptor=preemptor,
            preemption_time=datetime.now(timezone.utc).isoformat(),
        )
        log.info("Affinity group %s is lazy preempted from VC by %s", victim.name, preemptor)
        return original

    def _lazy_preempt_cell(self, c: VirtualCell, preemptor: str) -> None:
        """Reference: lazyPreemptCell, hived_algorithm.go:1192-1199."""
        if c.level == LOWEST_LEVEL and c.state == CELL_USED:
            self._lazy_preempt_affinity_group(c.physical_cell.using_group, preemptor)
        for child in c.children:
            assert isinstance(child, VirtualCell)
            self._lazy_preempt_cell(child, preemptor)

    def _revert_lazy_preempt(
        self, g: AlgoAffinityGroup, virtual_placement: GroupVirtualPlacement
    ) -> None:
        """Reference: revertLazyPreempt, hived_algorithm.go:1202-1219."""
        batch = UsedCountBatch()
        for leaf_cell_num, pod_placements in g.physical_leaf_cell_placement.items():
            for pod_index, pod_placement in enumerate(pod_placements):
                for leaf_cell_index, leaf_cell in enumerate(pod_placement):
                    if leaf_cell is None:
                        continue
                    assert isinstance(leaf_cell, PhysicalCell)
                    v_leaf_cell = virtual_placement[leaf_cell_num][pod_index][leaf_cell_index]
                    assert isinstance(v_leaf_cell, VirtualCell)
                    self._release_leaf_cell(leaf_cell, g.vc, batch)
                    self._allocate_leaf_cell(leaf_cell, v_leaf_cell, g.priority, g.vc, batch)
        batch.flush()
        g.virtual_leaf_cell_placement = virtual_placement
        g.placement_version += 1
        g.lazy_preemption_status = None
        log.info("Lazy preemption of affinity group %s is reverted", g.name)

    def _find_allocated_leaf_cell(
        self,
        index: int,
        physical_leaf_cell_indices: List[int],
        preassigned_cell_types: List[str],
        chain: CellChain,
        node: str,
        lazy_preempted: bool,
        s: api.PodSchedulingSpec,
        group: AlgoAffinityGroup,
        pod: Pod,
    ) -> Tuple[Optional[PhysicalCell], Optional[VirtualCell], Optional[bool]]:
        """Reference: findAllocatedLeafCell, hived_algorithm.go:1224-1290.
        Returns (physical, virtual, lazy_preempt) where lazy_preempt=None means
        the group is opportunistic (no virtual placement)."""
        priority = s.priority
        physical_leaf_cell_index = physical_leaf_cell_indices[index]
        p_leaf_cell = find_physical_leaf_cell(
            self.full_cell_list, chain, node, physical_leaf_cell_index,
            leaf_cell_index_map=self._leaf_cell_index,
        )
        if p_leaf_cell is None:
            log.warning(
                "[%s]: Cannot find leaf cell %s on node %s: not found in the spec. "
                "Pod ignored", internal_utils.key(pod), physical_leaf_cell_index, node,
            )
            return None, None, False
        if not preassigned_cell_types:
            log.warning("[%s]: Cannot find virtual cell: preassigned cell not found in "
                        "pod bind info", internal_utils.key(pod))
            return p_leaf_cell, None, True
        if group.virtual_leaf_cell_placement is not None and not lazy_preempted:
            preassigned_type = preassigned_cell_types[index]
            if preassigned_type:
                if p_leaf_cell.virtual_cell is not None:
                    # a still-bad leaf keeps its init-time doomed-bad child
                    # binding; mapPhysicalCellToVirtual would return that
                    # (possibly other-VC) vcell verbatim and the allocation
                    # books would be charged to the wrong VC — the reference
                    # silently corrupts vcFreeCellNum here via Go map
                    # auto-vivification. Reclaim the doomed chain first so
                    # the mapping re-derives from the pod's own VC quota.
                    b_pre = p_leaf_cell.virtual_cell.preassigned_cell
                    held = b_pre.physical_cell
                    if held is not None and held.priority < MIN_GUARANTEED_PRIORITY and self.vc_doomed_bad_cells[b_pre.vc][
                        held.chain
                    ].contains(held, held.level):
                        log.warning(
                            "[%s]: Recovered leaf %s carries doomed-bad "
                            "binding %s (VC %s); reclaiming it before mapping",
                            internal_utils.key(pod), p_leaf_cell.address,
                            p_leaf_cell.virtual_cell.address, b_pre.vc,
                        )
                        b_pre.set_physical_cell(None)
                        held.set_virtual_cell(None)
                        self._reclaim_doomed_cell(held, b_pre.vc)
                preassigned_level: Optional[CellLevel] = None
                for l, t in self.cell_types.get(p_leaf_cell.chain, {}).items():
                    if t == preassigned_type:
                        preassigned_level = l
                message = ""
                v_leaf_cell: Optional[VirtualCell] = None
                if preassigned_level is None:
                    message = (
                        f"Preassigned cell type {preassigned_type} not found in chain "
                        f"{p_leaf_cell.chain}"
                    )
                elif s.virtual_cluster not in self.vc_schedulers:
                    message = f"VC {s.virtual_cluster} not found"
                else:
                    vcs = self.vc_schedulers[s.virtual_cluster]
                    if s.pinned_cell_id:
                        vccl = vcs.pinned_cells.get(s.pinned_cell_id)
                        where = s.pinned_cell_id
                    else:
                        vccl = vcs.non_pinned_preassigned_cells.get(p_leaf_cell.chain)
                        where = str(p_leaf_cell.chain)
                    if vccl is None:
                        message = f"VC {s.virtual_cluster} has no cell for {where}"
                    else:
                        v_leaf_cell, message = map_physical_cell_to_virtual(
                            p_leaf_cell, vccl, preassigned_level, priority
                        )
                if v_leaf_cell is None:
                    log.warning("[%s]: Cannot find virtual cell: %s",
                                internal_utils.key(pod), message)
                    return p_leaf_cell, None, True
                if v_leaf_cell.vc != s.virtual_cluster:
                    # map_physical_cell_to_virtual returns an existing leaf
                    # binding verbatim (reference: mapPhysicalCellToVirtual,
                    # cell_allocation.go:320-346, which corrupts
                    # vcFreeCellNum at hived_algorithm.go:1356-1427 via Go
                    # map auto-vivification); when an ANOTHER-VC doomed-bad binding
                    # survived the reclaim guard above (its held cell already
                    # hosts guaranteed users, so reclaiming is illegal), that
                    # binding belongs to the wrong VC and allocating through
                    # it would charge this pod to the other VC's books
                    # (deviation documented in PARITY.md, found by the
                    # multi-chain invariant fuzz). Tolerance ladder: no
                    # usable virtual placement -> lazy preempt.
                    log.warning(
                        "[%s]: Recovered leaf %s maps to virtual cell %s of "
                        "VC %s, not this pod's VC %s (cross-VC doomed-bad "
                        "binding); lazy-preempting the group",
                        internal_utils.key(pod), p_leaf_cell.address,
                        v_leaf_cell.address, v_leaf_cell.vc,
                        s.virtual_cluster,
                    )
                    return p_leaf_cell, None, True
                # Recovery starts with every uninformed node bad, so
                # init-time doomed-bad binds can sit exactly where a
                # replayed pod must allocate — either holding the pod's own
                # preassigned vcell (pointed at the wrong physical cell) or
                # holding the physical ancestor the pod needs (the reference
                # panics in removeCellFromFreeList either way). A doomed
                # marker yields to the rightful owner; any other conflicting
                # binding lazy-preempts the group.
                p_pre = p_leaf_cell
                while p_pre.level < preassigned_level:
                    p_pre = p_pre.parent  # type: ignore[assignment]
                pac = v_leaf_cell.preassigned_cell
                if pac.physical_cell is not None and pac.physical_cell is not p_pre:
                    held = pac.physical_cell
                    if held.priority < MIN_GUARANTEED_PRIORITY and self.vc_doomed_bad_cells[
                        pac.vc
                    ][held.chain].contains(held, held.level):
                        log.warning(
                            "[%s]: Recovered preassigned cell %s is doomed-bad "
                            "bound to %s, not this pod's placement %s; "
                            "reclaiming the doomed binding",
                            internal_utils.key(pod), pac.address, held.address,
                            p_pre.address,
                        )
                        pac.set_physical_cell(None)
                        held.set_virtual_cell(None)
                        self._reclaim_doomed_cell(held, pac.vc)
                    else:
                        log.warning(
                            "[%s]: Recovered preassigned cell %s already bound "
                            "to %s, not this pod's placement %s; lazy preempting",
                            internal_utils.key(pod), pac.address, held.address,
                            p_pre.address,
                        )
                        return p_leaf_cell, None, True
                if pac.physical_cell is None:
                    # the fresh preassigned binding will need p_pre whole:
                    # clear any doomed-bad markers inside it (free-but-bad
                    # capacity yields to the returning owner; reclaiming
                    # also re-merges the buddies they split), then verify
                    # the cell is actually allocatable — anything still
                    # bound or split means a real conflicting binding, and
                    # the tolerance ladder says lazy preempt, not panic
                    self._reclaim_doomed_overlapping(p_pre)
                    if p_pre.split or not in_free_cell_list(p_pre):
                        log.warning(
                            "[%s]: Recovered placement needs cell %s which "
                            "is still held by conflicting bindings; lazy "
                            "preempting",
                            internal_utils.key(pod), p_pre.address,
                        )
                        return p_leaf_cell, None, True
                if (
                    v_leaf_cell.preassigned_cell.physical_cell is None
                    and self._under_foreign_pin(p_leaf_cell)
                ):
                    # Physical reconfiguration can move a pinned cell onto a
                    # placement recovered from annotations. Binding the fresh
                    # preassigned cell would need free-list surgery inside a
                    # pin that was never in the free list — the reference
                    # panics here (allocatePreassignedCell ->
                    # removeCellFromFreeList, hived_algorithm.go:1356-1427);
                    # we extend the tolerance ladder and lazy preempt instead.
                    log.warning(
                        "[%s]: Recovered placement lies inside a pinned cell "
                        "after reconfiguration; lazy preempting",
                        internal_utils.key(pod),
                    )
                    return p_leaf_cell, None, True
                return p_leaf_cell, v_leaf_cell, False
            return p_leaf_cell, None, None
        return p_leaf_cell, None, False

    @staticmethod
    def _under_foreign_pin(p_leaf_cell: PhysicalCell) -> bool:
        """True iff any cell on the leaf's path to the root is pinned —
        including pins rooted BELOW the preassigned level, whose init-time
        allocation also removed cells from the free list that the fresh
        preassigned binding would try to remove again. A non-pinned virtual
        mapping can never legitimately bind inside a pin (pins are
        exclusively owned), so a recovered placement matching this is a
        reconfiguration artifact."""
        c: Optional[PhysicalCell] = p_leaf_cell
        while c is not None:
            if c.pinned:
                return True
            c = c.parent  # type: ignore[assignment]
        return False

    # ------------------------------------------------------------------
    # leaf cell allocation / release with safety accounting
    # ------------------------------------------------------------------

    def _allocate_leaf_cell(
        self,
        p_leaf_cell: PhysicalCell,
        v_leaf_cell: Optional[VirtualCell],
        p: CellPriority,
        vcn: str,
        batch: Optional[UsedCountBatch] = None,
    ) -> Tuple[bool, str]:
        """Reference: allocateLeafCell, hived_algorithm.go:1294-1323."""
        safety_ok, reason = True, ""
        self._bump_chain_gen(p_leaf_cell.chain)
        if v_leaf_cell is not None:
            allocate_cell_walk(v_leaf_cell, p, batch)
            allocate_cell_walk(p_leaf_cell, p, batch)
            pac = v_leaf_cell.preassigned_cell
            preassigned_newly_bound = pac.physical_cell is None
            if p_leaf_cell.virtual_cell is None:
                # the binding may exist already (when the cell is bad)
                bind_cell(p_leaf_cell, v_leaf_cell)
            if preassigned_newly_bound:
                safety_ok, reason = self._allocate_preassigned_cell(
                    pac.physical_cell, vcn, doomed_bad=False
                )
        else:
            allocate_cell_walk(p_leaf_cell, OPPORTUNISTIC_PRIORITY, batch)
            p_leaf_cell.api_status.vc = vcn
            self.api_cluster_status.virtual_clusters[vcn].append(
                generate_ot_virtual_cell(p_leaf_cell.api_status)
            )
        return safety_ok, reason

    def _release_leaf_cell(
        self,
        p_leaf_cell: PhysicalCell,
        vcn: str,
        batch: Optional[UsedCountBatch] = None,
    ) -> None:
        """Reference: releaseLeafCell, hived_algorithm.go:1327-1352.

        Documented deviation (PARITY.md): when the virtual binding exists
        only because the cell is doomed-bad (virtual priority still FREE —
        the pod using the cell was opportunistic, and OT allocation never
        touches the virtual books), the release takes the opportunistic
        branch instead of the reference's virtual branch. The reference
        decrements the virtual used-counts at freePriority here, planting a
        permanent ``{freePriority: -1}`` entry that skews cluster-view
        scoring; found by tests/test_invariant_fuzz.py's recount invariant."""
        self._bump_chain_gen(p_leaf_cell.chain)
        v_leaf_cell = p_leaf_cell.virtual_cell
        doomed_only = (
            v_leaf_cell is not None and v_leaf_cell.priority == FREE_PRIORITY
        )
        if v_leaf_cell is not None and not doomed_only:
            release_cell_walk(v_leaf_cell, v_leaf_cell.priority, batch)
            preassigned_physical = v_leaf_cell.preassigned_cell.physical_cell
            if p_leaf_cell.healthy:
                # keep the binding if the cell is bad
                unbind_cell(p_leaf_cell)
            doomed_list = self.vc_doomed_bad_cells[vcn][preassigned_physical.chain]
            in_doomed = doomed_list.contains(
                preassigned_physical, preassigned_physical.level
            )
            if (
                in_doomed
                and preassigned_physical.virtual_cell is None
                and not preassigned_physical.pinned
            ):
                # the last user of a doomed cell that HEALED while in use
                # just released it (unbind_cell stripped the binding up to
                # the preassigned level): reclaim it now, else the books
                # count it allocated while in_free_cell_list sees it free
                # (deviation, PARITY.md; found by test_invariant_fuzz)
                log.info("Healed doomed cell %s reclaimed on release",
                         preassigned_physical.address)
                self._reclaim_doomed_cell(preassigned_physical, vcn)
            elif (
                not preassigned_physical.pinned
                and v_leaf_cell.preassigned_cell.priority < MIN_GUARANTEED_PRIORITY
                and not in_doomed
            ):
                self._release_preassigned_cell(preassigned_physical, vcn, doomed_bad=False)
        else:
            # doomed-bad-only binding: the binding (and its vc marking in
            # the API mirror) survives; only the opportunistic books go
            p_leaf_cell.api_status.vc = v_leaf_cell.vc if doomed_only else ""
            self.api_cluster_status.virtual_clusters[vcn] = delete_ot_virtual_cell(
                self.api_cluster_status.virtual_clusters[vcn], p_leaf_cell.address
            )
        release_cell_walk(p_leaf_cell, p_leaf_cell.priority, batch)

    def _allocate_preassigned_cell(
        self, c: PhysicalCell, vcn: str, doomed_bad: bool
    ) -> Tuple[bool, str]:
        """Remove from free list + full safety/doomed-bad accounting at every
        level (reference: allocatePreassignedCell, hived_algorithm.go:1356-1427)."""
        safety_ok, reason = True, ""
        chain, level = c.chain, c.level
        self._bump_chain_gen(chain)
        self.vc_free_cell_num[vcn][chain][level] -= 1
        self.all_vc_free_cell_num[chain][level] -= 1
        self.total_left_cell_num[chain][level] -= 1
        split_level_up_to = self._remove_cell_from_free_list(c)

        # pass 1: drop every bad ancestor from the bad free list BEFORE any
        # doomed rebind below can run — the split above already took them
        # out of the free list, so a rebind picking one mid-walk would
        # allocate a cell with no free-list entry (chaos defrag-v1 seed 23)
        parent = c.parent
        for l in range(level + 1, split_level_up_to + 1):
            assert isinstance(parent, PhysicalCell)
            if not parent.healthy:
                self.bad_free_cells[chain].remove(parent, l)
            parent = parent.parent

        parent = c.parent
        for l in range(level + 1, split_level_up_to + 1):
            self.total_left_cell_num[chain][l] -= 1
            if self.total_left_cell_num[chain][l] < self.all_vc_free_cell_num[chain].get(l, 0):
                safety_ok = False
                reason = (
                    f"Adding pod would lead to broken safety: cell type "
                    f"{self.cell_types[chain][l]}, {self.total_left_cell_num[chain][l]} "
                    f"left, {self.all_vc_free_cell_num[chain].get(l, 0)} free cells in all VCs"
                )
            assert isinstance(parent, PhysicalCell)
            if not parent.healthy:
                # parent bad: the healthy-free count is unchanged (total_left
                # and bad_free_cells both dropped by one), but an OUTSTANDING
                # doomed condition from an earlier reclaim may still need a
                # bind here — and this split just consumed one candidate, so
                # re-check now while others remain (chaos defrag-v1 seed 23)
                self._try_bind_doomed_bad_cell(chain, l)
            else:
                # healthy-free count decreased: try binding doomed bad cells
                self._try_bind_doomed_bad_cell(chain, l)
            parent = parent.parent
        if not c.healthy:
            self._allocate_bad_cell(c)
            if not doomed_bad:
                self._try_unbind_doomed_bad_cell(chain, level)
        else:
            self._try_bind_doomed_bad_cell(chain, level)
        num_to_reduce = len(c.children)
        for l in range(level - 1, LOWEST_LEVEL - 1, -1):
            self.total_left_cell_num[chain][l] -= num_to_reduce
            if self.total_left_cell_num[chain][l] < self.all_vc_free_cell_num[chain].get(l, 0):
                safety_ok = False
                reason = (
                    f"Adding pod would lead to broken safety: cell type "
                    f"{self.cell_types[chain][l]}, {self.total_left_cell_num[chain][l]} "
                    f"left, {self.all_vc_free_cell_num[chain].get(l, 0)} free cells in all VCs"
                )
            if not doomed_bad:
                self._try_bind_doomed_bad_cell(chain, l)
            num_to_reduce *= len(self.full_cell_list[chain][l][0].children) if l > 1 else 1
        return safety_ok, reason

    def _allocate_bad_cell(self, c: PhysicalCell) -> None:
        """Reference: allocateBadCell, hived_algorithm.go:1431-1447."""
        if self.bad_free_cells[c.chain].contains(c, c.level):
            self.bad_free_cells[c.chain].remove(c, c.level)
        if c.virtual_cell is None:
            parent = c.parent
            assert isinstance(parent, PhysicalCell) and parent.virtual_cell is not None
            vc = get_unbound_virtual_cell(parent.virtual_cell.children)
            c.set_virtual_cell(vc)
            vc.set_physical_cell(c)
            log.info("Virtual cell %s is bound to physical cell %s", vc.address, c.address)
        for child in c.children:
            assert isinstance(child, PhysicalCell)
            if not child.healthy:
                self._allocate_bad_cell(child)

    def _release_preassigned_cell(self, c: PhysicalCell, vcn: str, doomed_bad: bool) -> None:
        """Reference: releasePreassignedCell, hived_algorithm.go:1451-1485."""
        chain, level = c.chain, c.level
        self._bump_chain_gen(chain)
        self.vc_free_cell_num[vcn][chain][level] += 1
        self.all_vc_free_cell_num[chain][level] += 1
        self.total_left_cell_num[chain][level] += 1
        merge_level_up_to = self._add_cell_to_free_list(c)

        parent = c.parent
        bad_merge_levels: List[CellLevel] = []
        for l in range(level + 1, merge_level_up_to + 1):
            self.total_left_cell_num[chain][l] += 1
            assert isinstance(parent, PhysicalCell)
            if not parent.healthy:
                self.bad_free_cells[chain][l].append(parent)
                bad_merge_levels.append(l)
            else:
                self._try_unbind_doomed_bad_cell(chain, l)
            parent = parent.parent
        if not c.healthy:
            self._release_bad_cell(c)
            if not doomed_bad:
                self._try_bind_doomed_bad_cell(chain, level)
        else:
            self._try_unbind_doomed_bad_cell(chain, level)
        num_to_add = len(c.children)
        for l in range(level - 1, LOWEST_LEVEL - 1, -1):
            self.total_left_cell_num[chain][l] += num_to_add
            if not doomed_bad:
                self._try_unbind_doomed_bad_cell(chain, l)
            num_to_add *= len(self.full_cell_list[chain][l][0].children) if l > 1 else 1
        if bad_merge_levels:
            # bad free cells (re)appeared along the merge path: a doomed
            # condition deferred for lack of a bindable candidate can bind
            # now. Deferred past the merge walk — a rebind firing mid-walk
            # would allocate through ancestors not yet re-listed in
            # bad_free_cells (chaos defrag-v1 seed 2).
            self._try_bind_doomed_bad_cell(chain, bad_merge_levels[0])

    def _release_bad_cell(self, c: PhysicalCell) -> None:
        """Reference: releaseBadCell, hived_algorithm.go:1488-1500."""
        self.bad_free_cells[c.chain][c.level].append(c)
        vc = c.virtual_cell
        if vc is not None:
            c.set_virtual_cell(None)
            vc.set_physical_cell(None)
            log.info("Virtual cell %s is unbound from physical cell %s", vc.address, c.address)
        for child in c.children:
            assert isinstance(child, PhysicalCell)
            if not child.healthy:
                self._release_bad_cell(child)

    def _remove_cell_from_free_list(self, c: PhysicalCell) -> CellLevel:
        """Split ancestors as needed (reference: removeCellFromFreeList,
        hived_algorithm.go:1503-1527)."""
        chain = c.chain
        while True:
            l = c.level
            parent = c.parent
            terminate = False
            if parent is not None:
                assert isinstance(parent, PhysicalCell)
                if parent.split:
                    terminate = True
                else:
                    self.free_cell_list[chain][l] = self.free_cell_list[chain][l] + list(
                        parent.children
                    )
                    parent.split = True
            else:
                terminate = True
            self.free_cell_list[chain].remove(c, l)
            if terminate:
                return l
            c = parent  # type: ignore[assignment]

    def _add_cell_to_free_list(self, c: PhysicalCell) -> CellLevel:
        """Merge buddies as possible (reference: addCellToFreeList,
        hived_algorithm.go:1530-1565)."""
        chain = c.chain
        while True:
            l = c.level
            parent = c.parent
            terminate = False
            if parent is not None:
                assert isinstance(parent, PhysicalCell)
                all_buddy_free = all(
                    buddy is c or self.free_cell_list[chain].contains(buddy, l)
                    for buddy in parent.children
                )
                if not all_buddy_free:
                    terminate = True
                else:
                    for buddy in parent.children:
                        if buddy is not c:
                            self.free_cell_list[chain].remove(buddy, l)
                    parent.split = False
            else:
                terminate = True
            if terminate:
                self.free_cell_list[chain][l].append(c)
                return l
            c = parent  # type: ignore[assignment]
