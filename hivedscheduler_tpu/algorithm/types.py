"""Algorithm-internal types: cell lists, scheduling requests, affinity groups,
group placements and binding paths.

TPU-native analogue of the reference's ``pkg/algorithm/types.go``.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from hivedscheduler_tpu.api import types as api
from hivedscheduler_tpu.algorithm.cell import Cell, CellChain, CellLevel, CellPriority, PhysicalCell, VirtualCell, cell_equal
from hivedscheduler_tpu.algorithm.constants import GROUP_PREEMPTING
from hivedscheduler_tpu.k8s.types import Pod

CellList = List[Cell]


def cell_list_contains(cl: CellList, c: Cell) -> bool:
    # identity fast path runs at C speed; the address-equality scan only
    # matters if a list ever held a distinct object with the same address
    return c in cl or any(cell_equal(cc, c) for cc in cl)


def cell_list_remove(cl: CellList, c: Cell) -> CellList:
    """Swap-remove, mirroring CellList.remove (types.go:78-95)."""
    try:
        i = cl.index(c)  # identity scan at C speed (cells define no __eq__)
    except ValueError:
        i = next((j for j, cc in enumerate(cl) if cell_equal(cc, c)), -1)
        if i < 0:
            raise AssertionError(f"Cell not found in list when removing: {c.address}")
    cl[i] = cl[-1]
    cl.pop()
    return cl


def cell_list_to_string(cl: CellList) -> str:
    parts = []
    for c in cl:
        if isinstance(c, PhysicalCell):
            parts.append(f"{c.address}({c.priority})({c.get_physical_placement_string()})")
        else:
            parts.append(f"{c.address}({c.priority})")
    return ", ".join(parts)


class ChainCellList(Dict[CellLevel, CellList]):
    """Per-level cell lists of one chain (reference: types.go:98-130).

    Like the reference's Go map, reading an absent level yields an empty list
    (``__missing__`` inserts it), and instances may be sparse — e.g. a VC free
    list holds only its preassigned cells' level."""

    def __missing__(self, level: CellLevel) -> CellList:
        lst: CellList = []
        self[level] = lst
        return lst

    @staticmethod
    def new(top: CellLevel) -> "ChainCellList":
        ccl = ChainCellList()
        for i in range(1, top + 1):
            ccl[i] = []
        return ccl

    def contains(self, c: Cell, level: CellLevel) -> bool:
        return cell_list_contains(self.get(level, []), c)

    def remove(self, c: Cell, level: CellLevel) -> None:
        self[level] = cell_list_remove(self[level], c)

    def shallow_copy(self) -> "ChainCellList":
        copied = ChainCellList()
        for level in self:
            copied[level] = list(self[level])
        return copied

    def __str__(self) -> str:
        return "".join(
            f"level {level}: {cell_list_to_string(self[level])}\n" for level in sorted(self)
        )


@dataclass
class SchedulingRequest:
    """Reference: schedulingRequest, types.go:43-52."""

    vc: str = ""
    pinned_cell_id: str = ""
    chain: CellChain = ""
    affinity_group_name: str = ""
    affinity_group_pod_nums: Dict[int, int] = field(default_factory=dict)  # leafCellNum -> podNum
    priority: CellPriority = 0
    suggested_nodes: Set[str] = field(default_factory=set)
    ignore_suggested_nodes: bool = False
    multi_chain_relax: bool = True
    # "fewest" | "balanced" — see api.types.PodSchedulingSpec
    multi_chain_relax_policy: str = "fewest"


# placements: leafCellNum -> list over pods -> list of leaf cells of the pod
GroupPhysicalPlacement = Dict[int, List[CellList]]
GroupVirtualPlacement = Dict[int, List[CellList]]


def physical_placement_to_node_leaf_cell_indices(
    p: GroupPhysicalPlacement,
) -> Dict[str, List[int]]:
    """Reference: nodeToLeafCellIndices, types.go:223-238."""
    out: Dict[str, List[int]] = {}
    for pod_placements in p.values():
        for pod_placement in pod_placements:
            for leaf_cell in pod_placement:
                assert isinstance(leaf_cell, PhysicalCell)
                nodes, indices = leaf_cell.get_physical_placement()
                out.setdefault(nodes[0], []).append(indices[0])
    return out


def virtual_placement_to_preassigned_leaf_cells(
    p: GroupVirtualPlacement,
) -> Dict[str, List[str]]:
    """Reference: preassignedCellToLeafCells, types.go:244-261."""
    out: Dict[str, List[str]] = {}
    for pod_placements in p.values():
        for pod_placement in pod_placements:
            for leaf_cell in pod_placement:
                assert isinstance(leaf_cell, VirtualCell)
                pre = leaf_cell.preassigned_cell
                out.setdefault(pre.address, []).append(leaf_cell.address)
    return out


def virtual_to_physical_placement(
    p: GroupVirtualPlacement,
    bindings: Dict[str, PhysicalCell],
    leaf_cell_nums: List[int],
) -> GroupPhysicalPlacement:
    """Reference: toPhysicalPlacement, types.go:263-280."""
    physical: GroupPhysicalPlacement = {}
    for pod_leaf_cell_num in leaf_cell_nums:
        pod_placements = p[pod_leaf_cell_num]
        physical[pod_leaf_cell_num] = [
            [bindings[leaf_cell.address] for leaf_cell in pod_placement]
            for pod_placement in pod_placements
        ]
    return physical


class CellBindingPathVertex:
    """Vertex of a binding-path tree (reference: types.go:342-347).
    Slotted plain class: a gang's binding-path build creates one vertex per
    unbound virtual cell, which puts construction on the schedule hot path."""

    __slots__ = ("cell", "children_to_bind")

    def __init__(
        self,
        cell: VirtualCell,
        children_to_bind: Optional[List["CellBindingPathVertex"]] = None,
    ):
        self.cell = cell
        self.children_to_bind = (
            children_to_bind if children_to_bind is not None else []
        )


def to_binding_paths(
    p: GroupVirtualPlacement,
    leaf_cell_nums: List[int],
    bindings: Dict[str, PhysicalCell],
) -> Tuple[List[CellBindingPathVertex], List[List[CellBindingPathVertex]]]:
    """Collect the unbound virtual ancestors of all placed leaf cells and group
    them into binding-path trees (reference: toBindingPaths, types.go:285-340).

    Returns (preassigned roots, groups of non-preassigned roots that share an
    already-bound parent — grouped so they can be mapped to buddy physical
    cells together). Already-bound leaf cells are recorded into ``bindings``.

    Vertices are keyed by cell identity: a cell appears at most once per tree,
    so identity keys are equivalent to the reference's address keys without
    hashing the (long) hierarchical address strings per leaf."""
    all_vertices: Dict[int, CellBindingPathVertex] = {}
    preassigned: List[CellBindingPathVertex] = []
    non_preassigned: List[List[CellBindingPathVertex]] = []
    for pod_leaf_cell_num in leaf_cell_nums:
        for pod_placement in p[pod_leaf_cell_num]:
            for leaf_cell in pod_placement:
                if leaf_cell.physical_cell is not None:
                    bindings[leaf_cell.address] = leaf_cell.physical_cell
                    continue
                binding_path: List[VirtualCell] = []
                c: Optional[Cell] = leaf_cell
                while c is not None:
                    if c.physical_cell is not None or id(c) in all_vertices:
                        break
                    binding_path.append(c)
                    c = c.parent
                path_root = binding_path[-1]
                n = CellBindingPathVertex(cell=path_root)
                all_vertices[id(path_root)] = n
                parent = path_root.parent
                if parent is None:
                    preassigned.append(n)
                elif parent.physical_cell is not None:  # type: ignore[union-attr]
                    for group in non_preassigned:
                        if cell_equal(parent, group[0].cell.parent):
                            group.append(n)
                            break
                    else:
                        non_preassigned.append([n])
                else:
                    parent_node = all_vertices[id(path_root.parent)]
                    parent_node.children_to_bind.append(n)
                for c2 in reversed(binding_path[:-1]):
                    n2 = CellBindingPathVertex(cell=c2)
                    all_vertices[id(c2.parent)].children_to_bind.append(n2)
                    all_vertices[id(c2)] = n2
    return preassigned, non_preassigned


class AlgoAffinityGroup:
    """Algorithm-internal affinity group (reference: types.go:133-214)."""

    def __init__(
        self,
        spec: api.AffinityGroupSpec,
        vc: str,
        lazy_preemption_enable: bool,
        ignore_k8s_suggested_nodes: bool,
        priority: int,
        state: str,
    ):
        self.name = spec.name
        self.vc = vc
        self.lazy_preemption_enable = lazy_preemption_enable
        # If False we avoid binding cells on non-suggested nodes (best-effort;
        # bad nodes are always avoided).
        self.ignore_k8s_suggested_nodes = ignore_k8s_suggested_nodes
        self.priority = priority
        self.total_pod_nums: Dict[int, int] = {}
        for m in spec.members:
            self.total_pod_nums[m.leaf_cell_number] = (
                self.total_pod_nums.get(m.leaf_cell_number, 0) + m.pod_number
            )
        self.allocated_pods: Dict[int, List[Optional[Pod]]] = {}
        self.preempting_pods: Dict[str, Pod] = {} if state == GROUP_PREEMPTING else None
        self.physical_leaf_cell_placement: GroupPhysicalPlacement = {}
        self.virtual_leaf_cell_placement: GroupVirtualPlacement = {}
        self.state = state
        self.lazy_preemption_status: Optional[api.LazyPreemptionStatus] = None
        # bumped whenever either placement mutates; generate_affinity_group_
        # bind_info caches its (expensive, per-gang-quadratic) result per
        # version
        self.placement_version = 0
        self._bind_info_cache = None  # (version, bind_info_list, chain)
        self._placement_nodes_cache = None  # (version, {node names})
        # per-leaf-cell-num watermark: every allocated_pods slot below it is
        # non-None, so the "first free index" scan starts there instead of
        # rescanning the whole gang per pod (O(gang) instead of O(gang^2)
        # across a gang's bind sequence). Advanced in add_allocated_pod,
        # lowered in delete_allocated_pod — "first None" semantics are exact.
        self.pod_index_watermark: Dict[int, int] = {}
        for leaf_cell_num, pod_num in self.total_pod_nums.items():
            self.physical_leaf_cell_placement[leaf_cell_num] = [
                [None] * leaf_cell_num for _ in range(pod_num)
            ]
            self.virtual_leaf_cell_placement[leaf_cell_num] = [
                [None] * leaf_cell_num for _ in range(pod_num)
            ]
            self.allocated_pods[leaf_cell_num] = [None] * pod_num

    def placement_node_names(self) -> Set[str]:
        """Distinct node names of the physical placement, cached per
        placement version — the per-pod health/suggested scan reads this
        instead of walking every leaf cell."""
        cached = self._placement_nodes_cache
        if cached is not None and cached[0] == self.placement_version:
            return cached[1]
        nodes: Set[str] = set()
        for pod_placements in self.physical_leaf_cell_placement.values():
            for pod_placement in pod_placements:
                for c in pod_placement:
                    if c is not None:
                        nodes.add(c.nodes[0])
        self._placement_nodes_cache = (self.placement_version, nodes)
        return nodes

    def to_affinity_group(self) -> api.AffinityGroup:
        """Reference: ToAffinityGroup, types.go:185-214."""
        status = api.AffinityGroupStatus(
            vc=self.vc,
            priority=self.priority,
            state=self.state,
            lazy_preemption_status=self.lazy_preemption_status,
        )
        if self.physical_leaf_cell_placement:
            try:
                status.physical_placement = physical_placement_to_node_leaf_cell_indices(
                    self.physical_leaf_cell_placement
                )
            except (AssertionError, AttributeError):
                pass  # placement not fully decided yet
        if self.virtual_leaf_cell_placement:
            try:
                status.virtual_placement = virtual_placement_to_preassigned_leaf_cells(
                    self.virtual_leaf_cell_placement
                )
            except (AssertionError, AttributeError):
                pass
        for pods in self.allocated_pods.values():
            for p in pods:
                if p is not None:
                    status.allocated_pods.append(p.uid)
        if self.preempting_pods:
            status.preempting_pods.extend(self.preempting_pods.keys())
        return api.AffinityGroup(name=self.name, status=status)
