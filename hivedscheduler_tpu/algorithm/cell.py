"""Cell data model: generic cells, physical cells, virtual cells.

TPU-native analogue of the reference's ``pkg/algorithm/cell.go``. A Cell is a
set of chips affinitized by ICI topology, organized as a tree via parent/child
pointers. Physical cells in mesh chains additionally carry their sub-mesh
geometry (origin + shape), making "contiguous slice" part of the cell's
identity rather than an emergent property.

State/healthiness mirroring between a physical cell, its bound virtual cell,
and both API statuses follows ``cell.go:195-204`` (state), ``cell.go:302-312``
(healthiness) and the SetVirtualCell/SetPhysicalCell shallow-copy linking
(``cell.go:253-279``, ``cell.go:398-417``).
"""

from __future__ import annotations

import logging
from typing import Dict, List, Optional, Tuple

from hivedscheduler_tpu.api import types as api
from hivedscheduler_tpu.algorithm.constants import (
    CELL_FREE,
    FREE_PRIORITY,
)

log = logging.getLogger(__name__)

CellChain = str
CellLevel = int
CellPriority = int


def cell_equal(c1: Optional["Cell"], c2: Optional["Cell"]) -> bool:
    """Reference: CellEqual, cell.go:50-56."""
    if c1 is None or c2 is None:
        return c1 is None and c2 is None
    return c1.address == c2.address


class Cell:
    """Base cell (reference: GenericCell, cell.go:58-127)."""

    def __init__(
        self,
        chain: CellChain,
        level: CellLevel,
        address: str,
        at_or_higher_than_node: bool,
        total_leaf_cell_num: int,
    ):
        self.chain = chain
        self.level = level
        self.address = address
        self.parent: Optional[Cell] = None
        self.children: List[Cell] = []
        self.at_or_higher_than_node = at_or_higher_than_node
        self.priority: CellPriority = FREE_PRIORITY
        self.state: str = CELL_FREE
        # healthy is orthogonal to priority and state; all children healthy =>
        # healthy. Cells start healthy and are mass-marked bad by
        # HivedAlgorithm.init_bad_nodes until node informs arrive.
        self.healthy: bool = True
        self.total_leaf_cell_num = total_leaf_cell_num
        self.used_leaf_cell_num_at_priorities: Dict[CellPriority, int] = {}
        # Monotonic mutation counter driving the persistent cluster views
        # (algorithm/topology_aware.py): bumped on every used-count change,
        # healthiness transition, and binding change — anything a view's
        # per-node scoring reads. A view caches the counter value it last
        # saw per node and recomputes only nodes whose counter moved.
        self.view_gen = 0

    def set_priority(self, p: CellPriority) -> None:
        self.priority = p

    def increase_used_leaf_cell_num_at_priority(self, p: CellPriority, delta: int) -> None:
        n = self.used_leaf_cell_num_at_priorities.get(p, 0) + delta
        if n == 0:
            self.used_leaf_cell_num_at_priorities.pop(p, None)
        else:
            self.used_leaf_cell_num_at_priorities[p] = n
        self.view_gen += 1

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return f"<{type(self).__name__} {self.chain}/{self.address} L{self.level} P{self.priority} {self.state}>"


class PhysicalCell(Cell):
    """A cell in the physical cluster (reference: cell.go:130-312)."""

    def __init__(
        self,
        chain: CellChain,
        level: CellLevel,
        at_or_higher_than_node: bool,
        total_leaf_cell_num: int,
        cell_type: str,
        address: str,
        is_node_level: bool,
        mesh_origin: Optional[Tuple[int, ...]] = None,
        mesh_shape: Optional[Tuple[int, ...]] = None,
    ):
        super().__init__(chain, level, address, at_or_higher_than_node, total_leaf_cell_num)
        self.nodes: List[str] = []  # node names inside the cell
        self.leaf_cell_indices: List[int] = []  # [-1] above node level
        self.using_group = None  # type: Optional[object]  # AlgoAffinityGroup
        self.reserving_or_reserved_group = None  # type: Optional[object]
        self.virtual_cell: Optional["VirtualCell"] = None
        self.split = False
        self.pinned = False
        # TPU mesh geometry (None for generic chains).
        self.mesh_origin = mesh_origin
        self.mesh_shape = mesh_shape
        self.api_status = api.PhysicalCellStatus(
            cell_type=cell_type,
            is_node_level=is_node_level,
            cell_address=address,
            cell_state=CELL_FREE,
            cell_healthiness=api.CELL_HEALTHY,
            cell_priority=FREE_PRIORITY,
            mesh_origin=mesh_origin,
            mesh_shape=mesh_shape,
        )

    def set_children(self, children: List[Cell]) -> None:
        self.children = children
        for cc in children:
            assert isinstance(cc, PhysicalCell)
            self.api_status.cell_children.append(cc.api_status)

    def set_priority(self, p: CellPriority) -> None:
        self.priority = p
        self.api_status.cell_priority = p
        if self.api_status.virtual_cell is not None:
            self.api_status.virtual_cell.cell_priority = p

    def set_state(self, s: str) -> None:
        """Propagates to the bound virtual cell and all status mirrors
        (reference: cell.go:195-204)."""
        self.state = s
        self.api_status.cell_state = s
        if self.virtual_cell is not None:
            self.virtual_cell.state = s
            self.virtual_cell.api_status.cell_state = s
            self.api_status.virtual_cell.cell_state = s
            self.virtual_cell.api_status.physical_cell.cell_state = s

    def get_physical_placement(self) -> Tuple[List[str], List[int]]:
        return self.nodes, self.leaf_cell_indices

    def get_physical_placement_string(self) -> str:
        return f"{self.nodes}:{self.leaf_cell_indices}"

    def set_physical_resources(self, nodes: List[str], leaf_cell_indices: List[int]) -> None:
        self.nodes = nodes
        self.leaf_cell_indices = leaf_cell_indices

    def add_using_group(self, g) -> None:
        if self.using_group is not None:
            log.error(
                "Found another using affinity group %s when adding using group %s to cell %s",
                self.using_group.name, g.name, self.address,
            )
        self.using_group = g

    def delete_using_group(self, g) -> None:
        if self.using_group is None or self.using_group.name != g.name:
            log.error("Using affinity group %s not found when deleting from cell %s",
                      g.name, self.address)
        self.using_group = None

    def add_reserving_or_reserved_group(self, g) -> None:
        if self.reserving_or_reserved_group is not None:
            log.error(
                "Found another reserving/reserved group %s when adding group %s to cell %s",
                self.reserving_or_reserved_group.name, g.name, self.address,
            )
        self.reserving_or_reserved_group = g

    def delete_reserving_or_reserved_group(self, g) -> None:
        if (
            self.reserving_or_reserved_group is None
            or self.reserving_or_reserved_group.name != g.name
        ):
            log.error("Reserving/reserved group %s not found when deleting from cell %s",
                      g.name, self.address)
        self.reserving_or_reserved_group = None

    def set_virtual_cell(self, cell: Optional["VirtualCell"]) -> None:
        """Reference: cell.go:253-279 — keep a pointer-free shallow copy of the
        peer's status in the API mirror."""
        self.virtual_cell = cell
        if cell is None:
            self.api_status.virtual_cell = None
            self.api_status.vc = ""
        else:
            vcs = _shallow_copy_virtual_status(cell.api_status)
            self.api_status.virtual_cell = vcs
            self.api_status.vc = cell.vc

    def set_healthiness(self, h: str) -> None:
        """Reference: cell.go:302-312."""
        log.info("Cell %s is set to %s", self.address, h)
        self.healthy = h == api.CELL_HEALTHY
        self.view_gen += 1
        self.api_status.cell_healthiness = h
        if self.virtual_cell is not None:
            self.virtual_cell.healthy = self.healthy
            self.virtual_cell.view_gen += 1
            self.api_status.virtual_cell.cell_healthiness = h
            self.virtual_cell.api_status.cell_healthiness = h
            self.virtual_cell.api_status.physical_cell.cell_healthiness = h


class VirtualCell(Cell):
    """A cell in a VC (reference: cell.go:314-423)."""

    def __init__(
        self,
        vc: str,
        chain: CellChain,
        level: CellLevel,
        at_or_higher_than_node: bool,
        total_leaf_cell_num: int,
        preassigned_cell: Optional["VirtualCell"],
        cell_type: str,
        address: str,
        is_node_level: bool,
    ):
        super().__init__(chain, level, address, at_or_higher_than_node, total_leaf_cell_num)
        self.vc = vc
        self.pid: str = ""  # pinned cell id
        self.preassigned_cell = preassigned_cell
        self.physical_cell: Optional[PhysicalCell] = None
        self.api_status = api.VirtualCellStatus(
            cell_type=cell_type,
            is_node_level=is_node_level,
            cell_address=address,
            cell_state=CELL_FREE,
            cell_healthiness=api.CELL_HEALTHY,
            cell_priority=FREE_PRIORITY,
        )

    def set_children(self, children: List[Cell]) -> None:
        self.children = children
        for cc in children:
            assert isinstance(cc, VirtualCell)
            self.api_status.cell_children.append(cc.api_status)

    def set_priority(self, p: CellPriority) -> None:
        self.priority = p
        self.api_status.cell_priority = p
        if self.api_status.physical_cell is not None:
            self.api_status.physical_cell.cell_priority = p

    def set_pinned_cell_id(self, pid: str) -> None:
        self.pid = pid

    def set_physical_cell(self, cell: Optional[PhysicalCell]) -> None:
        """Reference: cell.go:398-417."""
        self.physical_cell = cell
        # a virtual node's health/suggested scoring proxies through the
        # bound physical cell — binding changes dirty the cluster views
        self.view_gen += 1
        if cell is None:
            self.api_status.physical_cell = None
            self.state = CELL_FREE
            self.healthy = True
            self.api_status.cell_healthiness = api.CELL_HEALTHY
            self.api_status.cell_state = CELL_FREE
        else:
            self.healthy = cell.healthy
            pcs = _shallow_copy_physical_status(cell.api_status)
            self.api_status.physical_cell = pcs
            self.api_status.cell_healthiness = pcs.cell_healthiness


def _shallow_copy_physical_status(s: api.PhysicalCellStatus) -> api.PhysicalCellStatus:
    """Copy every scalar field, drop children and the virtual cross-link
    (breaks serialization cycles). Implemented as a C-level ``__dict__`` copy:
    this runs twice per cell bind, which makes it a gang-allocation hot spot
    (guard: ``test_e2e.py::test_status_shallow_copy_covers_all_fields``)."""
    out = api.PhysicalCellStatus.__new__(api.PhysicalCellStatus)
    d = dict(s.__dict__)
    d["cell_children"] = []
    d["virtual_cell"] = None
    out.__dict__ = d
    return out


def _shallow_copy_virtual_status(s: api.VirtualCellStatus) -> api.VirtualCellStatus:
    """See ``_shallow_copy_physical_status``."""
    out = api.VirtualCellStatus.__new__(api.VirtualCellStatus)
    d = dict(s.__dict__)
    d["cell_children"] = []
    d["physical_cell"] = None
    out.__dict__ = d
    return out
